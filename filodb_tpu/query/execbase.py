"""Exec-tree foundations: data shapes, fused-leaf caches, the
3-phase aggregation finishers, and the ExecPlan base classes.

Split from the original query/exec.py (round 4, no behavior change);
`filodb_tpu.query.exec` re-exports everything, so import paths are
unchanged.  ref: query/.../exec/ExecPlan.scala:41-186,
AggrOverRangeVectors.scala:17-125.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax.numpy as jnp

from filodb_tpu.core.index import ColumnFilter, Equals
from filodb_tpu.ops import agg as agg_ops
from filodb_tpu.ops import hist as hist_ops
from filodb_tpu.ops.instant import (INSTANT_FUNCTIONS, ARITH_OPERATORS,
                                    COMPARISON_OPERATORS, apply_binary_op)
from filodb_tpu.ops import counter as counter_ops
from filodb_tpu.ops.rangefns import RANGE_FUNCTIONS, evaluate_range_function
from filodb_tpu.ops.timewindow import PAD_TS, to_offsets, make_window_ends
from filodb_tpu.query.rangevector import (QueryContext, QueryResult, QueryStats,
                                          RangeVectorKey, ResultBlock,
                                          concat_blocks, remove_nan_series)

# --------------------------------------------------------------- data shapes


class LazyKeys:
    """Sequence facade over `shard.keys_for(pids)` deferring the O(S)
    Python per-series key materialization until something actually reads
    a key.  Warm fused-path queries never do — group ids and group keys
    come from the snapshot-keyed group cache — so building RawBlock.keys
    eagerly charged every dashboard poll ~6 ms per 16k series (measured:
    keys_for was 35% of the batched 12-panel hist dashboard's host time)
    for a list nobody indexed.  len()/bool are O(1); iteration, indexing
    and slicing materialize once and memoize.

    Deferral widens the window in which eviction can recycle a pid
    between the leaf scan and first key read, so the shard's keys_epoch
    is captured at construction: if it moved by materialization time the
    pids may no longer name the snapshot's series — fall back to
    resolving each pid defensively (keys_for already yields a sentinel
    key for pruned slots) and count the event so the race is observable
    instead of silent (ADVICE r5)."""
    __slots__ = ("_shard", "_pids", "_keys", "_epoch")

    def __init__(self, shard, pids):
        self._shard = shard
        self._pids = pids
        self._keys = None
        self._epoch = shard.keys_epoch

    def _mat(self):
        if self._keys is None:
            if self._shard.keys_epoch != self._epoch:
                from filodb_tpu.utils.metrics import registry
                registry.counter("lazykeys_epoch_moved",
                                 dataset=self._shard.dataset).increment()
            self._keys = self._shard.keys_for(self._pids)
        return self._keys

    def __len__(self):
        return int(self._pids.size)

    def __bool__(self):
        return self._pids.size > 0

    def __iter__(self):
        return iter(self._mat())

    def __getitem__(self, i):
        return self._mat()[i]


@dataclasses.dataclass
class RawBlock:
    """Raw gathered samples for one schema on one shard: pre-step-grid.

    values are REBASED per series (absolute value - vbase[s]) so counter
    deltas survive the f32 device downcast; vbase is the per-series base
    in f64 (None = not rebased).  See ops/timewindow.series_value_base."""
    keys: List[RangeVectorKey]
    ts_off: np.ndarray                  # int32 [S, T] offsets from base_ms
    values: np.ndarray                  # [S, T] or [S, T, B]
    base_ms: int
    bucket_les: Optional[np.ndarray] = None
    samples: int = 0                    # total valid samples (stats)
    vbase: Optional[np.ndarray] = None  # [S] or [S, B]
    precorrected: bool = False          # counter reset-correction done host-side
    # shared scrape grid: row-0 ts offsets when ALL rows share one grid
    # (the pallas_fused precondition, tracked by the device mirror); None
    # otherwise.  `dense` qualifies it: True = no NaN holes anywhere in the
    # counted region; False = NaN-holed values on the shared grid, which
    # only the validity-weighted fused kinds accept.
    shared_ts_row: Optional[np.ndarray] = None
    dense: bool = True
    # working-set identity (shard keys_serial, keys_epoch, pids bytes):
    # lets key-preserving transformers reuse cached host group ids —
    # _group_ids is an O(S) Python loop that dominated warm general-path
    # queries (~0.3s of a 0.4s query at 65k series)
    cache_token: Optional[Tuple] = None
    # cost-based router verdict (round-5 item 6): True when the leaf's
    # estimated working set is below query.host_route_max_samples — the
    # gather then stays host-side and _try_fused evaluates in numpy
    # (ops/hostleaf) instead of paying the ~65 ms device dispatch floor
    route_host: bool = False


# Fused-leaf caches (see MultiSchemaPartitionsExec._try_fused): entries are
# keyed by (mirror serial, snapshot gen, ...) so any ingest naturally
# misses.  The VALUES cache holds the full padded device copies — shared
# across grouping variants (they depend only on the working set) and
# bounded in BYTES, since this HBM lives outside the DeviceMirror's own
# hbm_limit_bytes accounting.  The GROUP cache holds the small per-grouping
# gid arrays.
_FUSED_PLAN_CACHE: Dict[Tuple, object] = {}
_FUSED_VALS_CACHE: Dict[Tuple, object] = {}
_FUSED_GROUP_CACHE: Dict[Tuple, Tuple] = {}
# NaN-padded device copies for the reduce_window path's end=now shape,
# keyed (working set, t_needed) — small cap: each entry pins a full copy
_FUSED_MINMAX_PAD_CACHE: Dict[Tuple, object] = {}
_FUSED_VALS_CACHE_BYTES: Optional[int] = None    # resolved lazily
_MIRROR_LIMIT_SEEN: Optional[int] = None         # largest live mirror budget


def _note_mirror_limit(limit_bytes: int) -> None:
    """Record the largest DeviceMirror HBM budget actually constructed so
    the fused-cache budget subtracts the REAL mirror share, not just the
    compile-time default (review r3)."""
    global _MIRROR_LIMIT_SEEN, _FUSED_VALS_CACHE_BYTES
    if _MIRROR_LIMIT_SEEN is None or limit_bytes > _MIRROR_LIMIT_SEEN:
        _MIRROR_LIMIT_SEEN = limit_bytes
        _FUSED_VALS_CACHE_BYTES = None   # re-derive on next insert


def _fused_vals_budget() -> int:
    """Byte budget for the padded-values cache.  Configurable via
    FILODB_TPU_FUSED_CACHE_BYTES; otherwise derived from the device's
    reported HBM minus the live mirror budget so mirror + this cache +
    headroom cannot exceed the chip (ADVICE r2: the old fixed 4 GiB
    ignored the mirror's budget).  Resolved lazily — the backend is
    already initialized by the time the first fused query inserts."""
    global _FUSED_VALS_CACHE_BYTES
    if _FUSED_VALS_CACHE_BYTES is not None:
        return _FUSED_VALS_CACHE_BYTES
    env = os.environ.get("FILODB_TPU_FUSED_CACHE_BYTES")
    if env:
        _FUSED_VALS_CACHE_BYTES = int(env)
        return _FUSED_VALS_CACHE_BYTES
    budget = 4 << 30
    try:
        import jax

        from filodb_tpu.core.devicecache import DEFAULT_HBM_LIMIT_BYTES
        mirror_limit = _MIRROR_LIMIT_SEEN or DEFAULT_HBM_LIMIT_BYTES
        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit:
            budget = min(budget,
                         max(1 << 30, limit - mirror_limit - (2 << 30)))
    except Exception:  # noqa: BLE001 — stats unavailable: keep the default
        pass
    _FUSED_VALS_CACHE_BYTES = budget
    return budget
# queries run on HTTP worker threads (http/server.py ThreadingHTTPServer) —
# every cache read-modify-write holds this lock; the kernel runs outside it
_FUSED_CACHE_LOCK = threading.Lock()


class GroupCardinalityError(ValueError):
    """group-by cardinality limit exceeded — a real query error that must
    surface even from the fused fast path (everything else falls back)."""


class QueryError(Exception):
    """Typed query-path failure with a stable machine-readable code — the
    error taxonomy a scatter-gather root surfaces when a node dies
    mid-query (ref: the Akka ask's clean QueryError at the root,
    query/.../exec/PlanDispatcher.scala:31-55).  Codes:

      shard_unavailable — a child dispatch could not reach its shard
          owner (connection refused / reset, e.g. SIGKILL mid-query).
          Retryable: after failover reassigns the shard, a re-planned
          query succeeds (QueryEngine retries once when
          query.dispatch_retries > 0 and a replan hook is wired).
      dispatch_timeout — the remote accepted the plan but no reply
          arrived within the dispatcher timeout (query.ask_timeout_s).
          NOT retried automatically: the remote may still be executing,
          and a re-send would run the query twice.
      remote_failure — the remote executed the plan and returned an
          error (its exception text rides along).  Not retryable here;
          the same plan would fail the same way.
      query_timeout — the query's end-to-end deadline
          (query.default_timeout_s / the `timeout=` request param)
          expired: at an exec-node boundary, while queued in the
          frontend scheduler, or mid-dispatch when the remaining budget
          (not the per-hop ask timeout) bounded the socket wait.  Never
          retried and never dropped-for-partial — the budget is global,
          so continuing cannot produce a timely answer.
      query_canceled — the query's CancellationToken was tripped
          (admin kill via POST /admin/queries/<id>/kill, a client
          disconnect detected mid-query, or a kill frame from the
          coordinator): checked at every exec-node boundary, inside
          the demand-paging loop, and before fused kernel dispatches.
          Never retried, never dropped-for-partial, never cached —
          nobody is waiting for the answer.

    The string form is always "<code>: <detail>", so HTTP/CLI clients
    (and tests) can route on `error.split(':', 1)[0]`."""

    def __init__(self, code: str, detail: str):
        self.code = code
        super().__init__(detail)

    def __str__(self):
        return f"{self.code}: {super().__str__()}"


def _lru_touch(cache: Dict, key) -> object:
    """Get + move-to-back (dicts iterate in insertion order, so eviction
    pops the front = least-recently-used).  One idiom for all fused caches."""
    val = cache.get(key)
    if val is not None:
        cache[key] = cache.pop(key)
    return val


def _vals_nbytes(v) -> int:
    return int(v.vals_p.size * 4 + v.vbase_p.size * 4)


def _group_cache_lookup(key, by, without):
    """Cached (PaddedGroups, gkeys) for this working set + grouping, or
    (None, None).  Pairs with _group_cache_insert — the two halves of the
    group-cache protocol, shared by the kernel and reduce_window paths."""
    if key is None:
        return None, None
    with _FUSED_CACHE_LOCK:
        ent = _lru_touch(_FUSED_GROUP_CACHE, key + (by, without))
    return ent if ent is not None else (None, None)


def _group_cache_insert(key, by, without, groups, gkeys) -> None:
    """Insert a (PaddedGroups, gkeys) entry, evicting entries from older
    snapshot generations of the same mirror (each pins device arrays) and
    capping the cache.  The single home of the group-cache write rules —
    used by both the kernel path and the reduce_window path."""
    if key is None:
        return
    group_key = key + (by, without)
    with _FUSED_CACHE_LOCK:
        for k in [k for k in _FUSED_GROUP_CACHE
                  if k[0] == key[0] and k[1] != key[1]]:
            del _FUSED_GROUP_CACHE[k]
        _FUSED_GROUP_CACHE[group_key] = (groups, gkeys)
        while len(_FUSED_GROUP_CACHE) > 16:
            _FUSED_GROUP_CACHE.pop(next(iter(_FUSED_GROUP_CACHE)))


def _vals_cache_insert(key, v) -> None:
    _FUSED_VALS_CACHE[key] = v
    while len(_FUSED_VALS_CACHE) > 4 or sum(
            _vals_nbytes(e) for e in _FUSED_VALS_CACHE.values()
            ) > _fused_vals_budget():
        if len(_FUSED_VALS_CACHE) == 1:
            break                        # always keep the entry just added
        _FUSED_VALS_CACHE.pop(next(iter(_FUSED_VALS_CACHE)))


@dataclasses.dataclass
class ScalarResult:
    """One value per step (scalar plans)."""
    wends: np.ndarray                   # int64 [W]
    values: np.ndarray                  # float [W]


@dataclasses.dataclass
class AggPartial:
    """Partial aggregate: mesh-reducible (op-dependent) representation."""
    op: str
    group_keys: List[RangeVectorKey]
    wends: np.ndarray
    comp: Optional[np.ndarray] = None   # [G, W, C] associative component form
    # candidate form (topk/bottomk/quantile/count_values): raw rows
    cand_keys: Optional[List[RangeVectorKey]] = None
    cand_vals: Optional[np.ndarray] = None   # [N, W]
    cand_groups: Optional[np.ndarray] = None  # int [N] -> group_keys index
    params: Tuple = ()
    bucket_les: Optional[np.ndarray] = None  # hist_sum partials
    # quantile(): mergeable centroid sketch [G, W, K, 2] — O(groups) wire
    # cost instead of shipping every candidate series row
    # (ref: QuantileRowAggregator.scala:87 t-digest partials)
    sketch: Optional[np.ndarray] = None
    # working-set identity of the aggregated KEYS — ("agg", op, by,
    # without, source token): group keys are a pure function of the
    # source series set and the grouping, so downstream keys-only caches
    # (the PR 17 binary-join index maps) can reuse resolved matches
    # across dashboard re-polls.  Value-level identity is NOT implied
    # (rate and increase over one working set share a token by design).
    # Process-local like every cache_token — serialize nulls it.
    cache_token: Optional[Tuple] = None


def agg_token(op: str, by, without,
              data_token: Optional[Tuple]) -> Optional[Tuple]:
    """Token for an AggPartial built from a block carrying data_token."""
    if data_token is None:
        return None
    return ("agg", op, tuple(by), tuple(without), data_token)


Data = Union[RawBlock, ResultBlock, ScalarResult, AggPartial, None]


def _block_empty(wends: np.ndarray) -> ResultBlock:
    return ResultBlock([], wends, np.zeros((0, len(wends))))



def present_partial(p: AggPartial) -> Optional[ResultBlock]:
    """Finish an AggPartial into a ResultBlock."""
    if p.sketch is not None:
        from filodb_tpu.ops import sketch as sketch_ops
        q = float(p.params[0])
        out = sketch_ops.sketch_quantile(p.sketch, q)
        return ResultBlock(p.group_keys, p.wends, out,
                           cache_token=p.cache_token)
    if p.comp is not None:
        if p.op == "hist_sum":
            # [G, W, B+1] with present-series count in the last slot
            buckets = p.comp[..., :-1]
            present_cnt = p.comp[..., -1]
            out = np.where(present_cnt[..., None] > 0, buckets, np.nan)
            return ResultBlock(p.group_keys, p.wends, out, p.bucket_les,
                               cache_token=p.cache_token)
        out = np.asarray(agg_ops.present(p.op, jnp.asarray(p.comp)))
        return ResultBlock(p.group_keys, p.wends, out,
                           cache_token=p.cache_token)
    # candidate form
    if p.op in ("topk", "bottomk"):
        k = int(p.params[0])
        gids = p.cand_groups
        mask = np.asarray(agg_ops.topk_mask(
            jnp.asarray(p.cand_vals), jnp.asarray(gids), len(p.group_keys),
            k, largest=(p.op == "topk")))
        vals = np.where(mask, p.cand_vals, np.nan)
        block = ResultBlock(p.cand_keys, p.wends, vals)
        return remove_nan_series(block)
    if p.op == "quantile":
        q = float(p.params[0])
        out = np.asarray(agg_ops.quantile_agg(
            jnp.asarray(p.cand_vals), jnp.asarray(p.cand_groups),
            len(p.group_keys), q))
        return ResultBlock(p.group_keys, p.wends, out)
    if p.op == "count_values":
        label = str(p.params[0])
        vals = p.cand_vals
        out_keys: List[RangeVectorKey] = []
        out_rows: List[np.ndarray] = []
        W = vals.shape[1]
        for g in range(len(p.group_keys)):
            rows = vals[p.cand_groups == g]
            uniq = np.unique(rows[~np.isnan(rows)])
            for v in uniq:
                cnt = np.nansum(rows == v, axis=0).astype(float)
                cnt[cnt == 0] = np.nan
                lbls = dict(p.group_keys[g].labels)
                lbls[label] = f"{v:g}"
                out_keys.append(RangeVectorKey.make(lbls))
                out_rows.append(cnt)
        if not out_keys:
            return None
        return ResultBlock(out_keys, p.wends, np.stack(out_rows))
    raise ValueError(p.op)


def _union_scheme(les_list: List[Optional[np.ndarray]]) -> Optional[np.ndarray]:
    """Union bucket scheme across shards, or None when any shard carries no
    boundaries (widths must then match — checked by the caller's reshape)."""
    from filodb_tpu.memory.histogram import union_les
    known = [l for l in les_list if l is not None]
    if len(known) != len(les_list):
        return None
    out = known[0]
    for l in known[1:]:
        out = union_les(out, l)
    return out


def _align_hist_schemes(parts: List[AggPartial]) -> List[AggPartial]:
    """Rebucket hist_sum partials onto the union scheme so shards whose
    series changed bucket scheme mid-retention still merge
    (ref: HistogramBuckets.scala:340; replaces the fail-loudly behavior)."""
    from filodb_tpu.memory.histogram import rebucket
    les_list = [p.bucket_les for p in parts]
    if any(l is None for l in les_list):
        # boundary-less partials can only merge by width (legacy behavior);
        # order of children must not matter — and any two KNOWN schemes
        # that differ cannot be silently index-merged just because a third
        # partial lacks boundaries
        widths = {p.comp.shape[-1] for p in parts}
        known = [l for l in les_list if l is not None]
        if len(widths) > 1 or any(not np.array_equal(l, known[0])
                                  for l in known[1:]):
            raise ValueError(
                "cannot merge histogram partials of different schemes when "
                "some shards carry no bucket boundaries to re-map by")
        return parts
    if all(np.array_equal(l, les_list[0]) for l in les_list):
        return parts
    union = _union_scheme(les_list)

    def _rebucket_comp(p):
        # comp is [G, W, B+1]: B bucket slots + the present-series count
        B = len(p.bucket_les)
        buckets = rebucket(p.comp[..., :B], p.bucket_les, union)
        return np.concatenate([buckets, p.comp[..., B:]], axis=-1)

    return [dataclasses.replace(p, comp=_rebucket_comp(p), bucket_les=union)
            if not np.array_equal(p.bucket_les, union) else p
            for p in parts]


def _reduced_token(parts: List[AggPartial]) -> Optional[Tuple]:
    """Composite identity of a merged partial: the children's tokens in
    merge order (the merged key order is a pure function of them)."""
    toks = tuple(p.cache_token for p in parts)
    return ("red",) + toks if all(t is not None for t in toks) else None


def reduce_partials(parts: List[AggPartial],
                    compress: bool = True) -> Optional[AggPartial]:
    """Inter-shard reduce (ReduceAggregateExec): merge partials by group key.

    ``compress=False`` is the node-level pushdown mode for quantile
    sketches: the centroid axes are concatenated (zero-weight padded)
    but NOT re-compressed, so the coordinator's single
    ``merge_sketches`` over the node partials sees the same centroid
    multiset — in the same order, since pushdown groups children
    contiguously — as a flat per-shard merge would, making quantile
    pushdown bit-identical to the ship-everything path."""
    parts = [p for p in parts if p is not None]
    if not parts:
        return None
    op = parts[0].op
    if op == "hist_sum":
        parts = _align_hist_schemes(parts)
    gmap: Dict[RangeVectorKey, int] = {}
    gkeys: List[RangeVectorKey] = []
    for p in parts:
        for k in p.group_keys:
            if k not in gmap:
                gmap[k] = len(gkeys)
                gkeys.append(k)
    wends = parts[0].wends
    if parts[0].sketch is not None:
        # quantile sketches: concat centroid axis per group (zero-weight
        # padding for shards that lack a group), then re-compress to K
        from filodb_tpu.ops import sketch as sketch_ops
        G = len(gkeys)
        W = parts[0].sketch.shape[1]
        M = sum(p.sketch.shape[2] for p in parts)
        cat = np.zeros((G, W, M, 2))
        cat[..., 0] = np.nan
        off = 0
        for p in parts:
            idx = np.asarray([gmap[k] for k in p.group_keys], dtype=np.int64)
            m = p.sketch.shape[2]
            cat[idx, :, off:off + m] = p.sketch
            off += m
        return AggPartial(op, gkeys, wends,
                          sketch=(sketch_ops.merge_sketches(cat)
                                  if compress else cat),
                          params=parts[0].params,
                          cache_token=_reduced_token(parts))
    if parts[0].comp is not None:
        C = parts[0].comp.shape[-1]
        W = parts[0].comp.shape[1]
        combs = agg_ops.combiners_for(op, C)
        init = {"sum": 0.0, "min": np.inf, "max": -np.inf}
        out = np.empty((len(gkeys), W, C))
        for i, comb in enumerate(combs):
            out[..., i] = init[comb]
        for p in parts:
            idx = np.asarray([gmap[k] for k in p.group_keys], dtype=np.int64)
            for i, comb in enumerate(combs):
                ufunc = {"sum": np.add, "min": np.minimum,
                         "max": np.maximum}[comb]
                ufunc.at(out[..., i], idx, p.comp[..., i])
        return AggPartial(op, gkeys, wends, comp=out, params=parts[0].params,
                          bucket_les=parts[0].bucket_les,
                          cache_token=_reduced_token(parts))
    # candidate form: concat and remap groups
    ck: List[RangeVectorKey] = []
    cv: List[np.ndarray] = []
    cg: List[np.ndarray] = []
    for p in parts:
        idx = np.asarray([gmap[k] for k in p.group_keys], dtype=np.int64)
        ck.extend(p.cand_keys)
        cv.append(p.cand_vals)
        cg.append(idx[p.cand_groups])
    return AggPartial(op, gkeys, wends,
                      cand_keys=ck, cand_vals=np.concatenate(cv),
                      cand_groups=np.concatenate(cg), params=parts[0].params)


# ---------------------------------------------------------------- exec plans


class AnalyzeRecorder:
    """Per-node resource records for `/api/v1/explain?analyze=true` (the
    EXPLAIN ANALYZE of the exec tree): every locally-executed node
    appends its EXCLUSIVE wall/device/transfer attribution plus the
    cumulative scan counters its subtree produced.  Attach by setting
    `ctx.analyze = AnalyzeRecorder()` on the QueryContext BEFORE
    execution (a plain attribute, deliberately not a dataclass field, so
    remote-dispatched subtrees serialize without it — their spans still
    stitch into the trace; their per-node detail stays on their node)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.by_node: Dict[int, dict] = {}      # id(node) -> record
        self.order: List[dict] = []

    def add(self, node, rec: dict) -> None:
        with self._lock:
            self.by_node[id(node)] = rec
            self.order.append(rec)

    def annotation(self, node) -> str:
        """Tree-line suffix for print_tree(annot=...)."""
        r = self.by_node.get(id(node))
        if r is None:
            return "  [not executed locally]"
        out = ("  [self=%.3fms device=%.3fms transfer=%.3fms "
               "bytes=%d samples=%d series=%d]"
               % (r["self_s"] * 1e3, r["device_s"] * 1e3,
                  r["transfer_s"] * 1e3, r["bytes_transferred"],
                  r["samples_scanned"], r["series_scanned"]))
        if r.get("pushdown"):
            # node-group aggregation pushdown verdict (query/pushdown.py)
            out += f" [pushdown={r['pushdown']}]"
        return out


class PlanDispatcher:
    """ref: exec/PlanDispatcher.scala:20."""

    def dispatch(self, plan: "ExecPlan", source) -> QueryResultLike:
        raise NotImplementedError


QueryResultLike = Tuple[Data, QueryStats]


class InProcessPlanDispatcher(PlanDispatcher):
    """Run the subtree in-process (ref: exec/InProcessPlanDispatcher.scala:89)."""

    def dispatch(self, plan: "ExecPlan", source) -> QueryResultLike:
        return plan.execute_internal(source)


class ExecPlan:
    """Base execution node.  `execute_internal` returns raw Data + stats;
    `execute` materializes a QueryResult with limits enforced
    (ref: ExecPlan.scala:96-186)."""

    def __init__(self, ctx: Optional[QueryContext] = None):
        self.ctx = ctx or QueryContext()
        self.transformers: List[RangeVectorTransformer] = []
        self.dispatcher: PlanDispatcher = InProcessPlanDispatcher()

    def add_transformer(self, t: RangeVectorTransformer) -> "ExecPlan":
        self.transformers.append(t)
        return self

    @property
    def children(self) -> List["ExecPlan"]:
        return []

    # -- execution

    def _do_execute(self, source) -> QueryResultLike:
        raise NotImplementedError

    def _execute_impl(self, source) -> QueryResultLike:
        data, stats = self._do_execute(source)
        for t in self.transformers:
            data = t.apply(data, self.ctx, stats, source)
        return data, stats

    def execute_internal(self, source) -> QueryResultLike:
        """_execute_impl wrapped in the resource tally: each node's
        EXCLUSIVE wall time (total minus nested nodes') plus whatever
        device/transfer work the thread accumulated while this node ran
        lands in ITS QueryStats — children's contributions arrive via
        stats.merge, so the root totals are exact sums over nodes."""
        from filodb_tpu.utils.metrics import exec_tally
        # deadline check at every node boundary: a query past its budget
        # stops HERE instead of fanning out more work (getattr: contexts
        # serialized by an older peer lack the field)
        dl = getattr(self.ctx, "deadline_unix_s", 0.0)
        if dl and _time.time() >= dl:
            raise QueryError(
                "query_timeout",
                f"deadline exceeded at {type(self).__name__} "
                f"(budget expired {_time.time() - dl:.3f}s ago)")
        # cooperative cancellation at the same boundary: a killed query
        # stops HERE instead of fanning out more work (the token is a
        # plain attribute — it never rides the wire; remote nodes mint
        # their own, keyed by query id)
        tok = getattr(self.ctx, "cancel", None)
        if tok is not None and tok.cancelled:
            tok.raise_if_cancelled(f"at {type(self).__name__}")
        snap = exec_tally.snapshot()
        t0 = _time.perf_counter()
        try:
            data, stats = self._execute_impl(source)
        except BaseException:
            # attribution on the error path: the parent sees the whole
            # failed subtree as child time, never as its own cpu
            exec_tally.restore(snap, _time.perf_counter() - t0)
            raise
        total = _time.perf_counter() - t0
        # exclusive HOST cpu: nested nodes' wall AND this node's own
        # synchronous device/transfer waits are carved out, so the three
        # phase columns (exec/device/transfer) partition wall time
        # instead of double-counting it
        self_wall = max(total - exec_tally.child_wall
                        - exec_tally.device_s - exec_tally.transfer_s, 0.0)
        stats.cpu_seconds += self_wall
        stats.device_seconds += exec_tally.device_s
        stats.transfer_s += exec_tally.transfer_s
        stats.bytes_transferred += exec_tally.transfer_bytes
        stats.mirror_full_rebuilds += exec_tally.mirror_full
        stats.mirror_incremental += exec_tally.mirror_incremental
        # per-(device, kernel) split of device_seconds (PR 18): folded
        # under a flat "dev|kernel" key so the generic dataclass wire
        # codec ships it unchanged with dispatch replies
        for (dev, kern), cell in exec_tally.device_calls.items():
            key = f"{dev}|{kern}"
            mine = stats.device_calls.get(key)
            if mine is None:
                stats.device_calls[key] = [cell[0], cell[1]]
            else:
                mine[0] += cell[0]
                mine[1] += cell[1]
        rec = getattr(self.ctx, "analyze", None)
        if rec is not None:
            rec.add(self, {
                "plan": type(self).__name__,
                "self_s": self_wall,
                "device_s": exec_tally.device_s,
                "transfer_s": exec_tally.transfer_s,
                "bytes_transferred": exec_tally.transfer_bytes,
                # cumulative over this node's subtree (leaves: own scan)
                "samples_scanned": stats.samples_scanned,
                "series_scanned": stats.series_scanned,
                "shards_queried": stats.shards_queried,
            })
        # live-counter hook (query/activequeries.py): the registry entry
        # riding the context sees this node's contribution IN PLACE —
        # leaves add their scan counters, every node its exclusive
        # device work — so GET /admin/queries shows a query progressing,
        # not just existing
        ent = getattr(self.ctx, "active", None)
        if ent is not None:
            ent.tally(self, stats, exec_tally)
        exec_tally.restore(snap, total)
        return data, stats

    def execute(self, source) -> QueryResult:
        # span + error counters per plan type (ref: ExecPlan.scala:102-131
        # Kamon span around doExecute; query-error counters QueryActor:80-96)
        # bound to the query's trace id, so every span lands in ONE
        # cross-node trace (remote subtrees ship theirs back on the wire)
        from filodb_tpu.utils.metrics import registry, span, trace_context
        try:
            with trace_context(self.ctx.query_id), \
                    span("execplan", plan=type(self).__name__):
                data, stats = self.execute_internal(source)
        except QueryError as e:
            # typed taxonomy (shard_unavailable / dispatch_timeout /
            # remote_failure): str(e) already leads with the code
            registry.counter("query_errors",
                             plan=type(self).__name__,
                             code=e.code).increment()
            return QueryResult([], QueryStats(), error=str(e))
        except Exception as e:  # noqa: BLE001 — query errors surface in result
            registry.counter("query_errors",
                             plan=type(self).__name__).increment()
            return QueryResult([], QueryStats(), error=f"{type(e).__name__}: {e}")
        if isinstance(data, AggPartial):
            data = present_partial(data)
        if isinstance(data, ScalarResult):
            data = ResultBlock([RangeVectorKey(())], data.wends,
                               data.values[None, :])
        data = remove_nan_series(data)
        blocks = [data] if data is not None else []
        limit = self.ctx.planner_params.sample_limit
        result_samples = sum(int(np.asarray(b.values).size) for b in blocks)
        if limit and result_samples > limit:
            return QueryResult([], stats,
                               error=f"sample limit {limit} exceeded "
                                     f"({result_samples} samples)")
        stats.result_samples = result_samples
        stats.result_bytes = sum(int(np.asarray(b.values).nbytes)
                                 for b in blocks)
        if stats.partial:
            # root-level degradation counter (execute() runs once per
            # root; children go through execute_internal)
            registry.counter("query_partial_results").increment()
        return QueryResult(blocks, stats, partial=stats.partial)

    # -- plan printing (ref: ExecPlan.printTree, doc/query-engine.md:174-204)

    def args_str(self) -> str:
        return ""

    def print_tree(self, level: int = 0, annot=None) -> str:
        """annot: optional node -> suffix-string callable (the explain
        analyze mode passes AnalyzeRecorder.annotation)."""
        transf = [f"{'-' * (level + i + 1)}T~{type(t).__name__}({t.args_str()})"
                  for i, t in enumerate(reversed(self.transformers))]
        me = (f"{'-' * (level + len(self.transformers) + 1)}"
              f"E~{type(self).__name__}({self.args_str()})"
              + (annot(self) if annot is not None else ""))
        kids = [c.print_tree(level + len(self.transformers) + 1, annot)
                for c in self.children]
        return "\n".join(transf + [me] + kids)

    def __str__(self):
        return self.print_tree()


class LeafExecPlan(ExecPlan):
    pass


class EmptyResultExec(LeafExecPlan):
    """ref: exec/EmptyResultExec."""

    def _do_execute(self, source) -> QueryResultLike:
        return None, QueryStats()


class NonLeafExecPlan(ExecPlan):
    """Scatter-gather over children via their dispatchers
    (ref: ExecPlan.scala NonLeafExecPlan)."""

    # concat/reduce plans whose children are SAME-SELECTOR per-shard
    # leaves set this True (nonleaf.py): when two children name the same
    # shard — both owners listed during a live handoff window — only the
    # first to answer contributes, so an aggregation can never
    # double-count a shard's samples (replication/handoff.py dedup
    # contract).  Positional plans (BinaryJoin/SetOperator: lhs and rhs
    # legitimately repeat shard numbers) keep it False.
    dedup_shard_children = False

    def __init__(self, ctx: QueryContext, children: Sequence[ExecPlan]):
        super().__init__(ctx)
        self._children = list(children)

    @property
    def children(self) -> List[ExecPlan]:
        return self._children

    def _dedup_groups(self) -> Dict[int, Tuple]:
        """child index -> leaf-identity key, ONLY for children that
        appear more than once (a live-handoff window lists both owners
        of a shard).  Within a group the first child to answer is the
        shard's result; the rest are hot standbys.

        The key is the leaf's FULL identity — plan type, dataset,
        shard, args_str (filters/time range/columns), and the
        transformer chain — never just the shard number: a
        ShardKeyRegexPlanner fan-out legitimately puts two same-shard
        leaves with DIFFERENT selectors under one concat, and deduping
        those would silently drop a shard-key combo's data."""
        if not self.dedup_shard_children:
            return {}
        by_key: Dict[Tuple, List[int]] = {}
        for i, c in enumerate(self._children):
            shard = getattr(c, "shard", None)
            if shard is None:
                continue
            key = (type(c).__name__, getattr(c, "dataset", None), shard,
                   c.args_str(),
                   tuple((type(t).__name__, t.args_str())
                         for t in c.transformers))
            by_key.setdefault(key, []).append(i)
        return {i: key for key, idxs in by_key.items()
                if len(idxs) > 1 for i in idxs}

    def child_stream_fold(self, child) -> Optional[Callable]:
        """Factory for an incremental fold of a STREAMED child reply
        (parallel/streams.StreamFold): when non-None, the transport
        hands each row-slice frame to `factory().add(mini_block)` as it
        arrives and returns `.result()` — the child's full block never
        materializes on the coordinator.  Default: None (whole-block
        assembly).  ReduceAggregateExec overrides with its map+reduce
        fold."""
        return None

    def _gather(self, source) -> Tuple[List[Data], QueryStats]:
        stats = QueryStats()
        results = []
        ent = getattr(self.ctx, "active", None)
        if ent is not None:
            ent.set_phase("gathering")
        pp = self.ctx.planner_params
        allow_partial = pp.allow_partial_results
        # shard_unavailable drops only once the ENGINE has engaged
        # degradation (partial_now: re-plan retries exhausted) — so a
        # transient owner death still gets routed around before any data
        # is given up.  A peer blowing its deadline share
        # (dispatch_timeout) drops under the gate alone: retrying cannot
        # help inside the budget.  query_timeout NEVER drops — the
        # budget is global, so the root propagates the structured error.
        droppable = set()
        if allow_partial:
            droppable.add("dispatch_timeout")
            if getattr(pp, "partial_now", False):
                droppable.add("shard_unavailable")
        # handoff-window dedup: when the planner materialized BOTH
        # owners of a shard, the duplicates are hot standbys — only the
        # first to answer contributes (aggregations never double-count a
        # shard), and a standby absorbs its twin's shard_unavailable
        # BEFORE the partial machinery is consulted
        dedup_groups = self._dedup_groups()
        answered: set = set()       # keys already answered
        for i, c in enumerate(self._children):
            key = dedup_groups.get(i)
            if key is not None and key in answered:
                from filodb_tpu.utils.metrics import registry
                registry.counter("query_shard_dedup").increment()
                results.append(None)         # twin already answered
                continue
            has_later_twin = key is not None and any(
                j > i for j, k in dedup_groups.items() if k == key)
            ff = self.child_stream_fold(c)
            if ff is not None:
                # plain attribute, never serialized: the remote side
                # streams row slices and THIS side folds them in place
                c._stream_fold = ff
            try:
                data, st = c.dispatcher.dispatch(c, source)
                if key is not None:
                    answered.add(key)
            except QueryError as e:
                if e.code == "shard_unavailable" and has_later_twin:
                    # this owner is dead but its twin is still listed:
                    # the twin becomes the shard's answer — no partial,
                    # no error, exactly the handoff-window contract
                    results.append(None)
                    continue
                # a dead shard owner mid-query: fail the whole query with
                # the typed error — or, when partial results are engaged,
                # drop the child and FLAG the result (never silent
                # partials; ref: PlanDispatcher.scala:31-55,
                # PlannerParams.allowPartialResults)
                if e.code in droppable:
                    from filodb_tpu.utils.metrics import registry
                    registry.counter("query_partial_children",
                                     plan=type(self).__name__,
                                     code=e.code).increment()
                    stats.partial = True
                    stats.warnings.append(f"shard dropped ({e})")
                    # placeholder, NOT continue: BinaryJoin/SetOperator
                    # split `results` positionally at n_lhs, so a dropped
                    # child must keep its slot (every compose filters by
                    # isinstance, so None contributes nothing)
                    results.append(None)
                    continue
                raise
            stats.merge(st)
            results.append(data)
        return results, stats

    def compose(self, results: List[Data], stats: QueryStats) -> Data:
        raise NotImplementedError

    def _do_execute(self, source) -> QueryResultLike:
        results, stats = self._gather(source)
        return self.compose(results, stats), stats


