"""Whole-expression device compilation (PR 17).

The fused-leaf machinery (query/leafexec.py + query/fusedbatch.py)
compiles a single leaf's scan + range function + map phase into one
kernel dispatch.  This module lifts that one level: given a WHOLE
physical plan tree (or a dashboard batch of trees), it

  * walks the tree and runs the fused preflight on every in-process
    ``MultiSchemaPartitionsExec`` leaf (``prepare_fused``), so all the
    leaves' kernel work lands in one ``finish_fused_calls`` merged
    dispatch instead of one dispatch per leaf — a multi-shard
    ``sum(rate(...))`` or an ``a / b`` join over two selectors costs
    the same device round-trips as a single leaf;
  * resolves vector-matching binary-join label matching host-side ONCE
    into ``(mi, oi)`` index maps cached on the operand blocks'
    ``cache_token`` (``keys_serial``/``keys_epoch``-keyed, like the
    PR 6 pack memo) so a dashboard re-poll skips the per-series dict
    matching entirely — the join itself runs as one jitted
    gather+binop program (ops/select.py);
  * filters killed queries out of the merged dispatch (the PR 13
    kill-token contract: a cancelled query must be checked BEFORE
    fused kernel dispatch — its leaf keeps the parked FusedCall and
    ``_finish_or_degrade`` surfaces ``query_canceled``).

Any leaf whose shape the fused path can't take degrades node-by-node
to the general engine with bit-identical results — counted under
``query_exprfuse{verdict="degraded"}`` and surfaced per query in
``?stats=true`` (``stats.exprfuse``) — never an error.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

__all__ = ["compile_tree", "finish_prepared", "join_index_maps",
           "TreeCompilation"]


@dataclass
class TreeCompilation:
    """One tree's prepared-leaf bookkeeping (engine-side handle)."""
    calls: List[Tuple[object, object]] = field(default_factory=list)
    fused: int = 0          # leaves whose preflight produced fused work
    degraded: int = 0       # eligible leaves that fell to the general path


def _eligible_leaves(ep):
    from filodb_tpu.query.engine import _walk_plan
    from filodb_tpu.query.execbase import InProcessPlanDispatcher
    from filodb_tpu.query.leafexec import MultiSchemaPartitionsExec
    return [leaf for leaf in _walk_plan(ep)
            if isinstance(leaf, MultiSchemaPartitionsExec)
            and isinstance(leaf.dispatcher, InProcessPlanDispatcher)]


def compile_tree(ep, source, *, min_leaves: int = 1
                 ) -> Optional[TreeCompilation]:
    """Run the fused preflight over a tree's in-process leaves.

    Returns the prepared calls + per-tree verdict counts, or ``None``
    when the tree holds fewer than ``min_leaves`` eligible leaves (the
    single-query path passes ``min_leaves=2`` — one leaf gains nothing
    from cross-leaf merging and keeps its exact standalone behavior).
    Leaves whose preflight raises are reset to re-execute standalone;
    preparation failures never surface as query errors.
    """
    from filodb_tpu.utils.metrics import registry
    leaves = _eligible_leaves(ep)
    if len(leaves) < min_leaves:
        return None
    comp = TreeCompilation()
    for leaf in leaves:
        try:
            fc = leaf.prepare_fused(source)
        except Exception:  # noqa: BLE001 — leaf will re-execute
            leaf._prefused = None
            fc = None
        if fc is not None:
            comp.calls.append((leaf, fc))
        parked = getattr(leaf, "_prefused", None)
        if parked is not None and parked[2] is not None:
            comp.fused += 1
            registry.counter("query_exprfuse",
                             verdict="fused").increment()
        else:
            comp.degraded += 1
            registry.counter("query_exprfuse",
                             verdict="degraded").increment()
    return comp


def finish_prepared(calls) -> None:
    """Phase-2: merge the prepared FusedCalls into batched dispatches.

    Killed queries are filtered out BEFORE any device dispatch (PR 13
    contract) — their leaves keep the parked FusedCall, and phase-3's
    ``_finish_or_degrade`` cancel check surfaces ``query_canceled``
    without the kernel ever running.  A batch-level dispatch failure
    likewise leaves every FusedCall parked for standalone finishing.
    """
    from filodb_tpu.query.fusedbatch import finish_fused_calls
    if not calls:
        return
    live = []
    for leaf, fc in calls:
        tok = getattr(leaf.ctx, "cancel", None)
        if tok is not None and tok.cancelled:
            continue
        live.append((leaf, fc))
    if not live:
        return
    try:
        partials = finish_fused_calls([fc for _, fc in live])
    except Exception:  # noqa: BLE001 — leaves finish standalone
        return
    for (leaf, fc), partial in zip(live, partials):
        if partial is not None:
            leaf.inject_fused(partial)


# --------------------------------------------------- join index-map cache
#
# BinaryJoinExec resolves PromQL vector matching by building per-series
# match keys and pairing the sides through a dict — pure host work that
# is identical on every dashboard re-poll as long as neither side's
# series set changed.  Both operand blocks carry a ``cache_token``
# derived from (keys_serial, keys_epoch, row ids); the resolved
# (mi, oi, result keys) triple is memoized on those tokens.  An
# ingest-side epoch bump changes the token, so stale entries simply
# never match again and age out of the LRU.  Error shapes (many-to-many
# duplicates, one-to-one violations) are never cached.

_JOIN_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_JOIN_LOCK = threading.Lock()


def _join_cache_cap() -> int:
    from filodb_tpu.config import settings
    return settings().query.exprfuse_join_cache_entries


def join_index_maps(join, many_side, one_side):
    """Resolved match maps for ``BinaryJoinExec.compose``.

    Returns ``(mi, oi, keys)``: many-side / one-side row indices (numpy
    int arrays, one entry per output pair) and the per-pair result
    label keys.  Raises the exact errors the uncached path raises
    (many-to-many duplicate, one-to-one violation, join cardinality
    limit).  Caching engages only when both blocks carry a non-None
    ``cache_token``.
    """
    import numpy as np

    from filodb_tpu.utils.metrics import registry
    card_limit = join.ctx.planner_params.join_cardinality_limit
    key = None
    if many_side.cache_token is not None \
            and one_side.cache_token is not None:
        key = (many_side.cache_token, one_side.cache_token,
               join.cardinality, join.on, join.ignoring, join.include)
        with _JOIN_LOCK:
            hit = _JOIN_CACHE.get(key)
            if hit is not None:
                _JOIN_CACHE.move_to_end(key)
        if hit is not None:
            registry.counter("exprfuse_join_cache",
                             verdict="hit").increment()
            mi, oi, keys = hit
            if len(mi) > card_limit:
                raise ValueError(
                    f"join cardinality limit {card_limit} exceeded")
            return mi, oi, keys
        registry.counter("exprfuse_join_cache",
                         verdict="miss").increment()
    one_index = {}
    for i, k in enumerate(one_side.keys):
        mk = join._match_key(k)
        if mk in one_index:
            raise ValueError(
                "many-to-many matching not allowed: duplicate series on "
                f"'one' side for key {mk}")
        one_index[mk] = i
    pairs: List[Tuple[int, int]] = []
    for i, k in enumerate(many_side.keys):
        j = one_index.get(join._match_key(k))
        if j is not None:
            pairs.append((i, j))
            if len(pairs) > card_limit:
                raise ValueError(
                    f"join cardinality limit {card_limit} exceeded")
    if join.cardinality == "OneToOne":
        seen = {}
        for i, j in pairs:
            if j in seen:
                raise ValueError(
                    "one-to-one join has many-to-one matches; "
                    "use group_left/group_right")
            seen[j] = i
    mi = np.asarray([p[0] for p in pairs], dtype=np.int64)
    oi = np.asarray([p[1] for p in pairs], dtype=np.int64)
    keys = [join._result_labels(many_side.keys[i], one_side.keys[j])
            for i, j in pairs]
    if key is not None:
        with _JOIN_LOCK:
            _JOIN_CACHE[key] = (mi, oi, keys)
            _JOIN_CACHE.move_to_end(key)
            cap = max(_join_cache_cap(), 1)
            while len(_JOIN_CACHE) > cap:
                _JOIN_CACHE.popitem(last=False)
    return mi, oi, keys
