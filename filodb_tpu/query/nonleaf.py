"""Non-leaf exec plans: concat/stitch, tree-reduce aggregation, binary
joins and set operators, subqueries.

Split from query/exec.py (round 4, no behavior change).
ref: query/.../exec/DistConcatExec.scala, BinaryJoinExec.scala,
StitchRvsExec.scala, AggrOverRangeVectors.scala:51.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax.numpy as jnp

from filodb_tpu.core.index import ColumnFilter, Equals
from filodb_tpu.ops import agg as agg_ops
from filodb_tpu.ops import hist as hist_ops
from filodb_tpu.ops.instant import (INSTANT_FUNCTIONS, ARITH_OPERATORS,
                                    COMPARISON_OPERATORS, apply_binary_op)
from filodb_tpu.ops import counter as counter_ops
from filodb_tpu.ops.rangefns import RANGE_FUNCTIONS, evaluate_range_function
from filodb_tpu.ops.timewindow import PAD_TS, to_offsets, make_window_ends
from filodb_tpu.query.rangevector import (QueryContext, QueryResult, QueryStats,
                                          RangeVectorKey, ResultBlock,
                                          concat_blocks, remove_nan_series)

from filodb_tpu.query.execbase import (
    AggPartial, ExecPlan, NonLeafExecPlan, RawBlock, ScalarResult,
    _block_empty, _union_scheme, reduce_partials)
from filodb_tpu.query.transformers import _group_ids


class DistConcatExec(NonLeafExecPlan):
    """Concatenate child results (ref: exec/DistConcatExec.scala)."""

    # children are same-selector per-shard leaves: a shard listed twice
    # (both owners during a live handoff) must contribute exactly once
    dedup_shard_children = True

    def compose(self, results, stats):
        blocks = [r for r in results if isinstance(r, ResultBlock)]
        raws = [r for r in results if isinstance(r, RawBlock)]
        if raws:
            # raw blocks concat only if same grid/base — planner guarantees.
            # Cross-shard bucket-scheme drift is resolved by rebucketing
            # every block onto the union scheme (HistogramBuckets.scala:340)
            les0 = raws[0].bucket_les
            if any((r.bucket_les is None) != (les0 is None) or (
                    les0 is not None and r.bucket_les is not None
                    and not np.array_equal(les0, r.bucket_les))
                   for r in raws[1:]):
                union = _union_scheme([r.bucket_les for r in raws])
                if union is None:
                    raise ValueError(
                        "cannot concat histogram blocks: some shards carry "
                        "no bucket boundaries")
                # scheme drift across shards is a data-model event worth
                # seeing at /metrics: rebucketing is correct but costs an
                # O(S*T*B) remap per query until retention ages it out
                from filodb_tpu.utils.metrics import registry
                registry.counter("hist_concat_rebuckets").increment()
                from filodb_tpu.memory.histogram import rebucket
                raws = [dataclasses.replace(
                            r,
                            values=rebucket(np.asarray(r.values),
                                            r.bucket_les, union),
                            vbase=(rebucket(np.asarray(r.vbase),
                                            r.bucket_les, union)
                                   if r.vbase is not None
                                   and np.asarray(r.vbase).ndim == 2
                                   else r.vbase),
                            bucket_les=union)
                        if not np.array_equal(r.bucket_les, union) else r
                        for r in raws]
                les0 = union
            keys = []
            for r in raws:
                keys.extend(r.keys)
            T = max(r.ts_off.shape[1] for r in raws)
            def pad(a, fill):
                out = np.full((a.shape[0], T) + a.shape[2:], fill, a.dtype)
                out[:, :a.shape[1]] = a
                return out
            from filodb_tpu.ops.timewindow import PAD_TS
            ts = np.concatenate([pad(r.ts_off, PAD_TS) for r in raws])
            vals = np.concatenate([pad(np.asarray(r.values), np.nan)
                                   for r in raws])
            vbase = None
            if any(r.vbase is not None for r in raws):
                vbase = np.concatenate([
                    np.asarray(r.vbase) if r.vbase is not None
                    else np.zeros(np.asarray(r.values).shape[:1]
                                  + np.asarray(r.values).shape[2:])
                    for r in raws])
            return RawBlock(keys, ts, vals, raws[0].base_ms,
                            raws[0].bucket_les,
                            samples=sum(r.samples for r in raws),
                            vbase=vbase,
                            precorrected=all(r.precorrected for r in raws),
                            # pad NaNs live at PAD_TS slots (excluded via
                            # ts), so raggedness merges as AND over blocks
                            dense=all(r.dense for r in raws))
        return concat_blocks(blocks)


class LocalPartitionDistConcatExec(DistConcatExec):
    """ref: exec/DistConcatExec.scala LocalPartitionDistConcatExec."""


class _AggStreamFold:
    """Incremental fold for a STREAMED ship-everything child: each
    arriving row-slice mini block runs the map phase immediately and
    merges into ONE running AggPartial — the coordinator holds a frame
    plus a [G, W] partial, never the child's full [S, W] block.  The
    candidate ops stay correct piecewise for the same reason they are
    correct per shard: per-piece top-k is a superset of each group's
    true top-k, and the present phase applies the final mask."""

    def __init__(self, op, params, by, without, ctx):
        from filodb_tpu.query.transformers import AggregateMapReduce
        self._mapper = AggregateMapReduce(op, params, by, without)
        self._ctx = ctx
        self._stats = QueryStats()
        self._partial = None

    def add(self, block) -> None:
        p = self._mapper.apply(block, self._ctx, self._stats)
        if p is None:
            return
        self._partial = p if self._partial is None else \
            reduce_partials([self._partial, p])
        # the per-slice map only sees one slice's worth of groups, so
        # the limit must also be enforced on the MERGED partial — the
        # streamed fold raises exactly where non-streamed compose would
        limit = self._ctx.planner_params.group_by_cardinality_limit
        if limit and len(self._partial.group_keys) > limit:
            from filodb_tpu.query.execbase import GroupCardinalityError
            raise GroupCardinalityError(
                f"group-by cardinality limit {limit} exceeded "
                f"({len(self._partial.group_keys)} groups in the "
                f"streamed fold)")

    def result(self):
        return self._partial


# ops whose map phase may run per row slice and reduce across slices
# without changing the presented result (quantile's sketch
# re-compression is merge-tree-dependent — it assembles whole)
_FOLDABLE_OPS = frozenset({"sum", "count", "avg", "min", "max", "stddev",
                           "stdvar", "group", "topk", "bottomk",
                           "count_values"})


class ReduceAggregateExec(NonLeafExecPlan):
    """Reduce phase across shards (ref: AggrOverRangeVectors.scala:51).

    Children normally reply with AggPartial (the map phase rides the
    leaves).  With aggregation pushdown DISABLED (the ship-everything
    A/B baseline, query/pushdown.py), remote children ship their full
    per-series ResultBlocks instead and the map phase runs HERE — by/
    without are carried so the coordinator-side map is possible."""

    # a duplicate shard here would double-count its samples into the
    # aggregate — the dedup contract matters most on this plan
    dedup_shard_children = True

    # node-level reduce (RemoteAggregateExec): the composed partial is an
    # INTERMEDIATE that another reduce will merge — quantile sketches must
    # not re-compress here (reduce_partials compress=False) and candidate
    # partials may prune to the node-local top-k before crossing the wire
    node_level = False

    def __init__(self, ctx, children, op: str, params: Tuple = (),
                 by: Tuple[str, ...] = (), without: Tuple[str, ...] = ()):
        super().__init__(ctx, children)
        self.op = op
        self.params = params
        self.by = tuple(by)
        self.without = tuple(without)

    def args_str(self):
        return f"aggrOp={self.op}, aggrParams={list(self.params)}"

    def compose(self, results, stats):
        from filodb_tpu.query.transformers import AggregateMapReduce
        mapper = None
        parts = []
        for r in results:
            if isinstance(r, ResultBlock) and r.num_series:
                # ship-everything child (pushdown off): map phase runs
                # coordinator-side over the full shipped series block
                if mapper is None:
                    mapper = AggregateMapReduce(self.op, self.params,
                                                self.by, self.without)
                r = mapper.apply(r, self.ctx, stats)
            if isinstance(r, AggPartial):
                parts.append(r)
        return reduce_partials(parts, compress=not self.node_level)

    def child_stream_fold(self, child):
        if self.op not in _FOLDABLE_OPS:
            return None
        return lambda: _AggStreamFold(self.op, self.params, self.by,
                                      self.without, self.ctx)

    def _do_execute(self, source):
        results, stats = self._gather(source)
        # plan-time pushdown verdict (query/pushdown.py): remote children
        # this aggregation could NOT push surface in ?stats=true /
        # explain analyze / the slowlog next to the pushed counts the
        # dispatchers booked
        npn = getattr(self, "pushdown_not_pushable", 0)
        if npn:
            stats.pushdown_not_pushable += npn
        return self.compose(results, stats), stats


class RemoteAggregateExec(ReduceAggregateExec):
    """Node-level reduce pushdown (query/pushdown.py): children are the
    per-shard map subtrees owned by ONE data node, and the whole plan
    serializes to that node via its PushdownDispatcher — the node runs
    leaf scan + range function + map phase per shard, reduces locally
    (inherited compose = reduce_partials), and replies with a single
    [G, W] AggPartial.  Decoded on the data node the children fall back
    to InProcessPlanDispatcher, so execution there is the ordinary
    scatter-gather one level down (the PR-6 chip-level partial merge,
    promoted to nodes).

    Rank/sketch aggregations push exactly (PR 17): quantile node
    partials concatenate their shards' centroids WITHOUT re-compressing
    (node_level -> reduce_partials compress=False), so the
    coordinator's single merge sees the flat per-shard centroid layout;
    topk/bottomk node partials prune to the per-window node-local
    top-k before replying (ops/select.topk_keep_rows) — rows outside
    every window's local top-k cannot reach any global top-k, the same
    containment the streaming fold relies on."""

    node_level = True

    def compose(self, results, stats):
        part = super().compose(results, stats)
        if part is not None and part.cand_vals is not None \
                and self.op in ("topk", "bottomk") and len(part.cand_vals):
            from filodb_tpu.ops import select as select_ops
            keep = np.asarray(select_ops.topk_keep_rows(
                jnp.asarray(part.cand_vals), jnp.asarray(part.cand_groups),
                len(part.group_keys), int(self.params[0]),
                largest=(self.op == "topk")))
            if not keep.all():
                part = dataclasses.replace(
                    part,
                    cand_keys=[k for k, m in zip(part.cand_keys, keep) if m],
                    cand_vals=part.cand_vals[keep],
                    cand_groups=part.cand_groups[keep])
        return part

    def args_str(self):
        shards = sorted(getattr(c, "shard", -1) for c in self._children)
        return (f"aggrOp={self.op}, aggrParams={list(self.params)}, "
                f"shards={shards}")


class BinaryJoinExec(NonLeafExecPlan):
    """Vector-vector join (ref: exec/BinaryJoinExec.scala:210).

    lhs children come first, then rhs children; the split index separates
    them (mirrors the reference's lhs/rhs Seq[ExecPlan]).
    """

    def __init__(self, ctx, lhs: Sequence[ExecPlan], rhs: Sequence[ExecPlan],
                 operator: str, cardinality: str = "OneToOne",
                 on: Optional[Tuple[str, ...]] = None,
                 ignoring: Tuple[str, ...] = (),
                 include: Tuple[str, ...] = (),
                 bool_modifier: bool = False):
        super().__init__(ctx, list(lhs) + list(rhs))
        self.n_lhs = len(lhs)
        self.operator = operator
        self.cardinality = cardinality
        self.on = tuple(on) if on is not None else None
        self.ignoring = tuple(ignoring)
        self.include = tuple(include)
        self.bool_modifier = bool_modifier

    def args_str(self):
        return (f"binaryOp={self.operator}, on={self.on}, "
                f"ignoring={list(self.ignoring)}")

    def _match_key(self, k: RangeVectorKey) -> RangeVectorKey:
        if self.on is not None:
            return k.only(self.on)
        drop = self.ignoring + ("_metric_", "__name__")
        return k.without(drop)

    def compose(self, results, stats):
        lhs_blocks = [r for r in results[:self.n_lhs] if isinstance(r, ResultBlock)]
        rhs_blocks = [r for r in results[self.n_lhs:] if isinstance(r, ResultBlock)]
        lhs = concat_blocks(lhs_blocks)
        rhs = concat_blocks(rhs_blocks)
        if lhs is None or rhs is None:
            return None
        many_side, one_side = lhs, rhs
        flip = False
        if self.cardinality == "OneToMany":
            many_side, one_side = rhs, lhs
            flip = True
        # label matching resolves host-side ONCE into (mi, oi) index
        # maps, memoized on the operand blocks' cache_token (PR 17 —
        # query/exprfuse.py); the join itself is one jitted
        # gather+binop program over the full value blocks
        from filodb_tpu.query.exprfuse import join_index_maps
        from filodb_tpu.ops.select import gather_binop
        mi, oi, keys = join_index_maps(self, many_side, one_side)
        if not len(mi):
            return None
        mv = jnp.asarray(np.asarray(many_side.values))
        ov = jnp.asarray(np.asarray(one_side.values))
        # a = query LHS values
        a, b, ai, bi = (ov, mv, oi, mi) if flip else (mv, ov, mi, oi)
        out = np.asarray(gather_binop(
            a, b, jnp.asarray(ai), jnp.asarray(bi), op=self.operator,
            bool_modifier=self.bool_modifier, keep_side="lhs"))
        return ResultBlock(keys, many_side.wends, out)

    def _result_labels(self, many_key: RangeVectorKey,
                       one_key: RangeVectorKey) -> RangeVectorKey:
        if self.cardinality in ("ManyToOne", "OneToMany"):
            lbls = many_key.without(("_metric_", "__name__")).labels_dict
            if self.include:
                od = one_key.labels_dict
                for lbl in self.include:
                    if lbl in od:
                        lbls[lbl] = od[lbl]
                    else:
                        lbls.pop(lbl, None)
            return RangeVectorKey.make(lbls)
        if self.on is not None:
            return many_key.only(self.on)
        return many_key.without(self.ignoring + ("_metric_", "__name__"))


class SetOperatorExec(NonLeafExecPlan):
    """and/or/unless (ref: exec/SetOperatorExec.scala)."""

    def __init__(self, ctx, lhs: Sequence[ExecPlan], rhs: Sequence[ExecPlan],
                 operator: str, on: Optional[Tuple[str, ...]] = None,
                 ignoring: Tuple[str, ...] = ()):
        super().__init__(ctx, list(lhs) + list(rhs))
        self.n_lhs = len(lhs)
        self.operator = operator.lower()
        self.on = tuple(on) if on is not None else None
        self.ignoring = tuple(ignoring)

    def args_str(self):
        return f"binaryOp={self.operator}, on={self.on}, ignoring={list(self.ignoring)}"

    def _match_key(self, k: RangeVectorKey) -> RangeVectorKey:
        if self.on is not None:
            return k.only(self.on)
        return k.without(self.ignoring + ("_metric_", "__name__"))

    def _presence_by_key(self, block: ResultBlock) -> Dict[RangeVectorKey, np.ndarray]:
        """match-key -> [W] bool, True where any series with that key has a
        sample at the step.  Vectorized: one `_group_ids` pass maps each
        series to its match-key group, then a single grouped OR
        (`np.logical_or.reduceat` over gid-sorted rows) replaces the old
        per-series Python loop — this sits on every and/or/unless path."""
        vals = np.asarray(block.values)
        if vals.ndim == 3:                       # histogram block
            vals = vals[..., 0]
        S = len(block.keys)
        if S == 0:
            return {}
        if self.on is not None and not self.on:
            # on() with an empty label list: everything shares the empty
            # match key (k.only(()) — _group_ids' falsy-by branch would
            # wrongly take `without` semantics here)
            gids = np.zeros(S, dtype=np.int32)
            gkeys = [RangeVectorKey(())]
        elif self.on is not None:
            gids, gkeys = _group_ids(block.keys, tuple(self.on), ())
        else:
            # ignoring=() must still strip only _metric_/__name__ (the
            # _match_key rule); _group_ids' empty-without branch would
            # collapse everything onto the empty key, so pad with a
            # name no real label can carry
            gids, gkeys = _group_ids(block.keys, (),
                                     tuple(self.ignoring) or ("\x00",))
        present = ~np.isnan(vals)
        order = np.argsort(gids, kind="stable")
        starts = np.searchsorted(gids[order], np.arange(len(gkeys)))
        grouped = np.logical_or.reduceat(present[order], starts, axis=0)
        return {gk: grouped[g] for g, gk in enumerate(gkeys)}

    def compose(self, results, stats):
        lhs = concat_blocks([r for r in results[:self.n_lhs]
                             if isinstance(r, ResultBlock)])
        rhs = concat_blocks([r for r in results[self.n_lhs:]
                             if isinstance(r, ResultBlock)])
        op = self.operator
        if op == "and":
            if lhs is None or rhs is None:
                return None
            rhs_keys = {self._match_key(k) for k in rhs.keys}
            # per-step AND: lhs kept where rhs series present at that step
            rk_rows = self._presence_by_key(rhs)
            rows, outs = [], []
            lvals = np.asarray(lhs.values)
            for i, k in enumerate(lhs.keys):
                mk = self._match_key(k)
                if mk in rhs_keys:
                    rows.append(i)
                    outs.append(np.where(rk_rows[mk], lvals[i], np.nan))
            if not rows:
                return None
            return ResultBlock([lhs.keys[i] for i in rows], lhs.wends,
                               np.stack(outs))
        if op == "or":
            if lhs is None:
                return rhs
            if rhs is None:
                return lhs
            lvals = np.asarray(lhs.values)
            lhs_present = self._presence_by_key(lhs)
            keys = list(lhs.keys)
            vals = [lvals]
            rvals = np.asarray(rhs.values)
            extra_rows, extra_keys = [], []
            for i, k in enumerate(rhs.keys):
                mk = self._match_key(k)
                mask = lhs_present.get(mk)
                row = rvals[i]
                if mask is not None:
                    row = np.where(mask, np.nan, row)
                extra_rows.append(row)
                extra_keys.append(k)
            if extra_rows:
                keys = keys + extra_keys
                vals.append(np.stack(extra_rows))
            return ResultBlock(keys, lhs.wends, np.concatenate(vals))
        if op == "unless":
            if lhs is None:
                return None
            if rhs is None:
                return lhs
            rk_rows = self._presence_by_key(rhs)
            lvals = np.asarray(lhs.values)
            outs = []
            for i, k in enumerate(lhs.keys):
                mk = self._match_key(k)
                mask = rk_rows.get(mk)
                outs.append(np.where(mask, np.nan, lvals[i])
                            if mask is not None else lvals[i])
            return remove_nan_series(
                ResultBlock(list(lhs.keys), lhs.wends, np.stack(outs)))
        raise ValueError(op)


class SubqueryExec(NonLeafExecPlan):
    """Evaluate an outer range function over an inner periodic series
    (foo[5m:1m] with rate/max_over_time/... outside).  The inner child's
    step-grid samples are treated as raw samples for the outer window kernel
    (ref: exec/... subquery handling via PeriodicSamplesMapper on inner)."""

    def __init__(self, ctx, children, start_ms, step_ms, end_ms, function,
                 function_args, subquery_window_ms, subquery_step_ms,
                 offset_ms=0):
        super().__init__(ctx, children)
        self.start_ms, self.step_ms, self.end_ms = start_ms, step_ms, end_ms
        self.function = function
        self.function_args = tuple(function_args)
        self.subquery_window_ms = subquery_window_ms
        self.subquery_step_ms = subquery_step_ms
        self.offset_ms = offset_ms

    def args_str(self):
        return (f"function={self.function}, window={self.subquery_window_ms}, "
                f"step={self.subquery_step_ms}")

    def compose(self, results, stats):
        block = concat_blocks([r for r in results if isinstance(r, ResultBlock)])
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        if block is None:
            return _block_empty(wends)
        inner_ts = np.asarray(block.wends)
        base = int(inner_ts[0]) if len(inner_ts) else 0
        vals = np.asarray(block.values)
        S = vals.shape[0]
        ts_off = np.broadcast_to((inner_ts - base).astype(np.int32),
                                 (S, len(inner_ts))).copy()
        # NaN steps are absent samples; offsets stay valid (kernel masks NaN)
        eval_wends = (wends - self.offset_ms - base).astype(np.int32)
        out = np.asarray(evaluate_range_function(
            jnp.asarray(ts_off), jnp.asarray(vals), jnp.asarray(eval_wends),
            self.subquery_window_ms, self.function, self.function_args,
            base_ms=base, dense=not bool(np.isnan(vals).any())))
        return ResultBlock(block.keys, wends, out)


class StitchRvsExec(NonLeafExecPlan):
    """Merge same-key series evaluated over adjacent time ranges
    (ref: exec/StitchRvsExec.scala).

    Vectorized (PR 15): the old per-series dict-of-rows Python loop ran
    once per series per tier on EVERY long-range query's stitch path; it
    is now one searchsorted + one fancy-indexed scatter per block into a
    preallocated [S, W_union] output (histogram [S, W, B] blocks stitch
    bucketwise the same way — the old loop could not)."""

    def compose(self, results, stats):
        blocks = [r for r in results if isinstance(r, ResultBlock)]
        if not blocks:
            return None
        if len(blocks) == 1:
            return blocks[0]
        wends = np.unique(np.concatenate([np.asarray(b.wends)
                                          for b in blocks]))
        row_of: Dict[RangeVectorKey, int] = {}
        keys: List[RangeVectorKey] = []
        for b in blocks:
            for k in b.keys:
                if k not in row_of:
                    row_of[k] = len(keys)
                    keys.append(k)
        # shape + bucket scheme come from the widest block, not
        # blocks[0]: an EMPTY tier (0 series, 2-D values) may arrive
        # first while a later tier carries [S, W, B] histogram data
        ref = max(blocks, key=lambda b: np.asarray(b.values).ndim)
        extra = np.asarray(ref.values).shape[2:]
        out = np.full((len(keys), len(wends)) + extra, np.nan)
        for b in blocks:
            if b.num_series == 0:
                continue
            vals = np.asarray(b.values)
            pos = np.searchsorted(wends, np.asarray(b.wends))
            rows = np.fromiter((row_of[k] for k in b.keys),
                               dtype=np.int64, count=len(b.keys))
            # scatter present samples; absent (NaN) steps keep whatever
            # an earlier tier put there (later blocks win on overlap,
            # exactly the old loop's fill rule)
            idx = np.ix_(rows, pos)
            take = ~np.isnan(vals)
            out[idx] = np.where(take, vals, out[idx])
        les = next((b.bucket_les for b in blocks
                    if b.bucket_les is not None), None)
        return ResultBlock(keys, wends, out, les)

