"""Non-leaf exec plans: concat/stitch, tree-reduce aggregation, binary
joins and set operators, subqueries.

Split from query/exec.py (round 4, no behavior change).
ref: query/.../exec/DistConcatExec.scala, BinaryJoinExec.scala,
StitchRvsExec.scala, AggrOverRangeVectors.scala:51.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax.numpy as jnp

from filodb_tpu.core.index import ColumnFilter, Equals
from filodb_tpu.ops import agg as agg_ops
from filodb_tpu.ops import hist as hist_ops
from filodb_tpu.ops.instant import (INSTANT_FUNCTIONS, ARITH_OPERATORS,
                                    COMPARISON_OPERATORS, apply_binary_op)
from filodb_tpu.ops import counter as counter_ops
from filodb_tpu.ops.rangefns import RANGE_FUNCTIONS, evaluate_range_function
from filodb_tpu.ops.timewindow import PAD_TS, to_offsets, make_window_ends
from filodb_tpu.query.rangevector import (QueryContext, QueryResult, QueryStats,
                                          RangeVectorKey, ResultBlock,
                                          concat_blocks, remove_nan_series)

from filodb_tpu.query.execbase import (
    AggPartial, ExecPlan, NonLeafExecPlan, RawBlock, ScalarResult,
    _block_empty, _union_scheme, reduce_partials)
from filodb_tpu.query.transformers import _group_ids


class DistConcatExec(NonLeafExecPlan):
    """Concatenate child results (ref: exec/DistConcatExec.scala)."""

    # children are same-selector per-shard leaves: a shard listed twice
    # (both owners during a live handoff) must contribute exactly once
    dedup_shard_children = True

    def compose(self, results, stats):
        blocks = [r for r in results if isinstance(r, ResultBlock)]
        raws = [r for r in results if isinstance(r, RawBlock)]
        if raws:
            # raw blocks concat only if same grid/base — planner guarantees.
            # Cross-shard bucket-scheme drift is resolved by rebucketing
            # every block onto the union scheme (HistogramBuckets.scala:340)
            les0 = raws[0].bucket_les
            if any((r.bucket_les is None) != (les0 is None) or (
                    les0 is not None and r.bucket_les is not None
                    and not np.array_equal(les0, r.bucket_les))
                   for r in raws[1:]):
                union = _union_scheme([r.bucket_les for r in raws])
                if union is None:
                    raise ValueError(
                        "cannot concat histogram blocks: some shards carry "
                        "no bucket boundaries")
                # scheme drift across shards is a data-model event worth
                # seeing at /metrics: rebucketing is correct but costs an
                # O(S*T*B) remap per query until retention ages it out
                from filodb_tpu.utils.metrics import registry
                registry.counter("hist_concat_rebuckets").increment()
                from filodb_tpu.memory.histogram import rebucket
                raws = [dataclasses.replace(
                            r,
                            values=rebucket(np.asarray(r.values),
                                            r.bucket_les, union),
                            vbase=(rebucket(np.asarray(r.vbase),
                                            r.bucket_les, union)
                                   if r.vbase is not None
                                   and np.asarray(r.vbase).ndim == 2
                                   else r.vbase),
                            bucket_les=union)
                        if not np.array_equal(r.bucket_les, union) else r
                        for r in raws]
                les0 = union
            keys = []
            for r in raws:
                keys.extend(r.keys)
            T = max(r.ts_off.shape[1] for r in raws)
            def pad(a, fill):
                out = np.full((a.shape[0], T) + a.shape[2:], fill, a.dtype)
                out[:, :a.shape[1]] = a
                return out
            from filodb_tpu.ops.timewindow import PAD_TS
            ts = np.concatenate([pad(r.ts_off, PAD_TS) for r in raws])
            vals = np.concatenate([pad(np.asarray(r.values), np.nan)
                                   for r in raws])
            vbase = None
            if any(r.vbase is not None for r in raws):
                vbase = np.concatenate([
                    np.asarray(r.vbase) if r.vbase is not None
                    else np.zeros(np.asarray(r.values).shape[:1]
                                  + np.asarray(r.values).shape[2:])
                    for r in raws])
            return RawBlock(keys, ts, vals, raws[0].base_ms,
                            raws[0].bucket_les,
                            samples=sum(r.samples for r in raws),
                            vbase=vbase,
                            precorrected=all(r.precorrected for r in raws),
                            # pad NaNs live at PAD_TS slots (excluded via
                            # ts), so raggedness merges as AND over blocks
                            dense=all(r.dense for r in raws))
        return concat_blocks(blocks)


class LocalPartitionDistConcatExec(DistConcatExec):
    """ref: exec/DistConcatExec.scala LocalPartitionDistConcatExec."""


class ReduceAggregateExec(NonLeafExecPlan):
    """Reduce phase across shards (ref: AggrOverRangeVectors.scala:51)."""

    # a duplicate shard here would double-count its samples into the
    # aggregate — the dedup contract matters most on this plan
    dedup_shard_children = True

    def __init__(self, ctx, children, op: str, params: Tuple = ()):
        super().__init__(ctx, children)
        self.op = op
        self.params = params

    def args_str(self):
        return f"aggrOp={self.op}, aggrParams={list(self.params)}"

    def compose(self, results, stats):
        parts = [r for r in results if isinstance(r, AggPartial)]
        return reduce_partials(parts)


class BinaryJoinExec(NonLeafExecPlan):
    """Vector-vector join (ref: exec/BinaryJoinExec.scala:210).

    lhs children come first, then rhs children; the split index separates
    them (mirrors the reference's lhs/rhs Seq[ExecPlan]).
    """

    def __init__(self, ctx, lhs: Sequence[ExecPlan], rhs: Sequence[ExecPlan],
                 operator: str, cardinality: str = "OneToOne",
                 on: Optional[Tuple[str, ...]] = None,
                 ignoring: Tuple[str, ...] = (),
                 include: Tuple[str, ...] = (),
                 bool_modifier: bool = False):
        super().__init__(ctx, list(lhs) + list(rhs))
        self.n_lhs = len(lhs)
        self.operator = operator
        self.cardinality = cardinality
        self.on = tuple(on) if on is not None else None
        self.ignoring = tuple(ignoring)
        self.include = tuple(include)
        self.bool_modifier = bool_modifier

    def args_str(self):
        return (f"binaryOp={self.operator}, on={self.on}, "
                f"ignoring={list(self.ignoring)}")

    def _match_key(self, k: RangeVectorKey) -> RangeVectorKey:
        if self.on is not None:
            return k.only(self.on)
        drop = self.ignoring + ("_metric_", "__name__")
        return k.without(drop)

    def compose(self, results, stats):
        lhs_blocks = [r for r in results[:self.n_lhs] if isinstance(r, ResultBlock)]
        rhs_blocks = [r for r in results[self.n_lhs:] if isinstance(r, ResultBlock)]
        lhs = concat_blocks(lhs_blocks)
        rhs = concat_blocks(rhs_blocks)
        if lhs is None or rhs is None:
            return None
        many_side, one_side = lhs, rhs
        flip = False
        if self.cardinality == "OneToMany":
            many_side, one_side = rhs, lhs
            flip = True
        # index the "one" side by match key; duplicates are an error
        one_index: Dict[RangeVectorKey, int] = {}
        for i, k in enumerate(one_side.keys):
            mk = self._match_key(k)
            if mk in one_index:
                raise ValueError(
                    "many-to-many matching not allowed: duplicate series on "
                    f"'one' side for key {mk}")
            one_index[mk] = i
        card_limit = self.ctx.planner_params.join_cardinality_limit
        pairs: List[Tuple[int, int]] = []
        for i, k in enumerate(many_side.keys):
            j = one_index.get(self._match_key(k))
            if j is not None:
                pairs.append((i, j))
                if len(pairs) > card_limit:
                    raise ValueError(f"join cardinality limit {card_limit} exceeded")
        if self.cardinality == "OneToOne":
            seen: Dict[int, int] = {}
            for i, j in pairs:
                if j in seen:
                    raise ValueError("one-to-one join has many-to-one matches; "
                                     "use group_left/group_right")
                seen[j] = i
        if not pairs:
            return None
        mi = np.asarray([p[0] for p in pairs])
        oi = np.asarray([p[1] for p in pairs])
        mv = np.asarray(many_side.values)[mi]
        ov = np.asarray(one_side.values)[oi]
        a, b = (ov, mv) if flip else (mv, ov)   # a = query LHS values
        out = np.asarray(apply_binary_op(
            jnp.asarray(a), jnp.asarray(b), op=self.operator,
            bool_modifier=self.bool_modifier, keep_side="lhs"))
        keys = []
        for i, j in pairs:
            mk = many_side.keys[i]
            lbls = self._result_labels(mk, one_side.keys[j])
            keys.append(lbls)
        return ResultBlock(keys, many_side.wends, out)

    def _result_labels(self, many_key: RangeVectorKey,
                       one_key: RangeVectorKey) -> RangeVectorKey:
        if self.cardinality in ("ManyToOne", "OneToMany"):
            lbls = many_key.without(("_metric_", "__name__")).labels_dict
            if self.include:
                od = one_key.labels_dict
                for lbl in self.include:
                    if lbl in od:
                        lbls[lbl] = od[lbl]
                    else:
                        lbls.pop(lbl, None)
            return RangeVectorKey.make(lbls)
        if self.on is not None:
            return many_key.only(self.on)
        return many_key.without(self.ignoring + ("_metric_", "__name__"))


class SetOperatorExec(NonLeafExecPlan):
    """and/or/unless (ref: exec/SetOperatorExec.scala)."""

    def __init__(self, ctx, lhs: Sequence[ExecPlan], rhs: Sequence[ExecPlan],
                 operator: str, on: Optional[Tuple[str, ...]] = None,
                 ignoring: Tuple[str, ...] = ()):
        super().__init__(ctx, list(lhs) + list(rhs))
        self.n_lhs = len(lhs)
        self.operator = operator.lower()
        self.on = tuple(on) if on is not None else None
        self.ignoring = tuple(ignoring)

    def args_str(self):
        return f"binaryOp={self.operator}, on={self.on}, ignoring={list(self.ignoring)}"

    def _match_key(self, k: RangeVectorKey) -> RangeVectorKey:
        if self.on is not None:
            return k.only(self.on)
        return k.without(self.ignoring + ("_metric_", "__name__"))

    def _presence_by_key(self, block: ResultBlock) -> Dict[RangeVectorKey, np.ndarray]:
        """match-key -> [W] bool, True where any series with that key has a
        sample at the step."""
        vals = np.asarray(block.values)
        if vals.ndim == 3:                       # histogram block
            vals = vals[..., 0]
        present: Dict[RangeVectorKey, np.ndarray] = {}
        for i, k in enumerate(block.keys):
            mk = self._match_key(k)
            pres = ~np.isnan(vals[i])
            present[mk] = present.get(mk, False) | pres
        return present

    def compose(self, results, stats):
        lhs = concat_blocks([r for r in results[:self.n_lhs]
                             if isinstance(r, ResultBlock)])
        rhs = concat_blocks([r for r in results[self.n_lhs:]
                             if isinstance(r, ResultBlock)])
        op = self.operator
        if op == "and":
            if lhs is None or rhs is None:
                return None
            rhs_keys = {self._match_key(k) for k in rhs.keys}
            # per-step AND: lhs kept where rhs series present at that step
            rk_rows = self._presence_by_key(rhs)
            rows, outs = [], []
            lvals = np.asarray(lhs.values)
            for i, k in enumerate(lhs.keys):
                mk = self._match_key(k)
                if mk in rhs_keys:
                    rows.append(i)
                    outs.append(np.where(rk_rows[mk], lvals[i], np.nan))
            if not rows:
                return None
            return ResultBlock([lhs.keys[i] for i in rows], lhs.wends,
                               np.stack(outs))
        if op == "or":
            if lhs is None:
                return rhs
            if rhs is None:
                return lhs
            lvals = np.asarray(lhs.values)
            lhs_present = self._presence_by_key(lhs)
            keys = list(lhs.keys)
            vals = [lvals]
            rvals = np.asarray(rhs.values)
            extra_rows, extra_keys = [], []
            for i, k in enumerate(rhs.keys):
                mk = self._match_key(k)
                mask = lhs_present.get(mk)
                row = rvals[i]
                if mask is not None:
                    row = np.where(mask, np.nan, row)
                extra_rows.append(row)
                extra_keys.append(k)
            if extra_rows:
                keys = keys + extra_keys
                vals.append(np.stack(extra_rows))
            return ResultBlock(keys, lhs.wends, np.concatenate(vals))
        if op == "unless":
            if lhs is None:
                return None
            if rhs is None:
                return lhs
            rk_rows = self._presence_by_key(rhs)
            lvals = np.asarray(lhs.values)
            outs = []
            for i, k in enumerate(lhs.keys):
                mk = self._match_key(k)
                mask = rk_rows.get(mk)
                outs.append(np.where(mask, np.nan, lvals[i])
                            if mask is not None else lvals[i])
            return remove_nan_series(
                ResultBlock(list(lhs.keys), lhs.wends, np.stack(outs)))
        raise ValueError(op)


class SubqueryExec(NonLeafExecPlan):
    """Evaluate an outer range function over an inner periodic series
    (foo[5m:1m] with rate/max_over_time/... outside).  The inner child's
    step-grid samples are treated as raw samples for the outer window kernel
    (ref: exec/... subquery handling via PeriodicSamplesMapper on inner)."""

    def __init__(self, ctx, children, start_ms, step_ms, end_ms, function,
                 function_args, subquery_window_ms, subquery_step_ms,
                 offset_ms=0):
        super().__init__(ctx, children)
        self.start_ms, self.step_ms, self.end_ms = start_ms, step_ms, end_ms
        self.function = function
        self.function_args = tuple(function_args)
        self.subquery_window_ms = subquery_window_ms
        self.subquery_step_ms = subquery_step_ms
        self.offset_ms = offset_ms

    def args_str(self):
        return (f"function={self.function}, window={self.subquery_window_ms}, "
                f"step={self.subquery_step_ms}")

    def compose(self, results, stats):
        block = concat_blocks([r for r in results if isinstance(r, ResultBlock)])
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        if block is None:
            return _block_empty(wends)
        inner_ts = np.asarray(block.wends)
        base = int(inner_ts[0]) if len(inner_ts) else 0
        vals = np.asarray(block.values)
        S = vals.shape[0]
        ts_off = np.broadcast_to((inner_ts - base).astype(np.int32),
                                 (S, len(inner_ts))).copy()
        # NaN steps are absent samples; offsets stay valid (kernel masks NaN)
        eval_wends = (wends - self.offset_ms - base).astype(np.int32)
        out = np.asarray(evaluate_range_function(
            jnp.asarray(ts_off), jnp.asarray(vals), jnp.asarray(eval_wends),
            self.subquery_window_ms, self.function, self.function_args,
            base_ms=base, dense=not bool(np.isnan(vals).any())))
        return ResultBlock(block.keys, wends, out)


class StitchRvsExec(NonLeafExecPlan):
    """Merge same-key series evaluated over adjacent time ranges
    (ref: exec/StitchRvsExec.scala)."""

    def compose(self, results, stats):
        blocks = [r for r in results if isinstance(r, ResultBlock)]
        if not blocks:
            return None
        wends = np.unique(np.concatenate([b.wends for b in blocks]))
        merged: Dict[RangeVectorKey, np.ndarray] = {}
        for b in blocks:
            pos = np.searchsorted(wends, b.wends)
            vals = np.asarray(b.values)
            for i, k in enumerate(b.keys):
                row = merged.get(k)
                if row is None:
                    row = np.full(len(wends), np.nan)
                    merged[k] = row
                fill = vals[i]
                take = ~np.isnan(fill)
                row[pos[take]] = fill[take]
        keys = list(merged)
        return ResultBlock(keys, wends, np.stack([merged[k] for k in keys]))

