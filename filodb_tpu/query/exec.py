"""ExecPlan — the distributed execution tree.

Mirrors the reference's exec framework (ref: query/.../exec/ExecPlan.scala:41,
RangeVectorTransformer.scala:36, AggrOverRangeVectors.scala, BinaryJoinExec.scala,
DistConcatExec.scala, StitchRvsExec.scala) with a TPU-first data plane:

  - Leaves gather a shard's matching series into ONE dense [S, T] batch
    (RawBlock) instead of per-partition iterators.
  - PeriodicSamplesMapper runs the fused window kernel (ops/rangefns.py) on
    device producing a step-grid ResultBlock [S, W].
  - AggregateMapReduce emits mesh-reducible partial components; the
    map/reduce/present 3-phase contract is identical to the reference
    (doc/query-engine.md:311-330) so partials can ride psum collectives.

Dispatchers decouple tree topology from placement: InProcessPlanDispatcher
runs a subtree inline; the cluster layer adds remote dispatch.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax.numpy as jnp

from filodb_tpu.core.index import ColumnFilter, Equals
from filodb_tpu.ops import agg as agg_ops
from filodb_tpu.ops import hist as hist_ops
from filodb_tpu.ops.instant import (INSTANT_FUNCTIONS, ARITH_OPERATORS,
                                    COMPARISON_OPERATORS, apply_binary_op)
from filodb_tpu.ops import counter as counter_ops
from filodb_tpu.ops.rangefns import RANGE_FUNCTIONS, evaluate_range_function
from filodb_tpu.ops.timewindow import PAD_TS, to_offsets, make_window_ends
from filodb_tpu.query.rangevector import (QueryContext, QueryResult, QueryStats,
                                          RangeVectorKey, ResultBlock,
                                          concat_blocks, remove_nan_series)

# --------------------------------------------------------------- data shapes


@dataclasses.dataclass
class RawBlock:
    """Raw gathered samples for one schema on one shard: pre-step-grid.

    values are REBASED per series (absolute value - vbase[s]) so counter
    deltas survive the f32 device downcast; vbase is the per-series base
    in f64 (None = not rebased).  See ops/timewindow.series_value_base."""
    keys: List[RangeVectorKey]
    ts_off: np.ndarray                  # int32 [S, T] offsets from base_ms
    values: np.ndarray                  # [S, T] or [S, T, B]
    base_ms: int
    bucket_les: Optional[np.ndarray] = None
    samples: int = 0                    # total valid samples (stats)
    vbase: Optional[np.ndarray] = None  # [S] or [S, B]
    precorrected: bool = False          # counter reset-correction done host-side
    # shared scrape grid: row-0 ts offsets when ALL rows share one grid
    # (the pallas_fused precondition, tracked by the device mirror); None
    # otherwise.  `dense` qualifies it: True = no NaN holes anywhere in the
    # counted region; False = NaN-holed values on the shared grid, which
    # only the validity-weighted fused kinds accept.
    shared_ts_row: Optional[np.ndarray] = None
    dense: bool = True


# Fused-leaf caches (see MultiSchemaPartitionsExec._try_fused): entries are
# keyed by (mirror serial, snapshot gen, ...) so any ingest naturally
# misses.  The VALUES cache holds the full padded device copies — shared
# across grouping variants (they depend only on the working set) and
# bounded in BYTES, since this HBM lives outside the DeviceMirror's own
# hbm_limit_bytes accounting.  The GROUP cache holds the small per-grouping
# gid arrays.
_FUSED_PLAN_CACHE: Dict[Tuple, object] = {}
_FUSED_VALS_CACHE: Dict[Tuple, object] = {}
_FUSED_GROUP_CACHE: Dict[Tuple, Tuple] = {}
# NaN-padded device copies for the reduce_window path's end=now shape,
# keyed (working set, t_needed) — small cap: each entry pins a full copy
_FUSED_MINMAX_PAD_CACHE: Dict[Tuple, object] = {}
_FUSED_VALS_CACHE_BYTES: Optional[int] = None    # resolved lazily
_MIRROR_LIMIT_SEEN: Optional[int] = None         # largest live mirror budget


def _note_mirror_limit(limit_bytes: int) -> None:
    """Record the largest DeviceMirror HBM budget actually constructed so
    the fused-cache budget subtracts the REAL mirror share, not just the
    compile-time default (review r3)."""
    global _MIRROR_LIMIT_SEEN, _FUSED_VALS_CACHE_BYTES
    if _MIRROR_LIMIT_SEEN is None or limit_bytes > _MIRROR_LIMIT_SEEN:
        _MIRROR_LIMIT_SEEN = limit_bytes
        _FUSED_VALS_CACHE_BYTES = None   # re-derive on next insert


def _fused_vals_budget() -> int:
    """Byte budget for the padded-values cache.  Configurable via
    FILODB_TPU_FUSED_CACHE_BYTES; otherwise derived from the device's
    reported HBM minus the live mirror budget so mirror + this cache +
    headroom cannot exceed the chip (ADVICE r2: the old fixed 4 GiB
    ignored the mirror's budget).  Resolved lazily — the backend is
    already initialized by the time the first fused query inserts."""
    global _FUSED_VALS_CACHE_BYTES
    if _FUSED_VALS_CACHE_BYTES is not None:
        return _FUSED_VALS_CACHE_BYTES
    env = os.environ.get("FILODB_TPU_FUSED_CACHE_BYTES")
    if env:
        _FUSED_VALS_CACHE_BYTES = int(env)
        return _FUSED_VALS_CACHE_BYTES
    budget = 4 << 30
    try:
        import jax

        from filodb_tpu.core.devicecache import DEFAULT_HBM_LIMIT_BYTES
        mirror_limit = _MIRROR_LIMIT_SEEN or DEFAULT_HBM_LIMIT_BYTES
        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit:
            budget = min(budget,
                         max(1 << 30, limit - mirror_limit - (2 << 30)))
    except Exception:  # noqa: BLE001 — stats unavailable: keep the default
        pass
    _FUSED_VALS_CACHE_BYTES = budget
    return budget
# queries run on HTTP worker threads (http/server.py ThreadingHTTPServer) —
# every cache read-modify-write holds this lock; the kernel runs outside it
_FUSED_CACHE_LOCK = threading.Lock()


class GroupCardinalityError(ValueError):
    """group-by cardinality limit exceeded — a real query error that must
    surface even from the fused fast path (everything else falls back)."""


def _lru_touch(cache: Dict, key) -> object:
    """Get + move-to-back (dicts iterate in insertion order, so eviction
    pops the front = least-recently-used).  One idiom for all fused caches."""
    val = cache.get(key)
    if val is not None:
        cache[key] = cache.pop(key)
    return val


def _vals_nbytes(v) -> int:
    return int(v.vals_p.size * 4 + v.vbase_p.size * 4)


def _group_cache_lookup(key, by, without):
    """Cached (PaddedGroups, gkeys) for this working set + grouping, or
    (None, None).  Pairs with _group_cache_insert — the two halves of the
    group-cache protocol, shared by the kernel and reduce_window paths."""
    if key is None:
        return None, None
    with _FUSED_CACHE_LOCK:
        ent = _lru_touch(_FUSED_GROUP_CACHE, key + (by, without))
    return ent if ent is not None else (None, None)


def _group_cache_insert(key, by, without, groups, gkeys) -> None:
    """Insert a (PaddedGroups, gkeys) entry, evicting entries from older
    snapshot generations of the same mirror (each pins device arrays) and
    capping the cache.  The single home of the group-cache write rules —
    used by both the kernel path and the reduce_window path."""
    if key is None:
        return
    group_key = key + (by, without)
    with _FUSED_CACHE_LOCK:
        for k in [k for k in _FUSED_GROUP_CACHE
                  if k[0] == key[0] and k[1] != key[1]]:
            del _FUSED_GROUP_CACHE[k]
        _FUSED_GROUP_CACHE[group_key] = (groups, gkeys)
        while len(_FUSED_GROUP_CACHE) > 16:
            _FUSED_GROUP_CACHE.pop(next(iter(_FUSED_GROUP_CACHE)))


def _vals_cache_insert(key, v) -> None:
    _FUSED_VALS_CACHE[key] = v
    while len(_FUSED_VALS_CACHE) > 4 or sum(
            _vals_nbytes(e) for e in _FUSED_VALS_CACHE.values()
            ) > _fused_vals_budget():
        if len(_FUSED_VALS_CACHE) == 1:
            break                        # always keep the entry just added
        _FUSED_VALS_CACHE.pop(next(iter(_FUSED_VALS_CACHE)))


@dataclasses.dataclass
class ScalarResult:
    """One value per step (scalar plans)."""
    wends: np.ndarray                   # int64 [W]
    values: np.ndarray                  # float [W]


@dataclasses.dataclass
class AggPartial:
    """Partial aggregate: mesh-reducible (op-dependent) representation."""
    op: str
    group_keys: List[RangeVectorKey]
    wends: np.ndarray
    comp: Optional[np.ndarray] = None   # [G, W, C] associative component form
    # candidate form (topk/bottomk/quantile/count_values): raw rows
    cand_keys: Optional[List[RangeVectorKey]] = None
    cand_vals: Optional[np.ndarray] = None   # [N, W]
    cand_groups: Optional[np.ndarray] = None  # int [N] -> group_keys index
    params: Tuple = ()
    bucket_les: Optional[np.ndarray] = None  # hist_sum partials
    # quantile(): mergeable centroid sketch [G, W, K, 2] — O(groups) wire
    # cost instead of shipping every candidate series row
    # (ref: QuantileRowAggregator.scala:87 t-digest partials)
    sketch: Optional[np.ndarray] = None


Data = Union[RawBlock, ResultBlock, ScalarResult, AggPartial, None]


def _block_empty(wends: np.ndarray) -> ResultBlock:
    return ResultBlock([], wends, np.zeros((0, len(wends))))


# ------------------------------------------------------------- transformers


class RangeVectorTransformer:
    """ref: exec/RangeVectorTransformer.scala:36."""

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        raise NotImplementedError

    def args_str(self) -> str:
        return ""

    def __str__(self):
        return f"{type(self).__name__}({self.args_str()})"


@dataclasses.dataclass
class PeriodicSamplesMapper(RangeVectorTransformer):
    """Raw samples -> regular step grid, optional range function
    (ref: exec/PeriodicSamplesMapper.scala:27)."""
    start_ms: int
    step_ms: int
    end_ms: int
    window_ms: Optional[int] = None     # None => plain lookback sampling
    function: Optional[str] = None
    function_args: Tuple[float, ...] = ()
    offset_ms: int = 0
    lookback_ms: int = 5 * 60 * 1000

    def args_str(self):
        return (f"start={self.start_ms}, step={self.step_ms}, end={self.end_ms}, "
                f"window={self.window_ms}, functionId={self.function}, "
                f"offset={self.offset_ms}")

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        if data is None or (isinstance(data, RawBlock) and not data.keys):
            return _block_empty(wends)
        assert isinstance(data, RawBlock), "PeriodicSamplesMapper needs raw data"
        window = self.window_ms if self.window_ms else self.lookback_ms
        fn = self.function
        base = data.base_ms
        # timestamp(): the kernel computes f32 offset-seconds (exact for
        # query-sized ranges); the epoch base adds back below in f64 — f32
        # cannot hold epoch seconds to sub-minute precision
        kernel_base = 0 if fn == "timestamp" else base
        # offset: shift the window grid back, evaluate, keep original stamps
        eval_wends = wends - self.offset_ms
        wends_off = (eval_wends - base).astype(np.int32)
        vals = data.values
        vb = data.vbase
        # shared scrape grid: ship ONE [1, T] offset row and let it
        # broadcast through the kernel (exact for every range function —
        # window bounds come from row 0 and every gather takes the
        # column fast path).  Halves the general path's HBM timestamp
        # traffic and skips the S-fold ts transfer entirely.
        shared = data.shared_ts_row is not None
        ts_in = data.ts_off[:1] if shared else data.ts_off
        if vals.ndim == 3:
            S, T, B = vals.shape
            flat = np.moveaxis(vals, 2, 1).reshape(S * B, T)
            ts_rep = ts_in if shared else np.repeat(data.ts_off, B, axis=0)
            vb_flat = None if vb is None else jnp.asarray(vb).reshape(S * B)
            out = np.asarray(evaluate_range_function(
                jnp.asarray(ts_rep), jnp.asarray(flat),
                jnp.asarray(wends_off), window, fn,
                tuple(self.function_args), base_ms=kernel_base,
                vbase=vb_flat, precorrected=data.precorrected,
                shared_grid=shared, dense=data.dense))
            out = np.moveaxis(out.reshape(S, B, -1), 1, 2)     # [S, W, B]
        else:
            out = np.asarray(evaluate_range_function(
                jnp.asarray(ts_in), jnp.asarray(vals),
                jnp.asarray(wends_off), window, fn,
                tuple(self.function_args), base_ms=kernel_base,
                vbase=None if vb is None else jnp.asarray(vb),
                precorrected=data.precorrected, shared_grid=shared,
                dense=data.dense))
        if fn == "timestamp":
            out = out.astype(np.float64) + base / 1000.0
        return ResultBlock(data.keys, wends, out, data.bucket_les)


@dataclasses.dataclass
class RepeatToGridMapper(RangeVectorTransformer):
    """PromQL `@` modifier finisher: the upstream mapper evaluated on a
    single-step grid pinned at the @ timestamp; tile that one column
    across the query's output grid (Prometheus: the pinned value at every
    step)."""
    start_ms: int
    step_ms: int
    end_ms: int

    def args_str(self):
        return (f"start={self.start_ms}, step={self.step_ms}, "
                f"end={self.end_ms}")

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        if data is None:
            return None
        assert isinstance(data, ResultBlock), "@ repeat needs periodic data"
        vals = np.asarray(data.values)
        assert vals.shape[1] == 1, "@ inner grid must be single-step"
        reps = (1, len(wends)) + (1,) * (vals.ndim - 2)
        return ResultBlock(data.keys, wends, np.tile(vals, reps),
                           data.bucket_les)


@dataclasses.dataclass
class InstantVectorFunctionMapper(RangeVectorTransformer):
    """ref: exec/RangeVectorTransformer.scala:61."""
    function: str
    args: Tuple = ()

    def args_str(self):
        return f"function={self.function}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if not isinstance(data, ResultBlock) or data.num_series == 0:
            return data
        vals = data.values
        if self.function in ("histogram_quantile", "histogram_max_quantile"):
            assert data.is_histogram, "histogram_quantile needs histogram data"
            q = float(self._arg_value(self.args[0], source))
            out = np.asarray(hist_ops.histogram_quantile(
                q, jnp.asarray(vals), jnp.asarray(data.bucket_les)))
            return ResultBlock(data.keys, data.wends, out)
        if self.function == "histogram_bucket":
            le = float(self._arg_value(self.args[0], source))
            out = np.asarray(hist_ops.histogram_bucket(
                le, jnp.asarray(vals), jnp.asarray(data.bucket_les)))
            return ResultBlock(data.keys, data.wends, out)
        fn = INSTANT_FUNCTIONS[self.function]
        # elementwise functions broadcast per-step scalar args over [S, W]
        extra = [np.asarray(self._arg_value(a, source, per_step=True))
                 for a in self.args]
        out = np.asarray(fn(jnp.asarray(vals),
                            *[jnp.asarray(x) for x in extra]))
        return ResultBlock(data.keys, data.wends, out, data.bucket_les)

    @staticmethod
    def _arg_value(a, source, per_step: bool = False):
        """Resolve a (possibly deferred) scalar argument.  per_step returns a
        [W] array for elementwise functions; otherwise a single float — a
        genuinely time-varying scalar is rejected rather than silently
        collapsed to its first step."""
        if hasattr(a, "resolve"):                 # deferred scalar subplan
            a = a.resolve(source)
        if isinstance(a, ScalarResult):
            if len(a.values) == 0:
                return np.nan
            if per_step:
                return a.values
            vals = a.values[~np.isnan(a.values)]
            if len(vals) and not np.all(vals == vals[0]):
                raise ValueError(
                    "time-varying scalar argument not supported for this "
                    "function")
            return a.values[0] if len(vals) == 0 else vals[0]
        return a


@dataclasses.dataclass
class ScalarOperationMapper(RangeVectorTransformer):
    """vector op scalar (ref: RangeVectorTransformer.scala:186)."""
    operator: str
    scalar: Union[float, ScalarResult]
    scalar_is_lhs: bool = False
    bool_modifier: bool = False

    def args_str(self):
        return f"operator={self.operator}, scalarOnLhs={self.scalar_is_lhs}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if not isinstance(data, ResultBlock) or data.num_series == 0:
            return data
        vals = np.asarray(data.values)
        scalar = self.scalar
        if hasattr(scalar, "resolve"):            # deferred scalar subplan
            scalar = scalar.resolve(source)
        if isinstance(scalar, ScalarResult):
            # empty scalar stream (e.g. scalar(absent-selector) across
            # shards) behaves as NaN, same as the 1-shard path
            sv = (scalar.values[None, :] if scalar.values.shape[0]
                  == vals.shape[1] else np.full((1, 1), np.nan))
        else:
            sv = np.full((1, 1), float(scalar))
        sv = np.broadcast_to(sv, vals.shape)
        a, b = (sv, vals) if self.scalar_is_lhs else (vals, sv)
        # comparison filtering keeps the VECTOR side's value
        out = np.asarray(apply_binary_op(
            jnp.asarray(a), jnp.asarray(b), op=self.operator,
            bool_modifier=self.bool_modifier,
            keep_side=("rhs" if self.scalar_is_lhs else "lhs")))
        return ResultBlock(data.keys, data.wends, out, data.bucket_les)


def _group_ids(keys: Sequence[RangeVectorKey], by: Tuple[str, ...],
               without: Tuple[str, ...]) -> Tuple[np.ndarray, List[RangeVectorKey]]:
    """Host-side grouping: series key -> group key (by/without semantics)."""
    gmap: Dict[RangeVectorKey, int] = {}
    gids = np.empty(len(keys), dtype=np.int32)
    gkeys: List[RangeVectorKey] = []
    for i, k in enumerate(keys):
        if by:
            gk = k.only(by)
        elif without:
            gk = k.without(tuple(without) + ("_metric_", "__name__"))
        else:
            gk = RangeVectorKey(())
        gid = gmap.get(gk)
        if gid is None:
            gid = len(gkeys)
            gmap[gk] = gid
            gkeys.append(gk)
        gids[i] = gid
    return gids, gkeys


_CANDIDATE_OPS = {"topk", "bottomk", "count_values"}


@dataclasses.dataclass
class AggregateMapReduce(RangeVectorTransformer):
    """Map phase of 3-phase aggregation (ref: AggrOverRangeVectors.scala:76)."""
    op: str
    params: Tuple = ()
    by: Tuple[str, ...] = ()
    without: Tuple[str, ...] = ()

    def args_str(self):
        return (f"aggrOp={self.op}, aggrParams={list(self.params)}, "
                f"without={list(self.without)}, by={list(self.by)}")

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        assert isinstance(data, (ResultBlock, type(None)))
        if data is None or data.num_series == 0:
            return None
        vals = np.asarray(data.values)
        gids, gkeys = _group_ids(data.keys, self.by, self.without)
        limit = ctx.planner_params.group_by_cardinality_limit
        if limit and len(gkeys) > limit:
            raise GroupCardinalityError(
                f"group-by cardinality limit {limit} exceeded "
                f"({len(gkeys)} groups)")
        if data.is_histogram and self.op == "sum":
            # histogram sum: elementwise over buckets — [G, W, B+1] where the
            # extra slot counts present series (empty-step masking)
            present = ~np.isnan(vals)
            comp = np.where(present, vals, 0.0)
            G = len(gkeys)
            S, W, B = vals.shape
            agg = np.zeros((G, W, B + 1))
            np.add.at(agg[..., :B], gids, comp)     # view write-through
            np.add.at(agg[..., B], gids, present.any(axis=2).astype(float))
            return AggPartial("hist_sum", gkeys, data.wends, comp=agg,
                              params=self.params, bucket_les=data.bucket_les)
        if self.op == "quantile" and vals.ndim == 2:
            from filodb_tpu.ops import sketch as sketch_ops
            sk = sketch_ops.sketch_from_values(vals, gids, len(gkeys))
            return AggPartial(self.op, gkeys, data.wends, sketch=sk,
                              params=self.params)
        if self.op in _CANDIDATE_OPS or self.op == "quantile":
            cand_keys, cand_vals, cand_groups = self._candidates(
                data, vals, gids, len(gkeys))
            return AggPartial(self.op, gkeys, data.wends, cand_keys=cand_keys,
                              cand_vals=cand_vals, cand_groups=cand_groups,
                              params=self.params)
        comp = np.asarray(agg_ops.map_phase(
            self.op, jnp.asarray(vals), jnp.asarray(gids), len(gkeys)))
        return AggPartial(self.op, gkeys, data.wends, comp=comp,
                          params=self.params)

    def _candidates(self, data, vals, gids, num_groups):
        if self.op in ("topk", "bottomk"):
            k = int(self.params[0])
            mask = np.asarray(agg_ops.topk_mask(
                jnp.asarray(vals), jnp.asarray(gids), num_groups, k,
                largest=(self.op == "topk")))
            keep = mask.any(axis=1)
            rows = np.flatnonzero(keep)
        else:
            rows = np.arange(len(data.keys))
        return ([data.keys[int(r)] for r in rows], vals[rows], gids[rows])


class AggregatePresenter(RangeVectorTransformer):
    """Present phase (ref: AggrOverRangeVectors.scala:125)."""

    def __init__(self, op: str, params: Tuple = ()):
        self.op = op
        self.params = params

    def args_str(self):
        return f"aggrOp={self.op}, aggrParams={list(self.params)}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if data is None:
            return None
        assert isinstance(data, AggPartial)
        return present_partial(data)


def present_partial(p: AggPartial) -> Optional[ResultBlock]:
    """Finish an AggPartial into a ResultBlock."""
    if p.sketch is not None:
        from filodb_tpu.ops import sketch as sketch_ops
        q = float(p.params[0])
        out = sketch_ops.sketch_quantile(p.sketch, q)
        return ResultBlock(p.group_keys, p.wends, out)
    if p.comp is not None:
        if p.op == "hist_sum":
            # [G, W, B+1] with present-series count in the last slot
            buckets = p.comp[..., :-1]
            present_cnt = p.comp[..., -1]
            out = np.where(present_cnt[..., None] > 0, buckets, np.nan)
            return ResultBlock(p.group_keys, p.wends, out, p.bucket_les)
        out = np.asarray(agg_ops.present(p.op, jnp.asarray(p.comp)))
        return ResultBlock(p.group_keys, p.wends, out)
    # candidate form
    if p.op in ("topk", "bottomk"):
        k = int(p.params[0])
        gids = p.cand_groups
        mask = np.asarray(agg_ops.topk_mask(
            jnp.asarray(p.cand_vals), jnp.asarray(gids), len(p.group_keys),
            k, largest=(p.op == "topk")))
        vals = np.where(mask, p.cand_vals, np.nan)
        block = ResultBlock(p.cand_keys, p.wends, vals)
        return remove_nan_series(block)
    if p.op == "quantile":
        q = float(p.params[0])
        out = np.asarray(agg_ops.quantile_agg(
            jnp.asarray(p.cand_vals), jnp.asarray(p.cand_groups),
            len(p.group_keys), q))
        return ResultBlock(p.group_keys, p.wends, out)
    if p.op == "count_values":
        label = str(p.params[0])
        vals = p.cand_vals
        out_keys: List[RangeVectorKey] = []
        out_rows: List[np.ndarray] = []
        W = vals.shape[1]
        for g in range(len(p.group_keys)):
            rows = vals[p.cand_groups == g]
            uniq = np.unique(rows[~np.isnan(rows)])
            for v in uniq:
                cnt = np.nansum(rows == v, axis=0).astype(float)
                cnt[cnt == 0] = np.nan
                lbls = dict(p.group_keys[g].labels)
                lbls[label] = f"{v:g}"
                out_keys.append(RangeVectorKey.make(lbls))
                out_rows.append(cnt)
        if not out_keys:
            return None
        return ResultBlock(out_keys, p.wends, np.stack(out_rows))
    raise ValueError(p.op)


def _union_scheme(les_list: List[Optional[np.ndarray]]) -> Optional[np.ndarray]:
    """Union bucket scheme across shards, or None when any shard carries no
    boundaries (widths must then match — checked by the caller's reshape)."""
    from filodb_tpu.memory.histogram import union_les
    known = [l for l in les_list if l is not None]
    if len(known) != len(les_list):
        return None
    out = known[0]
    for l in known[1:]:
        out = union_les(out, l)
    return out


def _align_hist_schemes(parts: List[AggPartial]) -> List[AggPartial]:
    """Rebucket hist_sum partials onto the union scheme so shards whose
    series changed bucket scheme mid-retention still merge
    (ref: HistogramBuckets.scala:340; replaces the fail-loudly behavior)."""
    from filodb_tpu.memory.histogram import rebucket
    les_list = [p.bucket_les for p in parts]
    if any(l is None for l in les_list):
        # boundary-less partials can only merge by width (legacy behavior);
        # order of children must not matter — and any two KNOWN schemes
        # that differ cannot be silently index-merged just because a third
        # partial lacks boundaries
        widths = {p.comp.shape[-1] for p in parts}
        known = [l for l in les_list if l is not None]
        if len(widths) > 1 or any(not np.array_equal(l, known[0])
                                  for l in known[1:]):
            raise ValueError(
                "cannot merge histogram partials of different schemes when "
                "some shards carry no bucket boundaries to re-map by")
        return parts
    if all(np.array_equal(l, les_list[0]) for l in les_list):
        return parts
    union = _union_scheme(les_list)

    def _rebucket_comp(p):
        # comp is [G, W, B+1]: B bucket slots + the present-series count
        B = len(p.bucket_les)
        buckets = rebucket(p.comp[..., :B], p.bucket_les, union)
        return np.concatenate([buckets, p.comp[..., B:]], axis=-1)

    return [dataclasses.replace(p, comp=_rebucket_comp(p), bucket_les=union)
            if not np.array_equal(p.bucket_les, union) else p
            for p in parts]


def reduce_partials(parts: List[AggPartial]) -> Optional[AggPartial]:
    """Inter-shard reduce (ReduceAggregateExec): merge partials by group key."""
    parts = [p for p in parts if p is not None]
    if not parts:
        return None
    op = parts[0].op
    if op == "hist_sum":
        parts = _align_hist_schemes(parts)
    gmap: Dict[RangeVectorKey, int] = {}
    gkeys: List[RangeVectorKey] = []
    for p in parts:
        for k in p.group_keys:
            if k not in gmap:
                gmap[k] = len(gkeys)
                gkeys.append(k)
    wends = parts[0].wends
    if parts[0].sketch is not None:
        # quantile sketches: concat centroid axis per group (zero-weight
        # padding for shards that lack a group), then re-compress to K
        from filodb_tpu.ops import sketch as sketch_ops
        G = len(gkeys)
        W = parts[0].sketch.shape[1]
        M = sum(p.sketch.shape[2] for p in parts)
        cat = np.zeros((G, W, M, 2))
        cat[..., 0] = np.nan
        off = 0
        for p in parts:
            idx = np.asarray([gmap[k] for k in p.group_keys], dtype=np.int64)
            m = p.sketch.shape[2]
            cat[idx, :, off:off + m] = p.sketch
            off += m
        return AggPartial(op, gkeys, wends,
                          sketch=sketch_ops.merge_sketches(cat),
                          params=parts[0].params)
    if parts[0].comp is not None:
        C = parts[0].comp.shape[-1]
        W = parts[0].comp.shape[1]
        combs = agg_ops.combiners_for(op, C)
        init = {"sum": 0.0, "min": np.inf, "max": -np.inf}
        out = np.empty((len(gkeys), W, C))
        for i, comb in enumerate(combs):
            out[..., i] = init[comb]
        for p in parts:
            idx = np.asarray([gmap[k] for k in p.group_keys], dtype=np.int64)
            for i, comb in enumerate(combs):
                ufunc = {"sum": np.add, "min": np.minimum,
                         "max": np.maximum}[comb]
                ufunc.at(out[..., i], idx, p.comp[..., i])
        return AggPartial(op, gkeys, wends, comp=out, params=parts[0].params,
                          bucket_les=parts[0].bucket_les)
    # candidate form: concat and remap groups
    ck: List[RangeVectorKey] = []
    cv: List[np.ndarray] = []
    cg: List[np.ndarray] = []
    for p in parts:
        idx = np.asarray([gmap[k] for k in p.group_keys], dtype=np.int64)
        ck.extend(p.cand_keys)
        cv.append(p.cand_vals)
        cg.append(idx[p.cand_groups])
    return AggPartial(op, gkeys, wends,
                      cand_keys=ck, cand_vals=np.concatenate(cv),
                      cand_groups=np.concatenate(cg), params=parts[0].params)


@dataclasses.dataclass
class AbsentFunctionMapper(RangeVectorTransformer):
    """absent() (ref: RangeVectorTransformer.scala:340)."""
    filters: Tuple[ColumnFilter, ...]
    start_ms: int = 0
    step_ms: int = 0
    end_ms: int = 0

    def args_str(self):
        return "functionId=absent"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        wends = (data.wends if isinstance(data, ResultBlock)
                 else make_window_ends(self.start_ms, self.end_ms,
                                       max(self.step_ms, 1)))
        if isinstance(data, ResultBlock) and data.num_series:
            present = ~np.isnan(np.asarray(data.values)).all(axis=0)
        else:
            present = np.zeros(len(wends), dtype=bool)
        out = np.where(present, np.nan, 1.0)[None, :]
        labels = {f.column: f.value for f in self.filters
                  if isinstance(f, Equals)
                  and f.column not in ("__name__", "_metric_")}
        return ResultBlock([RangeVectorKey.make(labels)], wends, out)


@dataclasses.dataclass
class SortFunctionMapper(RangeVectorTransformer):
    """sort()/sort_desc() by mean value (ref: RangeVectorTransformer.scala:254)."""
    descending: bool = False

    def args_str(self):
        return f"function={'sort_desc' if self.descending else 'sort'}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if not isinstance(data, ResultBlock) or data.num_series <= 1:
            return data
        with np.errstate(invalid="ignore"):
            means = np.nanmean(np.asarray(data.values), axis=1)
        means = np.where(np.isnan(means), -np.inf if not self.descending else np.inf,
                         means)
        order = np.argsort(-means if self.descending else means, kind="stable")
        return data.select(order)


@dataclasses.dataclass
class MiscellaneousFunctionMapper(RangeVectorTransformer):
    """label_replace / label_join (ref: rangefn/MiscellaneousFunction.scala)."""
    function: str
    string_args: Tuple[str, ...] = ()

    def args_str(self):
        return f"function={self.function}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if not isinstance(data, ResultBlock):
            return data
        import re
        if self.function == "label_replace":
            dst, repl, src, regex = self.string_args
            pat = re.compile("^(?:" + regex + ")$")
            keys = []
            for k in data.keys:
                lbls = k.labels_dict
                m = pat.match(lbls.get(src, ""))
                if m:
                    val = m.expand(_dollar_to_backslash(repl))
                    if val:
                        lbls[dst] = val
                    else:
                        lbls.pop(dst, None)
                keys.append(RangeVectorKey.make(lbls))
            return ResultBlock(keys, data.wends, data.values, data.bucket_les)
        if self.function == "label_join":
            dst, sep, *srcs = self.string_args
            keys = []
            for k in data.keys:
                lbls = k.labels_dict
                val = sep.join(lbls.get(s, "") for s in srcs)
                if val:
                    lbls[dst] = val
                else:
                    lbls.pop(dst, None)
                keys.append(RangeVectorKey.make(lbls))
            return ResultBlock(keys, data.wends, data.values, data.bucket_les)
        raise ValueError(f"unknown misc function {self.function}")


def _dollar_to_backslash(repl: str) -> str:
    """PromQL uses $1; python re.expand uses \\1."""
    import re as _re
    return _re.sub(r"\$(\d+)", r"\\\1", repl)


@dataclasses.dataclass
class LimitFunctionMapper(RangeVectorTransformer):
    limit: int

    def args_str(self):
        return f"limit={self.limit}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if isinstance(data, ResultBlock) and data.num_series > self.limit:
            return data.select(np.arange(self.limit))
        return data


@dataclasses.dataclass
class ScalarFunctionMapper(RangeVectorTransformer):
    """scalar(vector): 1 series -> scalar stream, else NaN (ref:
    RangeVectorTransformer ScalarFunctionMapper)."""
    function: str = "scalar"

    def args_str(self):
        return f"function={self.function}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        assert isinstance(data, (ResultBlock, type(None)))
        if data is None or data.num_series != 1:
            wends = data.wends if data is not None else np.zeros(0, np.int64)
            return ScalarResult(wends, np.full(len(wends), np.nan))
        return ScalarResult(data.wends, np.asarray(data.values)[0])


@dataclasses.dataclass
class VectorFunctionMapper(RangeVectorTransformer):
    """vector(scalar) (ref: RangeVectorTransformer VectorFunctionMapper)."""

    def args_str(self):
        return "function=vector"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if isinstance(data, ScalarResult):
            return ResultBlock([RangeVectorKey(())], data.wends,
                               data.values[None, :])
        return data


# ---------------------------------------------------------------- exec plans


class PlanDispatcher:
    """ref: exec/PlanDispatcher.scala:20."""

    def dispatch(self, plan: "ExecPlan", source) -> QueryResultLike:
        raise NotImplementedError


QueryResultLike = Tuple[Data, QueryStats]


class InProcessPlanDispatcher(PlanDispatcher):
    """Run the subtree in-process (ref: exec/InProcessPlanDispatcher.scala:89)."""

    def dispatch(self, plan: "ExecPlan", source) -> QueryResultLike:
        return plan.execute_internal(source)


class ExecPlan:
    """Base execution node.  `execute_internal` returns raw Data + stats;
    `execute` materializes a QueryResult with limits enforced
    (ref: ExecPlan.scala:96-186)."""

    def __init__(self, ctx: Optional[QueryContext] = None):
        self.ctx = ctx or QueryContext()
        self.transformers: List[RangeVectorTransformer] = []
        self.dispatcher: PlanDispatcher = InProcessPlanDispatcher()

    def add_transformer(self, t: RangeVectorTransformer) -> "ExecPlan":
        self.transformers.append(t)
        return self

    @property
    def children(self) -> List["ExecPlan"]:
        return []

    # -- execution

    def _do_execute(self, source) -> QueryResultLike:
        raise NotImplementedError

    def execute_internal(self, source) -> QueryResultLike:
        data, stats = self._do_execute(source)
        for t in self.transformers:
            data = t.apply(data, self.ctx, stats, source)
        return data, stats

    def execute(self, source) -> QueryResult:
        # span + error counters per plan type (ref: ExecPlan.scala:102-131
        # Kamon span around doExecute; query-error counters QueryActor:80-96)
        from filodb_tpu.utils.metrics import registry, span
        try:
            with span("execplan", plan=type(self).__name__):
                data, stats = self.execute_internal(source)
        except Exception as e:  # noqa: BLE001 — query errors surface in result
            registry.counter("query_errors",
                             plan=type(self).__name__).increment()
            return QueryResult([], QueryStats(), error=f"{type(e).__name__}: {e}")
        if isinstance(data, AggPartial):
            data = present_partial(data)
        if isinstance(data, ScalarResult):
            data = ResultBlock([RangeVectorKey(())], data.wends,
                               data.values[None, :])
        data = remove_nan_series(data)
        blocks = [data] if data is not None else []
        limit = self.ctx.planner_params.sample_limit
        result_samples = sum(int(np.asarray(b.values).size) for b in blocks)
        if limit and result_samples > limit:
            return QueryResult([], stats,
                               error=f"sample limit {limit} exceeded "
                                     f"({result_samples} samples)")
        stats.result_samples = result_samples
        return QueryResult(blocks, stats)

    # -- plan printing (ref: ExecPlan.printTree, doc/query-engine.md:174-204)

    def args_str(self) -> str:
        return ""

    def print_tree(self, level: int = 0) -> str:
        transf = [f"{'-' * (level + i + 1)}T~{type(t).__name__}({t.args_str()})"
                  for i, t in enumerate(reversed(self.transformers))]
        me = (f"{'-' * (level + len(self.transformers) + 1)}"
              f"E~{type(self).__name__}({self.args_str()})")
        kids = [c.print_tree(level + len(self.transformers) + 1)
                for c in self.children]
        return "\n".join(transf + [me] + kids)

    def __str__(self):
        return self.print_tree()


class LeafExecPlan(ExecPlan):
    pass


class MultiSchemaPartitionsExec(LeafExecPlan):
    """Leaf: index lookup + dense gather on the owning shard
    (ref: exec/MultiSchemaPartitionsExec.scala:27-60,
    SelectRawPartitionsExec.doExecute:125)."""

    def __init__(self, ctx: QueryContext, dataset: str, shard: int,
                 filters: Sequence[ColumnFilter], chunk_start_ms: int,
                 chunk_end_ms: int, columns: Sequence[str] = (),
                 schema: Optional[str] = None):
        super().__init__(ctx)
        self.dataset = dataset
        self.shard = shard
        self.filters = list(filters)
        self.chunk_start_ms = chunk_start_ms
        self.chunk_end_ms = chunk_end_ms
        self.columns = list(columns)
        self.schema = schema
        self._transformer_overrides: Dict[int, RangeVectorTransformer] = {}

    def execute_internal(self, source) -> QueryResultLike:
        self._transformer_overrides = {}
        self._fused_cache_key = None
        data, stats = self._do_execute(source)
        start = 0
        try:
            fused = self._try_fused(data, stats)
        except GroupCardinalityError:
            raise                        # real query error — must surface
        except Exception as e:  # noqa: BLE001 — fusion is an optimization
            from filodb_tpu.utils.metrics import (log_fused_degradation,
                                                  registry)
            registry.counter("leaf_fused_errors").increment()
            log_fused_degradation("leaf", e)
            fused = None
        if fused is not None:
            data, start = fused, 2
        for i, t in enumerate(self.transformers[start:], start):
            t = self._transformer_overrides.get(i, t)
            data = t.apply(data, self.ctx, stats, source)
        return data, stats

    def _try_fused(self, data, stats):
        """Peephole: PeriodicSamplesMapper(rate|increase|delta) followed by
        AggregateMapReduce(sum) over a shared-grid fully-finite working set
        collapses into the single-HBM-pass MXU kernel (ops/pallas_fused.py)
        — the leaf analogue of the reference pushing AggregateMapReduce to
        data nodes (ref: AggrOverRangeVectors.scala:76), fused one level
        further.  Returns the AggPartial or None (general path)."""
        if len(self.transformers) < 2 or not isinstance(data, RawBlock) \
                or not data.keys or data.shared_ts_row is None:
            return None
        t0 = self._transformer_overrides.get(0, self.transformers[0])
        t1 = self._transformer_overrides.get(1, self.transformers[1])
        if not isinstance(t0, PeriodicSamplesMapper) \
                or not isinstance(t1, AggregateMapReduce):
            return None
        from filodb_tpu.ops import pallas_fused as pf
        vals = data.values
        ndim = getattr(vals, "ndim", 0)
        is_hist = ndim == 3
        if ndim not in (2, 3) or t0.function_args or t1.params:
            return None
        if t0.window_ms is None:
            # instant-vector selector (`sum by (x) (metric)`): plain
            # lookback sampling IS last_over_time over the stale-lookback
            # window — the same normalization the general apply() does
            if t0.function is not None:
                return None
            t0 = dataclasses.replace(t0, window_ms=t0.lookback_ms,
                                     function="last_over_time")
        fn = t0.function or ""
        dense = data.dense
        if not pf.can_fuse(fn, t1.op, True, dense):
            return None
        if is_hist:
            # histogram buckets are counters too: flatten [S, T, B] into
            # S*B kernel rows with per-(group, bucket) slots — the hist
            # analogue (ref: HistogramQueryBenchmark's
            # sum(rate(..._bucket[5m])) + histogram_quantile)
            if fn not in ("rate", "increase") or t1.op != "sum" \
                    or data.bucket_les is None or not dense:
                return None
        # host-only fast paths: under the dense shared grid every series
        # has IDENTICAL per-window sample counts, so count_over_time and
        # the count aggregate are pure host math — no device work at all
        if dense and not is_hist and fn == "count_over_time":
            return self._fused_count_over_time(data, t0, t1)
        if dense and not is_hist and t1.op == "count":
            return self._fused_count_agg(data, t0, t1)
        wends = make_window_ends(t0.start_ms, t0.end_ms, t0.step_ms)
        eval_wends = wends - t0.offset_ms - data.base_ms
        if eval_wends.size == 0 or abs(eval_wends).max() >= (1 << 30):
            return None
        if fn in pf.MINMAX_FNS:
            # pure-XLA reduce_window path — any backend, no Pallas
            return self._fused_minmax(data, t0, t1, wends, eval_wends)
        import jax
        backend = jax.default_backend()
        interpret = backend != "tpu"
        if interpret and not os.environ.get("FILODB_TPU_FUSED_INTERPRET"):
            return None                 # kernel is MXU-targeted
        if fn in ("rate", "increase") and not data.precorrected:
            return None
        # VMEM guard, part 1 (group count not yet known — use the minimum):
        # very long ranges with many windows must take the general path,
        # not fail at kernel lowering
        Tp = pf._pad_to(vals.shape[1], pf._LANE)
        Wp = pf._pad_to(eval_wends.size, pf._LANE)
        over_time = t0.function in pf.OVER_TIME_FNS
        ragged_rate = not dense and fn in ("rate", "increase", "delta")
        if pf.vmem_estimate(Tp, Wp, 8, over_time,
                            ragged_rate) > pf.VMEM_BUDGET:
            return None
        from filodb_tpu.utils.metrics import registry
        # plan + prepared-input caches: a repeat query over an unchanged
        # snapshot (the dashboard-poll pattern) skips the selection-matrix
        # rebuild AND the full padded device copy (PreparedInputs contract)
        key = self._fused_cache_key
        plan = padded_vals = groups = gkeys = None
        if key is not None:
            plan_key = key[:3] + (t0.start_ms, t0.step_ms, t0.end_ms,
                                  t0.offset_ms, t0.window_ms, data.base_ms)
            with _FUSED_CACHE_LOCK:
                plan = _lru_touch(_FUSED_PLAN_CACHE, plan_key)
                padded_vals = _lru_touch(_FUSED_VALS_CACHE, key)
            groups, gkeys = _group_cache_lookup(key, t1.by, t1.without)
            if padded_vals is not None:
                registry.counter("leaf_fused_prep_hits").increment()
        if plan is None:
            plan = pf.build_plan(data.shared_ts_row.astype(np.int64),
                                 eval_wends, t0.window_ms)
            if key is not None:
                with _FUSED_CACHE_LOCK:
                    for k in [k for k in _FUSED_PLAN_CACHE
                              if k[0] == key[0] and k[1] != key[1]]:
                        del _FUSED_PLAN_CACHE[k]
                    _FUSED_PLAN_CACHE[plan_key] = plan
                    while len(_FUSED_PLAN_CACHE) > 8:
                        _FUSED_PLAN_CACHE.pop(next(iter(_FUSED_PLAN_CACHE)))
        if gkeys is None:
            gids, gkeys = _group_ids(data.keys, t1.by, t1.without)
        self._check_group_limit(gkeys)
        B = vals.shape[2] if is_hist else 1
        num_slots = len(gkeys) * B      # hist: one kernel group per (g, b)
        # VMEM guard, part 2: full estimate now that group count is known —
        # BEFORE the padded device copy, so diverted queries cost nothing
        if pf.vmem_estimate(Tp, Wp, max(num_slots, 8),
                            over_time, ragged_rate) > pf.VMEM_BUDGET:
            return None
        if padded_vals is None:
            vbase = data.vbase
            if is_hist:
                # [S, T, B] -> [S*B, T] rows (bucket-major within a series,
                # same layout PeriodicSamplesMapper flattens to)
                flat = jnp.moveaxis(jnp.asarray(vals), 2, 1) \
                    .reshape(vals.shape[0] * B, vals.shape[1])
                vb_flat = (np.zeros(flat.shape[0], np.float32)
                           if vbase is None
                           else jnp.asarray(vbase,
                                            jnp.float32).reshape(-1))
                padded_vals = pf.pad_values(flat, vb_flat, plan)
            else:
                if vbase is None:
                    vbase = np.zeros(vals.shape[0], np.float32)
                padded_vals = pf.pad_values(vals, vbase, plan)
            if key is not None:
                # a new snapshot generation obsoletes this mirror's older
                # entries — drop them NOW, not at LRU eviction: each pins a
                # full padded copy of the working set in HBM
                with _FUSED_CACHE_LOCK:
                    for k in [k for k in _FUSED_VALS_CACHE
                              if k[0] == key[0] and k[1] != key[1]]:
                        del _FUSED_VALS_CACHE[k]
                    _vals_cache_insert(key, padded_vals)
        if groups is None:
            if is_hist:
                gids_flat = (np.asarray(gids, np.int64)[:, None] * B
                             + np.arange(B)[None, :]).reshape(-1)
                groups = pf.pad_groups(gids_flat, vals.shape[0] * B,
                                       num_slots)
            else:
                groups = pf.pad_groups(gids, vals.shape[0], len(gkeys))
            _group_cache_insert(key, t1.by, t1.without, groups, gkeys)
        prep = pf.PreparedInputs(padded_vals.vals_p, padded_vals.vbase_p,
                                 groups.gids_p, groups.gsize)
        registry.counter("leaf_fused_kernel").increment()
        if not is_hist:
            # broadened matmul path: any fusable (fn, agg) combination,
            # ragged (validity-weighted) when the working set has NaN holes
            comp = pf.fused_leaf_agg(
                plan, prep, groups.gids_p[:vals.shape[0], 0],
                len(gkeys), fn, t1.op, precorrected=data.precorrected,
                interpret=interpret, ragged=not dense)
            return AggPartial(t1.op, gkeys, wends, comp=comp)
        sums, _counts = pf.fused_rate_groupsum(
            None, None, None, plan, num_slots, fn_name=t0.function,
            precorrected=data.precorrected, interpret=interpret,
            prepared=prep)
        G = len(gkeys)
        buckets = np.asarray(sums, np.float64) \
            .reshape(G, B, -1).transpose(0, 2, 1)           # [G, W, B]
        # series-per-group count: every bucket row of a series shares
        # presence under the dense gate, so any bucket slot's size IS
        # the group's series count (works on the group-cache hit path
        # too, where the raw gids were never recomputed)
        gsize = groups.gsize.reshape(G, B)[:, 0]
        cnt = gsize[:, None] * plan.wvalid[None, :].astype(np.float64)
        comp = np.concatenate([buckets, cnt[..., None]], axis=2)
        return AggPartial("hist_sum", gkeys, wends, comp=comp,
                          bucket_les=data.bucket_les)

    def args_str(self):
        fs = ",".join(str(f) for f in self.filters)
        return (f"dataset={self.dataset}, shard={self.shard}, "
                f"chunkMethod=TimeRangeChunkScan({self.chunk_start_ms},"
                f"{self.chunk_end_ms}), filters=[{fs}], colName={self.columns}")

    def _window_counts_groups(self, data, t0, t1):
        """Shared host math for the no-device fast paths: per-window
        sample counts on the dense shared grid + grouping."""
        wends = make_window_ends(t0.start_ms, t0.end_ms, t0.step_ms)
        eval_wends = wends - t0.offset_ms - data.base_ms
        if eval_wends.size == 0 or abs(eval_wends).max() >= (1 << 30):
            return None
        from filodb_tpu.ops import pallas_fused as pf
        gids, gkeys = _group_ids(data.keys, t1.by, t1.without)
        self._check_group_limit(gkeys)
        n = pf.window_counts(data.shared_ts_row.astype(np.int64),
                             eval_wends, t0.window_ms).astype(np.float64)
        gsize = np.bincount(np.asarray(gids),
                            minlength=len(gkeys))[:len(gkeys)]
        return wends, gkeys, n, gsize.astype(np.float64)

    def _fused_count_over_time(self, data, t0, t1):
        """agg by (count_over_time(...)): under the shared dense grid every
        series has IDENTICAL per-window sample counts, so the whole result
        is host math over (gsize, n) — no device work at all.  Handles all
        five fusable aggregates: each series' value at window w is n[w]."""
        r = self._window_counts_groups(data, t0, t1)
        if r is None:
            return None
        wends, gkeys, n, gsize = r
        valid = (n >= 1).astype(np.float64)
        op = t1.op
        if op in ("sum", "avg"):
            comp = np.stack([gsize[:, None] * n[None, :] * valid,
                             gsize[:, None] * valid[None, :]], axis=-1)
        elif op == "count":
            comp = (gsize[:, None] * valid[None, :])[..., None]
        else:                            # min/max: every series agrees on n
            absent = np.inf if op == "min" else -np.inf
            per = np.where(valid > 0, n, absent)
            comp = np.stack(
                [np.broadcast_to(per[None, :], (len(gkeys), len(n))),
                 gsize[:, None] * valid[None, :]], axis=-1)
        from filodb_tpu.utils.metrics import registry
        registry.counter("leaf_fused_count_host").increment()
        return AggPartial(op, gkeys, wends, comp=comp)

    def _fused_count_agg(self, data, t0, t1):
        """count by (fn(...)) on a dense shared grid: the count of series
        emitting a value at window w is gsize * 1{n[w] >= min_samples} —
        host math, no device work (the value itself never matters)."""
        r = self._window_counts_groups(data, t0, t1)
        if r is None:
            return None
        wends, gkeys, n, gsize = r
        minsamp = 2 if t0.function in ("rate", "increase", "delta") else 1
        valid = (n >= minsamp).astype(np.float64)
        from filodb_tpu.utils.metrics import registry
        registry.counter("leaf_fused_count_host").increment()
        comp = (gsize[:, None] * valid[None, :])[..., None]
        return AggPartial("count", gkeys, wends, comp=comp)

    def _fused_minmax(self, data, t0, t1, wends, eval_wends):
        """min/max_over_time + any aggregate in one jit via the XLA
        reduce_window path (ops/pallas_fused.fused_minmax_agg) — one HBM
        pass, no host round trip of the [S, T] working set, any backend.
        Requires uniform window geometry; else the general path runs."""
        from filodb_tpu.ops import pallas_fused as pf
        ts_row0 = np.asarray(data.shared_ts_row)
        real = ts_row0[ts_row0 < PAD_TS]
        geom = pf.uniform_window_geometry(real.astype(np.int64),
                                          eval_wends, t0.window_ms)
        if geom is None:
            return None
        f0, stride, width, t_needed = geom
        if t_needed > 2 * real.size:
            # a grid hanging FAR past the data (end=now long after the last
            # scrape) would pad more columns than the data itself — the
            # general path handles that without materializing the padding
            return None
        # grouping: reuse the shared per-working-set group cache (the same
        # per-series label hashing the kernel path caches away)
        key = self._fused_cache_key
        groups_c, gkeys = _group_cache_lookup(key, t1.by, t1.without)
        if gkeys is None:
            gids, gkeys = _group_ids(data.keys, t1.by, t1.without)
            self._check_group_limit(gkeys)      # reject BEFORE caching
            _group_cache_insert(key, t1.by, t1.without,
                                pf.pad_groups(gids, len(data.keys),
                                              len(gkeys)), gkeys)
        else:
            self._check_group_limit(gkeys)
            gids = np.asarray(groups_c.gids_p[:len(data.keys), 0])
        vb = data.vbase
        vals = jnp.asarray(data.values)
        ragged = not data.dense
        if t_needed > real.size:
            # windows hang past the data's right edge (end=now queries):
            # extend with NaN columns so the ragged variant masks them —
            # cached per (working set, t_needed): the dashboard-poll shape
            # would otherwise re-copy the whole set on device every refresh
            pad_key = None if key is None else key + ("minmax_pad",
                                                      t_needed)
            padded = None
            if pad_key is not None:
                with _FUSED_CACHE_LOCK:
                    padded = _lru_touch(_FUSED_MINMAX_PAD_CACHE, pad_key)
            if padded is None:
                padded = jnp.pad(vals[:, :real.size],
                                 ((0, 0), (0, t_needed - real.size)),
                                 constant_values=np.nan)
                if pad_key is not None:
                    with _FUSED_CACHE_LOCK:
                        for k in [k for k in _FUSED_MINMAX_PAD_CACHE
                                  if k[0] == pad_key[0]
                                  and k[1] != pad_key[1]]:
                            del _FUSED_MINMAX_PAD_CACHE[k]
                        _FUSED_MINMAX_PAD_CACHE[pad_key] = padded
                        while len(_FUSED_MINMAX_PAD_CACHE) > 2:
                            _FUSED_MINMAX_PAD_CACHE.pop(
                                next(iter(_FUSED_MINMAX_PAD_CACHE)))
            vals = padded
            ragged = True
        comp = pf.fused_minmax_agg(
            vals, None if vb is None else jnp.asarray(vb),
            jnp.asarray(gids, jnp.int32), f0, stride, width,
            int(eval_wends.size), t0.function, t1.op, len(gkeys),
            ragged=ragged)
        from filodb_tpu.utils.metrics import registry
        registry.counter("leaf_fused_minmax").increment()
        return AggPartial(t1.op, gkeys, wends,
                          comp=np.asarray(comp, np.float64))

    def _check_group_limit(self, gkeys) -> None:
        limit = self.ctx.planner_params.group_by_cardinality_limit
        if limit and len(gkeys) > limit:
            raise GroupCardinalityError(
                f"group-by cardinality limit {limit} exceeded "
                f"({len(gkeys)} groups)")

    def _do_execute(self, source) -> QueryResultLike:
        stats = QueryStats(shards_queried=1)
        shard = source.get_shard(self.dataset, self.shard)
        if shard is None:
            return None, stats
        lookup = shard.lookup_partitions(self.filters, self.chunk_start_ms,
                                         self.chunk_end_ms)
        schema_name = self.schema or lookup.first_schema
        if schema_name is None:
            return None, stats
        pids = lookup.pids_by_schema.get(schema_name)
        if pids is None or pids.size == 0:
            return None, stats
        store = shard.stores[schema_name]
        rows = shard.rows_for(pids)

        # Cap data scanned BEFORE materializing (or paging) the [S, T]
        # matrix — a pathological selector must fail fast, not OOM first
        # (ref: OnDemandPagingShard.scala:55 capDataScannedPerShardCheck,
        # ExecPlan.scala:139-180 enforcedLimits).  The estimate clips each
        # series to the query's chunk range assuming uniform spacing (the
        # reference estimates from chunk metadata the same way); checked
        # against the resident data before ODP and again after paging.
        limit = self.ctx.planner_params.scan_limit
        enforced = limit and self.ctx.planner_params.enforced_limits

        def _check_scan_cap(when: str):
            if not enforced:
                return
            to_scan = _estimate_scan(store, rows, self.chunk_start_ms,
                                     self.chunk_end_ms)
            if to_scan > limit:
                raise ValueError(
                    f"shard {self.shard}: query would scan ~{to_scan} "
                    f"samples ({when}), over the scan limit {limit} — "
                    f"narrow the filters or time range")

        _check_scan_cap("resident")
        shard.ensure_paged_pids(schema_name, pids,
                                self.chunk_start_ms, self.chunk_end_ms,
                                max_samples=limit if enforced else None)
        _check_scan_cap("after demand paging")
        schema = shard.schemas[schema_name]
        col_name = (self.columns[0] if self.columns
                    else schema.value_column)
        # schema-specific column + range-function substitution for the
        # downsample gauge schema: min_over_time reads the `min` column,
        # count_over_time becomes sum_over_time over `count`, etc.  Applied
        # as per-execution overrides so the plan stays reusable
        # (ref: MultiSchemaPartitionsExec.finalizePlan schema substitutions;
        # Schemas DS_GAUGE_FN_SUBSTITUTION)
        if schema.name == "ds-gauge" and not self.columns:
            from filodb_tpu.core.schemas import DS_GAUGE_FN_SUBSTITUTION
            for i, t in enumerate(self.transformers):
                if isinstance(t, PeriodicSamplesMapper):
                    sub = DS_GAUGE_FN_SUBSTITUTION.get(t.function)
                    if sub is not None:
                        col_name = sub[0]
                        if sub[1] != t.function:
                            self._transformer_overrides[i] = \
                                dataclasses.replace(t, function=sub[1])
                    break
        # counter semantics: counter-typed columns are reset-corrected in
        # f64 host-side (ops/counter.host_counter_correct) when the range
        # function has counter semantics, so post-rebase f32 deltas are
        # exact even across resets.  Non-counter functions on counter
        # columns (resets/delta/changes) need the RAW values and therefore
        # bypass the (pre-corrected) device mirror.
        col_def = next((c for c in schema.data_columns
                        if c.name == col_name), None)
        counter_col = col_def is not None and (col_def.detect_drops
                                               or col_def.counter)
        fn_is_counter = False
        for t in self.transformers:
            if isinstance(t, PeriodicSamplesMapper):
                spec = RANGE_FUNCTIONS.get(t.function or "")
                fn_is_counter = spec.is_counter if spec else False
                break
        # device-resident fast path: gather rows from the HBM mirror instead
        # of re-shipping the matrix every query (ref: block-memory working
        # set, BlockManager.scala; see core/devicecache.py)
        mirror = None
        if getattr(shard.config.store, "device_mirror_enabled", True) and (
                not counter_col or fn_is_counter):
            mirror = getattr(store, "device_mirror", None)
            if mirror is None:
                from filodb_tpu.core.devicecache import (
                    DEFAULT_HBM_LIMIT_BYTES, DeviceMirror)
                limit = getattr(shard.config.store,
                                "device_mirror_hbm_limit",
                                DEFAULT_HBM_LIMIT_BYTES)
                mirror = store.device_mirror = DeviceMirror(limit)
                _note_mirror_limit(limit)

        # Mirror refresh (a full host->device upload) runs at most once per
        # query, under the write lock so it can't race a mutation; the
        # subsequent row gather reads only the immutable device copy.  The
        # host fallback copies out under the seqlock so a concurrent
        # ingest/flush can't hand the kernel a torn matrix.
        mirrored = snap = None
        if mirror is not None:
            ok = mirror.is_fresh(store)
            if not ok:
                with shard._write_locked("mirror_refresh"):
                    ok = mirror.ensure_fresh(store)
            if ok:
                # one snapshot read serves gather AND fused-eligibility:
                # pairing a newer snapshot's grid with an older one's values
                # would feed the kernel zero-padded phantom columns
                snap = mirror.snapshot()
                mirrored = mirror.gather_cached(rows, snap)
        # value column selection: histograms gather [S, T, B]
        shared_ts_row = None
        dense = True
        if mirrored is not None:
            ts_off, dev_cols, dev_vbases, base = mirrored
            vals = dev_cols[col_name]
            vbase = dev_vbases.get(col_name)
            counts = shard.snapshot_read(store,
                                         lambda: store.counts[rows].copy())
            precorrected = counter_col   # mirror corrects counter columns
            shared_ts_row = mirror.fused_eligible(col_name, snap,
                                                  allow_ragged=True)
            # col_dense is grid-independent (counted cells finite; pads are
            # excluded via PAD_TS), so a non-shared grid with finite values
            # keeps the cheap slot-boundary rate path
            dense = mirror.col_dense(col_name, snap)
            if shared_ts_row is not None:
                # cache identity for the fused path's prepared-input reuse
                # (mirror.serial, not id(): ids are reused after GC; raw
                # rows bytes, not their hash: a collision would silently
                # serve another row-set's values)
                self._fused_cache_key = (mirror.serial, snap.gen, col_name,
                                         rows.tobytes())
        else:
            ts, cols, counts = shard.snapshot_read(
                store, lambda: store.gather_rows(rows))
            base = self.chunk_start_ms
            ts_off = to_offsets(ts, counts, base)
            # correct (f64) + rebase so counter deltas stay exact on chip
            precorrected = counter_col and fn_is_counter
            vals, vbase = counter_ops.rebase_values(cols[col_name],
                                                    precorrected)
            # NaN anywhere (staleness markers or ragged-length padding)
            # routes the rate family onto its valid-boundary variant
            dense = not bool(np.isnan(vals).any())
        keys = shard.keys_for(pids)
        stats.series_scanned = int(pids.size)
        stats.samples_scanned = int(counts.sum())
        les = store.bucket_les if vals.ndim == 3 else None
        return RawBlock(keys, ts_off, vals, base, les,
                        samples=stats.samples_scanned, vbase=vbase,
                        precorrected=precorrected,
                        shared_ts_row=shared_ts_row, dense=dense), stats


def _estimate_scan(store, rows: np.ndarray, start_ms: int,
                   end_ms: int) -> int:
    """Estimated samples in [start_ms, end_ms] across the given store rows,
    from per-series extents under a uniform-spacing assumption — O(S), no
    [S, T] materialization."""
    cnt = store.counts[rows].astype(np.int64)
    if store.ts.shape[1] == 0 or not cnt.any():
        return 0
    first = store.ts[rows, 0]
    last = store.ts[rows, np.maximum(cnt - 1, 0)]
    lo = np.maximum(first, start_ms)
    hi = np.minimum(last, end_ms)
    span = np.maximum(last - first, 1).astype(np.float64)
    frac = np.clip((hi - lo).astype(np.float64) / span, 0.0, 1.0)
    est = np.where((cnt > 0) & (hi >= lo), np.maximum(cnt * frac, 1.0), 0.0)
    return int(est.sum())


class EmptyResultExec(LeafExecPlan):
    """ref: exec/EmptyResultExec."""

    def _do_execute(self, source) -> QueryResultLike:
        return None, QueryStats()


class NonLeafExecPlan(ExecPlan):
    """Scatter-gather over children via their dispatchers
    (ref: ExecPlan.scala NonLeafExecPlan)."""

    def __init__(self, ctx: QueryContext, children: Sequence[ExecPlan]):
        super().__init__(ctx)
        self._children = list(children)

    @property
    def children(self) -> List[ExecPlan]:
        return self._children

    def _gather(self, source) -> Tuple[List[Data], QueryStats]:
        stats = QueryStats()
        results = []
        for c in self._children:
            data, st = c.dispatcher.dispatch(c, source)
            stats.merge(st)
            results.append(data)
        return results, stats

    def compose(self, results: List[Data], stats: QueryStats) -> Data:
        raise NotImplementedError

    def _do_execute(self, source) -> QueryResultLike:
        results, stats = self._gather(source)
        return self.compose(results, stats), stats


class DistConcatExec(NonLeafExecPlan):
    """Concatenate child results (ref: exec/DistConcatExec.scala)."""

    def compose(self, results, stats):
        blocks = [r for r in results if isinstance(r, ResultBlock)]
        raws = [r for r in results if isinstance(r, RawBlock)]
        if raws:
            # raw blocks concat only if same grid/base — planner guarantees.
            # Cross-shard bucket-scheme drift is resolved by rebucketing
            # every block onto the union scheme (HistogramBuckets.scala:340)
            les0 = raws[0].bucket_les
            if any((r.bucket_les is None) != (les0 is None) or (
                    les0 is not None and r.bucket_les is not None
                    and not np.array_equal(les0, r.bucket_les))
                   for r in raws[1:]):
                union = _union_scheme([r.bucket_les for r in raws])
                if union is None:
                    raise ValueError(
                        "cannot concat histogram blocks: some shards carry "
                        "no bucket boundaries")
                from filodb_tpu.memory.histogram import rebucket
                raws = [dataclasses.replace(
                            r,
                            values=rebucket(np.asarray(r.values),
                                            r.bucket_les, union),
                            vbase=(rebucket(np.asarray(r.vbase),
                                            r.bucket_les, union)
                                   if r.vbase is not None
                                   and np.asarray(r.vbase).ndim == 2
                                   else r.vbase),
                            bucket_les=union)
                        if not np.array_equal(r.bucket_les, union) else r
                        for r in raws]
                les0 = union
            keys = []
            for r in raws:
                keys.extend(r.keys)
            T = max(r.ts_off.shape[1] for r in raws)
            def pad(a, fill):
                out = np.full((a.shape[0], T) + a.shape[2:], fill, a.dtype)
                out[:, :a.shape[1]] = a
                return out
            from filodb_tpu.ops.timewindow import PAD_TS
            ts = np.concatenate([pad(r.ts_off, PAD_TS) for r in raws])
            vals = np.concatenate([pad(np.asarray(r.values), np.nan)
                                   for r in raws])
            vbase = None
            if any(r.vbase is not None for r in raws):
                vbase = np.concatenate([
                    np.asarray(r.vbase) if r.vbase is not None
                    else np.zeros(np.asarray(r.values).shape[:1]
                                  + np.asarray(r.values).shape[2:])
                    for r in raws])
            return RawBlock(keys, ts, vals, raws[0].base_ms,
                            raws[0].bucket_les,
                            samples=sum(r.samples for r in raws),
                            vbase=vbase,
                            precorrected=all(r.precorrected for r in raws),
                            # pad NaNs live at PAD_TS slots (excluded via
                            # ts), so raggedness merges as AND over blocks
                            dense=all(r.dense for r in raws))
        return concat_blocks(blocks)


class LocalPartitionDistConcatExec(DistConcatExec):
    """ref: exec/DistConcatExec.scala LocalPartitionDistConcatExec."""


class ReduceAggregateExec(NonLeafExecPlan):
    """Reduce phase across shards (ref: AggrOverRangeVectors.scala:51)."""

    def __init__(self, ctx, children, op: str, params: Tuple = ()):
        super().__init__(ctx, children)
        self.op = op
        self.params = params

    def args_str(self):
        return f"aggrOp={self.op}, aggrParams={list(self.params)}"

    def compose(self, results, stats):
        parts = [r for r in results if isinstance(r, AggPartial)]
        return reduce_partials(parts)


class BinaryJoinExec(NonLeafExecPlan):
    """Vector-vector join (ref: exec/BinaryJoinExec.scala:210).

    lhs children come first, then rhs children; the split index separates
    them (mirrors the reference's lhs/rhs Seq[ExecPlan]).
    """

    def __init__(self, ctx, lhs: Sequence[ExecPlan], rhs: Sequence[ExecPlan],
                 operator: str, cardinality: str = "OneToOne",
                 on: Optional[Tuple[str, ...]] = None,
                 ignoring: Tuple[str, ...] = (),
                 include: Tuple[str, ...] = (),
                 bool_modifier: bool = False):
        super().__init__(ctx, list(lhs) + list(rhs))
        self.n_lhs = len(lhs)
        self.operator = operator
        self.cardinality = cardinality
        self.on = tuple(on) if on is not None else None
        self.ignoring = tuple(ignoring)
        self.include = tuple(include)
        self.bool_modifier = bool_modifier

    def args_str(self):
        return (f"binaryOp={self.operator}, on={self.on}, "
                f"ignoring={list(self.ignoring)}")

    def _match_key(self, k: RangeVectorKey) -> RangeVectorKey:
        if self.on is not None:
            return k.only(self.on)
        drop = self.ignoring + ("_metric_", "__name__")
        return k.without(drop)

    def compose(self, results, stats):
        lhs_blocks = [r for r in results[:self.n_lhs] if isinstance(r, ResultBlock)]
        rhs_blocks = [r for r in results[self.n_lhs:] if isinstance(r, ResultBlock)]
        lhs = concat_blocks(lhs_blocks)
        rhs = concat_blocks(rhs_blocks)
        if lhs is None or rhs is None:
            return None
        many_side, one_side = lhs, rhs
        flip = False
        if self.cardinality == "OneToMany":
            many_side, one_side = rhs, lhs
            flip = True
        # index the "one" side by match key; duplicates are an error
        one_index: Dict[RangeVectorKey, int] = {}
        for i, k in enumerate(one_side.keys):
            mk = self._match_key(k)
            if mk in one_index:
                raise ValueError(
                    "many-to-many matching not allowed: duplicate series on "
                    f"'one' side for key {mk}")
            one_index[mk] = i
        card_limit = self.ctx.planner_params.join_cardinality_limit
        pairs: List[Tuple[int, int]] = []
        for i, k in enumerate(many_side.keys):
            j = one_index.get(self._match_key(k))
            if j is not None:
                pairs.append((i, j))
                if len(pairs) > card_limit:
                    raise ValueError(f"join cardinality limit {card_limit} exceeded")
        if self.cardinality == "OneToOne":
            seen: Dict[int, int] = {}
            for i, j in pairs:
                if j in seen:
                    raise ValueError("one-to-one join has many-to-one matches; "
                                     "use group_left/group_right")
                seen[j] = i
        if not pairs:
            return None
        mi = np.asarray([p[0] for p in pairs])
        oi = np.asarray([p[1] for p in pairs])
        mv = np.asarray(many_side.values)[mi]
        ov = np.asarray(one_side.values)[oi]
        a, b = (ov, mv) if flip else (mv, ov)   # a = query LHS values
        out = np.asarray(apply_binary_op(
            jnp.asarray(a), jnp.asarray(b), op=self.operator,
            bool_modifier=self.bool_modifier, keep_side="lhs"))
        keys = []
        for i, j in pairs:
            mk = many_side.keys[i]
            lbls = self._result_labels(mk, one_side.keys[j])
            keys.append(lbls)
        return ResultBlock(keys, many_side.wends, out)

    def _result_labels(self, many_key: RangeVectorKey,
                       one_key: RangeVectorKey) -> RangeVectorKey:
        if self.cardinality in ("ManyToOne", "OneToMany"):
            lbls = many_key.without(("_metric_", "__name__")).labels_dict
            if self.include:
                od = one_key.labels_dict
                for lbl in self.include:
                    if lbl in od:
                        lbls[lbl] = od[lbl]
                    else:
                        lbls.pop(lbl, None)
            return RangeVectorKey.make(lbls)
        if self.on is not None:
            return many_key.only(self.on)
        return many_key.without(self.ignoring + ("_metric_", "__name__"))


class SetOperatorExec(NonLeafExecPlan):
    """and/or/unless (ref: exec/SetOperatorExec.scala)."""

    def __init__(self, ctx, lhs: Sequence[ExecPlan], rhs: Sequence[ExecPlan],
                 operator: str, on: Optional[Tuple[str, ...]] = None,
                 ignoring: Tuple[str, ...] = ()):
        super().__init__(ctx, list(lhs) + list(rhs))
        self.n_lhs = len(lhs)
        self.operator = operator.lower()
        self.on = tuple(on) if on is not None else None
        self.ignoring = tuple(ignoring)

    def args_str(self):
        return f"binaryOp={self.operator}, on={self.on}, ignoring={list(self.ignoring)}"

    def _match_key(self, k: RangeVectorKey) -> RangeVectorKey:
        if self.on is not None:
            return k.only(self.on)
        return k.without(self.ignoring + ("_metric_", "__name__"))

    def _presence_by_key(self, block: ResultBlock) -> Dict[RangeVectorKey, np.ndarray]:
        """match-key -> [W] bool, True where any series with that key has a
        sample at the step."""
        vals = np.asarray(block.values)
        if vals.ndim == 3:                       # histogram block
            vals = vals[..., 0]
        present: Dict[RangeVectorKey, np.ndarray] = {}
        for i, k in enumerate(block.keys):
            mk = self._match_key(k)
            pres = ~np.isnan(vals[i])
            present[mk] = present.get(mk, False) | pres
        return present

    def compose(self, results, stats):
        lhs = concat_blocks([r for r in results[:self.n_lhs]
                             if isinstance(r, ResultBlock)])
        rhs = concat_blocks([r for r in results[self.n_lhs:]
                             if isinstance(r, ResultBlock)])
        op = self.operator
        if op == "and":
            if lhs is None or rhs is None:
                return None
            rhs_keys = {self._match_key(k) for k in rhs.keys}
            # per-step AND: lhs kept where rhs series present at that step
            rk_rows = self._presence_by_key(rhs)
            rows, outs = [], []
            lvals = np.asarray(lhs.values)
            for i, k in enumerate(lhs.keys):
                mk = self._match_key(k)
                if mk in rhs_keys:
                    rows.append(i)
                    outs.append(np.where(rk_rows[mk], lvals[i], np.nan))
            if not rows:
                return None
            return ResultBlock([lhs.keys[i] for i in rows], lhs.wends,
                               np.stack(outs))
        if op == "or":
            if lhs is None:
                return rhs
            if rhs is None:
                return lhs
            lvals = np.asarray(lhs.values)
            lhs_present = self._presence_by_key(lhs)
            keys = list(lhs.keys)
            vals = [lvals]
            rvals = np.asarray(rhs.values)
            extra_rows, extra_keys = [], []
            for i, k in enumerate(rhs.keys):
                mk = self._match_key(k)
                mask = lhs_present.get(mk)
                row = rvals[i]
                if mask is not None:
                    row = np.where(mask, np.nan, row)
                extra_rows.append(row)
                extra_keys.append(k)
            if extra_rows:
                keys = keys + extra_keys
                vals.append(np.stack(extra_rows))
            return ResultBlock(keys, lhs.wends, np.concatenate(vals))
        if op == "unless":
            if lhs is None:
                return None
            if rhs is None:
                return lhs
            rk_rows = self._presence_by_key(rhs)
            lvals = np.asarray(lhs.values)
            outs = []
            for i, k in enumerate(lhs.keys):
                mk = self._match_key(k)
                mask = rk_rows.get(mk)
                outs.append(np.where(mask, np.nan, lvals[i])
                            if mask is not None else lvals[i])
            return remove_nan_series(
                ResultBlock(list(lhs.keys), lhs.wends, np.stack(outs)))
        raise ValueError(op)


class SubqueryExec(NonLeafExecPlan):
    """Evaluate an outer range function over an inner periodic series
    (foo[5m:1m] with rate/max_over_time/... outside).  The inner child's
    step-grid samples are treated as raw samples for the outer window kernel
    (ref: exec/... subquery handling via PeriodicSamplesMapper on inner)."""

    def __init__(self, ctx, children, start_ms, step_ms, end_ms, function,
                 function_args, subquery_window_ms, subquery_step_ms,
                 offset_ms=0):
        super().__init__(ctx, children)
        self.start_ms, self.step_ms, self.end_ms = start_ms, step_ms, end_ms
        self.function = function
        self.function_args = tuple(function_args)
        self.subquery_window_ms = subquery_window_ms
        self.subquery_step_ms = subquery_step_ms
        self.offset_ms = offset_ms

    def args_str(self):
        return (f"function={self.function}, window={self.subquery_window_ms}, "
                f"step={self.subquery_step_ms}")

    def compose(self, results, stats):
        block = concat_blocks([r for r in results if isinstance(r, ResultBlock)])
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        if block is None:
            return _block_empty(wends)
        inner_ts = np.asarray(block.wends)
        base = int(inner_ts[0]) if len(inner_ts) else 0
        vals = np.asarray(block.values)
        S = vals.shape[0]
        ts_off = np.broadcast_to((inner_ts - base).astype(np.int32),
                                 (S, len(inner_ts))).copy()
        # NaN steps are absent samples; offsets stay valid (kernel masks NaN)
        eval_wends = (wends - self.offset_ms - base).astype(np.int32)
        out = np.asarray(evaluate_range_function(
            jnp.asarray(ts_off), jnp.asarray(vals), jnp.asarray(eval_wends),
            self.subquery_window_ms, self.function, self.function_args,
            base_ms=base, dense=not bool(np.isnan(vals).any())))
        return ResultBlock(block.keys, wends, out)


class StitchRvsExec(NonLeafExecPlan):
    """Merge same-key series evaluated over adjacent time ranges
    (ref: exec/StitchRvsExec.scala)."""

    def compose(self, results, stats):
        blocks = [r for r in results if isinstance(r, ResultBlock)]
        if not blocks:
            return None
        wends = np.unique(np.concatenate([b.wends for b in blocks]))
        merged: Dict[RangeVectorKey, np.ndarray] = {}
        for b in blocks:
            pos = np.searchsorted(wends, b.wends)
            vals = np.asarray(b.values)
            for i, k in enumerate(b.keys):
                row = merged.get(k)
                if row is None:
                    row = np.full(len(wends), np.nan)
                    merged[k] = row
                fill = vals[i]
                take = ~np.isnan(fill)
                row[pos[take]] = fill[take]
        keys = list(merged)
        return ResultBlock(keys, wends, np.stack([merged[k] for k in keys]))


# ------------------------------------------------------------- scalar execs


class TimeScalarGeneratorExec(LeafExecPlan):
    """time(), hour(), ... (ref: exec/TimeScalarGeneratorExec:84)."""

    def __init__(self, ctx, start_ms, step_ms, end_ms, function="time"):
        super().__init__(ctx)
        self.start_ms, self.step_ms, self.end_ms = start_ms, step_ms, end_ms
        self.function = function

    def args_str(self):
        return f"function={self.function}"

    def _do_execute(self, source) -> QueryResultLike:
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        secs = wends / 1000.0
        if self.function == "time":
            vals = secs
        else:
            # hour()/minute()/day_of_week()... on step timestamps: the date
            # INSTANT_FUNCTIONS already interpret values as epoch seconds
            vals = np.asarray(INSTANT_FUNCTIONS[self.function](jnp.asarray(secs)))
        return ScalarResult(wends, np.asarray(vals, dtype=float)), QueryStats()


class ScalarFixedDoubleExec(LeafExecPlan):
    """Literal scalar (ref: exec/ScalarFixedDoubleExec:76)."""

    def __init__(self, ctx, start_ms, step_ms, end_ms, value: float):
        super().__init__(ctx)
        self.start_ms, self.step_ms, self.end_ms = start_ms, step_ms, end_ms
        self.value = value

    def args_str(self):
        return f"value={self.value}"

    def _do_execute(self, source) -> QueryResultLike:
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        return ScalarResult(wends, np.full(len(wends), self.value)), QueryStats()


class ScalarBinaryOperationExec(LeafExecPlan):
    """scalar op scalar (ref: exec/ScalarBinaryOperationExec:72)."""

    def __init__(self, ctx, start_ms, step_ms, end_ms, operator, lhs, rhs):
        super().__init__(ctx)
        self.start_ms, self.step_ms, self.end_ms = start_ms, step_ms, end_ms
        self.operator = operator
        self.lhs = lhs          # float or ScalarBinaryOperationExec
        self.rhs = rhs

    def args_str(self):
        return f"operator={self.operator}"

    def _eval(self, x, source):
        if isinstance(x, ScalarBinaryOperationExec):
            return x._do_execute(source)[0].values
        return float(x)

    def _do_execute(self, source) -> QueryResultLike:
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        a = np.broadcast_to(self._eval(self.lhs, source), wends.shape).astype(float)
        b = np.broadcast_to(self._eval(self.rhs, source), wends.shape).astype(float)
        # scalar-scalar comparisons always behave as `bool` (PromQL requires it)
        out = np.asarray(apply_binary_op(
            jnp.asarray(a), jnp.asarray(b), op=self.operator,
            bool_modifier=True))
        return ScalarResult(wends, out), QueryStats()


# ----------------------------------------------------------- metadata execs


class SelectChunkInfosExec(LeafExecPlan):
    """Chunk-metadata debug plan: per-partition chunk infos (id, numRows,
    time range, bytes, per-column encodings) for the series a filter
    resolves to (ref: query/.../exec/SelectChunkInfosExec.scala:1-78 —
    id/NumRows/startTime/endTime/numBytes/readerKlazz).  Covers BOTH
    tiers: sealed chunks in the resident cache and the unsealed tail of
    the dense store (reported as encoding 'dense-unsealed')."""

    def __init__(self, ctx, dataset, shard, filters, start_ms, end_ms,
                 schema=None, col_name=None):
        super().__init__(ctx)
        self.dataset, self.shard = dataset, shard
        self.filters = list(filters)
        self.start_ms, self.end_ms = start_ms, end_ms
        self.schema = schema
        self.col_name = col_name

    def args_str(self):
        return (f"shard={self.shard}, chunkMethod=TimeRangeChunkScan("
                f"{self.start_ms},{self.end_ms}), "
                f"filters={[str(f) for f in self.filters]}, "
                f"col={self.col_name}")

    def _do_execute(self, source) -> QueryResultLike:
        shard = source.get_shard(self.dataset, self.shard)
        stats = QueryStats(shards_queried=1)
        if shard is None:
            return None, stats
        lookup = shard.lookup_partitions(self.filters, self.start_ms,
                                         self.end_ms)
        rows = []
        for schema_name, parts in lookup.parts_by_schema.items():
            if self.schema and schema_name != self.schema:
                continue
            store = shard.stores[schema_name]
            for p in parts:
                labels = {**p.part_key.tags_dict,
                          "_metric_": p.part_key.metric}
                chunks = [(cs, "resident") for cs in shard.resident.read(
                    p.part_id, self.start_ms, self.end_ms)]
                if not chunks:
                    # evicted / recovered partitions: the persisted tier
                    # still knows the chunk metadata
                    try:
                        chunks = [(cs, "persisted")
                                  for cs in shard.column_store.read_chunks(
                                      self.dataset, self.shard, p.part_key,
                                      self.start_ms, self.end_ms)]
                    except Exception:  # noqa: BLE001 — Null store etc.
                        chunks = []
                for cs, tier in chunks:
                    cols = {name: c.kind
                            for name, c in cs.columns.items()
                            if self.col_name in (None, name)}
                    rows.append({
                        **labels, "shard": self.shard, "partId": p.part_id,
                        "chunkId": cs.info.chunk_id,
                        "numRows": cs.info.num_rows,
                        "startTime": cs.info.start_time_ms,
                        "endTime": cs.info.end_time_ms,
                        "numBytes": cs.nbytes,
                        "ingestionTime": cs.info.ingestion_time_ms,
                        "encodings": cols, "tier": tier})
                # the unsealed dense-store tail is one writable chunk
                cnt = int(store.counts[p.row])
                sealed = int(store.sealed[p.row])
                if cnt > sealed:
                    ts_row = store.ts[p.row, sealed:cnt]
                    t0, t1 = int(ts_row[0]), int(ts_row[-1])
                    if t1 >= self.start_ms and t0 <= self.end_ms:
                        per_cell = sum(
                            (arr.dtype.itemsize
                             * (arr.shape[2] if arr.ndim == 3 else 1))
                            for name, arr in store.cols.items()
                            if arr is not None
                            and self.col_name in (None, name)) + 8
                        rows.append({
                            **labels, "shard": self.shard,
                            "partId": p.part_id, "chunkId": -1,
                            "numRows": cnt - sealed,
                            "startTime": t0, "endTime": t1,
                            "numBytes": (cnt - sealed) * per_cell,
                            "ingestionTime": -1,
                            "encodings": {"*": "dense-unsealed"},
                            "tier": "dense"})
        stats.series_scanned = sum(
            len(v) for v in lookup.parts_by_schema.values())
        return QueryResult([], stats, data=rows), stats


class PartKeysExec(LeafExecPlan):
    """Series-key metadata query (ref: exec/MetadataExecPlan.scala)."""

    def __init__(self, ctx, dataset, shard, filters, start_ms, end_ms):
        super().__init__(ctx)
        self.dataset, self.shard = dataset, shard
        self.filters = list(filters)
        self.start_ms, self.end_ms = start_ms, end_ms

    def args_str(self):
        return f"shard={self.shard}, filters={[str(f) for f in self.filters]}"

    def _do_execute(self, source) -> QueryResultLike:
        shard = source.get_shard(self.dataset, self.shard)
        stats = QueryStats(shards_queried=1)
        if shard is None:
            return None, stats
        res = shard.lookup_partitions(self.filters, self.start_ms, self.end_ms)
        keys = []
        for parts in res.parts_by_schema.values():
            for p in parts:
                keys.append({**p.part_key.tags_dict,
                             "_metric_": p.part_key.metric})
        data = QueryResult([], stats, data=keys)
        return data, stats


class LabelValuesExec(LeafExecPlan):
    """ref: exec/MetadataExecPlan.scala LabelValuesExec."""

    def __init__(self, ctx, dataset, shard, filters, labels, start_ms, end_ms):
        super().__init__(ctx)
        self.dataset, self.shard = dataset, shard
        self.filters = list(filters)
        self.labels = list(labels)
        self.start_ms, self.end_ms = start_ms, end_ms

    def args_str(self):
        return f"shard={self.shard}, labels={self.labels}"

    def _do_execute(self, source) -> QueryResultLike:
        shard = source.get_shard(self.dataset, self.shard)
        stats = QueryStats(shards_queried=1)
        if shard is None:
            return None, stats
        if not self.labels:        # LabelNames query (ref: LabelNamesExec)
            return QueryResult([], stats,
                               data=shard.index.label_names(self.filters)), stats
        out: Dict[str, List[str]] = {}
        for lbl in self.labels:
            out[lbl] = shard.index.label_values(lbl, self.filters or None)
        return QueryResult([], stats, data=out), stats


def _canon(x):
    """Hashable canonical form for metadata dedup (str or label dict)."""
    return tuple(sorted(x.items())) if isinstance(x, dict) else x


class MetadataMergeExec(NonLeafExecPlan):
    """Merge metadata results across shards."""

    def compose(self, results, stats):
        merged = None
        for r in results:
            if not isinstance(r, QueryResult) or r.data is None:
                continue
            if merged is None:
                merged = list(r.data) if isinstance(r.data, list) else r.data
                if isinstance(merged, list):
                    seen = {_canon(x) for x in merged}
            elif isinstance(merged, list):
                for x in r.data:
                    c = _canon(x)
                    if c not in seen:
                        seen.add(c)
                        merged.append(x)
            elif isinstance(merged, dict):
                for k, v in r.data.items():
                    vals = set(merged.get(k, [])) | set(v)
                    merged[k] = sorted(vals)
        return QueryResult([], stats, data=merged)
