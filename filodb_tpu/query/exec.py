"""ExecPlan — the distributed execution tree (facade).

Mirrors the reference's exec framework (ref: query/.../exec/ExecPlan.scala:41,
RangeVectorTransformer.scala:36, AggrOverRangeVectors.scala, BinaryJoinExec.scala,
DistConcatExec.scala, StitchRvsExec.scala) with a TPU-first data plane:

  - Leaves gather a shard's matching series into ONE dense [S, T] batch
    (RawBlock) instead of per-partition iterators.
  - PeriodicSamplesMapper runs the fused window kernel (ops/rangefns.py) on
    device producing a step-grid ResultBlock [S, W].
  - AggregateMapReduce emits mesh-reducible partial components; the
    map/reduce/present 3-phase contract is identical to the reference
    (doc/query-engine.md:311-330) so partials can ride psum collectives.

Dispatchers decouple tree topology from placement: InProcessPlanDispatcher
runs a subtree inline; the cluster layer adds remote dispatch.

Round 4: the implementation lives in execbase / transformers / leafexec /
nonleaf / metaexec (each under 800 LoC); this module re-exports every name
so existing import paths keep working.
"""
from filodb_tpu.query.execbase import (  # noqa: F401
    AggPartial, AnalyzeRecorder, Data, EmptyResultExec, ExecPlan,
    GroupCardinalityError, LazyKeys, QueryError,
    InProcessPlanDispatcher, LeafExecPlan, NonLeafExecPlan, PlanDispatcher,
    QueryResultLike, RawBlock, ScalarResult, _FUSED_CACHE_LOCK,
    _FUSED_GROUP_CACHE, _FUSED_MINMAX_PAD_CACHE, _FUSED_PLAN_CACHE,
    _FUSED_VALS_CACHE,
    _align_hist_schemes, _block_empty, _fused_vals_budget,
    _group_cache_insert, _group_cache_lookup, _lru_touch,
    _note_mirror_limit, _union_scheme, _vals_cache_insert, _vals_nbytes,
    present_partial, reduce_partials)
from filodb_tpu.query.transformers import (  # noqa: F401
    AbsentFunctionMapper, AggregateMapReduce, AggregatePresenter,
    InstantVectorFunctionMapper, LimitFunctionMapper,
    MiscellaneousFunctionMapper, PeriodicSamplesMapper,
    RangeVectorTransformer, RepeatToGridMapper, ScalarFunctionMapper,
    ScalarOperationMapper, SortFunctionMapper, VectorFunctionMapper,
    _CANDIDATE_OPS, _dollar_to_backslash, _group_ids)
from filodb_tpu.query.leafexec import (  # noqa: F401
    MultiSchemaPartitionsExec, SelectPersistedSegmentsExec,
    ScalarBinaryOperationExec,
    ScalarFixedDoubleExec, TimeScalarGeneratorExec, _estimate_scan)
from filodb_tpu.query.nonleaf import (  # noqa: F401
    BinaryJoinExec, DistConcatExec, LocalPartitionDistConcatExec,
    ReduceAggregateExec, RemoteAggregateExec, SetOperatorExec,
    StitchRvsExec, SubqueryExec)
from filodb_tpu.query.metaexec import (  # noqa: F401
    LabelValuesExec, MetadataMergeExec, PartKeysExec, SelectChunkInfosExec,
    _canon)
from filodb_tpu.query.rangevector import (  # noqa: F401 — the original
    # module re-exported these transitively; keep import-path compat
    QueryContext, QueryResult, QueryStats, RangeVectorKey, ResultBlock,
    concat_blocks, remove_nan_series)
from filodb_tpu.core.index import ColumnFilter, Equals  # noqa: F401
from filodb_tpu.ops.timewindow import (  # noqa: F401
    PAD_TS, make_window_ends, to_offsets)
