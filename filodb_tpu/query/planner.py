"""SingleClusterPlanner — materializes LogicalPlan into a distributed ExecPlan.

ref: coordinator/.../queryplanner/SingleClusterPlanner.scala:39-117:
  - shard set from shard-key filters (_ws_/_ns_/_metric_) via
    shardKeyHash + spread -> ShardMapper.queryShards
  - one leaf MultiSchemaPartitionsExec per shard, transformers pushed down
    to leaves (PeriodicSamplesMapper, AggregateMapReduce)
  - cross-shard composition: LocalPartitionDistConcatExec or
    ReduceAggregateExec (+ AggregatePresenter at the root)
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from filodb_tpu.core.index import ColumnFilter, Equals
from filodb_tpu.core.partkey import strip_metric_suffix, PartKey
from filodb_tpu.core.schemas import PartitionSchema
from filodb_tpu.parallel.shardmapper import ShardMapper, SpreadProvider
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec import (AbsentFunctionMapper, AggregateMapReduce,
                                   AggregatePresenter, BinaryJoinExec,
                                   DistConcatExec, EmptyResultExec, ExecPlan,
                                   InstantVectorFunctionMapper,
                                   LabelValuesExec, LimitFunctionMapper,
                                   LocalPartitionDistConcatExec,
                                   MetadataMergeExec,
                                   MiscellaneousFunctionMapper,
                                   MultiSchemaPartitionsExec, PartKeysExec,
                                   PeriodicSamplesMapper, PlanDispatcher,
                                   ReduceAggregateExec, ScalarBinaryOperationExec,
                                   ScalarFixedDoubleExec, ScalarFunctionMapper,
                                   ScalarOperationMapper, ScalarResult,
                                   SetOperatorExec, SortFunctionMapper,
                                   StitchRvsExec, TimeScalarGeneratorExec,
                                   VectorFunctionMapper)
from filodb_tpu.query.rangevector import QueryContext

SET_OPERATORS = ("and", "or", "unless")


class QueryPlanner:
    """ref: queryplanner/QueryPlanner.scala:41."""

    def materialize(self, plan: lp.LogicalPlan, ctx: QueryContext) -> ExecPlan:
        raise NotImplementedError


class SingleClusterPlanner(QueryPlanner):

    def __init__(self, dataset: str, shard_mapper: ShardMapper,
                 spread_provider: Optional[SpreadProvider] = None,
                 part_schema: Optional[PartitionSchema] = None,
                 dispatcher_factory: Optional[Callable[[int], PlanDispatcher]] = None,
                 stale_lookback_ms: int = 5 * 60 * 1000):
        self.dataset = dataset
        self.shard_mapper = shard_mapper
        self.spread_provider = spread_provider or SpreadProvider()
        self.part_schema = part_schema or PartitionSchema()
        self.dispatcher_factory = dispatcher_factory
        self.stale_lookback_ms = stale_lookback_ms

    # ------------------------------------------------------------ shard calc

    def shards_from_filters(self, filters: Sequence[ColumnFilter],
                            ctx: QueryContext) -> List[int]:
        """ref: SingleClusterPlanner.shardsFromFilters:55-62."""
        if ctx.planner_params.shard_overrides:
            return list(ctx.planner_params.shard_overrides)
        eq = {f.column: f.value for f in filters if isinstance(f, Equals)}
        opts = self.part_schema.options
        shard_key: Dict[str, str] = {}
        for col in opts.shard_key_columns:
            if col in ("_metric_", "__name__"):
                metric = eq.get("_metric_") or eq.get("__name__")
                if metric is None:
                    return self.shard_mapper.all_shards()
                shard_key[col] = strip_metric_suffix(metric, self.part_schema)
            else:
                v = eq.get(col)
                if v is None:
                    return self.shard_mapper.all_shards()
                shard_key[col] = v
        spread = self.spread_provider.spread_for(shard_key)
        pk = PartKey(shard_key.get("_metric_", ""),
                     tuple(sorted((k, v) for k, v in shard_key.items()
                                  if k not in ("_metric_", "__name__"))))
        h = pk.shard_key_hash(self.part_schema)
        return self.shard_mapper.query_shards(h, spread)

    def _dispatcher(self, shard: int) -> Optional[PlanDispatcher]:
        if self.dispatcher_factory is not None:
            return self.dispatcher_factory(shard)
        return None

    # ----------------------------------------------------------- materialize

    def materialize(self, plan: lp.LogicalPlan, ctx: QueryContext) -> ExecPlan:
        # instant-vector timestamp() windows resolve to THIS planner's
        # configured lookback, not the parser's compile-time default
        plan = lp.resolve_lookback_windows(plan, self.stale_lookback_ms)
        out = self._walk(plan, ctx)
        if isinstance(out, list):
            if len(out) == 1:
                return out[0]
            return LocalPartitionDistConcatExec(ctx, out)
        return out

    def _leaves(self, plan, ctx) -> List[ExecPlan]:
        """Materialize to a list of per-shard plans (not yet concatenated)."""
        out = self._walk(plan, ctx)
        return out if isinstance(out, list) else [out]

    def _walk(self, plan: lp.LogicalPlan, ctx: QueryContext):
        m = getattr(self, "_m_" + type(plan).__name__, None)
        if m is None:
            raise ValueError(f"cannot materialize {type(plan).__name__}")
        return m(plan, ctx)

    # raw + periodic ----------------------------------------------------------

    def _m_RawSeries(self, p: lp.RawSeries, ctx: QueryContext) -> List[ExecPlan]:
        candidates = self.shards_from_filters(p.filters, ctx)
        shards = self.shard_mapper.active_shards(candidates) or candidates
        plans = [MultiSchemaPartitionsExec(
            ctx, self.dataset, s, p.filters,
            p.range_selector.from_ms, p.range_selector.to_ms,
            columns=p.columns) for s in shards]
        return self._with_dispatcher(plans, shards)

    def _m_PeriodicSeries(self, p: lp.PeriodicSeries, ctx: QueryContext):
        lookback = p.raw_series.lookback_ms or self.stale_lookback_ms
        offset = p.offset_ms or 0
        raw = lp.RawSeries(
            lp.IntervalSelector(p.start_ms - lookback - offset,
                                p.end_ms - offset),
            p.raw_series.filters, p.raw_series.columns,
            p.raw_series.lookback_ms, p.raw_series.offset_ms)
        leaves = self._m_RawSeries(raw, ctx)
        for leaf in leaves:
            leaf.add_transformer(PeriodicSamplesMapper(
                p.start_ms, p.step_ms, p.end_ms, None, None, (),
                offset_ms=offset, lookback_ms=lookback))
        return leaves

    def _m_PeriodicSeriesWithWindowing(self, p: lp.PeriodicSeriesWithWindowing,
                                       ctx: QueryContext):
        offset = p.offset_ms or 0
        raw = lp.RawSeries(
            lp.IntervalSelector(p.start_ms - p.window_ms - offset,
                                p.end_ms - offset),
            p.series.filters, p.series.columns,
            p.series.lookback_ms, p.series.offset_ms)
        leaves = self._m_RawSeries(raw, ctx)
        for leaf in leaves:
            leaf.add_transformer(PeriodicSamplesMapper(
                p.start_ms, p.step_ms, p.end_ms, p.window_ms, p.function,
                tuple(p.function_args), offset_ms=offset,
                lookback_ms=self.stale_lookback_ms))
        return leaves

    def _m_ApplyAtTimestamp(self, p: lp.ApplyAtTimestamp, ctx: QueryContext):
        from filodb_tpu.query.exec import RepeatToGridMapper
        out = self._walk(p.inner, ctx)
        if not p.repeat:                 # matrix-valued pins (subqueries)
            return out
        mapper = RepeatToGridMapper(p.start_ms, p.step_ms, p.end_ms)
        if isinstance(out, list):
            for leaf in out:
                leaf.add_transformer(mapper)
            return out
        out.add_transformer(mapper)
        return out

    # subqueries --------------------------------------------------------------

    def _m_TopLevelSubquery(self, p: lp.TopLevelSubquery, ctx: QueryContext):
        return self._walk(p.inner, ctx)

    def _m_SubqueryWithWindowing(self, p: lp.SubqueryWithWindowing,
                                 ctx: QueryContext):
        from filodb_tpu.query.exec import SubqueryExec
        inner = self.materialize(p.inner, ctx)
        return SubqueryExec(ctx, [inner], p.start_ms, p.step_ms, p.end_ms,
                            p.function, tuple(p.function_args),
                            p.subquery_window_ms, p.subquery_step_ms,
                            p.offset_ms or 0)

    # aggregates --------------------------------------------------------------

    def _m_Aggregate(self, p: lp.Aggregate, ctx: QueryContext) -> ExecPlan:
        from filodb_tpu.query.exec import InProcessPlanDispatcher
        from filodb_tpu.query.pushdown import plan_aggregate_pushdown
        children = self._leaves(p.vectors, ctx)
        ship_raw = bool(getattr(ctx.planner_params, "ship_raw_series",
                                False))
        for c in children:
            # every child keeps its leaf-side map phase (the pre-pushdown
            # contract: per-shard dispatches reply with [G, W] partials,
            # so aggregation_pushdown=false restores exactly today's
            # path).  The one exception is the bench-only ship_raw_series
            # strawman, which forces remote leaves to reply with FULL
            # per-series blocks so bench.py distexec can measure the
            # ship-everything wire cost; the map then runs on the
            # coordinator (ReduceAggregateExec.compose).  Local children
            # always map in place — there is no wire to win by hoisting.
            if not ship_raw or isinstance(c.dispatcher,
                                          InProcessPlanDispatcher):
                c.add_transformer(AggregateMapReduce(
                    p.operator, tuple(p.params), tuple(p.by),
                    tuple(p.without)))
        # node-level pushdown (query/pushdown.py): same-node map subtrees
        # collapse into RemoteAggregateExec groups whose reduce runs ON
        # the data node — only a [G, W] partial per NODE crosses the wire
        children, not_pushable = plan_aggregate_pushdown(
            children, p.operator, tuple(p.params), ctx)
        reducer = ReduceAggregateExec(ctx, children, p.operator,
                                      tuple(p.params), by=tuple(p.by),
                                      without=tuple(p.without))
        if not_pushable:
            reducer.pushdown_not_pushable = not_pushable
        reducer.add_transformer(AggregatePresenter(p.operator, tuple(p.params)))
        return reducer

    # joins -------------------------------------------------------------------

    def _m_BinaryJoin(self, p: lp.BinaryJoin, ctx: QueryContext) -> ExecPlan:
        lhs = self._leaves(p.lhs, ctx)
        rhs = self._leaves(p.rhs, ctx)
        op = p.operator[:-5] if p.operator.endswith("_bool") else p.operator
        bool_mod = p.operator.endswith("_bool")
        if op.lower() in SET_OPERATORS:
            return SetOperatorExec(ctx, lhs, rhs, op.lower(),
                                   on=p.on, ignoring=p.ignoring)
        return BinaryJoinExec(ctx, lhs, rhs, op, p.cardinality,
                              on=p.on, ignoring=p.ignoring, include=p.include,
                              bool_modifier=bool_mod)

    def _m_ScalarVectorBinaryOperation(self, p: lp.ScalarVectorBinaryOperation,
                                       ctx: QueryContext) -> ExecPlan:
        vec = self.materialize(p.vector, ctx)
        op = p.operator[:-5] if p.operator.endswith("_bool") else p.operator
        bool_mod = p.operator.endswith("_bool")
        scalar_exec = self.materialize(p.scalar_arg, ctx)
        # fixed scalars fold to a float; varying scalars execute separately
        if isinstance(scalar_exec, ScalarFixedDoubleExec):
            scalar: object = scalar_exec.value
        else:
            scalar = _DeferredScalar(scalar_exec)
        vec.add_transformer(ScalarOperationMapper(
            op, scalar, scalar_is_lhs=p.scalar_is_lhs, bool_modifier=bool_mod))
        return vec

    # functions ---------------------------------------------------------------

    def _m_ApplyInstantFunction(self, p: lp.ApplyInstantFunction,
                                ctx: QueryContext) -> ExecPlan:
        child = self.materialize(p.vectors, ctx)
        args = tuple(self._fold_scalar(a, ctx) for a in p.function_args)
        child.add_transformer(InstantVectorFunctionMapper(p.function, args))
        return child

    def _m_ApplyMiscellaneousFunction(self, p, ctx) -> ExecPlan:
        child = self.materialize(p.vectors, ctx)
        child.add_transformer(MiscellaneousFunctionMapper(
            p.function, tuple(p.string_args)))
        return child

    def _m_ApplySortFunction(self, p, ctx) -> ExecPlan:
        child = self.materialize(p.vectors, ctx)
        child.add_transformer(SortFunctionMapper(p.function == "sort_desc"))
        return child

    def _m_ApplyAbsentFunction(self, p: lp.ApplyAbsentFunction, ctx) -> ExecPlan:
        child = self.materialize(p.vectors, ctx)
        child.add_transformer(AbsentFunctionMapper(
            tuple(p.filters), p.start_ms, p.step_ms, p.end_ms))
        return child

    def _m_ApplyLimitFunction(self, p, ctx) -> ExecPlan:
        child = self.materialize(p.vectors, ctx)
        child.add_transformer(LimitFunctionMapper(p.limit))
        return child

    # scalars -----------------------------------------------------------------

    def _m_ScalarTimeBasedPlan(self, p: lp.ScalarTimeBasedPlan, ctx) -> ExecPlan:
        return TimeScalarGeneratorExec(ctx, p.start_ms, p.step_ms, p.end_ms,
                                       p.function)

    def _m_ScalarFixedDoublePlan(self, p: lp.ScalarFixedDoublePlan, ctx):
        return ScalarFixedDoubleExec(ctx, p.start_ms, p.step_ms, p.end_ms,
                                     p.scalar)

    def _m_ScalarVaryingDoublePlan(self, p: lp.ScalarVaryingDoublePlan, ctx):
        child = self.materialize(p.vectors, ctx)
        child.add_transformer(ScalarFunctionMapper())
        return child

    def _m_ScalarBinaryOperation(self, p: lp.ScalarBinaryOperation, ctx):
        def conv(x):
            if isinstance(x, lp.ScalarBinaryOperation):
                return ScalarBinaryOperationExec(
                    ctx, x.start_ms, x.step_ms, x.end_ms, x.operator,
                    conv(x.lhs), conv(x.rhs))
            return float(x)
        return ScalarBinaryOperationExec(ctx, p.start_ms, p.step_ms, p.end_ms,
                                         p.operator, conv(p.lhs), conv(p.rhs))

    def _m_VectorPlan(self, p: lp.VectorPlan, ctx) -> ExecPlan:
        child = self.materialize(p.scalars, ctx)
        child.add_transformer(VectorFunctionMapper())
        return child

    def _fold_scalar(self, arg, ctx):
        if isinstance(arg, lp.ScalarFixedDoublePlan):
            return arg.scalar
        if isinstance(arg, lp.LogicalPlan):
            return _DeferredScalar(self.materialize(arg, ctx))
        return arg

    # metadata ----------------------------------------------------------------

    def _with_dispatcher(self, plans: List[ExecPlan],
                         shards: Sequence[int]) -> List[ExecPlan]:
        for e, s in zip(plans, shards):
            d = self._dispatcher(s)
            if d is not None:
                e.dispatcher = d
        return plans

    def _m_LabelValues(self, p: lp.LabelValues, ctx) -> ExecPlan:
        shards = self.shard_mapper.all_shards()
        children = [LabelValuesExec(ctx, self.dataset, s, p.filters,
                                    p.label_names, p.start_ms, p.end_ms)
                    for s in shards]
        return MetadataMergeExec(ctx, self._with_dispatcher(children, shards))

    def _m_LabelNames(self, p: lp.LabelNames, ctx) -> ExecPlan:
        shards = self.shard_mapper.all_shards()
        children = [LabelValuesExec(ctx, self.dataset, s, p.filters,
                                    [], p.start_ms, p.end_ms)
                    for s in shards]
        return MetadataMergeExec(ctx, self._with_dispatcher(children, shards))

    def _m_SeriesKeysByFilters(self, p: lp.SeriesKeysByFilters, ctx) -> ExecPlan:
        shards = self.shards_from_filters(p.filters, ctx)
        children = [PartKeysExec(ctx, self.dataset, s, p.filters,
                                 p.start_ms, p.end_ms) for s in shards]
        return MetadataMergeExec(ctx, self._with_dispatcher(children, shards))


class _DeferredScalar:
    """Scalar subplan evaluated lazily at transformer-apply time.  Wraps the
    exec plan; resolved by ScalarOperationMapper/InstantVectorFunctionMapper
    via duck-typed `.values` after execution."""

    def __init__(self, plan: ExecPlan):
        self.plan = plan
        self._result: Optional[ScalarResult] = None

    def resolve(self, source) -> ScalarResult:
        if self._result is None:
            data, _ = self.plan.execute_internal(source)
            assert isinstance(data, ScalarResult)
            self._result = data
        return self._result
