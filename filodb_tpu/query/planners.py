"""Composed planner hierarchy above SingleClusterPlanner.

Mirrors the reference's coordinator/queryplanner stack:
  - LongTimeRangePlanner — raw vs downsample cluster split + stitch
    (ref: queryplanner/LongTimeRangePlanner.scala:27-40)
  - HighAvailabilityPlanner + FailureProvider — failure-window routing to a
    remote replica over PromQL HTTP (ref: HighAvailabilityPlanner.scala:22,
    FailureProvider.scala:45, FailureRoutingStrategy.scala)
  - MultiPartitionPlanner + PartitionLocationProvider — federation across
    independent FiloDB partitions (ref: MultiPartitionPlanner.scala:12-52)
  - SinglePartitionPlanner — per-metric planner selection
    (ref: SinglePartitionPlanner.scala)
  - ShardKeyRegexPlanner — fan-out of regex/multi-valued shard keys
    (ref: ShardKeyRegexPlanner.scala)

All remote hops go through PromQlRemoteExec with an injectable transport, so
tests run without a network (the reference stubs sttp the same way).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.core.index import ColumnFilter, Equals, EqualsRegex, In
from filodb_tpu.query import logical as lp
from filodb_tpu.query import planutils as pu
from filodb_tpu.query.exec import (DistConcatExec, ExecPlan, LeafExecPlan,
                                   NonLeafExecPlan, StitchRvsExec)
from filodb_tpu.query.planner import QueryPlanner, SingleClusterPlanner
from filodb_tpu.query.planutils import TimeRange
from filodb_tpu.query.rangevector import (QueryContext, QueryStats,
                                          RangeVectorKey, ResultBlock)

# ------------------------------------------------------------- remote exec


class PromQlRemoteExec(LeafExecPlan):
    """Dispatch a PromQL query to a remote cluster over HTTP
    (ref: exec/PromQlRemoteExec.scala:247).

    `transport(endpoint, params) -> prom-matrix-json` is injectable; the
    default uses urllib at execute time.  Params mirror the reference's
    PromQlQueryParams (query/start/step/end in seconds).
    """

    def __init__(self, ctx: QueryContext, endpoint: str, promql: str,
                 start_ms: int, step_ms: int, end_ms: int,
                 transport: Optional[Callable] = None):
        super().__init__(ctx)
        self.endpoint = endpoint
        self.promql = promql
        self.start_ms, self.step_ms, self.end_ms = start_ms, step_ms, end_ms
        self.transport = transport or _http_transport

    def args_str(self) -> str:
        return (f"endpoint={self.endpoint}, promql={self.promql}, "
                f"start={self.start_ms}, step={self.step_ms}, "
                f"end={self.end_ms}")

    def _do_execute(self, source):
        params = {"query": self.promql, "start": self.start_ms // 1000,
                  "step": max(self.step_ms // 1000, 1),
                  "end": self.end_ms // 1000}
        payload = self.transport(self.endpoint, params)
        return _matrix_json_to_block(payload), QueryStats()


def _http_transport(endpoint: str, params: Dict) -> Dict:
    import json
    import urllib.parse
    import urllib.request
    url = endpoint + "?" + urllib.parse.urlencode(params)
    with urllib.request.urlopen(url, timeout=60) as r:
        return json.loads(r.read())


def _matrix_json_to_block(payload: Dict) -> Optional[ResultBlock]:
    """Prometheus matrix JSON → dense ResultBlock (NaN-padded grid union)."""
    result = (payload.get("data") or {}).get("result") or []
    if not result:
        return None
    all_ts = sorted({int(t * 1000) for series in result
                     for t, _ in series.get("values", [])})
    if not all_ts:
        return None
    wends = np.asarray(all_ts, dtype=np.int64)
    keys, rows = [], []
    for series in result:
        keys.append(RangeVectorKey.make(series.get("metric", {})))
        row = np.full(len(wends), np.nan)
        for t, v in series.get("values", []):
            row[np.searchsorted(wends, int(t * 1000))] = float(v)
        rows.append(row)
    return ResultBlock(keys, wends, np.stack(rows))


# --------------------------------------------------------- long time range


class LongTimeRangePlanner(QueryPlanner):
    """Route recent ranges to the raw cluster, old ranges to the downsample
    cluster, split + stitch when a query straddles raw retention
    (ref: queryplanner/LongTimeRangePlanner.scala:27-40).

    With a `persisted_planner` wired, a THIRD tier sits between them: the
    full-resolution persisted-segment tier (the compacted historical
    store).  Instants too old for the in-memory working set but covered by
    segments route there; only instants older than segment coverage fall
    to downsample.  One query over months stitches all three into one
    grid (the real-LTS contract: raw | persisted | downsample)."""

    def __init__(self, raw_planner: QueryPlanner,
                 downsample_planner: Optional[QueryPlanner],
                 earliest_raw_time_fn: Callable[[], int],
                 latest_downsample_time_fn: Callable[[], int],
                 stale_lookback_ms: int = 5 * 60 * 1000,
                 persisted_planner: Optional[QueryPlanner] = None,
                 persisted_range_fn: Optional[Callable] = None):
        self.raw = raw_planner
        self.downsample = downsample_planner
        self.earliest_raw_time_fn = earliest_raw_time_fn
        self.latest_downsample_time_fn = latest_downsample_time_fn
        self.stale_lookback_ms = stale_lookback_ms
        self.persisted = persisted_planner
        # () -> (floor_ms, ceil_ms) of segment coverage, or None when no
        # segments exist yet (PersistedTier.range)
        self.persisted_range_fn = persisted_range_fn

    def _downsample_or_raw(self):
        return self.downsample if self.downsample is not None else self.raw

    def materialize(self, plan: lp.LogicalPlan, ctx: QueryContext) -> ExecPlan:
        if not isinstance(plan, lp.PeriodicSeriesPlan):
            return self.raw.materialize(plan, ctx)   # metadata → raw cluster
        if lp.contains_at_pin(plan):
            # @ (anywhere in the tree) reads data at pinned times, not the
            # outer grid: route the WHOLE query by the true data range —
            # straddle-splitting the outer grid cannot relocate pinned
            # reads.  Fits-raw wins; else persisted when it covers the
            # whole data range; else downsample when it covers the range
            # end; else conservatively raw.
            dr = lp.pinned_data_range(plan, self.stale_lookback_ms)
            if dr is None:
                return self.raw.materialize(plan, ctx)
            if dr[0] >= self.earliest_raw_time_fn():
                return self.raw.materialize(plan, ctx)
            pr = self.persisted_range_fn() \
                if (self.persisted is not None
                    and self.persisted_range_fn is not None) else None
            if pr is not None and dr[0] >= pr[0] and dr[1] <= pr[1]:
                return self.persisted.materialize(plan, ctx)
            if self.downsample is not None \
                    and dr[1] <= self.latest_downsample_time_fn():
                return self.downsample.materialize(plan, ctx)
            return self.raw.materialize(plan, ctx)
        earliest_raw = self.earliest_raw_time_fn()
        lookback = pu.get_lookback_ms(plan, self.stale_lookback_ms)
        offset = pu.get_offset_ms(plan)
        start, step, end = plan.start_ms, plan.step_ms, plan.end_ms
        # instants whose full window [t-lookback-offset, t] is inside raw
        # retention can be answered by the raw cluster alone
        if start - lookback - offset >= earliest_raw:
            return self.raw.materialize(plan, ctx)
        pr = self.persisted_range_fn() \
            if (self.persisted is not None
                and self.persisted_range_fn is not None) else None
        if end - offset < earliest_raw:
            if pr is None:
                return self._downsample_or_raw().materialize(plan, ctx)
            return self._materialize_old(plan, ctx, pr, lookback, offset)
        # first grid instant fully covered by raw data
        need = earliest_raw + lookback + offset
        k = -((start - need) // step)                # ceil((need-start)/step)
        first_raw_instant = start + k * step
        if first_raw_instant > end:
            if pr is None:
                return self._downsample_or_raw().materialize(plan, ctx)
            return self._materialize_old(plan, ctx, pr, lookback, offset)
        old_end = first_raw_instant - step
        raw_plan = pu.copy_with_time_range(plan, TimeRange(first_raw_instant,
                                                           end))
        if old_end < start:
            return self.raw.materialize(plan, ctx)
        old_plan = pu.copy_with_time_range(plan, TimeRange(start, old_end))
        if pr is not None:
            old = self._materialize_old(old_plan, ctx, pr, lookback, offset)
            return StitchRvsExec(ctx, [old,
                                       self.raw.materialize(raw_plan, ctx)])
        latest_ds = self.latest_downsample_time_fn()
        ds_end = min(old_end, latest_ds)
        if ds_end < start:
            return self.raw.materialize(plan, ctx)
        ds_plan = pu.copy_with_time_range(plan, TimeRange(start, ds_end))
        return StitchRvsExec(
            ctx, [self._downsample_or_raw().materialize(ds_plan, ctx),
                  self.raw.materialize(raw_plan, ctx)])

    def _materialize_old(self, plan, ctx, pr, lookback: int,
                         offset: int) -> ExecPlan:
        """Route a fully-before-raw plan across persisted + downsample:
        instants whose window [t-lookback-offset, t-offset] sits inside
        segment coverage go to the persisted tier at full resolution; only
        older instants fall to downsample."""
        start, step, end = plan.start_ms, plan.step_ms, plan.end_ms
        p0, p1 = pr
        # first grid instant whose whole window is inside segment coverage
        # (clamped to the grid start: coverage reaching further back than
        # the query must not mint extra instants before it)
        need = p0 + lookback + offset
        k = max(-((start - need) // step), 0)
        first_p = start + k * step
        # last instant whose data end (t - offset) segments still cover
        last_p = end if p1 >= end - offset else \
            start + ((p1 + offset - start) // step) * step
        if first_p > end or last_p < start or first_p > last_p:
            # segments cover none of the grid
            return self._downsample_or_raw().materialize(plan, ctx)
        children: List[ExecPlan] = []
        if first_p > start:
            # grid head older than segment coverage: downsample when
            # wired, else the raw cluster's chunk-paging path (retention
            # never prunes frames no segment covers, so raw still holds
            # that data — dropping the head would silently truncate)
            if self.downsample is not None:
                ds_end = min(first_p - step,
                             self.latest_downsample_time_fn())
            else:
                ds_end = first_p - step
            if ds_end >= start:
                head = pu.copy_with_time_range(plan,
                                               TimeRange(start, ds_end))
                children.append(
                    self._downsample_or_raw().materialize(head, ctx))
        children.append(self.persisted.materialize(
            pu.copy_with_time_range(plan, TimeRange(first_p, last_p)), ctx))
        if last_p < end:
            # newer than segment coverage but older than raw: the raw
            # cluster's chunk-paging path is the only source
            children.append(self.raw.materialize(
                pu.copy_with_time_range(plan,
                                        TimeRange(last_p + step, end)),
                ctx))
        if len(children) == 1:
            return children[0]
        return StitchRvsExec(ctx, children)


class PersistedClusterPlanner(SingleClusterPlanner):
    """SingleClusterPlanner variant whose leaves read the persisted-segment
    tier (SelectPersistedSegmentsExec) instead of shard memory.  Long
    ranges split on the step grid (`tier.plan_split_ms` slices, stitched)
    so each leaf merges a bounded number of segments and int32 time
    offsets never overflow."""

    def __init__(self, dataset: str, shard_mapper, tier, **kwargs):
        super().__init__(dataset, shard_mapper, **kwargs)
        self.tier = tier

    def materialize(self, plan: lp.LogicalPlan, ctx: QueryContext) -> ExecPlan:
        split = getattr(self.tier, "plan_split_ms", 0)
        if split and isinstance(plan, lp.PeriodicSeriesPlan) \
                and not lp.contains_at_pin(plan) \
                and plan.end_ms - plan.start_ms > split:
            parts = pu.split_plans(plan, split)
            if len(parts) > 1:
                return StitchRvsExec(
                    ctx, [super(PersistedClusterPlanner, self)
                          .materialize(p, ctx) for p in parts])
        return super().materialize(plan, ctx)

    def _m_RawSeries(self, p: lp.RawSeries, ctx: QueryContext):
        from filodb_tpu.query.leafexec import SelectPersistedSegmentsExec
        candidates = self.shards_from_filters(p.filters, ctx)
        shards = self.shard_mapper.active_shards(candidates) or candidates
        plans = [SelectPersistedSegmentsExec(
            ctx, self.dataset, s, p.filters,
            p.range_selector.from_ms, p.range_selector.to_ms, self.tier,
            columns=p.columns) for s in shards]
        # same owner routing as the memstore leaves: a cluster-mode
        # persisted planner dispatches cold leaves to the shard's node
        # (and the PR-15 pushdown can then group them per node)
        return self._with_dispatcher(plans, shards)


# ------------------------------------------------------------ HA routing


@dataclasses.dataclass(frozen=True)
class FailureTimeRange:
    """A known data-gap window in one cluster
    (ref: queryplanner/FailureProvider.scala FailureTimeRange)."""
    cluster: str
    time_range: TimeRange
    is_remote: bool = False


class FailureProvider:
    """ref: FailureProvider.scala:45."""

    def get_failures(self, dataset: str, tr: TimeRange) -> List[FailureTimeRange]:
        return []


@dataclasses.dataclass(frozen=True)
class LocalRoute:
    time_range: Optional[TimeRange] = None          # None = whole query


@dataclasses.dataclass(frozen=True)
class RemoteRoute:
    time_range: TimeRange


def plan_routes(start_ms: int, step_ms: int, end_ms: int,
                local_failures: Sequence[TimeRange],
                lookback_ms: int) -> List:
    """Split the query grid into alternating local/remote routes so no local
    instant's lookback window overlaps a local failure
    (ref: queryplanner/FailureRoutingStrategy.scala QueryRoutingStrategy)."""
    if not local_failures:
        return [LocalRoute()]
    merged: List[TimeRange] = []
    for f in sorted(local_failures, key=lambda t: t.start_ms):
        if merged and f.start_ms <= merged[-1].end_ms:
            merged[-1] = TimeRange(merged[-1].start_ms,
                                   max(merged[-1].end_ms, f.end_ms))
        else:
            merged.append(f)
    routes: List = []
    cur = start_ms
    for f in merged:
        if cur > end_ms:
            break
        # local instants strictly before any instant whose window touches f
        bad_from = f.start_ms                         # t-lookback < f.end …
        last_local = bad_from - 1
        # snap to grid: largest instant <= last_local with window clear of f
        n = (last_local - start_ms) // step_ms
        last_local_instant = start_ms + n * step_ms
        if last_local_instant >= cur and last_local_instant - lookback_ms >= 0:
            routes.append(LocalRoute(TimeRange(cur, last_local_instant)))
            cur = last_local_instant + step_ms
        # remote covers instants while windows overlap the failure
        clear = f.end_ms + lookback_ms
        k = -((start_ms - clear) // step_ms)
        first_clear_instant = start_ms + k * step_ms
        remote_end = min(first_clear_instant - step_ms, end_ms)
        if remote_end >= cur:
            routes.append(RemoteRoute(TimeRange(cur, remote_end)))
            cur = remote_end + step_ms
    if cur <= end_ms:
        routes.append(LocalRoute(TimeRange(cur, end_ms)))
    return routes


class HighAvailabilityPlanner(QueryPlanner):
    """Route failure windows of the local cluster to a remote replica via
    PromQlRemoteExec (ref: queryplanner/HighAvailabilityPlanner.scala:22)."""

    def __init__(self, dataset: str, local_planner: QueryPlanner,
                 failure_provider: FailureProvider, remote_endpoint: str,
                 transport: Optional[Callable] = None,
                 stale_lookback_ms: int = 5 * 60 * 1000):
        self.dataset = dataset
        self.local = local_planner
        self.failure_provider = failure_provider
        self.remote_endpoint = remote_endpoint
        self.transport = transport
        self.stale_lookback_ms = stale_lookback_ms

    def materialize(self, plan: lp.LogicalPlan, ctx: QueryContext) -> ExecPlan:
        if not isinstance(plan, lp.PeriodicSeriesPlan):
            return self.local.materialize(plan, ctx)
        if lp.contains_at_pin(plan):
            # @ reads at pinned times: check failures against the true
            # data range and route the WHOLE query (slicing the outer
            # grid cannot relocate a pinned read)
            dr = lp.pinned_data_range(plan, self.stale_lookback_ms)
            if dr is not None:
                failures = self.failure_provider.get_failures(
                    self.dataset, TimeRange(dr[0], dr[1]))
                if any(not f.is_remote for f in failures):
                    return PromQlRemoteExec(
                        ctx, self.remote_endpoint, pu.unparse(plan),
                        plan.start_ms, plan.step_ms, plan.end_ms,
                        transport=self.transport)
            return self.local.materialize(plan, ctx)
        lookback = pu.get_lookback_ms(plan, self.stale_lookback_ms)
        offset = pu.get_offset_ms(plan)
        tr = TimeRange(plan.start_ms - lookback - offset, plan.end_ms)
        failures = self.failure_provider.get_failures(self.dataset, tr)
        local_fail = [f.time_range for f in failures if not f.is_remote]
        if not local_fail:
            return self.local.materialize(plan, ctx)
        routes = plan_routes(plan.start_ms, plan.step_ms, plan.end_ms,
                             local_fail, lookback + offset)
        children: List[ExecPlan] = []
        for r in routes:
            if isinstance(r, LocalRoute):
                sub = plan if r.time_range is None else \
                    pu.copy_with_time_range(plan, r.time_range)
                children.append(self.local.materialize(sub, ctx))
            else:
                sub = pu.copy_with_time_range(plan, r.time_range)
                children.append(PromQlRemoteExec(
                    ctx, self.remote_endpoint, pu.unparse(sub),
                    sub.start_ms, sub.step_ms, sub.end_ms,
                    transport=self.transport))
        if len(children) == 1:
            return children[0]
        return StitchRvsExec(ctx, children)


# -------------------------------------------------------- multi-partition


@dataclasses.dataclass(frozen=True)
class PartitionAssignment:
    """ref: MultiPartitionPlanner PartitionAssignment."""
    partition_name: str
    endpoint: str
    time_range: TimeRange


class PartitionLocationProvider:
    """ref: MultiPartitionPlanner.scala PartitionLocationProvider."""

    def get_partitions(self, filters: Sequence[ColumnFilter],
                       tr: TimeRange) -> List[PartitionAssignment]:
        raise NotImplementedError

    def get_metadata_partitions(self, filters: Sequence[ColumnFilter],
                                tr: TimeRange) -> List[PartitionAssignment]:
        return self.get_partitions(filters, tr)


class MultiPartitionPlanner(QueryPlanner):
    """Fan a query out across independent FiloDB partitions (clusters) and
    stitch by time (ref: queryplanner/MultiPartitionPlanner.scala:12-52)."""

    def __init__(self, partition_provider: PartitionLocationProvider,
                 local_partition_name: str, local_planner: QueryPlanner,
                 transport: Optional[Callable] = None,
                 stale_lookback_ms: int = 5 * 60 * 1000):
        self.provider = partition_provider
        self.local_name = local_partition_name
        self.local = local_planner
        self.transport = transport
        self.stale_lookback_ms = stale_lookback_ms

    def materialize(self, plan: lp.LogicalPlan, ctx: QueryContext) -> ExecPlan:
        if not isinstance(plan, lp.PeriodicSeriesPlan):
            return self.local.materialize(plan, ctx)
        filter_groups = pu.get_raw_series_filters(plan)
        tr = pu.get_time_range(plan)
        if lp.contains_at_pin(plan):
            return self._materialize_pinned(plan, ctx, filter_groups)
        # a partition may own several disjoint windows (data moved away and
        # back) — dedupe on the full assignment, never just the name
        assignments: List[PartitionAssignment] = []
        seen = set()
        for fg in (filter_groups or [()]):
            for a in self.provider.get_partitions(fg, tr):
                if a not in seen:
                    seen.add(a)
                    assignments.append(a)
        if not assignments or all(a.partition_name == self.local_name
                                  for a in assignments):
            return self.local.materialize(plan, ctx)
        step = plan.step_ms
        children: List[ExecPlan] = []
        for a in sorted(assignments, key=lambda x: x.time_range.start_ms):
            # clamp the plan onto this partition's assignment period
            s = max(plan.start_ms, _snap_up(a.time_range.start_ms,
                                            plan.start_ms, step))
            e = min(plan.end_ms, a.time_range.end_ms)
            if s > e:
                continue
            sub = pu.copy_with_time_range(plan, TimeRange(s, e))
            if a.partition_name == self.local_name:
                children.append(self.local.materialize(sub, ctx))
            else:
                children.append(PromQlRemoteExec(
                    ctx, a.endpoint, pu.unparse(sub), sub.start_ms,
                    sub.step_ms, sub.end_ms, transport=self.transport))
        if len(children) == 1:
            return children[0]
        return StitchRvsExec(ctx, children)

    def _materialize_pinned(self, plan: lp.LogicalPlan, ctx: QueryContext,
                            filter_groups) -> ExecPlan:
        """@ plans read data at the PINNED time, not the outer grid: select
        the partition by the true data range and send the WHOLE plan there
        (slicing the outer grid cannot relocate a pinned read).  A pinned
        data range that SPANS partitions is an error: no single node holds
        the whole range, so local evaluation would silently return partial
        results (every partition is missing part of the window), and the
        outer-grid stitch used for unpinned plans cannot split a pinned
        read either."""
        dr = lp.pinned_data_range(plan, self.stale_lookback_ms)
        if dr is None:
            return self.local.materialize(plan, ctx)
        tr = TimeRange(dr[0], dr[1])
        names = set()
        endpoint = None
        for fg in (filter_groups or [()]):
            for a in self.provider.get_partitions(fg, tr):
                names.add(a.partition_name)
                if a.partition_name != self.local_name:
                    endpoint = a.endpoint
        if len(names) > 1:
            raise ValueError(
                "@-pinned expression reads data spanning partitions "
                f"{sorted(names)}; a pinned read cannot be split — narrow "
                "the @ timestamp or the selector range")
        if len(names) == 1 and endpoint is not None:
            return PromQlRemoteExec(
                ctx, endpoint, pu.unparse(plan), plan.start_ms,
                plan.step_ms, plan.end_ms, transport=self.transport)
        return self.local.materialize(plan, ctx)


def _snap_up(t: int, grid_start: int, step: int) -> int:
    if t <= grid_start:
        return grid_start
    k = -((grid_start - t) // step)
    return grid_start + k * step


# ------------------------------------------------------- single partition


class SinglePartitionPlanner(QueryPlanner):
    """Pick one of several cluster planners by metric name within a single
    partition (ref: queryplanner/SinglePartitionPlanner.scala)."""

    def __init__(self, planners: Dict[str, QueryPlanner],
                 planner_selector: Callable[[str], str],
                 default: Optional[str] = None):
        self.planners = planners
        self.planner_selector = planner_selector
        self.default = default or next(iter(planners))

    def _pick(self, plan: lp.LogicalPlan) -> QueryPlanner:
        for fg in pu.get_raw_series_filters(plan):
            for f in fg:
                if f.column in ("_metric_", "__name__") and isinstance(f, Equals):
                    return self.planners[self.planner_selector(f.value)]
        return self.planners[self.default]

    def materialize(self, plan: lp.LogicalPlan, ctx: QueryContext) -> ExecPlan:
        return self._pick(plan).materialize(plan, ctx)


# ------------------------------------------------------ shard-key regex


ShardKeyMatcher = Callable[[Sequence[ColumnFilter]], List[Sequence[ColumnFilter]]]


def default_shard_key_matcher(index_label_values: Callable[[str], List[str]],
                              shard_key_columns: Sequence[str]) -> ShardKeyMatcher:
    """Expand regex/In shard-key filters against known label values."""
    import re

    def matcher(filters: Sequence[ColumnFilter]) -> List[Sequence[ColumnFilter]]:
        from filodb_tpu.core.index import NotEquals, NotEqualsRegex, NotIn
        combos: List[List[ColumnFilter]] = [[]]
        for f in filters:
            if f.column not in shard_key_columns:
                continue
            if isinstance(f, Equals):
                vals = [f.value]
            elif isinstance(f, In):
                vals = sorted(f.values)
            elif isinstance(f, EqualsRegex):
                rx = re.compile(f.pattern)
                vals = [v for v in index_label_values(f.column)
                        if rx.fullmatch(v)]
            elif isinstance(f, NotEquals):
                vals = [v for v in index_label_values(f.column)
                        if v != f.value]
            elif isinstance(f, NotIn):
                vals = [v for v in index_label_values(f.column)
                        if v not in f.values]
            elif isinstance(f, NotEqualsRegex):
                rx = re.compile(f.pattern)
                vals = [v for v in index_label_values(f.column)
                        if not rx.fullmatch(v)]
            else:
                vals = index_label_values(f.column)
            combos = [c + [Equals(f.column, v)] for c in combos for v in vals]
        return [tuple(c) for c in combos]
    return matcher


class ShardKeyRegexPlanner(QueryPlanner):
    """Fan out regex / multi-valued shard-key filters into N concrete
    shard-key combinations, each materialized by the wrapped planner; combine
    with a reduce (when the top is an Aggregate) or concat
    (ref: queryplanner/ShardKeyRegexPlanner.scala)."""

    NONEXPANDABLE = (Equals,)

    def __init__(self, planner: QueryPlanner, shard_key_matcher: ShardKeyMatcher,
                 shard_key_columns: Sequence[str] = ("_ws_", "_ns_")):
        self.planner = planner
        self.matcher = shard_key_matcher
        self.shard_key_columns = tuple(shard_key_columns)

    def _needs_fanout(self, plan: lp.LogicalPlan) -> bool:
        for fg in pu.get_raw_series_filters(plan):
            for f in fg:
                if f.column in self.shard_key_columns and \
                        not isinstance(f, self.NONEXPANDABLE):
                    return True
        return False

    def materialize(self, plan: lp.LogicalPlan, ctx: QueryContext) -> ExecPlan:
        if not self._needs_fanout(plan):
            return self.planner.materialize(plan, ctx)
        if isinstance(plan, lp.BinaryJoin):
            # each side fans out independently — rewriting one side's combos
            # onto the other would corrupt the join
            # (ref: ShardKeyRegexPlanner materializeBinaryJoin)
            return self._materialize_join(plan, ctx)
        if not self._concat_safe(plan):
            # a cross-series op (avg/topk/sort/...) that cannot be rebuilt
            # from per-combo presented results: let the wrapped planner fan
            # to all shards and apply the regex at the index — correct,
            # just less targeted
            return self.planner.materialize(plan, ctx)
        groups = pu.get_raw_series_filters(plan)
        base = groups[0] if groups else ()
        key_of = lambda fs: frozenset(  # noqa: E731
            f for f in fs if f.column in self.shard_key_columns)
        if any(key_of(g) != key_of(base) for g in groups[1:]):
            # selectors disagree on shard-key filters: fall back to the
            # wrapped planner, which fans to all shards and applies the
            # regex at the index — correct, just less targeted
            return self.planner.materialize(plan, ctx)
        combos = self.matcher([f for f in base
                               if f.column in self.shard_key_columns])
        if not combos:
            return self.planner.materialize(plan, ctx)
        if len(combos) == 1:
            return self.planner.materialize(
                pu.rewrite_filters(plan, combos[0]), ctx)
        children = [self.planner.materialize(pu.rewrite_filters(plan, c), ctx)
                    for c in combos]
        if isinstance(plan, lp.Aggregate) and \
                plan.operator in MultiPartitionReduceAggregateExec.COMBINE:
            return MultiPartitionReduceAggregateExec(ctx, children,
                                                     plan.operator)
        return DistConcatExec(ctx, children)

    def _concat_safe(self, plan: lp.LogicalPlan) -> bool:
        """True when per-combo results compose correctly: either the top is a
        combinable Aggregate, or the plan contains no cross-series operation
        at all (pure per-series pipelines concatenate cleanly)."""
        if isinstance(plan, lp.Aggregate):
            return plan.operator in MultiPartitionReduceAggregateExec.COMBINE \
                and self._per_series_only(plan.vectors)
        return self._per_series_only(plan)

    @staticmethod
    def _per_series_only(plan) -> bool:
        import dataclasses as _dc
        if isinstance(plan, (lp.Aggregate, lp.ApplySortFunction,
                             lp.ApplyLimitFunction)):
            return False
        if _dc.is_dataclass(plan):
            for f in _dc.fields(plan):
                v = getattr(plan, f.name)
                if isinstance(v, lp.LogicalPlan) and \
                        not ShardKeyRegexPlanner._per_series_only(v):
                    return False
        return True

    def _materialize_join(self, plan: lp.BinaryJoin,
                          ctx: QueryContext) -> ExecPlan:
        from filodb_tpu.query.exec import BinaryJoinExec, SetOperatorExec
        from filodb_tpu.query.planner import SET_OPERATORS
        lhs = self.materialize(plan.lhs, ctx)
        rhs = self.materialize(plan.rhs, ctx)
        op = plan.operator[:-5] if plan.operator.endswith("_bool") \
            else plan.operator
        if op.lower() in SET_OPERATORS:
            return SetOperatorExec(ctx, [lhs], [rhs], op.lower(),
                                   on=plan.on, ignoring=plan.ignoring)
        return BinaryJoinExec(ctx, [lhs], [rhs], op, plan.cardinality,
                              on=plan.on, ignoring=plan.ignoring,
                              include=plan.include,
                              bool_modifier=plan.operator.endswith("_bool"))


class MultiPartitionReduceAggregateExec(NonLeafExecPlan):
    """Re-aggregate already-presented aggregate results coming from multiple
    shard-key fan-out branches, merging rows that share a group key
    (ref: exec/AggrOverRangeVectors.scala MultiPartitionReduceAggregateExec).
    Only ops whose presented form re-combines exactly are allowed."""

    COMBINE = {"sum": np.nansum, "min": np.nanmin, "max": np.nanmax,
               "count": np.nansum, "group": np.nanmax}

    def __init__(self, ctx, children, op: str):
        super().__init__(ctx, children)
        self.op = op

    def args_str(self):
        return f"aggrOp={self.op}"

    def compose(self, results, stats):
        blocks = [r for r in results if isinstance(r, ResultBlock)]
        if not blocks:
            return None
        wends = blocks[0].wends
        rows: Dict[RangeVectorKey, List[np.ndarray]] = {}
        for b in blocks:
            vals = np.asarray(b.values)
            for i, k in enumerate(b.keys):
                rows.setdefault(k, []).append(vals[i])
        comb = self.COMBINE[self.op]
        keys = list(rows)
        out = np.stack([
            np.where(np.all(np.isnan(np.stack(v)), axis=0), np.nan,
                     comb(np.stack(v), axis=0))
            for v in (rows[k] for k in keys)])
        return ResultBlock(keys, wends, out)
