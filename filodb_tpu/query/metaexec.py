"""Metadata exec plans: chunk-info debug scans, part-key and label
queries, cross-shard metadata merge.

Split from query/exec.py (round 4, no behavior change).
ref: query/.../exec/SelectChunkInfosExec.scala:1-78,
MetadataExecPlan.scala.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax.numpy as jnp

from filodb_tpu.core.index import ColumnFilter, Equals
from filodb_tpu.ops import agg as agg_ops
from filodb_tpu.ops import hist as hist_ops
from filodb_tpu.ops.instant import (INSTANT_FUNCTIONS, ARITH_OPERATORS,
                                    COMPARISON_OPERATORS, apply_binary_op)
from filodb_tpu.ops import counter as counter_ops
from filodb_tpu.ops.rangefns import RANGE_FUNCTIONS, evaluate_range_function
from filodb_tpu.ops.timewindow import PAD_TS, to_offsets, make_window_ends
from filodb_tpu.query.rangevector import (QueryContext, QueryResult, QueryStats,
                                          RangeVectorKey, ResultBlock,
                                          concat_blocks, remove_nan_series)

from filodb_tpu.query.execbase import (
    LeafExecPlan, NonLeafExecPlan, QueryResultLike)


# ----------------------------------------------------------- metadata execs


class SelectChunkInfosExec(LeafExecPlan):
    """Chunk-metadata debug plan: per-partition chunk infos (id, numRows,
    time range, bytes, per-column encodings) for the series a filter
    resolves to (ref: query/.../exec/SelectChunkInfosExec.scala:1-78 —
    id/NumRows/startTime/endTime/numBytes/readerKlazz).  Covers BOTH
    tiers: sealed chunks in the resident cache and the unsealed tail of
    the dense store (reported as encoding 'dense-unsealed')."""

    def __init__(self, ctx, dataset, shard, filters, start_ms, end_ms,
                 schema=None, col_name=None):
        super().__init__(ctx)
        self.dataset, self.shard = dataset, shard
        self.filters = list(filters)
        self.start_ms, self.end_ms = start_ms, end_ms
        self.schema = schema
        self.col_name = col_name

    def args_str(self):
        return (f"shard={self.shard}, chunkMethod=TimeRangeChunkScan("
                f"{self.start_ms},{self.end_ms}), "
                f"filters={[str(f) for f in self.filters]}, "
                f"col={self.col_name}")

    def _do_execute(self, source) -> QueryResultLike:
        shard = source.get_shard(self.dataset, self.shard)
        stats = QueryStats(shards_queried=1)
        if shard is None:
            return None, stats
        lookup = shard.lookup_partitions(self.filters, self.start_ms,
                                         self.end_ms)
        rows = []
        for schema_name, parts in lookup.parts_by_schema.items():
            if self.schema and schema_name != self.schema:
                continue
            store = shard.stores[schema_name]
            for p in parts:
                labels = {**p.part_key.tags_dict,
                          "_metric_": p.part_key.metric}
                chunks = [(cs, "resident") for cs in shard.resident.read(
                    p.part_id, self.start_ms, self.end_ms)]
                if not chunks:
                    # evicted / recovered partitions: the persisted tier
                    # still knows the chunk metadata
                    try:
                        chunks = [(cs, "persisted")
                                  for cs in shard.column_store.read_chunks(
                                      self.dataset, self.shard, p.part_key,
                                      self.start_ms, self.end_ms)]
                    except Exception:  # noqa: BLE001 — Null store etc.
                        chunks = []
                for cs, tier in chunks:
                    cols = {name: c.kind
                            for name, c in cs.columns.items()
                            if self.col_name in (None, name)}
                    rows.append({
                        **labels, "shard": self.shard, "partId": p.part_id,
                        "chunkId": cs.info.chunk_id,
                        "numRows": cs.info.num_rows,
                        "startTime": cs.info.start_time_ms,
                        "endTime": cs.info.end_time_ms,
                        "numBytes": cs.nbytes,
                        "ingestionTime": cs.info.ingestion_time_ms,
                        "encodings": cols, "tier": tier})
                # the unsealed dense-store tail is one writable chunk
                cnt = int(store.counts[p.row])
                sealed = int(store.sealed[p.row])
                if cnt > sealed:
                    ts_row = store.ts[p.row, sealed:cnt]
                    t0, t1 = int(ts_row[0]), int(ts_row[-1])
                    if t1 >= self.start_ms and t0 <= self.end_ms:
                        per_cell = sum(
                            (arr.dtype.itemsize
                             * (arr.shape[2] if arr.ndim == 3 else 1))
                            for name, arr in store.cols.items()
                            if arr is not None
                            and self.col_name in (None, name)) + 8
                        rows.append({
                            **labels, "shard": self.shard,
                            "partId": p.part_id, "chunkId": -1,
                            "numRows": cnt - sealed,
                            "startTime": t0, "endTime": t1,
                            "numBytes": (cnt - sealed) * per_cell,
                            "ingestionTime": -1,
                            "encodings": {"*": "dense-unsealed"},
                            "tier": "dense"})
        stats.series_scanned = sum(
            len(v) for v in lookup.parts_by_schema.values())
        return QueryResult([], stats, data=rows), stats


class PartKeysExec(LeafExecPlan):
    """Series-key metadata query (ref: exec/MetadataExecPlan.scala)."""

    def __init__(self, ctx, dataset, shard, filters, start_ms, end_ms):
        super().__init__(ctx)
        self.dataset, self.shard = dataset, shard
        self.filters = list(filters)
        self.start_ms, self.end_ms = start_ms, end_ms

    def args_str(self):
        return f"shard={self.shard}, filters={[str(f) for f in self.filters]}"

    def _do_execute(self, source) -> QueryResultLike:
        shard = source.get_shard(self.dataset, self.shard)
        stats = QueryStats(shards_queried=1)
        if shard is None:
            return None, stats
        res = shard.lookup_partitions(self.filters, self.start_ms, self.end_ms)
        keys = []
        for parts in res.parts_by_schema.values():
            for p in parts:
                keys.append({**p.part_key.tags_dict,
                             "_metric_": p.part_key.metric})
        # metadata scans report their touched-series count too, so
        # ?stats=true attribution covers /series like data queries
        stats.series_scanned = len(keys)
        data = QueryResult([], stats, data=keys)
        return data, stats


class LabelValuesExec(LeafExecPlan):
    """ref: exec/MetadataExecPlan.scala LabelValuesExec."""

    def __init__(self, ctx, dataset, shard, filters, labels, start_ms, end_ms):
        super().__init__(ctx)
        self.dataset, self.shard = dataset, shard
        self.filters = list(filters)
        self.labels = list(labels)
        self.start_ms, self.end_ms = start_ms, end_ms

    def args_str(self):
        # filters are part of the string: the gather's duplicate-shard
        # dedup keys on args_str, and two same-shard children with
        # different selectors must never collapse
        return (f"shard={self.shard}, labels={self.labels}, "
                f"filters={[str(f) for f in self.filters]}")

    def _do_execute(self, source) -> QueryResultLike:
        shard = source.get_shard(self.dataset, self.shard)
        stats = QueryStats(shards_queried=1)
        if shard is None:
            return None, stats
        if not self.labels:        # LabelNames query (ref: LabelNamesExec)
            return QueryResult([], stats,
                               data=shard.index.label_names(self.filters)), stats
        out: Dict[str, List[str]] = {}
        for lbl in self.labels:
            out[lbl] = shard.index.label_values(lbl, self.filters or None)
        return QueryResult([], stats, data=out), stats


def _canon(x):
    """Hashable canonical form for metadata dedup (str or label dict)."""
    return tuple(sorted(x.items())) if isinstance(x, dict) else x


class MetadataMergeExec(NonLeafExecPlan):
    """Merge metadata results across shards."""

    # per-shard metadata leaves: dup shards (handoff window) answer once
    dedup_shard_children = True

    def compose(self, results, stats):
        merged = None
        for r in results:
            if not isinstance(r, QueryResult) or r.data is None:
                continue
            if merged is None:
                merged = list(r.data) if isinstance(r.data, list) else r.data
                if isinstance(merged, list):
                    seen = {_canon(x) for x in merged}
            elif isinstance(merged, list):
                for x in r.data:
                    c = _canon(x)
                    if c not in seen:
                        seen.add(c)
                        merged.append(x)
            elif isinstance(merged, dict):
                for k, v in r.data.items():
                    vals = set(merged.get(k, [])) | set(v)
                    merged[k] = sorted(vals)
        # a dropped shard set stats.partial in _gather: the flag must
        # ride the RESULT too (the metadata HTTP payloads surface it —
        # a label dropdown missing a dead node's values is exactly the
        # silent partial the contract forbids)
        return QueryResult([], stats, data=merged, partial=stats.partial)

