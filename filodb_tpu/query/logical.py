"""LogicalPlan — the PromQL-level algebra.

Mirrors the reference's LogicalPlan ADT (ref: query/src/main/scala/filodb/
query/LogicalPlan.scala:6-577): RawSeries at the bottom, periodic
transformations, aggregates, joins, scalar plans and metadata plans.  Plans
are immutable dataclasses; planners pattern-match on type.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from filodb_tpu.core.index import ColumnFilter


class LogicalPlan:
    """Base marker.  is_raw_series / is_periodic mirror the reference's
    RawSeriesLikePlan / PeriodicSeriesPlan split (LogicalPlan.scala:6-64)."""


class RawSeriesLikePlan(LogicalPlan):
    pass


class PeriodicSeriesPlan(LogicalPlan):
    """Evaluates to regular-step samples: startMs/stepMs/endMs required."""
    start_ms: int
    step_ms: int
    end_ms: int


class MetadataQueryPlan(LogicalPlan):
    pass


@dataclasses.dataclass(frozen=True)
class IntervalSelector:
    """Chunk-scan time range (ref: LogicalPlan.scala:73 RangeSelector)."""
    from_ms: int
    to_ms: int


@dataclasses.dataclass(frozen=True)
class RawSeries(RawSeriesLikePlan):
    """Select raw chunk data for matching series
    (ref: LogicalPlan.scala:91 RawSeries)."""
    range_selector: IntervalSelector
    filters: Tuple[ColumnFilter, ...]
    columns: Tuple[str, ...] = ()
    lookback_ms: Optional[int] = None
    offset_ms: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class RawChunkMeta(RawSeriesLikePlan):
    """Chunk metadata debug plan (ref: LogicalPlan.scala:119)."""
    range_selector: IntervalSelector
    filters: Tuple[ColumnFilter, ...]
    column: str = ""


@dataclasses.dataclass(frozen=True)
class PeriodicSeries(PeriodicSeriesPlan):
    """Raw -> regular step, last-sample-in-lookback semantics
    (ref: LogicalPlan.scala:147)."""
    raw_series: RawSeries
    start_ms: int
    step_ms: int
    end_ms: int
    offset_ms: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class PeriodicSeriesWithWindowing(PeriodicSeriesPlan):
    """Range-function application over sliding windows
    (ref: LogicalPlan.scala:245)."""
    series: RawSeries
    start_ms: int
    step_ms: int
    end_ms: int
    window_ms: int
    function: str                                   # range function name
    function_args: Tuple[float, ...] = ()
    offset_ms: Optional[int] = None
    # instant-vector timestamp(): the window IS the stale lookback; the
    # parser stores its default here and the planner re-resolves it to the
    # deployment's configured stale_lookback_ms before materializing
    window_is_lookback: bool = False


def resolve_lookback_windows(plan: LogicalPlan, lookback_ms: int
                             ) -> LogicalPlan:
    """Rewrite every window_is_lookback PSWW to the configured lookback."""
    import dataclasses as _dc

    def walk(p):
        if not _dc.is_dataclass(p):
            return p
        changes = {}
        for f in _dc.fields(p):
            v = getattr(p, f.name)
            if isinstance(v, LogicalPlan):
                nv = walk(v)
                if nv is not v:
                    changes[f.name] = nv
            elif isinstance(v, tuple) and any(
                    isinstance(x, LogicalPlan) for x in v):
                nv = tuple(walk(x) if isinstance(x, LogicalPlan) else x
                           for x in v)
                if nv != v:
                    changes[f.name] = nv
        if isinstance(p, PeriodicSeriesWithWindowing) \
                and p.window_is_lookback:
            changes.update(window_ms=lookback_ms, window_is_lookback=False)
            raw = changes.get("series", p.series)
            changes["series"] = _dc.replace(
                raw, range_selector=IntervalSelector(
                    p.start_ms - lookback_ms - (p.offset_ms or 0),
                    raw.range_selector.to_ms))
        return _dc.replace(p, **changes) if changes else p

    return walk(plan)


@dataclasses.dataclass(frozen=True)
class SubqueryWithWindowing(PeriodicSeriesPlan):
    """foo[5m:1m] with an outer range function
    (ref: LogicalPlan.scala:196)."""
    inner: PeriodicSeriesPlan
    start_ms: int
    step_ms: int
    end_ms: int
    function: str
    function_args: Tuple[float, ...]
    subquery_window_ms: int
    subquery_step_ms: int
    offset_ms: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class TopLevelSubquery(PeriodicSeriesPlan):
    """Top-level foo[5m:1m] (ref: LogicalPlan.scala:223)."""
    inner: PeriodicSeriesPlan
    start_ms: int
    step_ms: int
    end_ms: int
    original_lookback_ms: Optional[int] = None
    offset_ms: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class Aggregate(PeriodicSeriesPlan):
    """Cross-series aggregation with by/without clauses
    (ref: LogicalPlan.scala:269)."""
    operator: str                                   # sum/min/max/avg/...
    vectors: PeriodicSeriesPlan
    params: Tuple = ()                              # k for topk, q for quantile
    by: Tuple[str, ...] = ()
    without: Tuple[str, ...] = ()

    @property
    def start_ms(self): return self.vectors.start_ms
    @property
    def step_ms(self): return self.vectors.step_ms
    @property
    def end_ms(self): return self.vectors.end_ms


@dataclasses.dataclass(frozen=True)
class BinaryJoin(PeriodicSeriesPlan):
    """Vector-vector binary operation with matching rules
    (ref: LogicalPlan.scala:292)."""
    lhs: PeriodicSeriesPlan
    operator: str
    rhs: PeriodicSeriesPlan
    cardinality: str = "OneToOne"                   # OneToOne/OneToMany/ManyToOne/ManyToMany
    on: Optional[Tuple[str, ...]] = None
    ignoring: Tuple[str, ...] = ()
    include: Tuple[str, ...] = ()                   # group_left/right labels

    @property
    def start_ms(self): return self.lhs.start_ms
    @property
    def step_ms(self): return self.lhs.step_ms
    @property
    def end_ms(self): return self.lhs.end_ms


@dataclasses.dataclass(frozen=True)
class ScalarVectorBinaryOperation(PeriodicSeriesPlan):
    """vector op scalar (ref: LogicalPlan.scala:314)."""
    operator: str
    scalar_arg: "PeriodicSeriesPlan"                # ScalarPlan
    vector: PeriodicSeriesPlan
    scalar_is_lhs: bool = False

    @property
    def start_ms(self): return self.vector.start_ms
    @property
    def step_ms(self): return self.vector.step_ms
    @property
    def end_ms(self): return self.vector.end_ms


@dataclasses.dataclass(frozen=True)
class ApplyInstantFunction(PeriodicSeriesPlan):
    """abs()/ceil()/histogram_quantile()/... (ref: LogicalPlan.scala:331)."""
    vectors: PeriodicSeriesPlan
    function: str
    function_args: Tuple = ()

    @property
    def start_ms(self): return self.vectors.start_ms
    @property
    def step_ms(self): return self.vectors.step_ms
    @property
    def end_ms(self): return self.vectors.end_ms


@dataclasses.dataclass(frozen=True)
class ApplyMiscellaneousFunction(PeriodicSeriesPlan):
    """label_replace/label_join/sort_desc etc (ref: LogicalPlan.scala:410 area)."""
    vectors: PeriodicSeriesPlan
    function: str
    string_args: Tuple[str, ...] = ()

    @property
    def start_ms(self): return self.vectors.start_ms
    @property
    def step_ms(self): return self.vectors.step_ms
    @property
    def end_ms(self): return self.vectors.end_ms


@dataclasses.dataclass(frozen=True)
class ApplySortFunction(PeriodicSeriesPlan):
    vectors: PeriodicSeriesPlan
    function: str                                   # sort | sort_desc

    @property
    def start_ms(self): return self.vectors.start_ms
    @property
    def step_ms(self): return self.vectors.step_ms
    @property
    def end_ms(self): return self.vectors.end_ms


@dataclasses.dataclass(frozen=True)
class ApplyAbsentFunction(PeriodicSeriesPlan):
    """absent() (ref: LogicalPlan.scala:478)."""
    vectors: PeriodicSeriesPlan
    filters: Tuple[ColumnFilter, ...]
    start_ms: int
    step_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class ApplyAtTimestamp(PeriodicSeriesPlan):
    """PromQL `@` modifier: `inner` is evaluated on a single-step grid
    pinned at the @ timestamp; its one column is then repeated across the
    query's output grid (Prometheus semantics: the pinned value at every
    step).  repeat=False marks pinned plans whose result is a matrix
    (top-level subqueries) — the wrapper still carries the pin for
    planners/copiers, but no repeating happens."""
    inner: PeriodicSeriesPlan       # start_ms == end_ms == the pinned time
    start_ms: int
    step_ms: int
    end_ms: int
    repeat: bool = True

    @property
    def at_ms(self) -> int:
        return self.inner.start_ms


def contains_at_pin(plan: LogicalPlan) -> bool:
    """True when any subtree is pinned by an @ modifier (planners must
    then route by pinned data times, not the outer grid)."""
    if isinstance(plan, ApplyAtTimestamp):
        return True
    if dataclasses.is_dataclass(plan):
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            if isinstance(v, LogicalPlan) and contains_at_pin(v):
                return True
    return False


def pinned_data_range(plan: LogicalPlan, default_lookback_ms: int):
    """(earliest_data_ms, latest_data_ms) the plan actually READS.
    Correct for @ pins WITHOUT special-casing them: the parser bakes
    pinned grids into every selector (a pinned selector's own
    start/end IS the pinned time; a pinned subquery's inner grid is
    already shifted onto it), so each selector's own grid minus its
    lookback/offset is the truth.  Returns None when the plan has no
    selectors."""
    from filodb_tpu.query import planutils as pu
    lo: List[int] = []
    hi: List[int] = []

    def walk(p):
        if isinstance(p, (PeriodicSeries, PeriodicSeriesWithWindowing)):
            look = pu.get_lookback_ms(p, default_lookback_ms)
            off = pu.get_offset_ms(p)
            lo.append(p.start_ms - look - off)
            hi.append(p.end_ms - off)
            return
        if dataclasses.is_dataclass(p):
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, LogicalPlan):
                    walk(v)
    walk(plan)
    if not lo:
        return None
    return min(lo), max(hi)


@dataclasses.dataclass(frozen=True)
class ApplyLimitFunction(PeriodicSeriesPlan):
    vectors: PeriodicSeriesPlan
    limit: int

    @property
    def start_ms(self): return self.vectors.start_ms
    @property
    def step_ms(self): return self.vectors.step_ms
    @property
    def end_ms(self): return self.vectors.end_ms


# ------------------------------------------------------------- scalar plans

class ScalarPlan(PeriodicSeriesPlan):
    """Evaluates to one value per step (ref: LogicalPlan.scala:395-475)."""


@dataclasses.dataclass(frozen=True)
class ScalarTimeBasedPlan(ScalarPlan):
    """time(), hour(), ... of the step timestamps (ref: :404)."""
    function: str
    start_ms: int
    step_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class ScalarFixedDoublePlan(ScalarPlan):
    """Literal number (ref: :417)."""
    scalar: float
    start_ms: int
    step_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class ScalarVaryingDoublePlan(ScalarPlan):
    """scalar(vector) (ref: :395)."""
    vectors: PeriodicSeriesPlan
    function: str = "scalar"

    @property
    def start_ms(self): return self.vectors.start_ms
    @property
    def step_ms(self): return self.vectors.step_ms
    @property
    def end_ms(self): return self.vectors.end_ms


@dataclasses.dataclass(frozen=True)
class ScalarBinaryOperation(ScalarPlan):
    """scalar op scalar, possibly nested (ref: :457)."""
    operator: str
    lhs: "float | ScalarBinaryOperation"
    rhs: "float | ScalarBinaryOperation"
    start_ms: int
    step_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class VectorPlan(PeriodicSeriesPlan):
    """vector(scalar) (ref: :444)."""
    scalars: ScalarPlan

    @property
    def start_ms(self): return self.scalars.start_ms
    @property
    def step_ms(self): return self.scalars.step_ms
    @property
    def end_ms(self): return self.scalars.end_ms


# ----------------------------------------------------------- metadata plans

@dataclasses.dataclass(frozen=True)
class LabelValues(MetadataQueryPlan):
    """ref: LogicalPlan.scala:105."""
    label_names: Tuple[str, ...]
    filters: Tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class LabelNames(MetadataQueryPlan):
    filters: Tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class SeriesKeysByFilters(MetadataQueryPlan):
    """ref: LogicalPlan.scala:110."""
    filters: Tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class LabelCardinality(MetadataQueryPlan):
    filters: Tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class TsCardinalities(MetadataQueryPlan):
    """Cardinality overview (ref: LogicalPlan.scala TsCardinalities)."""
    shard_key_prefix: Tuple[str, ...]
    num_group_by_fields: int


# ------------------------------------------------------------------- helpers

def raw_series_filters(plan: LogicalPlan) -> List[Tuple[ColumnFilter, ...]]:
    """Collect the filter sets of every RawSeries under `plan`
    (ref: LogicalPlan.getRawSeriesFilters)."""
    out: List[Tuple[ColumnFilter, ...]] = []
    def walk(p):
        if isinstance(p, RawSeries):
            out.append(p.filters)
        elif dataclasses.is_dataclass(p):
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, LogicalPlan):
                    walk(v)
    walk(plan)
    return out
