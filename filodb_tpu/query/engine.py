"""QueryEngine — parse -> plan -> execute facade (the QueryActor analogue).

ref: coordinator/.../QueryActor.scala:119-137 (LogicalPlan2Query ->
SingleClusterPlanner.materialize -> ExecPlan.execute) and
prometheus/.../query/PrometheusModel.scala (result JSON conversion).
"""
from __future__ import annotations

import math
import time as _time
import uuid
from typing import Dict, List, Optional

import numpy as np

from filodb_tpu.parallel.shardmapper import ShardMapper, SpreadProvider
from filodb_tpu.promql.parser import (TimeStepParams,
                                      query_range_to_logical_plan)
from filodb_tpu.query import logical as lp
from filodb_tpu.query.planner import SingleClusterPlanner
from filodb_tpu.query.rangevector import (PlannerParams, QueryContext,
                                          QueryResult)


class QueryEngine:

    def __init__(self, dataset: str, source,
                 shard_mapper: Optional[ShardMapper] = None,
                 spread_provider: Optional[SpreadProvider] = None,
                 planner: Optional[SingleClusterPlanner] = None,
                 replan_hook=None, config=None):
        self.dataset = dataset
        self.source = source
        # deployment-injected FilodbSettings (FiloServer passes its own,
        # matching the frontends') — None falls back to the settings()
        # singleton per call so bare constructions track config reloads
        self.config = config
        # embedded-engine deployments (no FiloServer) still get the
        # persistent compile cache; idempotent under the standalone path
        from filodb_tpu.config import apply_jax_runtime, settings
        apply_jax_runtime(settings())
        self.shard_mapper = shard_mapper or _single_shard_mapper()
        self.planner = planner or SingleClusterPlanner(
            dataset, self.shard_mapper, spread_provider)
        # () -> SingleClusterPlanner with a FRESH shard-map snapshot.
        # When a scatter-gather fails shard_unavailable (owner died
        # mid-query), the engine re-plans through this hook up to
        # query.dispatch_retries times — after failover the new plan
        # dispatches to the reassigned owner (ref: the HA planner's
        # route-around-failure stance, HighAvailabilityPlanner.scala:22)
        self.replan_hook = replan_hook

    def _ctx(self, planner_params: Optional[PlannerParams]) -> QueryContext:
        from filodb_tpu.query.activequeries import take_admission
        from filodb_tpu.query.rangevector import compute_deadline
        q = self._qconfig()
        if planner_params is None:
            # bare-engine callers inherit the server's partial-results
            # stance; explicit PlannerParams always win
            planner_params = PlannerParams(
                allow_partial_results=q.allow_partial_results)
        # frontend-admitted queries carry their ActiveQuery entry across
        # the layer gap on a thread-local: the context adopts its id —
        # so the registry key, the trace id, and ctx.query_id are ONE
        # stable identifier — and its CancellationToken
        ent = take_admission()
        qid = ent.query_id if ent is not None else str(uuid.uuid4())
        # end-to-end deadline: the frontend stamps deadline_unix_s at
        # ADMISSION (queue wait counts); otherwise the budget starts now
        ctx = QueryContext(query_id=qid,
                           submit_time_ms=int(_time.time() * 1000),
                           planner_params=planner_params,
                           deadline_unix_s=compute_deadline(
                               planner_params, q.default_timeout_s))
        if ent is not None:
            # plain attributes, NOT dataclass fields: a dispatched
            # subtree serializes without them (remote nodes register
            # their own entry under the same query id)
            ctx.cancel = ent.token
            ctx.active = ent
            # the tenant workspace rides the context so the replica-
            # failover dispatcher can apply the tenant's shuffle-shard
            # node preference (query/qos.py) at dispatch time
            ctx.tenant_ws = ent.tenant_ws
        return ctx

    def _qconfig(self):
        if self.config is not None:
            return self.config.query
        from filodb_tpu.config import settings
        return settings().query

    def query_range(self, promql: str, start_s: int, step_s: int, end_s: int,
                    planner_params: Optional[PlannerParams] = None
                    ) -> QueryResult:
        from filodb_tpu.query.activequeries import peek_admission
        from filodb_tpu.utils.metrics import span
        ent = peek_admission()
        if ent is not None:
            ent.set_phase("parsing")
        t_parse0 = _time.perf_counter()
        try:
            # span: the parse share of the fixed per-query floor is
            # attributable in traces (parse itself is AST-memoized —
            # promql.parser.parse_query_cached — so re-polled dashboard
            # strings skip tokenization entirely)
            with span("query_parse"):
                plan = query_range_to_logical_plan(
                    promql, TimeStepParams(start_s, step_s, end_s))
        except Exception as e:  # noqa: BLE001 — parse errors surface in result
            return QueryResult([], error=f"parse error: {e}")
        parse_t = _time.perf_counter() - t_parse0
        res = self.exec_logical_plan(plan, planner_params)
        res.stats.parse_s += parse_t
        return res

    def query_instant(self, promql: str, time_s: int,
                      planner_params: Optional[PlannerParams] = None
                      ) -> QueryResult:
        return self.query_range(promql, time_s, 1, time_s, planner_params)

    def query_range_batch(self, promqls: List[str], start_s: int,
                          step_s: int, end_s: int,
                          planner_params: Optional[PlannerParams] = None
                          ) -> List[QueryResult]:
        """Evaluate a dashboard's worth of queries over one time grid,
        merging compatible fused leaves into single kernel dispatches.

        The round-4 on-chip measurements (doc/kernels.md) show a fused
        leaf query is dominated by per-call dispatch latency, not device
        time — so P panels over the same working set and window grid
        should cost ONE dispatch, not P.  Three phases: (1) every
        in-process MultiSchemaPartitionsExec leaf runs its gather + fused
        preflight (prepare_fused), parking the gathered data; (2)
        compatible FusedCalls merge via fusedbatch.finish_fused_calls
        (disjoint-group multi-hot epilogue, at most two dispatches per
        compatible set); (3) each tree executes normally, leaves reusing
        the parked data and injected partials.  Queries that don't fit
        the pattern (parse errors, metadata plans, non-fusable shapes,
        remote-dispatched leaves) take their normal paths unchanged.

        The reference has no analogue — its iterator engine pays per-
        series cost either way; this is a TPU-shaped throughput feature
        (amortizing dispatch the way the MXU amortizes FLOPs).
        """
        from filodb_tpu.ops import hostleaf
        from filodb_tpu.query import exprfuse
        from filodb_tpu.query.activequeries import (set_admission,
                                                    take_admission)
        # the coalesce LEADER's admission entry must bind to ITS query,
        # not to whichever batch member happens to mint a context first
        # (a parse failure on the leader's own query would otherwise
        # hand its id/token to another client's query — a kill of the
        # leader's id would then cancel the wrong tenant's work)
        adm = take_admission()
        results: List[Optional[QueryResult]] = [None] * len(promqls)
        entries = []
        for i, q in enumerate(promqls):
            mine = adm is not None and q == adm.promql
            if mine:
                set_admission(adm)
                adm = None
            t0 = _time.perf_counter()
            try:
                plan = query_range_to_logical_plan(
                    q, TimeStepParams(start_s, step_s, end_s))
            except Exception as e:  # noqa: BLE001
                results[i] = QueryResult([], error=f"parse error: {e}")
                if mine:
                    take_admission()     # never leak to the next query
                continue
            parse_t = _time.perf_counter() - t0
            if isinstance(plan, lp.MetadataQueryPlan):
                results[i] = self.exec_logical_plan(plan, planner_params)
                results[i].stats.parse_s += parse_t
                continue
            ctx = self._ctx(planner_params)
            t0 = _time.perf_counter()
            try:
                ep = self.planner.materialize(plan, ctx)
            except Exception as e:  # noqa: BLE001
                results[i] = QueryResult([], error=f"planning error: {e}")
                continue
            entries.append((i, ep, ctx, plan,
                            parse_t, _time.perf_counter() - t0))
        # whole-expression compilation (query/exprfuse.py): EVERY tree's
        # in-process leaves run their fused preflight — under one gather
        # memo scope, so N panels over a shared working set scan it once
        # — then all the prepared kernel work merges into the batched
        # dispatch (killed queries filtered out before the dispatch)
        calls = []
        comps = {}
        if self._qconfig().exprfuse_enabled:
            with hostleaf.batch_gather_memo():
                for i, ep, _, _, _, _ in entries:
                    comp = exprfuse.compile_tree(ep, self.source)
                    if comp is not None:
                        comps[i] = comp
                        calls.extend(comp.calls)
            exprfuse.finish_prepared(calls)
        for i, ep, ctx, plan, parse_t, plan_t in entries:
            res = ep.execute(self.source)
            res.trace_id = ctx.query_id
            if res.error and res.error.startswith("shard_unavailable") \
                    and (self.replan_hook is not None
                         or ctx.planner_params.allow_partial_results):
                # failover retry (and, past the retries, the partial-
                # result degrade) for the dashboard-batch path too: the
                # retried query re-plans through exec_logical_plan (it
                # loses this batch's fusion, which is moot — its shard
                # owner just died)
                res = self.exec_logical_plan(plan, planner_params)
            res.stats.parse_s += parse_t
            res.stats.plan_s += plan_t
            comp = comps.get(i)
            if comp is not None:
                res.stats.exprfuse_fused += comp.fused
                res.stats.exprfuse_degraded += comp.degraded
            results[i] = res
        return results

    def _engage_partial_replan(self, plan: lp.LogicalPlan, ctx):
        """The shard STAYED unavailable after the re-plan retries and
        partials are allowed: degrade instead of fail — engage the
        scatter-gather drop (partial_now) and re-materialize; with the
        peer's breaker now open the next pass fails fast per dropped
        child and the survivors merge into a FLAGGED partial result
        (ref: the Thanos/Cortex partial-response stance).  One home for
        the degrade protocol shared by the metadata and data paths; the
        dataclasses copy keeps the caller's PlannerParams unmutated."""
        import dataclasses as _dc

        from filodb_tpu.utils.metrics import registry
        registry.counter("query_partial_engaged").increment()
        ctx.planner_params = _dc.replace(ctx.planner_params,
                                         partial_now=True)
        return self.planner.materialize(plan, ctx)

    def exec_logical_plan(self, plan: lp.LogicalPlan,
                          planner_params: Optional[PlannerParams] = None
                          ) -> QueryResult:
        from filodb_tpu.utils.metrics import span
        ctx = self._ctx(planner_params)
        ent = getattr(ctx, "active", None)
        if ent is not None:
            ent.set_phase("planning")
        t_plan0 = _time.perf_counter()
        try:
            with span("query_plan"):
                ep = self.planner.materialize(plan, ctx)
        except Exception as e:  # noqa: BLE001
            return QueryResult([], error=f"planning error: {e}")
        plan_t = _time.perf_counter() - t_plan0
        if ent is not None:
            ent.set_phase("executing")
        if isinstance(plan, lp.MetadataQueryPlan):
            from filodb_tpu.query.execbase import QueryError
            try:
                try:
                    data, stats = ep.execute_internal(self.source)
                except QueryError as e:
                    if e.code != "shard_unavailable" or \
                            not ctx.planner_params.allow_partial_results:
                        raise
                    # metadata scatters degrade like data queries: a
                    # shard that stays down is dropped and the merged
                    # result flagged partial (labels/series from the
                    # survivors beat a hard error on every dashboard's
                    # label dropdown)
                    try:
                        ep = self._engage_partial_replan(plan, ctx)
                    except QueryError:
                        raise
                    except Exception as e2:  # noqa: BLE001
                        return QueryResult([], error=f"replan error: {e2}")
                    data, stats = ep.execute_internal(self.source)
            except QueryError as e:
                # same structured surface as data queries: a dead peer
                # or an expired deadline on a metadata scatter is a
                # typed result error, not a 500
                return QueryResult([], error=str(e))
            stats.plan_s += plan_t
            if isinstance(data, QueryResult):
                if data.partial:
                    # same root-level counter data queries get from
                    # ExecPlan.execute (metadata plans run through
                    # execute_internal, which never increments it)
                    from filodb_tpu.utils.metrics import registry
                    registry.counter("query_partial_results").increment()
                return data
            return QueryResult([], stats)
        # whole-expression compilation (query/exprfuse.py): a multi-leaf
        # tree (joins, multi-shard scatter) batches its leaves' fused
        # preflights into one merged dispatch; single-leaf trees keep
        # the leaf's exact standalone path (min_leaves=2)
        comp = None
        if self._qconfig().exprfuse_enabled:
            from filodb_tpu.query import exprfuse
            comp = exprfuse.compile_tree(ep, self.source, min_leaves=2)
            if comp is not None:
                exprfuse.finish_prepared(comp.calls)
        res = ep.execute(self.source)
        if comp is not None:
            res.stats.exprfuse_fused += comp.fused
            res.stats.exprfuse_degraded += comp.degraded
        res.stats.plan_s += plan_t
        res.trace_id = ctx.query_id
        if res.error and res.error.startswith("shard_unavailable") \
                and self.replan_hook is not None:
            from filodb_tpu.utils.metrics import registry
            for _ in range(max(self._qconfig().dispatch_retries, 0)):
                # a shard owner died mid-query: re-plan against a fresh
                # shard-map snapshot and retry on the reassigned owner
                # (only shard_unavailable — dispatch_timeout is never
                # retried, the remote may still be executing)
                registry.counter("query_replan_retries").increment()
                try:
                    self.planner = self.replan_hook()
                    ep = self.planner.materialize(plan, ctx)
                except Exception as e:  # noqa: BLE001
                    return QueryResult([], error=f"replan error: {e}")
                res = ep.execute(self.source)
                res.trace_id = ctx.query_id
                if not (res.error
                        and res.error.startswith("shard_unavailable")):
                    break
        if res.error and res.error.startswith("shard_unavailable") \
                and ctx.planner_params.allow_partial_results:
            try:
                ep = self._engage_partial_replan(plan, ctx)
            except Exception as e:  # noqa: BLE001
                return QueryResult([], error=f"replan error: {e}")
            res = ep.execute(self.source)
            res.trace_id = ctx.query_id
        return res

    # ------------------------------------------------- Prometheus JSON model

    @staticmethod
    def to_prom_matrix(result: QueryResult) -> Dict:
        """ref: PrometheusModel.toPromSuccessResponse (matrix result)."""
        err = _prom_error_payload(result)
        if err is not None:
            return err
        out = []
        for b in result.blocks:
            vals = np.asarray(b.values)
            if vals.ndim != 2:      # histogram series -> skip buckets here
                continue
            # block-level assembly: one seconds conversion + one NaN mask
            # per block instead of per-sample Python math — the result-
            # serialization share of the fixed per-query floor
            secs = (np.asarray(b.wends, np.int64) / 1000.0).tolist()
            present = ~np.isnan(vals)
            for i, key in enumerate(b.keys):
                idx = np.flatnonzero(present[i]).tolist()
                if not idx:
                    continue
                row = vals[i]
                out.append({"metric": _prom_labels(key.labels_dict),
                            "values": [[secs[j], _fmt(row[j])]
                                       for j in idx]})
        payload = {"status": "success",
                   "data": {"resultType": "matrix", "result": out}}
        return _attach_partial_fields(payload, result.partial,
                                      result.stats.warnings)

    @staticmethod
    def to_prom_vector(result: QueryResult) -> Dict:
        """Instant-vector response (last step of each series)."""
        err = _prom_error_payload(result)
        if err is not None:
            return err
        out = []
        for key, wends, vals in result.series():
            if vals.ndim == 2 or len(vals) == 0:
                continue
            v = vals[-1]
            if not math.isnan(v):
                out.append({"metric": _prom_labels(key.labels_dict),
                            "value": [int(wends[-1]) / 1000.0, _fmt(v)]})
        payload = {"status": "success",
                   "data": {"resultType": "vector", "result": out}}
        return _attach_partial_fields(payload, result.partial,
                                      result.stats.warnings)


def _walk_plan(ep):
    """Yield every node of an exec tree (pre-order)."""
    yield ep
    for c in ep.children:
        yield from _walk_plan(c)


def _prom_error_payload(result: QueryResult) -> Optional[Dict]:
    """Error half of the Prometheus envelope, or None for success.  One
    home for the errorType taxonomy (deadline expiry maps to "timeout",
    a kill to "canceled", so clients can route on it) shared by the
    matrix and vector serializers."""
    if not result.error:
        return None
    if result.error.startswith("query_timeout"):
        etype = "timeout"
    elif result.error.startswith("query_canceled"):
        etype = "canceled"
    elif result.error.startswith(("tenant_overloaded",
                                  "tenant_limit_exceeded")):
        # read-side throttles share the write side's errorType (the
        # remote_write 429s use it too): clients route on it to back off
        etype = "too_many_requests"
    else:
        etype = "query_error"
    return {"status": "error", "errorType": etype, "error": result.error}


def _attach_partial_fields(payload: Dict, partial: bool,
                           warnings: List[str]) -> Dict:
    """Degradation fields of the envelope — never-silent partials: the
    warnings list plus "partial": true.  Shared by the matrix and vector
    serializers AND the metadata route handlers (labels/series payloads
    flag dropped shards the same way)."""
    if partial or warnings:
        payload["warnings"] = (
            list(warnings)
            or ["partial results: one or more shards were unreachable"])
    if partial:
        payload["partial"] = True
    return payload


def _prom_labels(labels: Dict[str, str]) -> Dict[str, str]:
    out = dict(labels)
    metric = out.pop("_metric_", None)
    if metric:
        out["__name__"] = metric
    return out


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return f"{v:.17g}" if v == v else "NaN"


def _single_shard_mapper() -> ShardMapper:
    from filodb_tpu.parallel.shardmapper import ShardEvent
    m = ShardMapper(1)
    m.update_from_event(ShardEvent("IngestionStarted", "", 0, "local"))
    return m
