"""Batched fused-leaf dispatch: merge a dashboard's compatible panels
into single kernel launches (phase-2 of engine.query_range_batch).

Split from query/leafexec.py (round 4, no behavior change); see
doc/kernels.md "Dashboard batching" for the design and on-chip numbers.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from filodb_tpu.query.execbase import AggPartial

@dataclasses.dataclass
class FusedCall:
    """A fused matmul-kernel leaf evaluation with everything resolved
    except the kernel dispatch itself — the unit of merging for
    engine.query_range_batch.  Compatible calls (same plan + device
    values + function flavor) become panels of ONE
    ops/pallas_fused.fused_leaf_agg_batch dispatch: the dashboard case,
    where per-call dispatch latency dominates device time
    (doc/kernels.md round-4 measurements)."""
    plan: object                  # pf.FusedPlan
    values: object                # pf.PaddedValues (device-resident)
    groups: object                # pf.PaddedGroups
    gkeys: List
    wends: np.ndarray
    fn: str
    op: str
    precorrected: bool
    interpret: bool
    ragged: bool
    num_series: int
    # semantic identity (mirror serial + snapshot gen + column + row
    # subset + window params): lets equal-but-distinct plan/values
    # objects merge when the LRU caches declined to share them
    cache_key: Optional[tuple] = None
    # histogram leaf (sum(rate(bucket_metric[...]))): groups carry
    # (group, bucket) SLOTS; the finisher reshapes sums to [G, W, B] and
    # appends the present-series count (AggPartial op "hist_sum")
    bucket_les: Optional[np.ndarray] = None
    num_buckets: int = 1
    # keys-identity token for the produced AggPartial (execbase.agg_token
    # semantics) — rides through _present so kernel-path join operands
    # hit the exprfuse index-map cache like the host-routed ones
    cache_token: Optional[tuple] = None

    def compat_key(self):
        base = (self.fn, self.precorrected, self.interpret, self.ragged)
        if self.cache_key is not None:
            return ("k",) + base + (self.cache_key,)
        return ("id",) + base + (id(self.plan), id(self.values.vals_p))


def finish_fused_calls(calls: List[FusedCall]) -> List[AggPartial]:
    """Phase-2 of engine.query_range_batch: dispatch every FusedCall,
    merging compatible ones into single kernel launches.  A merged set
    whose combined group count would blow the VMEM budget is split back
    into singleton dispatches instead of degrading to the general path
    (the per-panel gate in _try_fused already passed)."""
    from filodb_tpu.ops import pallas_fused as pf
    out: List[Optional[AggPartial]] = [None] * len(calls)
    # dedup identical panels first — a quantile dashboard's p50/p90/p99
    # queries differ only ABOVE the leaf (histogram_quantile transformer),
    # so their leaf calls are the same work: compute once, share the comp
    prim: Dict[tuple, int] = {}
    alias: Dict[int, int] = {}
    for i, fc in enumerate(calls):
        k = fc.compat_key() + (id(fc.groups.gids_p), fc.op, fc.num_buckets)
        if k in prim:
            alias[i] = prim[k]
        else:
            prim[k] = i
    if alias:
        from filodb_tpu.utils.metrics import registry
        registry.counter("fused_batch_deduped").increment(len(alias))
    by_key: Dict[tuple, List[int]] = {}
    for i, fc in enumerate(calls):
        if i in alias:
            continue
        by_key.setdefault(fc.compat_key(), []).append(i)
    def slots(i):
        # histogram panels aggregate over (group, bucket) SLOTS
        return len(calls[i].gkeys) * calls[i].num_buckets

    import time as _time

    # two-phase execution: phase A dispatches every merged set's kernel
    # work WITHOUT reading anything back, phase B synchronizes.  With
    # sharded DeviceMirrors a multi-shard query's leaves hold their
    # working sets on different chips — dispatching everything first
    # lets those chips compute concurrently instead of serializing on
    # each set's host readback (the per-device dispatch contract,
    # doc/multichip.md).
    pending = []
    for idxs in by_key.values():
        fc0 = calls[idxs[0]]
        while idxs:
            take = idxs

            def in_group_mode(i):
                # which panels join the merged group-mode dispatch: min/max
                # run per-series (Gp-independent) and dense count is host
                # math, so neither counts toward the multi-hot group total
                op = calls[i].op
                return op in ("sum", "avg") or (op == "count" and fc0.ragged)

            if len(idxs) > 1:
                Tp = fc0.plan.Tp
                Wp = pf._pad_to(max(fc0.plan.W, 1), pf._LANE)
                over_time = fc0.fn in pf.OVER_TIME_FNS
                ragged_rate = fc0.ragged and fc0.fn in ("rate", "increase",
                                                        "delta")
                kind = fc0.fn if over_time else "rate_family"
                gmode = pf.gather_default(kind)
                while len(take) > 1:
                    n_group = sum(1 for i in take if in_group_mode(i))
                    total = sum(slots(i) for i in take
                                if in_group_mode(i))
                    if total == 0 or pf.pick_block(
                            Tp, Wp, pf.pad_group_count(total),
                            over_time, ragged_rate,
                            panels=max(n_group, 1),
                            gather=gmode) is not None:
                        break
                    take = take[:max(1, len(take) // 2)]
            panels = [(calls[i].groups, slots(i), calls[i].op)
                      for i in take]
            if len(take) > 1:
                # observability of the batching win: actual kernel
                # launches this merged set costs (group-mode + per-series
                # mode), and how many panels shared them
                from filodb_tpu.utils.metrics import registry
                launches = (any(in_group_mode(i) for i in take)
                            + any(calls[i].op in ("min", "max")
                                  for i in take))
                registry.counter("fused_batch_dispatches") \
                    .increment(launches)
                registry.counter("fused_batch_merged_panels") \
                    .increment(len(take))
            _t0 = _time.perf_counter()
            finisher = pf.fused_leaf_agg_batch(
                fc0.plan, fc0.values, panels, fc0.fn,
                precorrected=fc0.precorrected, interpret=fc0.interpret,
                ragged=fc0.ragged, num_series=fc0.num_series, lazy=True)
            pending.append((take, finisher, _time.perf_counter() - _t0))
            idxs = idxs[len(take):]
    from filodb_tpu.utils.devicetelem import telem
    for take, finisher, disp_s in pending:
        _t0 = _time.perf_counter()
        comps = finisher()
        for i, comp in zip(take, comps):
            out[i] = _present(calls[i], comp)
        # kernel dispatch + result readback (np conversion in _present
        # synchronizes), attributed to the node that triggered it AND
        # recorded in the per-chip kernel ledger (utils/devicetelem) —
        # record_dispatch feeds the same exec tally note_device_time
        # did, so QueryStats.device_seconds is unchanged
        fc0 = calls[take[0]]
        telem.record_dispatch(
            f"fused_{fc0.fn}",
            device=pf._committed_device(fc0.values.vals_p),
            shape=(f"S{fc0.num_series}xW{len(fc0.wends)}"
                   f"x{len(take)}p" + (":ragged" if fc0.ragged else "")),
            seconds=disp_s + (_time.perf_counter() - _t0),
            bytes_in=int(getattr(fc0.values.vals_p, "nbytes", 0)),
            bytes_out=sum(int(getattr(c, "nbytes", 0)) for c in comps))
    for i, j in alias.items():
        src = out[j]
        out[i] = dataclasses.replace(src) if src is not None else None
    return out


def _present(fc: FusedCall, comp) -> AggPartial:
    if fc.bucket_les is None:
        return AggPartial(fc.op, fc.gkeys, fc.wends, comp=comp,
                          cache_token=fc.cache_token)
    # histogram: comp[..., 0] is the per-(group, bucket)-slot sum, masked
    # where the window has no samples — the hist_sum presenter NaNs those
    # windows via the count column anyway, so the mask is invisible
    G, B = len(fc.gkeys), fc.num_buckets
    buckets = np.asarray(comp[..., 0], np.float64) \
        .reshape(G, B, -1).transpose(0, 2, 1)           # [G, W, B]
    if fc.ragged:
        # ragged bucket rows (round-5 item 5): per-(slot, window) counts
        # come back from the kernel's presence output; scrape holes hit
        # whole scrape rows, so every bucket of a series shares one
        # validity pattern — bucket 0's count IS the series count
        cnt = np.asarray(comp[..., 1], np.float64) \
            .reshape(G, B, -1)[:, 0, :]                  # [G, W]
    else:
        gsize = fc.groups.gsize.reshape(G, B)[:, 0]
        cnt = gsize[:, None] * fc.plan.wvalid[None, :].astype(np.float64)
    hist_comp = np.concatenate([buckets, cnt[..., None]], axis=2)
    return AggPartial("hist_sum", fc.gkeys, fc.wends, comp=hist_comp,
                      bucket_les=fc.bucket_les, cache_token=fc.cache_token)
