"""Live query introspection: the active-query registry + cooperative
cancellation.

The read path is deeply attributed AFTER the fact (QueryStats, slowlog,
traces), but an in-flight query was invisible and unstoppable: a 30-day
cold-tier scan wedging a node could not be listed, inspected, or killed
— PR 4 deadlines only fire when the budget expires.  The reference runs
every query as a supervised actor that can be observed and terminated
mid-flight (ref: coordinator/.../QueryActor.scala dispatch loop);
production TSDBs treat a live active-query log with kill as table
stakes (Prometheus `--query.active-query-tracker`, ClickHouse
`system.processes` + `KILL QUERY`).  This module is that substrate:

  * ActiveQueryRegistry — every query from frontend admission to
    completion: stable query id (= the trace id), tenant, promql,
    origin, live phase (queued → parsing → planning → executing →
    gathering), and live resource counters updated in place by the
    execbase tally hooks.  Remote leaf executions register under the
    SAME query id with role="remote", so one id names the whole
    distributed query.
  * CancellationToken — stamped on QueryContext as a plain attribute
    (never serialized; remote nodes mint their own and key it by query
    id).  Checked at every exec-node boundary, inside the demand-paging
    loop, and before fused kernel dispatches; `kill()` flips it locally
    AND propagates kill frames to every remote child node recorded at
    dispatch time.
  * Crash-durable active-query file (the Prometheus pattern): entries
    appended at admission, tombstoned at completion; on boot, leftover
    entries are journaled as `query_active_at_crash` events so "what
    was running when the node died" is answerable.
  * Client-disconnect watcher: HTTP query routes bind their socket via
    `bind_client_conn`; a background poller detects the peer closing
    mid-query and trips the same token
    (`queries_killed_total{reason="disconnect"}`), so abandoned
    dashboard polls stop consuming the concurrency semaphore and
    device time.

Killed queries surface as the structured `query_canceled` error code
(QueryError taxonomy), release their frontend semaphore slot, never
poison the result cache (error results are never stored), and
singleflight/coalescer followers see the leader's cancellation and
re-execute instead of inheriting it.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

# the live-phase vocabulary (doc/observability.md): last-write-wins,
# set by the frontend (queued), engine (parsing/planning/executing) and
# scatter-gather roots (gathering)
PHASES = ("queued", "parsing", "planning", "executing", "gathering")


# one lock for ALL token flips: cancel() is the cold path (a kill, a
# disconnect), and sharing the lock keeps CancellationToken allocation
#— which happens once per query on the serving hot path — free of a
# per-instance Lock object
_CANCEL_LOCK = threading.Lock()


class CancellationToken:
    """Cooperative cancellation flag shared by every exec node of one
    query on one node.  `cancel()` is idempotent — the FIRST caller's
    reason wins (double-kill keeps reason=admin; a later disconnect of
    an already-killed query changes nothing)."""

    __slots__ = ("_cancelled", "reason", "detail")

    def __init__(self):
        self._cancelled = False
        self.reason = ""
        self.detail = ""

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    def cancel(self, reason: str, detail: str = "") -> bool:
        """Returns True iff THIS call flipped the token."""
        with _CANCEL_LOCK:
            if self._cancelled:
                return False
            self.reason = reason
            self.detail = detail
            self._cancelled = True
            return True

    def raise_if_cancelled(self, where: str = "") -> None:
        if self._cancelled:
            from filodb_tpu.query.execbase import QueryError
            raise QueryError(
                "query_canceled",
                f"query killed (reason={self.reason or 'admin'})"
                + (f" {where}" if where else "")
                + (f": {self.detail}" if self.detail else ""))


class ActiveQuery:
    """One live execution on THIS node.  Counters mutate in place (plain
    int/float writes under the GIL — readers tolerate slightly-stale
    values; a torn multi-field read only skews a live display row)."""

    __slots__ = ("query_id", "promql", "tenant_ws", "tenant_ns", "origin",
                 "role", "phase", "start_unix", "token", "verdict",
                 "samples_scanned", "samples_paged", "bytes_paged",
                 "device_dispatches", "device_seconds", "remote_nodes",
                 "client_conn", "_registry")

    def __init__(self, query_id: str, promql: str, tenant: Tuple[str, str],
                 origin: str, role: str, registry: "ActiveQueryRegistry",
                 client_conn=None):
        self.query_id = query_id
        self.promql = promql
        self.tenant_ws, self.tenant_ns = tenant
        self.origin = origin
        self.role = role                      # "frontend" | "remote"
        self.phase = "queued"
        self.start_unix = time.time()
        self.token = CancellationToken()
        self.verdict = ""                     # set at deregister
        self.samples_scanned = 0
        self.samples_paged = 0
        self.bytes_paged = 0
        self.device_dispatches = 0
        self.device_seconds = 0.0
        self.remote_nodes: List[str] = []     # "host:port" children
        self.client_conn = client_conn
        self._registry = registry

    # ------------------------------------------------------ live updates

    def set_phase(self, phase: str) -> None:
        if phase != self.phase:
            self._registry._phase_moved(self, self.phase, phase)
            self.phase = phase

    def add(self, samples: int = 0, paged_samples: int = 0,
            paged_bytes: int = 0, dispatches: int = 0,
            device_s: float = 0.0) -> None:
        self.samples_scanned += int(samples)
        self.samples_paged += int(paged_samples)
        self.bytes_paged += int(paged_bytes)
        self.device_dispatches += int(dispatches)
        self.device_seconds += float(device_s)

    def tally(self, node, stats, exec_tally) -> None:
        """execute_internal's per-node hook: leaves own their scan
        counters (parents only merge children's — adding those again
        would double-count); device work is EXCLUSIVE per node, so every
        node may add its own."""
        if not node.children:
            self.add(samples=stats.samples_scanned,
                     paged_samples=stats.samples_paged,
                     paged_bytes=stats.bytes_paged)
        if exec_tally.device_s > 0:
            self.add(dispatches=1, device_s=exec_tally.device_s)

    def note_remote(self, where: str) -> None:
        """Record a remote child node at dispatch time — the kill fan-out
        list (and the /admin/queries `remoteNodes` column)."""
        if where not in self.remote_nodes:
            self.remote_nodes.append(where)

    def to_dict(self) -> dict:
        return {
            "queryID": self.query_id,
            "promql": self.promql,
            "tenant": {"ws": self.tenant_ws, "ns": self.tenant_ns},
            "origin": self.origin,
            "role": self.role,
            "phase": self.phase,
            "ageSeconds": round(time.time() - self.start_unix, 3),
            "startUnixSeconds": round(self.start_unix, 3),
            "canceled": self.token.cancelled,
            "cancelReason": self.token.reason,
            "counters": {
                "samplesScanned": self.samples_scanned,
                "samplesPaged": self.samples_paged,
                "bytesPaged": self.bytes_paged,
                "deviceDispatches": self.device_dispatches,
                "deviceSeconds": round(self.device_seconds, 6),
            },
            "remoteNodes": list(self.remote_nodes),
        }


def verdict_of(result) -> str:
    """Final verdict for a finished query — the value slowlog entries,
    trace payloads, and deregistration share (one home, no drift)."""
    err = getattr(result, "error", None) if result is not None else None
    if not err:
        return "completed"
    if err.startswith("query_canceled"):
        return "killed"
    if err.startswith("query_timeout"):
        return "deadline"
    if err.startswith("tenant_overloaded"):
        return "shed"
    return "error"


class ActiveQueryRegistry:
    """Process-wide table of in-flight queries.  Entries are grouped by
    query id: a coordinator entry and this node's remote-leaf executions
    of OTHER coordinators' queries live side by side (one process can be
    both), and `kill()` flips every token registered under the id."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_id: Dict[str, List[ActiveQuery]] = {}
        self.enabled = True
        # crash-durable active-query file (JSONL: {"op": "+"/"-"} pairs;
        # unmatched "+" at boot = running at crash time)
        self._path = ""
        self._file = None
        # per-ws inflight/queued counts backing the live gauges, plus a
        # per-ws cache of the Gauge objects themselves: the serving hot
        # path updates both on every register/deregister, and re-keying
        # through the metrics registry each time (tag-tuple sort + dict
        # hit) under 8-thread contention was measurable
        self._inflight: Dict[str, int] = {}
        self._queued: Dict[str, int] = {}
        self._gauge_cache: Dict[str, Tuple] = {}
        # disconnect watcher (lazily started on the first entry that
        # carries a client socket)
        self._watcher: Optional[threading.Thread] = None
        self.watch_interval_s = 0.1

    # ----------------------------------------------------------- config

    def configure(self, enabled: Optional[bool] = None,
                  path: Optional[str] = None) -> "ActiveQueryRegistry":
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if path is not None and path != self._path:
                if self._file is not None:
                    try:
                        self._file.close()
                    except OSError:
                        pass
                self._path = path
                self._file = None
        return self

    def replay_crash_log(self) -> int:
        """Boot step: journal every entry the previous process left
        unmatched in the active-query file as `query_active_at_crash`,
        then truncate.  Returns how many were found."""
        with self._lock:
            path = self._path
        if not path:
            return 0
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            return 0
        open_entries: Dict[str, dict] = {}
        for line in lines:
            try:
                rec = json.loads(line)
            except ValueError:
                continue                     # torn tail from the crash
            if rec.get("op") == "+":
                open_entries[rec.get("id", "")] = rec
            else:
                open_entries.pop(rec.get("id", ""), None)
        from filodb_tpu.utils.events import journal
        for qid, rec in open_entries.items():
            journal.emit("query_active_at_crash", subsystem="query",
                         query_id=qid, promql=rec.get("promql", ""),
                         ws=rec.get("ws", ""), origin=rec.get("origin", ""),
                         started_unix=rec.get("unix"))
        try:
            with open(path, "w"):
                pass
        except OSError:
            pass
        return len(open_entries)

    def _log(self, op: str, ent: ActiveQuery) -> None:
        """Append one crash-log line (best-effort: the registry is the
        record; the file is the crash forensics)."""
        if not self._path:
            return
        rec = {"op": op, "id": ent.query_id}
        if op == "+":
            rec.update(promql=ent.promql[:300], ws=ent.tenant_ws,
                       origin=ent.origin, role=ent.role,
                       unix=round(ent.start_unix, 3))
        try:
            with self._lock:
                if self._file is None:
                    self._file = open(self._path, "a")
                self._file.write(json.dumps(rec, separators=(",", ":"))
                                 + "\n")
                self._file.flush()
        except OSError:
            from filodb_tpu.utils.metrics import registry
            registry.counter("active_query_log_errors").increment()

    # -------------------------------------------------------- lifecycle

    def register(self, query_id: str, promql: str = "",
                 tenant: Tuple[str, str] = ("", ""), origin: str = "query",
                 role: str = "frontend") -> Optional[ActiveQuery]:
        """New live entry (None when the registry is disabled — callers
        treat a None entry as 'no introspection', not an error).  The
        HTTP shell's client socket, when bound on this thread, rides
        along for the disconnect watcher."""
        if not self.enabled:
            return None
        conn = getattr(_conn_local, "sock", None)
        ent = ActiveQuery(query_id, promql, tenant, origin, role, self,
                          client_conn=conn)
        ws = ent.tenant_ws
        with self._lock:
            self._by_id.setdefault(query_id, []).append(ent)
            self._inflight[ws] = self._inflight.get(ws, 0) + 1
            self._queued[ws] = self._queued.get(ws, 0) + 1
        if self._path:
            self._log("+", ent)
        if conn is not None:
            self._ensure_watcher()
        return ent

    def deregister(self, ent: Optional[ActiveQuery],
                   verdict: str = "completed") -> None:
        if ent is None:
            return
        ent.verdict = verdict
        ws = ent.tenant_ws
        with self._lock:
            ents = self._by_id.get(ent.query_id)
            if ents is None:
                return                       # double-deregister: no-op
            try:
                ents.remove(ent)
            except ValueError:
                return                       # double-deregister: no-op
            if not ents:
                del self._by_id[ent.query_id]
            self._inflight[ws] = max(self._inflight.get(ws, 1) - 1, 0)
            if ent.phase == "queued":
                self._queued[ws] = max(self._queued.get(ws, 1) - 1, 0)
        if self._path:
            self._log("-", ent)
        if verdict == "deadline":
            # the deadline reaper is a kill too (the metric's third
            # reason): token-flipped kills count in kill() instead
            from filodb_tpu.utils.metrics import registry
            registry.counter("queries_killed", reason="deadline").increment()

    def _phase_moved(self, ent: ActiveQuery, old: str, new: str) -> None:
        if (old == "queued") == (new == "queued"):
            return
        ws = ent.tenant_ws
        with self._lock:
            if new == "queued":
                self._queued[ws] = self._queued.get(ws, 0) + 1
            else:
                self._queued[ws] = max(self._queued.get(ws, 1) - 1, 0)

    def refresh_gauges(self) -> None:
        """Publish the per-tenant inflight/queue-depth counts as gauges
        — called at SCRAPE time (routes._own_metrics), the same refresh-
        on-scrape pattern the shard gauges use, so the serving hot path
        pays dict arithmetic only, never metric-registry traffic."""
        from filodb_tpu.utils.metrics import registry
        with self._lock:
            snap_in = dict(self._inflight)
            snap_q = dict(self._queued)
        for ws, v in snap_in.items():
            g = self._gauge_cache.get(ws)
            if g is None:
                g = self._gauge_cache[ws] = (
                    registry.gauge("queries_inflight", ws=ws),
                    registry.gauge("query_queue_depth", ws=ws))
            g[0].update(v)
            g[1].update(snap_q.get(ws, 0))

    # ------------------------------------------------------------- read

    def entries(self) -> List[ActiveQuery]:
        with self._lock:
            return [e for ents in self._by_id.values() for e in ents]

    def get(self, query_id: str) -> List[ActiveQuery]:
        with self._lock:
            return list(self._by_id.get(query_id, ()))

    def snapshot(self) -> List[dict]:
        """The /admin/queries payload, oldest-first."""
        ents = sorted(self.entries(), key=lambda e: e.start_unix)
        return [e.to_dict() for e in ents]

    # ------------------------------------------------------------- kill

    def kill(self, query_id: str, reason: str = "admin", detail: str = "",
             propagate: bool = True) -> dict:
        """Flip every token registered under the id; `propagate` also
        sends kill frames to the remote child nodes the entries recorded
        at dispatch time (so remote leaves stop scanning instead of
        computing a result nobody will read).  Idempotent: killing an
        unknown or already-killed id reports killed=False and changes
        nothing."""
        ents = self.get(query_id)
        killed = 0
        remotes: List[str] = []
        for ent in ents:
            if ent.token.cancel(reason, detail):
                killed += 1
            for where in ent.remote_nodes:
                if where not in remotes:
                    remotes.append(where)
        if killed:
            from filodb_tpu.utils.metrics import registry
            registry.counter("queries_killed", reason=reason).increment()
            from filodb_tpu.utils.events import journal
            journal.emit("query_killed", subsystem="query",
                         query_id=query_id, reason=reason,
                         remote_nodes=",".join(remotes))
        prop_errors = 0
        if propagate and killed and remotes:
            from filodb_tpu.parallel.transport import send_kill
            for where in remotes:
                host, _, port = where.rpartition(":")
                try:
                    send_kill(host, int(port), query_id, reason=reason)
                except Exception:  # noqa: BLE001 — a dead child needs no kill
                    prop_errors += 1
                    from filodb_tpu.utils.metrics import registry
                    registry.counter("queries_kill_propagation_errors"
                                     ).increment()
        return {"killed": killed > 0, "entries": len(ents),
                "remoteNodes": remotes, "propagationErrors": prop_errors}

    # ------------------------------------------- client-disconnect watch

    def _ensure_watcher(self) -> None:
        with self._lock:
            if self._watcher is not None:
                return
            self._watcher = threading.Thread(target=self._watch_loop,
                                             name="query-disconnect-watch",
                                             daemon=True)
            self._watcher.start()

    def _kill_async(self, query_id: str) -> None:
        """Disconnect kills run OFF the watcher thread: the remote
        kill-frame fan-out can block seconds per unreachable child, and
        one wedged propagation must not stall disconnect detection for
        every OTHER abandoned query on the node."""
        threading.Thread(
            target=self.kill, args=(query_id,),
            kwargs={"reason": "disconnect",
                    "detail": "client closed the connection"},
            name="query-disconnect-kill", daemon=True).start()

    def _watch_loop(self) -> None:
        import select
        import socket as _socket
        while True:
            time.sleep(self.watch_interval_s)
            for ent in self.entries():
                sock = ent.client_conn
                if sock is None or ent.token.cancelled:
                    continue
                try:
                    readable, _, _ = select.select([sock], [], [], 0)
                    if not readable:
                        continue
                    # EOF (empty peek) = the client hung up mid-query;
                    # pending pipelined bytes are NOT a disconnect
                    if sock.recv(1, _socket.MSG_PEEK) == b"":
                        self._kill_async(ent.query_id)
                except (OSError, ValueError):
                    # closed/invalid fd: same verdict as an EOF
                    self._kill_async(ent.query_id)


active_queries = ActiveQueryRegistry()


# ------------------------------------------------- admission handoff

# The frontend hands the registration DOWN the serving stack on a
# thread-local, in two stages:
#
#   * `set_pending((tenant, origin))` at _serve admission — two plain
#     attribute writes, the ONLY cost a cache hit or singleflight
#     follower ever pays.  Queries that finish inside the serving
#     layers (sub-millisecond, holding no slot and no device) never
#     register at all — the Prometheus active-query-tracker stance of
#     wrapping engine execution, not the cache.
#   * the scheduler layer (_run) consumes the pending marker and
#     registers the ActiveQuery the moment REAL work begins — before
#     the semaphore wait, so a queued query is listable and killable
#     with the slot never held.
#
# `set_admission(ent)` then carries the entry to the engine, whose _ctx
# adopts its id — so ctx.query_id == the registered id == the trace id.
_admission = threading.local()


def set_pending(info: Optional[Tuple]) -> None:
    _admission.pending = info


def take_pending() -> Optional[Tuple]:
    info = getattr(_admission, "pending", None)
    _admission.pending = None
    return info


def set_admission(ent: Optional[ActiveQuery]) -> None:
    _admission.entry = ent


def peek_admission() -> Optional[ActiveQuery]:
    return getattr(_admission, "entry", None)


def take_admission() -> Optional[ActiveQuery]:
    ent = getattr(_admission, "entry", None)
    _admission.entry = None
    return ent


# -------------------------------------------- HTTP connection binding

_conn_local = threading.local()


class bind_client_conn:
    """Bind the serving thread's client socket for the duration of a
    request so `register()` can attach it to the entry (the disconnect
    watcher's handle).  The HTTP shell wraps `api.handle` in this."""

    def __init__(self, sock):
        self.sock = sock

    def __enter__(self):
        self._prev = getattr(_conn_local, "sock", None)
        _conn_local.sock = self.sock
        return self

    def __exit__(self, exc_type, exc, tb):
        _conn_local.sock = self._prev
        return False
