"""Node-level aggregation pushdown — ship the reduce, not the series.

The 3-phase map/reduce/present aggregation contract (ops/agg.py,
ref: AggrOverRangeVectors.scala) already runs the MAP phase on whatever
node executes the leaf, so per-shard dispatches reply with [G, W]
partials.  What still scaled with the shard count was the coordinator's
side: one round trip per shard, one partial per shard buffered whole,
and the inter-shard reduce running entirely on the coordinator.

This module promotes the PR-6 chip-level partial-merge architecture one
level up, exactly like FiloDB's queryplanner hierarchy pushes
`sum by (...)` into the data nodes (PAPER.md §1; the Thanos/Cortex
query-frontend map/reduce split): the planner groups an aggregation's
per-shard map subtrees by OWNING NODE and wraps each group in a
`RemoteAggregateExec` (query/nonleaf.py) dispatched to that node as ONE
unit.  The data node scans its shards, runs the local reduce, and
replies with a single [G, W] AggPartial — one round trip and one tiny
partial per NODE, merged coordinator-side by the unchanged
`execbase.reduce_partials`.

Correctness rules:

  - Only EXACTLY-mergeable partial forms push.  PUSHABLE_OPS are the
    component-form aggregators whose reduce is an order-insensitive
    elementwise sum/min/max.  CANDIDATE_PUSHABLE_OPS (PR 17) push via
    the node-level intermediate mode (nonleaf.RemoteAggregateExec
    docstring): `quantile` concatenates centroids without
    re-compressing, `topk`/`bottomk` prune candidates to the node-
    local per-window top-k, `count_values` ships candidate rows.
    Joins and raw selectors keep the per-shard path.
  - A shard listed TWICE (both owners during a live-handoff window)
    never enters a node group: the duplicate leaves stay direct
    children of the coordinator reducer so the PR-11 gather dedup
    (first owner to answer wins, twin absorbs shard_unavailable)
    keeps working on partials.
  - A node group that cannot be reached falls back to the per-shard
    dispatch path (`PushdownDispatcher`): the wrapped leaves kept
    their own per-shard (replica-failover) dispatchers, so a dead
    primary still fails over — availability never loses to pushdown.

Verdicts (`pushed` / `fallback` / `not_pushable`) land in QueryStats
(`?stats=true`, explain analyze, slowlog) and the `query_pushdown`
counter.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from filodb_tpu.query.execbase import (InProcessPlanDispatcher,
                                       PlanDispatcher, QueryError)

# component/sketch-form ops whose partial merge is associative and
# order-insensitive enough to regroup per node without changing results
# (histogram sum rides op="sum" and merges bucketwise the same way)
PUSHABLE_OPS = frozenset({"sum", "count", "avg", "min", "max",
                          "stddev", "stdvar", "group"})

# rank/candidate/sketch aggregations made exactly-pushable by PR 17
# (query/nonleaf.py RemoteAggregateExec.node_level): quantile node
# partials concatenate centroids without re-compressing, topk/bottomk
# prune to the node-local per-window top-k (ops/select.topk_keep_rows),
# count_values ships its candidate rows — in every case the
# coordinator's final merge sees data bit-identical to the flat
# per-shard path
CANDIDATE_PUSHABLE_OPS = frozenset({"topk", "bottomk", "quantile",
                                    "count_values"})


def pushdown_enabled(ctx) -> bool:
    """Per-request PlannerParams override, else the server config."""
    v = getattr(ctx.planner_params, "aggregation_pushdown", None)
    if v is not None:
        return bool(v)
    from filodb_tpu.config import settings
    return settings().query.aggregation_pushdown


def _count_not_pushable(n: int) -> None:
    if n:
        from filodb_tpu.utils.metrics import registry
        registry.counter("query_pushdown",
                         verdict="not_pushable").increment(n)


def _target_of(dispatcher) -> Optional[PlanDispatcher]:
    """The node-address dispatcher a child's dispatcher resolves to, or
    None when the child is local / not addressable as one node."""
    fn = getattr(dispatcher, "pushdown_target", None)
    if fn is None:
        return None
    try:
        return fn()
    except Exception:  # noqa: BLE001 — an empty owner list etc.
        return None


class PushdownDispatcher(PlanDispatcher):
    """Dispatcher for one RemoteAggregateExec node group: the whole
    subtree ships to the owning data node; if that node is unreachable
    (connect refused / breaker open) the group degrades to TODAY'S path
    — the group plan executes in-process on the coordinator, which
    scatter-gathers its leaves through their own per-shard
    replica-failover dispatchers and reduces locally."""

    def __init__(self, target: PlanDispatcher):
        self.target = target

    def dispatch(self, plan, source):
        from filodb_tpu.utils.metrics import registry
        try:
            data, stats = self.target.dispatch(plan, source)
        except QueryError as e:
            if e.code != "shard_unavailable":
                # dispatch_timeout / query_timeout / remote_failure never
                # fall back: the remote may still be executing, and a
                # re-run would spend the survivors' budget twice — the
                # parent's partial/deadline machinery owns these
                raise
            registry.counter("query_pushdown",
                             verdict="fallback").increment()
            data, stats = InProcessPlanDispatcher().dispatch(plan, source)
            stats.pushdown_fallback += 1
            return data, stats
        registry.counter("query_pushdown", verdict="pushed").increment()
        stats.pushdown_pushed += 1
        rec = getattr(plan.ctx, "analyze", None)
        if rec is not None:
            rec.add(plan, {"plan": type(plan).__name__, "self_s": 0.0,
                           "device_s": 0.0, "transfer_s": 0.0,
                           "bytes_transferred": stats.bytes_transferred,
                           "samples_scanned": stats.samples_scanned,
                           "series_scanned": stats.series_scanned,
                           "shards_queried": stats.shards_queried,
                           "pushdown": "pushed"})
        return data, stats


def plan_aggregate_pushdown(children: List, op: str, params: Tuple,
                            ctx) -> Tuple[List, int]:
    """Regroup an aggregation's materialized children for node-level
    pushdown.  Returns (children', not_pushable_count): same-node
    pushable map subtrees collapse into RemoteAggregateExec groups; the
    rest pass through unchanged.  not_pushable_count is the number of
    REMOTE children the aggregation could not push (local children are
    not a verdict — there is no wire to win)."""
    from filodb_tpu.query.leafexec import MultiSchemaPartitionsExec
    from filodb_tpu.query.nonleaf import RemoteAggregateExec
    from filodb_tpu.query.transformers import (AggregateMapReduce,
                                               PeriodicSamplesMapper,
                                               RepeatToGridMapper)

    def _remote(c) -> bool:
        return not isinstance(c.dispatcher, InProcessPlanDispatcher)

    n_remote = sum(1 for c in children if _remote(c))
    if n_remote == 0:
        return children, 0
    if not pushdown_enabled(ctx):
        return children, 0
    if op not in PUSHABLE_OPS and op not in CANDIDATE_PUSHABLE_OPS:
        _count_not_pushable(n_remote)
        return children, n_remote
    # duplicate shards (both owners materialized during a live handoff)
    # stay direct children so the gather dedup contract keeps holding
    shard_seen: Dict[object, int] = {}
    for c in children:
        s = getattr(c, "shard", None)
        if s is not None:
            shard_seen[s] = shard_seen.get(s, 0) + 1

    groups: Dict[Tuple, List] = {}
    group_targets: Dict[Tuple, PlanDispatcher] = {}
    order: List[Tuple[str, object]] = []       # rebuild in original order
    not_pushable = 0
    for c in children:
        tgt = _target_of(c.dispatcher) if _remote(c) else None
        pushable = (
            tgt is not None
            and isinstance(c, MultiSchemaPartitionsExec)
            and shard_seen.get(getattr(c, "shard", None), 0) == 1
            and c.transformers
            and isinstance(c.transformers[-1], AggregateMapReduce)
            and all(isinstance(t, (PeriodicSamplesMapper,
                                   AggregateMapReduce, RepeatToGridMapper))
                    for t in c.transformers))
        if not pushable:
            if _remote(c):
                not_pushable += 1
            order.append(("child", c))
            continue
        key = (getattr(tgt, "host", None), getattr(tgt, "port", None))
        if key not in groups:
            groups[key] = []
            group_targets[key] = tgt
            order.append(("group", key))
        groups[key].append(c)
    out: List = []
    for kind, item in order:
        if kind == "child":
            out.append(item)
            continue
        node = RemoteAggregateExec(ctx, groups[item], op, params)
        node.dispatcher = PushdownDispatcher(group_targets[item])
        out.append(node)
    _count_not_pushable(not_pushable)
    return out, not_pushable
