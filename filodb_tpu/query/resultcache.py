"""Step-aligned incremental PromQL result cache.

The Thanos/Cortex query-frontend pattern (ref: cortexproject
queryrange/results_cache.go, thanos-io queryfrontend — PAPERS.md survey
of serving stacks), adapted to this store's consistency machinery: a
dashboard re-poll of `query_range` recomputes only the windows the
append horizon hasn't frozen yet and merges them with the cached prefix,
instead of rescanning the full range.  BENCH_r05 shows the per-query
floor (~75 ms) is flat from 8k to 1M series — so for a 30-window re-poll
where 28 windows are cache-final, this turns 30 windows of work into 2.

Soundness model (why a cached window can be reused at all):

  * Appends are strictly in-order per series (DenseSeriesStore drops
    out-of-order samples: ingest checks ts > last_ts), so every FUTURE
    sample of row r lands after last_ts[r].  Windows ending at or before
    ``horizon = min over live rows of last_ts`` can never change under
    ingest — that horizon is the entry's ``immutable_upto``.
  * Changes to the SERIES SET (new partitions, eviction, pid recycling)
    move `index.mutations` / `keys_epoch`; both ride in the entry's
    ``token`` and any mismatch drops the entry.  This is what lets the
    cache survive eviction-driven `shift_version` bumps without ever
    serving rows keyed to a dead mirror snapshot: the cache stores final
    RESULT windows, not device state, and the only store facts it relies
    on (in-order appends, series-set identity) are exactly the ones the
    token tracks.
  * Queries whose value at window w depends on anything other than data
    in (-inf, w] are never cached: `@ start()/end()` pins, negative
    offsets (windows reading the future), and the arbitrary-choice
    limitk family.  See `_plan_cacheable`.

Entries hold per-series float64 rows on the query's step grid.  Grid
identity is (promql, step, start mod step, planner-params repr): two
polls of one dashboard panel share a grid even as start/end slide.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from filodb_tpu.ops.timewindow import make_window_ends
from filodb_tpu.query.rangevector import (QueryResult, QueryStats,
                                          RangeVectorKey, ResultBlock,
                                          remove_nan_series)

# functions excluded from caching: limitk/limit_ratio keep an ARBITRARY
# series subset, so a prefix chosen on one poll need not match the
# subset a full recompute would choose
_UNCACHEABLE_CALLS = frozenset({"limitk", "limit_ratio"})


def _plan_cacheable(promql: str) -> bool:
    """True when per-window results are immutable under in-order appends:
    no @-pinning, no negative offsets, no arbitrary-subset functions.
    Parse failures return False — the engine will surface the error."""
    from filodb_tpu.promql import ast as A
    from filodb_tpu.promql.parser import parse_query_cached

    try:
        expr = parse_query_cached(promql)
    except Exception:  # noqa: BLE001 — parse errors: engine reports them
        return False

    def walk(node) -> bool:
        if isinstance(node, A.Expr):
            if getattr(node, "at_ms", None) is not None:
                return False
            if getattr(node, "offset_ms", 0) < 0:
                return False
            if isinstance(node, A.Subquery):
                # the converter builds the inner grid from the QUERY start
                # (parser._conv: `start - off - window`), not an absolute
                # alignment — two polls sharing an outer grid phase can
                # sample the subquery at different inner timestamps, so a
                # cached window need not equal a fresh recompute
                return False
            if isinstance(node, A.Call) and node.name in _UNCACHEABLE_CALLS:
                return False
        if dataclasses.is_dataclass(node):
            return all(walk(getattr(node, f.name))
                       for f in dataclasses.fields(node))
        if isinstance(node, (list, tuple)):
            return all(walk(x) for x in node)
        return True

    return walk(expr)


@dataclasses.dataclass
class _Entry:
    wends: np.ndarray                          # int64 ms grid, contiguous
    series: Dict[RangeVectorKey, np.ndarray]   # f64 [W] per series
    immutable_upto: int                        # wends <= this are final
    token: Tuple                               # shard series-set identity
    nbytes: int
    ws: str = ""                               # owning tenant workspace


def _series_map(res: QueryResult, width: int) -> Optional[
        Dict[RangeVectorKey, np.ndarray]]:
    """Flatten result blocks to a per-key row map, or None when the shape
    is uncacheable (histogram-valued blocks, duplicate keys, rows not on
    the expected window grid — a clamped/split grid must bypass, not
    crash the merge)."""
    out: Dict[RangeVectorKey, np.ndarray] = {}
    for b in res.blocks:
        vals = np.asarray(b.values, dtype=np.float64)
        if vals.ndim != 2 or vals.shape[1] != width:
            return None
        for i, k in enumerate(b.keys):
            if k in out:
                return None              # ambiguous identity: don't cache
            out[k] = vals[i]
    return out


class ResultCache:

    def __init__(self, max_entries: int = 256,
                 max_entry_bytes: int = 32 << 20,
                 max_total_bytes: int = 256 << 20,
                 tenant_quota_bytes: int = 0):
        self.max_entries = max_entries
        self.max_entry_bytes = max_entry_bytes
        self.max_total_bytes = max_total_bytes
        # per-tenant (_ws_) byte quota — the cache half of noisy-
        # neighbor isolation (query.result_cache_tenant_quota_bytes):
        # inserting past it evicts the tenant's OWN oldest entries, and
        # an entry that cannot fit inside the quota is rejected outright
        # — another tenant's entry is NEVER evicted to make room for an
        # over-quota one.  0 disables (global LRU only).
        self.tenant_quota_bytes = tenant_quota_bytes
        self._lock = threading.Lock()
        self._entries: Dict[Tuple, _Entry] = {}
        self._total_bytes = 0
        self._tenant_bytes: Dict[str, int] = {}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._total_bytes = 0
            self._tenant_bytes.clear()

    def tenant_bytes(self, ws: str) -> int:
        with self._lock:
            return self._tenant_bytes.get(ws, 0)

    def __len__(self) -> int:
        return len(self._entries)

    # ------------------------------------------------------------- serve

    def query_range(self, run, promql: str, start_s: int, step_s: int,
                    end_s: int, pp_key: str,
                    state: Optional[Tuple[Tuple, int]]) -> QueryResult:
        """Serve (promql, start, step, end) through the cache.  `run(s, e)`
        executes the underlying engine over [s, e] seconds on the same
        step; `state` is (token, horizon_ms) from the owning shards, or
        None to bypass (remote/unknown sources)."""
        from filodb_tpu.utils.metrics import registry
        if state is None:
            return run(start_s, end_s)
        token, horizon_ms = state
        step_ms = max(int(step_s), 1) * 1000
        start_ms, end_ms = int(start_s) * 1000, int(end_s) * 1000
        wends_new = make_window_ends(start_ms, end_ms, step_ms)
        if wends_new.size == 0:
            return run(start_s, end_s)
        key = (promql, step_ms, start_ms % step_ms, pp_key)
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:          # LRU touch
                self._entries[key] = self._entries.pop(key)
        if ent is not None and ent.token != token:
            registry.counter("query_result_cache_invalidations").increment()
            self._drop(key, ent)
            ent = None
        n_reuse = 0
        if ent is not None:
            # reusable prefix: new windows covered by the entry AND final.
            # The grids share a phase, so coverage is a contiguous prefix
            # of wends_new unless the request reaches back before the
            # entry's own start (then: plain miss).
            lim = min(int(ent.wends[-1]), ent.immutable_upto)
            if int(wends_new[0]) >= int(ent.wends[0]):
                n_reuse = int(np.searchsorted(wends_new, lim, side="right"))
        if n_reuse == 0:
            registry.counter("query_result_cache_misses").increment()
            res = run(start_s, end_s)
            res.stats.result_cache = "miss"
            self._store(key, wends_new, res, token, horizon_ms)
            return res
        if n_reuse == wends_new.size:
            registry.counter("query_result_cache_hits").increment()
            res = self._from_cache(ent, wends_new)
            res.stats.result_cache = "hit"
            return res
        # partial hit: compute only the non-final tail and merge
        registry.counter("query_result_cache_partial_hits").increment()
        tail_start_s = int(wends_new[n_reuse]) // 1000
        tail = run(tail_start_s, end_s)
        if tail.error is not None and tail.error.startswith(
                "tenant_overloaded"):
            # the scheduler SHED the tail run: the cached prefix is
            # still perfectly valid (nothing about the data changed) —
            # keep it, surface the 429 as-is, and do NOT burn a second
            # full run through the very admission gate that just shed
            # us (that would amplify load exactly when shedding it)
            return tail
        if tail.error is not None or tail.partial or tail.data is not None:
            # errors/partials must surface exactly as a full run would —
            # and never be merged into or stored over good windows.  Drop
            # the entry so a degraded system pays ONE full run per poll
            # from here on, not tail + full every time
            self._drop(key, ent)
            res = run(start_s, end_s)
            res.stats.result_cache = "miss"
            return res
        tail_map = _series_map(tail, wends_new.size - n_reuse)
        if tail_map is None:
            self._drop(key, ent)
            res = run(start_s, end_s)
            res.stats.result_cache = "miss"
            return res
        merged: Dict[RangeVectorKey, np.ndarray] = {}
        W = wends_new.size
        off = int(np.searchsorted(ent.wends, wends_new[0]))
        for k, row in ent.series.items():
            out = np.full(W, np.nan)
            out[:n_reuse] = row[off:off + n_reuse]
            merged[k] = out
        for k, row in tail_map.items():
            out = merged.get(k)
            if out is None:
                out = merged[k] = np.full(W, np.nan)
            out[n_reuse:] = row
        res = self._build_result(merged, wends_new, tail.stats)
        res.stats.result_cache = "partial"
        res.trace_id = tail.trace_id
        self._insert(key, _Entry(
            wends_new, merged, min(horizon_ms, int(wends_new[-1])), token,
            sum(r.nbytes for r in merged.values())))
        return res

    # ----------------------------------------------------------- helpers

    def _from_cache(self, ent: _Entry, wends_new: np.ndarray) -> QueryResult:
        off = int(np.searchsorted(ent.wends, wends_new[0]))
        W = wends_new.size
        series = {k: row[off:off + W] for k, row in ent.series.items()}
        return self._build_result(series, wends_new, QueryStats())

    @staticmethod
    def _build_result(series: Dict[RangeVectorKey, np.ndarray],
                      wends: np.ndarray, stats: QueryStats) -> QueryResult:
        if not series:
            return QueryResult([], stats)
        keys = list(series)
        vals = np.stack([series[k] for k in keys])
        block = remove_nan_series(ResultBlock(keys, wends, vals))
        # keep the tail run's phase/resource attribution (that IS the
        # cost this poll paid) — only the result-shape counters change
        st = dataclasses.replace(stats, result_samples=int(vals.size),
                                 result_bytes=int(vals.nbytes))
        return QueryResult([block] if block is not None else [], st)

    def _drop(self, key, ent: _Entry) -> None:
        with self._lock:
            if self._entries.get(key) is ent:
                del self._entries[key]
                self._uncount_locked(ent)

    def _uncount_locked(self, ent: _Entry) -> None:
        self._total_bytes -= ent.nbytes
        left = self._tenant_bytes.get(ent.ws, 0) - ent.nbytes
        if left > 0:
            self._tenant_bytes[ent.ws] = left
        else:
            self._tenant_bytes.pop(ent.ws, None)

    def _store(self, key, wends: np.ndarray, res: QueryResult, token,
               horizon_ms: int) -> None:
        if res.error is not None or res.partial or res.data is not None:
            return
        smap = _series_map(res, wends.size)
        if smap is None:
            return
        nbytes = sum(r.nbytes for r in smap.values())
        if nbytes > self.max_entry_bytes:
            return
        self._insert(key, _Entry(wends, smap,
                                 min(horizon_ms, int(wends[-1])), token,
                                 nbytes))

    def _insert(self, key, ent: _Entry) -> None:
        if ent.nbytes > self.max_entry_bytes:
            return
        # the owning tenant: the query's _ws_ shard key (memoized parse)
        from filodb_tpu.utils.usage import tenant_of
        ent.ws = tenant_of(key[0])[0]
        quota = self.tenant_quota_bytes
        if quota and ent.nbytes > quota:
            # over-quota entries are REJECTED, never fitted by evicting
            # someone else (isolation invariant: a tenant's churn only
            # ever costs that tenant's entries under the quota rule)
            from filodb_tpu.utils.metrics import registry
            registry.counter("result_cache_tenant_quota_rejections",
                             ws=ent.ws).increment()
            return
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._uncount_locked(old)
            if quota:
                # evict this tenant's OWN oldest entries until the new
                # one fits inside its quota — other tenants' entries are
                # untouchable here by construction
                while self._tenant_bytes.get(ent.ws, 0) + ent.nbytes \
                        > quota:
                    victim = next((k for k, e in self._entries.items()
                                   if e.ws == ent.ws), None)
                    if victim is None:
                        break
                    self._uncount_locked(self._entries.pop(victim))
                    from filodb_tpu.utils.metrics import registry
                    registry.counter("result_cache_tenant_quota_evictions",
                                     ws=ent.ws).increment()
            self._entries[key] = ent
            self._total_bytes += ent.nbytes
            self._tenant_bytes[ent.ws] = \
                self._tenant_bytes.get(ent.ws, 0) + ent.nbytes
            while self._entries and (
                    len(self._entries) > self.max_entries
                    or self._total_bytes > self.max_total_bytes):
                if len(self._entries) == 1:
                    break                # always keep the newest entry
                k = next(iter(self._entries))
                self._uncount_locked(self._entries.pop(k))
