"""Query-serving frontend: turns a fast single-query engine into fast
concurrent TRAFFIC.

Three layers wrap one QueryEngine, outermost first (ref: the Cortex/
Thanos query-frontend split — dedup, result caching and scheduling live
in front of the querier, not inside it):

  1. singleflight — byte-identical in-flight `query_range` requests
     share ONE execution (N dashboard clients polling the same panel
     cost one query; `query_singleflight_hits` counts the shares).
  2. incremental result cache (query/resultcache.py) — a re-poll
     computes only the windows past the append horizon and merges them
     with the cached prefix.
  3. scheduler — a semaphore bounds concurrently EXECUTING queries
     (query.max_concurrent_queries), and the window-grid coalescer
     (query/coalesce.py) still merges same-grid peers into one
     engine.query_range_batch when query.batch_window_ms > 0.

Cache hits and dedup'd followers never touch the semaphore, so the
bound applies exactly to the expensive device-dispatching work.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from filodb_tpu.core.shard import NO_HORIZON_MS
from filodb_tpu.query.coalesce import QueryCoalescer
from filodb_tpu.query.resultcache import ResultCache, _plan_cacheable


class _Flight:
    __slots__ = ("done", "result")

    def __init__(self):
        self.done = threading.Event()
        self.result = None


class QueryFrontend:
    """Per-dataset serving frontend around one QueryEngine."""

    def __init__(self, engine, window_s: float = 0.0, config=None):
        if config is None:
            from filodb_tpu.config import settings
            config = settings()
        q = config.query
        self.engine = engine
        self.coalescer = QueryCoalescer(engine, window_s)
        self.cache: Optional[ResultCache] = (
            ResultCache(q.result_cache_max_entries,
                        q.result_cache_max_entry_bytes)
            if q.result_cache_enabled else None)
        self._sf_enabled = q.singleflight_enabled
        self._sf_lock = threading.Lock()
        self._inflight: Dict[Tuple, _Flight] = {}
        n = q.max_concurrent_queries
        self._sem = threading.BoundedSemaphore(n) if n > 0 else None
        self._ask_timeout_s = q.ask_timeout_s
        # promql -> cacheability memo (parse once per distinct string)
        self._cacheable: Dict[str, bool] = {}

    # ------------------------------------------------------------ public

    def query_range(self, promql: str, start_s: int, step_s: int,
                    end_s: int, planner_params=None):
        if not self._sf_enabled:
            return self._cached_query(promql, start_s, step_s, end_s,
                                      planner_params)
        key = (promql, start_s, step_s, end_s, repr(planner_params))
        with self._sf_lock:
            flight = self._inflight.get(key)
            leader = flight is None
            if leader:
                flight = self._inflight[key] = _Flight()
        if not leader:
            from filodb_tpu.utils.metrics import registry
            registry.counter("query_singleflight_hits").increment()
            # generous bound mirroring the coalescer's: a wedged leader
            # must not strand followers — they fall back to running solo
            flight.done.wait(timeout=max(300.0, 3 * self._ask_timeout_s))
            if flight.result is not None:
                return flight.result
            return self._cached_query(promql, start_s, step_s, end_s,
                                      planner_params)
        try:
            res = self._cached_query(promql, start_s, step_s, end_s,
                                     planner_params)
            flight.result = res
            return res
        finally:
            with self._sf_lock:
                if self._inflight.get(key) is flight:
                    del self._inflight[key]
            flight.done.set()

    # ----------------------------------------------------------- layers

    def _cached_query(self, promql, start_s, step_s, end_s, pp):
        cache = self.cache
        if cache is None or not self._promql_cacheable(promql):
            return self._run(promql, start_s, step_s, end_s, pp)

        def run(s0, e0):
            return self._run(promql, s0, step_s, e0, pp)

        return cache.query_range(run, promql, start_s, step_s, end_s,
                                 repr(pp), self._state())

    def _run(self, promql, start_s, step_s, end_s, pp):
        sem = self._sem
        if sem is None:
            return self.coalescer.query_range(promql, start_s, step_s,
                                              end_s, pp)
        # never fail a query on queue pressure: a full queue just means
        # this request executes unthrottled after the wait (observable
        # via the counter rather than a user-visible error)
        acquired = sem.acquire(timeout=self._ask_timeout_s)
        if not acquired:
            from filodb_tpu.utils.metrics import registry
            registry.counter("query_scheduler_timeouts").increment()
        try:
            return self.coalescer.query_range(promql, start_s, step_s,
                                              end_s, pp)
        finally:
            if acquired:
                sem.release()

    def _promql_cacheable(self, promql: str) -> bool:
        ok = self._cacheable.get(promql)
        if ok is None:
            ok = _plan_cacheable(promql)
            if len(self._cacheable) > 1024:
                self._cacheable.clear()
            self._cacheable[promql] = ok
        return ok

    # ------------------------------------------------------ store state

    def _state(self) -> Optional[Tuple[Tuple, int]]:
        """(series-set token, append horizon ms) across the engine's local
        shards, or None when the source can't vouch for them (remote /
        unknown sources bypass the cache)."""
        source = getattr(self.engine, "source", None)
        shards_for = getattr(source, "shards_for", None)
        if shards_for is None:
            return None
        try:
            shards = shards_for(self.engine.dataset)
        except Exception:  # noqa: BLE001 — exotic sources: just bypass
            return None
        if not shards:
            return None
        token = []
        horizon = None
        for sh in shards:
            token.append((sh.keys_serial, sh.keys_epoch,
                          sh.index.mutations))
            h = sh.append_horizon_ms()
            horizon = h if horizon is None else min(horizon, h)
        if horizon is None or horizon <= NO_HORIZON_MS:
            return None
        return tuple(token), horizon
