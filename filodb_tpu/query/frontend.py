"""Query-serving frontend: turns a fast single-query engine into fast
concurrent TRAFFIC.

Three layers wrap one QueryEngine, outermost first (ref: the Cortex/
Thanos query-frontend split — dedup, result caching and scheduling live
in front of the querier, not inside it):

  1. singleflight — byte-identical in-flight `query_range` requests
     share ONE execution (N dashboard clients polling the same panel
     cost one query; `query_singleflight_hits` counts the shares).
  2. incremental result cache (query/resultcache.py) — a re-poll
     computes only the windows past the append horizon and merges them
     with the cached prefix.
  3. scheduler — a WEIGHTED-FAIR scheduler (query/qos.py) bounds
     concurrently EXECUTING queries (query.max_concurrent_queries) with
     per-tenant queues, configurable concurrency shares and deficit-
     round-robin dispatch (an idle tenant's share redistributes), plus
     adaptive load shedding: queries whose predicted queue wait would
     blow their deadline budget — or whose tenant queue is already at
     query.tenant_max_queue_depth — are rejected at admission with the
     structured `tenant_overloaded` error (HTTP 429 + Retry-After,
     write-side parity with the ingest limits).  The window-grid
     coalescer (query/coalesce.py) still merges same-grid peers into
     one engine.query_range_batch when query.batch_window_ms > 0.

Cache hits and dedup'd followers never touch the scheduler, so the
bound applies exactly to the expensive device-dispatching work.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Dict, Optional, Tuple

from filodb_tpu.core.shard import NO_HORIZON_MS
from filodb_tpu.query.coalesce import QueryCoalescer
from filodb_tpu.query.qos import (SHED_ERROR_CODE, WeightedFairScheduler,
                                  account_wait)
from filodb_tpu.query.rangevector import (PlannerParams, QueryResult,
                                          remaining_budget)
from filodb_tpu.query.resultcache import ResultCache, _plan_cacheable


class _Flight:
    __slots__ = ("done", "result")

    def __init__(self):
        self.done = threading.Event()
        self.result = None


def _canceled_result(tok, where: str) -> QueryResult:
    """The structured query_canceled result for a kill that landed while
    the request was BLOCKED in the serving stack (queue / dedup wait) —
    before any exec node existed to raise it."""
    return QueryResult([], error=("query_canceled: query killed "
                                  f"{where} (reason={tok.reason or 'admin'})"
                                  + (f": {tok.detail}" if tok.detail
                                     else "")))


class QueryFrontend:
    """Per-dataset serving frontend around one QueryEngine."""

    def __init__(self, engine, window_s: float = 0.0, config=None):
        if config is None:
            from filodb_tpu.config import settings
            config = settings()
        q = config.query
        self.engine = engine
        self.coalescer = QueryCoalescer(engine, window_s)
        self.cache: Optional[ResultCache] = (
            ResultCache(q.result_cache_max_entries,
                        q.result_cache_max_entry_bytes,
                        tenant_quota_bytes=q
                        .result_cache_tenant_quota_bytes)
            if q.result_cache_enabled else None)
        self._sf_enabled = q.singleflight_enabled
        self._sf_lock = threading.Lock()
        self._inflight: Dict[Tuple, _Flight] = {}
        n = q.max_concurrent_queries
        # weighted-fair admission over the execution capacity (PR 14):
        # the old global BoundedSemaphore let one abusive tenant fill
        # every slot; the scheduler dispatches per-tenant queues by
        # deficit round robin and sheds doomed queries at admission
        self._sched = WeightedFairScheduler(
            n, shares=q.tenant_shares,
            default_share=q.tenant_default_share,
            max_queue_depth=q.tenant_max_queue_depth,
            shed_enabled=q.shed_enabled) if n > 0 else None
        self._ask_timeout_s = q.ask_timeout_s
        # promql -> cacheability memo (parse once per distinct string)
        self._cacheable: Dict[str, bool] = {}
        # --- observability (PR 3): slowlog + per-tenant usage/limits ---
        self._slow_s = q.slow_query_threshold_s
        self._usage_enabled = q.tenant_usage_enabled
        self._warn_limit = q.tenant_samples_warn_limit
        self._fail_limit = q.tenant_samples_fail_limit
        # --- failure-domain hardening (PR 4): end-to-end deadlines ---
        self._default_timeout_s = q.default_timeout_s
        self._allow_partial_default = q.allow_partial_results
        # shed slowlog records are rate-limited PER TENANT (one per
        # second): a flood producing hundreds of sheds/s must not turn
        # the flight recorder into the overload's biggest CPU consumer —
        # the counter counts every shed; the slowlog keeps representative
        # records
        self._last_shed_log: Dict[str, float] = {}

    # ------------------------------------------------------------ public

    @property
    def scheduler(self):
        """The weighted-fair admission scheduler (query/qos.py), or
        None when max_concurrent_queries == 0 (unbounded)."""
        return self._sched

    def query_range(self, promql: str, start_s: int, step_s: int,
                    end_s: int, planner_params=None):
        """The serving entry point: tenant admission, then the
        singleflight/cache/scheduler stack, then usage accounting + the
        slow-query flight recorder on the way out.  The recorded
        duration is the CLIENT-OBSERVED wall (queue wait and dedup wait
        included) — that's the latency an operator is paged for."""
        # the deadline clock starts at ADMISSION: scheduler queue wait
        # and singleflight dedup wait spend from the same budget the
        # exec tree enforces (doc/robustness.md deadline semantics)
        pp = self._admit_params(planner_params)
        key = (promql, start_s, step_s, end_s, repr(pp))
        return self._serve(
            key, lambda: self._cached_query(promql, start_s, step_s,
                                            end_s, pp),
            promql, (start_s, step_s, end_s), pp, None, "query_range")

    def query_instant(self, promql: str, time_s: int, planner_params=None,
                      tenant=None, origin: str = "query"):
        """Instant queries through the SAME serving stack as query_range
        — tenant admission/limits, deadline stamped at admission,
        singleflight dedup, the concurrency semaphore, usage accounting
        and the slowlog — minus the step-aligned result cache (a
        one-step grid has no reusable prefix).  Before this the
        /api/v1/query route called eng.query_instant directly, a free
        pass around every one of those; the ruler evaluates every rule
        through here (`tenant` override -> the `_rules_` accounting
        bucket, `origin` tags its slowlog records)."""
        pp = self._admit_params(planner_params)
        # an instant query at t IS the range query (t, 1, t): sharing
        # the range key-space lets a dashboard's instant poll dedup
        # against an identical in-flight one
        key = (promql, time_s, 1, time_s, repr(pp))
        return self._serve(
            key, lambda: self._run(promql, time_s, 1, time_s, pp),
            promql, (time_s, 1, time_s), pp, tenant, origin)

    def _serve(self, key, run, promql, grid, pp, tenant, origin):
        """Admission -> singleflight -> accounting: the shared serving
        wrapper for both query shapes."""
        from filodb_tpu.query.activequeries import set_pending, verdict_of
        from filodb_tpu.utils.slowlog import slowlog
        from filodb_tpu.utils.usage import tenant_of, usage
        if self._usage_enabled:
            if tenant is None:
                tenant = tenant_of(promql)
            err = usage.admit(tenant[0], tenant[1], self._warn_limit,
                              self._fail_limit)
            if err is not None:
                res = QueryResult([], error=err)
                # scan-limit 429s answer with the same Retry-After
                # contract as the ingest limits and the overload sheds:
                # seconds until the tenant's rolling window resets
                res.retry_after_s = usage.scan_retry_after(tenant[0],
                                                           tenant[1])
                return res
        if tenant is None:
            tenant = ("", "")
        # live introspection (query/activequeries.py): mark the request
        # so the SCHEDULER layer registers it the moment real work
        # begins (before the semaphore wait).  Cache hits and dedup'd
        # followers finish inside the serving layers holding nothing —
        # they pay these two thread-local writes and never register.
        set_pending((tenant, origin))
        t0 = _time.perf_counter()
        res = None
        try:
            res, shared = self._singleflight(key, run, pp)
        finally:
            set_pending(None)
        dur = _time.perf_counter() - t0
        # singleflight followers received the LEADER's result: the work
        # (and its samples_scanned) happened once — re-recording it per
        # follower would bill a tenant N× for one execution and write N
        # identical slowlog records, throttling tenants fastest exactly
        # when dedup makes their traffic cheapest
        if not shared:
            if self._usage_enabled and res is not None:
                usage.record_query(tenant[0], tenant[1], dur,
                                   res.stats.samples_scanned,
                                   res.stats.result_bytes)
            # shed queries are force-recorded (verdict `shed`): an
            # operator triaging "why is this tenant getting 429s" reads
            # the actual shed requests, not just a counter — they never
            # cross the slow threshold on their own (shedding is fast;
            # that is the point).  Rate-limited to one record per tenant
            # per second so a shed storm can't make the recorder itself
            # a load source.
            shed = (res is not None and res.error is not None
                    and res.error.startswith(SHED_ERROR_CODE))
            if shed:
                now = _time.monotonic()
                shed = now - self._last_shed_log.get(tenant[0],
                                                     -1e9) >= 1.0
                if shed:
                    if len(self._last_shed_log) > 1024:
                        self._last_shed_log.clear()  # hostile ws churn
                    self._last_shed_log[tenant[0]] = now
            slowlog.maybe_record(promql, grid[0], grid[1], grid[2], dur,
                                 res, tenant=tenant, origin=origin,
                                 threshold_s=self._slow_s, force=shed)
            # serving-latency histogram with the trace id as its
            # OpenMetrics exemplar (p99 spike -> the exact trace in one
            # hop), and the trace tagged with its door for the
            # /admin/traces?origin= filter
            from filodb_tpu.utils.metrics import collector, registry
            tid = getattr(res, "trace_id", "") if res is not None else ""
            registry.histogram("query_latency_seconds",
                               origin=origin).record(dur,
                                                     exemplar=tid or None)
            if tid:
                collector.note_origin(
                    tid, "rule_eval" if origin.startswith("rule_")
                    else "query")
                # final verdict on the trace (completed/killed/deadline)
                # so /admin/traces/<id> answers "how did it end" —
                # the slowlog cross-link's other half
                collector.note_verdict(tid, verdict_of(res))
        return res

    def _singleflight(self, key, run, planner_params=None):
        """Returns (result, shared): shared=True iff this caller rode a
        singleflight leader's execution instead of running its own.
        A killed LEADER's result is never inherited — followers
        re-execute under their own (freshly-registered) token."""
        if not self._sf_enabled:
            return run(), False
        with self._sf_lock:
            flight = self._inflight.get(key)
            leader = flight is None
            if leader:
                flight = self._inflight[key] = _Flight()
        if not leader:
            from filodb_tpu.utils.metrics import registry
            registry.counter("query_singleflight_hits").increment()
            # generous bound mirroring the coalescer's: a wedged leader
            # must not strand followers — they fall back to running solo.
            # The follower's DEADLINE bounds the wait too (dedup wait
            # spends the same budget as execution); an expired budget
            # then surfaces as the structured query_timeout via the solo
            # path's scheduler/exec-boundary checks.
            bound = remaining_budget(planner_params,
                                     max(300.0, 3 * self._ask_timeout_s))
            dl = getattr(planner_params, "deadline_unix_s", 0.0) \
                if planner_params is not None else 0.0
            completed = flight.done.wait(timeout=bound)
            if flight.result is not None:
                shared = flight.result
                # never inherit the LEADER's deadline expiry OR its
                # kill: budgets and kills are per-request (repr-excluded
                # from the dedup key), so a short-timeout or killed
                # leader must not fail its followers — they run solo
                # under their own deadline/token
                if not (shared.error is not None
                        and (shared.error.startswith("query_timeout")
                             or shared.error.startswith("query_canceled"))):
                    return shared, True
            res = run()
            if not completed and not (dl and _time.time() >= dl):
                # the leader wedged past the full bound (NOT our own
                # deadline expiring): the fallback must be visible to
                # operators, not a silent doubled execution
                registry.counter("singleflight_leader_timeouts").increment()
                if res is not None:
                    res.stats.warnings.append(
                        "singleflight leader timed out; follower fell "
                        "back to solo execution")
            return res, False
        try:
            res = run()
            flight.result = res
            return res, False
        finally:
            with self._sf_lock:
                if self._inflight.get(key) is flight:
                    del self._inflight[key]
            flight.done.set()

    def analyze_range(self, promql: str, start_s: int, step_s: int,
                      end_s: int, planner_params=None):
        """EXPLAIN ANALYZE execution (/api/v1/explain?analyze=true):
        the SAME tenant admission, scheduler bound, and usage/slowlog
        accounting as query_range — an unaccounted analyze endpoint
        would be a free pass around the limits and the concurrency
        bound — but runs a recorder-attached plan and bypasses the
        result caches (annotations must reflect a real execution).
        Returns (result, recorder, exec_tree); recorder/tree are None
        when admission rejected the query.  Parse/planning errors
        propagate (the HTTP edge turns them into 400s, exactly like the
        plain explain path)."""
        import uuid as _uuid

        from filodb_tpu.promql.parser import (TimeStepParams,
                                              query_range_to_logical_plan)
        from filodb_tpu.query.execbase import AnalyzeRecorder
        from filodb_tpu.query.rangevector import QueryContext, QueryResult
        from filodb_tpu.utils.slowlog import slowlog
        from filodb_tpu.utils.usage import tenant_of, usage
        tenant = ("", "")
        if self._usage_enabled:
            tenant = tenant_of(promql)
            err = usage.admit(tenant[0], tenant[1], self._warn_limit,
                              self._fail_limit)
            if err is not None:
                return QueryResult([], error=err), None, None
        t0 = _time.perf_counter()
        plan = query_range_to_logical_plan(
            promql, TimeStepParams(start_s, step_s, end_s))
        ctx = QueryContext(query_id=_uuid.uuid4().hex[:16])
        # analyze executions are live-listable/killable like any other
        # (an unkillable analyze verb would be a free pass around the
        # introspection layer, exactly like the limits)
        from filodb_tpu.query.activequeries import (active_queries,
                                                    verdict_of)
        ent = active_queries.register(ctx.query_id, promql=promql,
                                      tenant=tenant,
                                      origin="explain_analyze")
        if ent is not None:
            ctx.cancel = ent.token
            ctx.active = ent
            ent.set_phase("planning")
        # same deadline semantics as query_range: the budget starts at
        # admission and the exec tree below enforces it.  analyze has no
        # re-plan/retry layer, so the partial-results gate engages the
        # scatter-gather drop directly — a dead shard yields a flagged
        # partial analysis, not a hard error
        import dataclasses as _dc
        planner_params = self._admit_params(planner_params)
        if planner_params.allow_partial_results:
            planner_params = _dc.replace(planner_params, partial_now=True)
        ctx.planner_params = planner_params
        ctx.deadline_unix_s = planner_params.deadline_unix_s
        ep = self.engine.planner.materialize(plan, ctx)
        rec = AnalyzeRecorder()
        # plain attribute, NOT a dataclass field: remote-dispatched
        # subtrees must serialize without it (see AnalyzeRecorder doc)
        ctx.analyze = rec
        sched = self._sched
        adm = None
        res = None
        if sched is not None:
            adm = sched.admit(
                tenant[0],
                remaining_budget(planner_params, self._ask_timeout_s),
                ent.token if ent is not None else None,
                deadline_unix_s=planner_params.deadline_unix_s)
            if adm.status == "shed":
                # analyze is accounted and scheduled like any query —
                # and therefore SHED like any query (an unsheddable
                # analyze verb would be a free pass around the overload
                # protection, exactly like the limits)
                res = self._shed_result(tenant[0], adm)
                active_queries.deregister(ent, verdict_of(res))
                return res, None, None
        try:
            if ent is not None:
                ent.set_phase("executing")
            res = ep.execute(self.engine.source)
        finally:
            if adm is not None and adm.acquired:
                sched.release(tenant[0])
            active_queries.deregister(ent, verdict_of(res))
        res.trace_id = ctx.query_id
        account_wait(res, adm)
        dur = _time.perf_counter() - t0
        if self._usage_enabled:
            usage.record_query(tenant[0], tenant[1], dur,
                               res.stats.samples_scanned,
                               res.stats.result_bytes)
        slowlog.maybe_record(promql, start_s, step_s, end_s, dur, res,
                             tenant=tenant, origin="explain_analyze",
                             threshold_s=self._slow_s)
        return res, rec, ep

    # ----------------------------------------------------------- layers

    def _cached_query(self, promql, start_s, step_s, end_s, pp):
        cache = self.cache
        if cache is None or not self._promql_cacheable(promql):
            return self._run(promql, start_s, step_s, end_s, pp)

        def run(s0, e0):
            return self._run(promql, s0, step_s, e0, pp)

        return cache.query_range(run, promql, start_s, step_s, end_s,
                                 repr(pp), self._state())

    def _admit_params(self, pp):
        """Copy of the caller's PlannerParams with the end-to-end
        deadline stamped (None → server defaults).  The request's
        timeout_s is CAPPED by query.default_timeout_s; the returned
        copy keys identically to the input (deadline is repr-excluded),
        so singleflight/coalescer/result-cache keys are unaffected."""
        import dataclasses as _dc

        from filodb_tpu.query.rangevector import compute_deadline
        if pp is None:
            pp = PlannerParams(
                allow_partial_results=self._allow_partial_default)
        deadline = compute_deadline(pp, self._default_timeout_s)
        if deadline == pp.deadline_unix_s:
            return pp
        return _dc.replace(pp, deadline_unix_s=deadline)

    def _run(self, promql, start_s, step_s, end_s, pp):
        """The registration boundary (query/activequeries.py): the
        pending marker set at admission becomes a live ActiveQuery HERE
        — the moment the request is about to consume real resources
        (scheduler slot, engine, device).  The entry's id becomes
        ctx.query_id (= the trace id) via the thread-local handoff the
        engine adopts in _ctx; deregistration (with the final verdict)
        happens when execution returns, canceled-in-queue included."""
        from filodb_tpu.query.activequeries import (active_queries,
                                                    set_admission,
                                                    take_admission,
                                                    take_pending,
                                                    verdict_of)
        info = take_pending()
        ws = info[0][0] if info is not None else ""
        ent = None
        if info is not None:
            from filodb_tpu.utils.metrics import mint_trace_id
            ent = active_queries.register(mint_trace_id(), promql=promql,
                                          tenant=info[0], origin=info[1])
        if ent is None:
            return self._run_scheduled(promql, start_s, step_s, end_s,
                                       pp, None, ws)
        set_admission(ent)
        res = None
        try:
            res = self._run_scheduled(promql, start_s, step_s, end_s,
                                      pp, ent, ws)
            return res
        finally:
            take_admission()         # clear if the engine never adopted
            active_queries.deregister(ent, verdict_of(res))

    def _shed_result(self, ws: str, adm) -> QueryResult:
        """One home for the shed surface: the structured
        tenant_overloaded result (Retry-After riding along for the HTTP
        edge), the queries_shed{ws,reason} counter, and the queue-wait
        attribution every outcome gets.  The counter tags the
        scheduler's FOLDED ws (adm.ws), never the raw client-controlled
        one — hostile ws churn must not grow metric cardinality."""
        from filodb_tpu.utils.metrics import registry
        registry.counter("queries_shed", ws=adm.ws or ws,
                         reason=adm.reason).increment()
        res = QueryResult([], error=adm.shed_error())
        res.retry_after_s = adm.retry_after_s
        account_wait(res, adm)
        return res

    def _run_scheduled(self, promql, start_s, step_s, end_s, pp, ent,
                       ws=""):
        sched = self._sched
        tok = ent.token if ent is not None else None
        if sched is None:
            return self.coalescer.query_range(promql, start_s, step_s,
                                              end_s, pp)
        # weighted-fair admission: the tenant's queue, the tenant's
        # share.  Shedding happens HERE, before any wait — a query whose
        # predicted queue wait would blow its deadline (or whose tenant
        # queue is full) 429s immediately instead of burning a slot
        # until query_timeout.  Past that gate the pre-QoS stances hold:
        # never fail a query on queue pressure alone (a scheduler-wait
        # timeout runs unthrottled, observable via the counter), but the
        # DEADLINE does bound the wait — time queued spends from the
        # same end-to-end budget as execution.
        dl = getattr(pp, "deadline_unix_s", 0.0) if pp is not None else 0.0
        timeout = remaining_budget(pp, self._ask_timeout_s)
        adm = sched.admit(ws, timeout, tok, deadline_unix_s=dl)
        if adm.status == "shed":
            return self._shed_result(ws, adm)
        try:
            if adm.status == "cancelled" or (tok is not None
                                             and tok.cancelled):
                # killed while queued: the structured error, with the
                # slot either never held (kill interrupted the wait) or
                # released by the finally below before anyone noticed
                res = _canceled_result(tok, "in the scheduler queue")
                account_wait(res, adm)
                return res
            if dl and _time.time() >= dl:
                from filodb_tpu.utils.metrics import registry
                registry.counter("query_timeouts_in_queue").increment()
                res = QueryResult(
                    [], error=("query_timeout: deadline exceeded after "
                               f"{adm.waited_s:.3f}s in the scheduler "
                               "queue"))
                account_wait(res, adm)
                return res
            if not adm.acquired:
                from filodb_tpu.utils.metrics import registry
                registry.counter("query_scheduler_timeouts").increment()
            res = self.coalescer.query_range(promql, start_s, step_s,
                                             end_s, pp)
            # queue attribution: scheduler wait is part of the query's
            # serving cost but not of any exec node's cpu time
            account_wait(res, adm)
            return res
        finally:
            if adm.acquired:
                sched.release(ws)

    def _promql_cacheable(self, promql: str) -> bool:
        ok = self._cacheable.get(promql)
        if ok is None:
            ok = _plan_cacheable(promql)
            if len(self._cacheable) > 1024:
                self._cacheable.clear()
            self._cacheable[promql] = ok
        return ok

    # ------------------------------------------------------ store state

    def _state(self) -> Optional[Tuple[Tuple, int]]:
        """(series-set token, append horizon ms) across the engine's local
        shards, or None when the source can't vouch for them (remote /
        unknown sources bypass the cache).

        A federated planner additionally folds its registry state —
        participating cluster set, per-cluster health transitions, and
        each remote door's per-dataset data tokens (ride FPING replies)
        — into the token, so a remote cluster dying, recovering or
        ingesting invalidates cached federated answers exactly like
        local ingest does (doc/federation.md cache safety)."""
        source = getattr(self.engine, "source", None)
        shards_for = getattr(source, "shards_for", None)
        if shards_for is None:
            return None
        try:
            shards = shards_for(self.engine.dataset)
        except Exception:  # noqa: BLE001 — exotic sources: just bypass
            return None
        if not shards:
            return None
        token = []
        horizon = None
        for sh in shards:
            token.append((sh.keys_serial, sh.keys_epoch,
                          sh.index.mutations))
            h = sh.append_horizon_ms()
            horizon = h if horizon is None else min(horizon, h)
        if horizon is None or horizon <= NO_HORIZON_MS:
            return None
        fed_fn = getattr(self.engine.planner, "federation_state", None)
        if fed_fn is not None:
            try:
                return (tuple(token), ("federation",) + fed_fn()), horizon
            except Exception:  # noqa: BLE001 — registry trouble: bypass
                return None
        return tuple(token), horizon
