"""Result types: range-vector keys, dense result blocks, query context.

The reference materializes per-series SerializedRangeVectors (ref:
core/.../query/RangeVector.scala:121, ResultTypes.scala).  The TPU-native
design keeps results BATCH-DENSE: one ResultBlock = many series sharing the
same step grid, values in a single [S, W] (or [S, W, B] histogram) matrix.
Transformers and reducers operate on whole blocks on device; per-series
objects only exist at the JSON/serialization edge.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class RangeVectorKey:
    """Series identity in results (ref: RangeVector.scala:27
    CustomRangeVectorKey)."""
    labels: Tuple[Tuple[str, str], ...]             # sorted

    @staticmethod
    def make(labels: Dict[str, str]) -> "RangeVectorKey":
        return RangeVectorKey(tuple(sorted(labels.items())))

    @property
    def labels_dict(self) -> Dict[str, str]:
        return dict(self.labels)

    def without(self, names: Sequence[str]) -> "RangeVectorKey":
        ns = set(names)
        return RangeVectorKey(tuple((k, v) for k, v in self.labels
                                    if k not in ns))

    def only(self, names: Sequence[str]) -> "RangeVectorKey":
        ns = set(names)
        return RangeVectorKey(tuple((k, v) for k, v in self.labels
                                    if k in ns))

    def __str__(self) -> str:
        return "{" + ",".join(f'{k}="{v}"' for k, v in self.labels) + "}"


@dataclasses.dataclass
class ResultBlock:
    """A batch of series on a common step grid.

    values: [S, W] float (NaN = absent at that step), or [S, W, B] for
    histogram-valued vectors (bucket_les gives upper bounds).
    """
    keys: List[RangeVectorKey]
    wends: np.ndarray                               # int64 [W] step timestamps ms
    values: np.ndarray
    bucket_les: Optional[np.ndarray] = None
    # working-set identity for the host group-id cache; ONLY propagate
    # through transformers that keep `keys` unchanged 1:1 (a stale token
    # on a re-keyed block would serve another key set's group ids)
    cache_token: Optional[tuple] = None

    @property
    def num_series(self) -> int:
        return len(self.keys)

    @property
    def is_histogram(self) -> bool:
        return self.values.ndim == 3

    def select(self, rows: np.ndarray) -> "ResultBlock":
        return ResultBlock([self.keys[int(r)] for r in rows], self.wends,
                           np.asarray(self.values)[rows], self.bucket_les)


def concat_blocks(blocks: Sequence[ResultBlock]) -> Optional[ResultBlock]:
    """Concatenate blocks sharing a step grid (DistConcatExec analogue)."""
    blocks = [b for b in blocks if b is not None and b.num_series > 0]
    if not blocks:
        return None
    if len(blocks) == 1:
        return blocks[0]
    keys: List[RangeVectorKey] = []
    for b in blocks:
        keys.extend(b.keys)
    return ResultBlock(keys, blocks[0].wends,
                       np.concatenate([np.asarray(b.values) for b in blocks]),
                       blocks[0].bucket_les)


@dataclasses.dataclass
class QueryStats:
    """ref: QueryStats / TimeSeriesShardStats query-side counters."""
    samples_scanned: int = 0
    series_scanned: int = 0
    result_samples: int = 0
    shards_queried: int = 0
    # set when allow_partial_results dropped an unreachable child —
    # propagates bottom-up through merge() to the root QueryResult
    partial: bool = False

    def merge(self, other: "QueryStats") -> None:
        self.samples_scanned += other.samples_scanned
        self.series_scanned += other.series_scanned
        self.result_samples += other.result_samples
        self.shards_queried += other.shards_queried
        self.partial = self.partial or other.partial


@dataclasses.dataclass
class QueryResult:
    """ref: filodb.query QueryResult / QueryError."""
    blocks: List[ResultBlock]
    stats: QueryStats = dataclasses.field(default_factory=QueryStats)
    error: Optional[str] = None
    # metadata-query payloads (label values etc.) ride in `data`
    data: Optional[object] = None
    # the query's trace id (= ctx.query_id): fetch the stitched cross-node
    # span tree from utils.metrics.collector / GET /admin/traces/<id>
    trace_id: str = ""
    # True when allow_partial_results dropped unreachable shards from a
    # scatter-gather (ref: QueryContext.scala PlannerParams
    # allowPartialResults / QueryResult mayBePartial): NEVER silently —
    # to_prom_matrix surfaces it as a warning + "partial": true
    partial: bool = False

    @property
    def num_series(self) -> int:
        return sum(b.num_series for b in self.blocks)

    def series(self):
        """Iterate (key, wends, values_row) across blocks — serialization edge."""
        for b in self.blocks:
            vals = np.asarray(b.values)
            for i, k in enumerate(b.keys):
                yield k, b.wends, vals[i]


@dataclasses.dataclass
class PlannerParams:
    """ref: core/.../query/QueryContext.scala:98 PlannerParams."""
    spread: int = 1
    sample_limit: int = 1_000_000        # RESULT samples (post-transform)
    # samples a leaf may SCAN (gather/page) per shard for one query — the
    # fail-fast guard against pathological selectors (ref:
    # OnDemandPagingShard.scala:55 capDataScannedPerShardCheck).  Distinct
    # from sample_limit: aggregations scan much more than they return.
    scan_limit: int = 50_000_000
    group_by_cardinality_limit: int = 100_000
    join_cardinality_limit: int = 100_000
    enforced_limits: bool = True
    shard_overrides: Optional[List[int]] = None
    process_multi_partition: bool = False
    # scatter-gather children whose shard owner is unreachable are
    # DROPPED (result flagged partial) instead of failing the query
    # (ref: PlannerParams.allowPartialResults)
    allow_partial_results: bool = False


@dataclasses.dataclass
class QueryContext:
    """Per-query context threaded through planning + execution
    (ref: QueryContext.scala)."""
    query_id: str = ""
    submit_time_ms: int = 0
    origin: str = ""
    planner_params: PlannerParams = dataclasses.field(default_factory=PlannerParams)
    lookback_ms: int = 5 * 60 * 1000                # staleness window


def remove_nan_series(block: Optional[ResultBlock]) -> Optional[ResultBlock]:
    """Drop series that are NaN at every step (the reference filters
    all-NaN SerializedRangeVectors before responding)."""
    if block is None:
        return None
    vals = np.asarray(block.values)
    axis = tuple(range(1, vals.ndim))
    keep = ~np.isnan(vals).all(axis=axis)
    if keep.all():
        return block
    rows = np.flatnonzero(keep)
    if len(rows) == 0:
        return None
    return block.select(rows)
