"""Result types: range-vector keys, dense result blocks, query context.

The reference materializes per-series SerializedRangeVectors (ref:
core/.../query/RangeVector.scala:121, ResultTypes.scala).  The TPU-native
design keeps results BATCH-DENSE: one ResultBlock = many series sharing the
same step grid, values in a single [S, W] (or [S, W, B] histogram) matrix.
Transformers and reducers operate on whole blocks on device; per-series
objects only exist at the JSON/serialization edge.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class RangeVectorKey:
    """Series identity in results (ref: RangeVector.scala:27
    CustomRangeVectorKey)."""
    labels: Tuple[Tuple[str, str], ...]             # sorted

    @staticmethod
    def make(labels: Dict[str, str]) -> "RangeVectorKey":
        return RangeVectorKey(tuple(sorted(labels.items())))

    @property
    def labels_dict(self) -> Dict[str, str]:
        return dict(self.labels)

    def without(self, names: Sequence[str]) -> "RangeVectorKey":
        ns = set(names)
        return RangeVectorKey(tuple((k, v) for k, v in self.labels
                                    if k not in ns))

    def only(self, names: Sequence[str]) -> "RangeVectorKey":
        ns = set(names)
        return RangeVectorKey(tuple((k, v) for k, v in self.labels
                                    if k in ns))

    def __str__(self) -> str:
        return "{" + ",".join(f'{k}="{v}"' for k, v in self.labels) + "}"


@dataclasses.dataclass
class ResultBlock:
    """A batch of series on a common step grid.

    values: [S, W] float (NaN = absent at that step), or [S, W, B] for
    histogram-valued vectors (bucket_les gives upper bounds).
    """
    keys: List[RangeVectorKey]
    wends: np.ndarray                               # int64 [W] step timestamps ms
    values: np.ndarray
    bucket_les: Optional[np.ndarray] = None
    # working-set identity for the host group-id cache; ONLY propagate
    # through transformers that keep `keys` unchanged 1:1 (a stale token
    # on a re-keyed block would serve another key set's group ids)
    cache_token: Optional[tuple] = None

    @property
    def num_series(self) -> int:
        return len(self.keys)

    @property
    def is_histogram(self) -> bool:
        return self.values.ndim == 3

    def select(self, rows: np.ndarray) -> "ResultBlock":
        return ResultBlock([self.keys[int(r)] for r in rows], self.wends,
                           np.asarray(self.values)[rows], self.bucket_les)


def concat_blocks(blocks: Sequence[ResultBlock]) -> Optional[ResultBlock]:
    """Concatenate blocks sharing a step grid (DistConcatExec analogue)."""
    blocks = [b for b in blocks if b is not None and b.num_series > 0]
    if not blocks:
        return None
    if len(blocks) == 1:
        return blocks[0]
    keys: List[RangeVectorKey] = []
    for b in blocks:
        keys.extend(b.keys)
    # the concatenation's identity is the ordered tuple of part tokens —
    # valid (keys are the parts' keys, in order) iff every part carries
    # one; used by the PR 17 join index-map cache
    token = None
    if all(b.cache_token is not None for b in blocks):
        token = ("cat",) + tuple(b.cache_token for b in blocks)
    return ResultBlock(keys, blocks[0].wends,
                       np.concatenate([np.asarray(b.values) for b in blocks]),
                       blocks[0].bucket_les, cache_token=token)


@dataclasses.dataclass
class QueryStats:
    """Per-query resource attribution, merged bottom-up through the exec
    tree and carried over the wire with dispatch replies (ref: the
    reference's QueryStats threaded through every ExecPlan +
    TimeSeriesShardStats query-side counters; Prometheus `stats=all`).

    Phase seconds are EXCLUSIVE per node and therefore additive: the
    root's cpu_seconds is the sum of every node's own work (remote nodes
    included — their stats merge in from the reply), never a
    double-count of nested wall time.  See utils.metrics._ExecTally."""
    samples_scanned: int = 0
    series_scanned: int = 0
    result_samples: int = 0
    shards_queried: int = 0
    # set when allow_partial_results dropped an unreachable child —
    # propagates bottom-up through merge() to the root QueryResult
    partial: bool = False
    # human-readable degradation notes (one per dropped child / wedged
    # leader fallback), merged bottom-up and over the wire; surfaced as
    # the Prometheus envelope's `warnings` list, in `?stats=true`, and
    # in slowlog records — degradation is NEVER silent
    warnings: List[str] = dataclasses.field(default_factory=list)
    # --- phase attribution (seconds) ---
    queue_wait_s: float = 0.0       # frontend scheduler semaphore wait
    parse_s: float = 0.0            # PromQL → logical plan
    plan_s: float = 0.0             # logical plan → exec tree
    cpu_seconds: float = 0.0        # host work inside exec nodes (exclusive)
    device_seconds: float = 0.0     # device gather + kernel dispatch wall
    transfer_s: float = 0.0         # host→device uploads + wire round-trips
    # --- bytes ---
    bytes_transferred: int = 0      # host→device upload + wire reply bytes
    result_bytes: int = 0           # final result-matrix bytes at the root
    # --- distributed execution (PR 15) ---
    # bytes that actually crossed node-to-node sockets (request + reply
    # frames) — bytes_transferred conflates these with host→device
    # uploads, so wire attribution gets its own counter
    wire_bytes: int = 0
    # reply frames received on streamed (multi-frame) dispatches
    streamed_frames: int = 0
    # per-node aggregation-pushdown verdicts: node groups whose reduce
    # ran ON the data node (pushed), groups that fell back to per-shard
    # dispatch because the node was unreachable (fallback), and remote
    # children an aggregation could not push (not_pushable)
    pushdown_pushed: int = 0
    pushdown_fallback: int = 0
    pushdown_not_pushable: int = 0
    # --- cache attribution ---
    # result-cache verdict for this poll: "" (bypass) | "hit" | "partial"
    # | "miss" — set by the serving frontend, not merged bottom-up
    result_cache: str = ""
    # device-mirror uploads THIS query paid for on its critical path
    mirror_full_rebuilds: int = 0
    mirror_incremental: int = 0
    # --- historical-tier attribution ---
    # samples materialized from persistence on THIS query's critical path
    # (chunk-frame ODP page-ins + cold-segment builds); counted into
    # samples_scanned too, so tenant scan limits see paged work
    samples_paged: int = 0
    bytes_paged: int = 0            # decoded segment bytes uploaded/built
    # tier verdict (result_cache-style): "" (no cold-capable leaf) |
    # "hot" (all in memory) | "cold_hit" (served from the resident cold
    # region) | "cold_paged" (paid a page-in).  merge keeps the WORST.
    cold_tier: str = ""
    # --- whole-expression compilation (PR 17, query/exprfuse.py) ---
    # per-leaf verdicts when the expression compiler engaged: leaves
    # whose work joined a fused/batched dispatch vs leaves that
    # degraded to the general path (both zero = compiler not engaged)
    exprfuse_fused: int = 0
    exprfuse_degraded: int = 0
    # --- per-device kernel breakdown (PR 18, utils/devicetelem.py) ---
    # "device|kernel" -> [seconds, dispatches]: the split of
    # device_seconds by chip and kernel, folded from the exec tally by
    # execbase and merged additively (locally and over the wire) — the
    # sum of seconds over entries equals device_seconds
    device_calls: Dict[str, List[float]] = dataclasses.field(
        default_factory=dict)

    _COLD_ORDER = ("", "hot", "cold_hit", "cold_paged")

    def merge(self, other: "QueryStats") -> None:
        self.samples_scanned += other.samples_scanned
        self.series_scanned += other.series_scanned
        self.result_samples += other.result_samples
        self.shards_queried += other.shards_queried
        self.partial = self.partial or other.partial
        if other.warnings:
            self.warnings.extend(other.warnings)
        self.queue_wait_s += other.queue_wait_s
        self.parse_s += other.parse_s
        self.plan_s += other.plan_s
        self.cpu_seconds += other.cpu_seconds
        self.device_seconds += other.device_seconds
        self.transfer_s += other.transfer_s
        self.bytes_transferred += other.bytes_transferred
        self.result_bytes += other.result_bytes
        self.wire_bytes += other.wire_bytes
        self.streamed_frames += other.streamed_frames
        self.pushdown_pushed += other.pushdown_pushed
        self.pushdown_fallback += other.pushdown_fallback
        self.pushdown_not_pushable += other.pushdown_not_pushable
        self.result_cache = self.result_cache or other.result_cache
        self.mirror_full_rebuilds += other.mirror_full_rebuilds
        self.mirror_incremental += other.mirror_incremental
        self.samples_paged += other.samples_paged
        self.bytes_paged += other.bytes_paged
        if self._COLD_ORDER.index(other.cold_tier) > \
                self._COLD_ORDER.index(self.cold_tier):
            self.cold_tier = other.cold_tier
        self.exprfuse_fused += other.exprfuse_fused
        self.exprfuse_degraded += other.exprfuse_degraded
        for key, cell in other.device_calls.items():
            mine = self.device_calls.get(key)
            if mine is None:
                self.device_calls[key] = [cell[0], cell[1]]
            else:
                mine[0] += cell[0]
                mine[1] += cell[1]

    def to_dict(self) -> Dict[str, object]:
        """The `?stats=true` wire shape (http/routes attaches it to the
        query_range payload; doc/observability.md documents the fields)."""
        return {
            "samplesScanned": self.samples_scanned,
            "seriesScanned": self.series_scanned,
            "resultSamples": self.result_samples,
            "resultBytes": self.result_bytes,
            "shardsQueried": self.shards_queried,
            "bytesTransferred": self.bytes_transferred,
            "partial": self.partial,
            "warnings": list(self.warnings),
            "phases": {
                "queue_s": round(self.queue_wait_s, 6),
                "parse_s": round(self.parse_s, 6),
                "plan_s": round(self.plan_s, 6),
                "exec_s": round(self.cpu_seconds, 6),
                "device_s": round(self.device_seconds, 6),
                "transfer_s": round(self.transfer_s, 6),
            },
            "samplesPaged": self.samples_paged,
            "bytesPaged": self.bytes_paged,
            "wireBytes": self.wire_bytes,
            "streamedFrames": self.streamed_frames,
            "pushdown": {
                "pushed": self.pushdown_pushed,
                "fallback": self.pushdown_fallback,
                "notPushable": self.pushdown_not_pushable,
            },
            "exprfuse": {
                "fused": self.exprfuse_fused,
                "degraded": self.exprfuse_degraded,
            },
            "cache": {
                "result": self.result_cache,
                "mirrorFullRebuilds": self.mirror_full_rebuilds,
                "mirrorIncremental": self.mirror_incremental,
                "coldTier": self.cold_tier,
            },
            # device -> kernel -> {seconds, dispatches}: the per-chip
            # split of phases.device_s (empty when no kernel ran)
            "devices": self._devices_dict(),
        }

    def _devices_dict(self) -> Dict[str, object]:
        out: Dict[str, Dict[str, object]] = {}
        for key, (secs, count) in sorted(self.device_calls.items()):
            dev, _, kern = key.partition("|")
            out.setdefault(dev, {})[kern] = {
                "seconds": round(secs, 6), "dispatches": int(count)}
        return out


@dataclasses.dataclass
class QueryResult:
    """ref: filodb.query QueryResult / QueryError."""
    blocks: List[ResultBlock]
    stats: QueryStats = dataclasses.field(default_factory=QueryStats)
    error: Optional[str] = None
    # metadata-query payloads (label values etc.) ride in `data`
    data: Optional[object] = None
    # the query's trace id (= ctx.query_id): fetch the stitched cross-node
    # span tree from utils.metrics.collector / GET /admin/traces/<id>
    trace_id: str = ""
    # True when allow_partial_results dropped unreachable shards from a
    # scatter-gather (ref: QueryContext.scala PlannerParams
    # allowPartialResults / QueryResult mayBePartial): NEVER silently —
    # to_prom_matrix surfaces it as a warning + "partial": true
    partial: bool = False

    @property
    def num_series(self) -> int:
        return sum(b.num_series for b in self.blocks)

    def series(self):
        """Iterate (key, wends, values_row) across blocks — serialization edge."""
        for b in self.blocks:
            vals = np.asarray(b.values)
            for i, k in enumerate(b.keys):
                yield k, b.wends, vals[i]


@dataclasses.dataclass
class PlannerParams:
    """ref: core/.../query/QueryContext.scala:98 PlannerParams."""
    spread: int = 1
    sample_limit: int = 1_000_000        # RESULT samples (post-transform)
    # samples a leaf may SCAN (gather/page) per shard for one query — the
    # fail-fast guard against pathological selectors (ref:
    # OnDemandPagingShard.scala:55 capDataScannedPerShardCheck).  Distinct
    # from sample_limit: aggregations scan much more than they return.
    scan_limit: int = 50_000_000
    group_by_cardinality_limit: int = 100_000
    join_cardinality_limit: int = 100_000
    enforced_limits: bool = True
    shard_overrides: Optional[List[int]] = None
    process_multi_partition: bool = False
    # scatter-gather children whose shard owner is unreachable are
    # DROPPED (result flagged partial) instead of failing the query
    # (ref: PlannerParams.allowPartialResults).  This is the GATE: a
    # shard_unavailable still gets the engine's re-plan retries first;
    # only when those are exhausted does the engine engage the drop via
    # `partial_now` (peers blowing their deadline share — dispatch
    # timeouts — drop under the gate alone, since retrying them cannot
    # help within the budget)
    allow_partial_results: bool = False
    # --- deadline/degradation fields, repr=False: the serving keys
    # (singleflight, coalescer, result cache) are repr(planner_params),
    # and neither per-request budgets, absolute deadlines, nor engine-
    # engaged degradation state may split byte-identical requests into
    # distinct keys (two clients polling one panel with different
    # timeouts share one execution; each follower's own deadline still
    # bounds its wait in the frontend) ---
    # per-request time budget in seconds; 0 = query.default_timeout_s.
    # The server config CAPS it (a client cannot extend past the cap).
    timeout_s: float = dataclasses.field(default=0.0, repr=False)
    # absolute unix deadline stamped at admission (frontend) so queue
    # wait counts against the budget; 0 = engine stamps at exec start
    deadline_unix_s: float = dataclasses.field(default=0.0, repr=False)
    # set by the ENGINE after re-plan retries are exhausted: scatter-
    # gathers may now drop unreachable children (see gate note above)
    partial_now: bool = dataclasses.field(default=False, repr=False)
    # per-request override of query.aggregation_pushdown (None = server
    # config).  repr=False: pushdown on/off is bit-identical by contract
    # (exactly-mergeable partials only), so the serving keys must not
    # split identical requests by routing stance.
    aggregation_pushdown: Optional[bool] = dataclasses.field(
        default=None, repr=False)
    # benchmark-only strawman: suppress the leaf-side map phase so
    # remote children ship FULL per-series blocks (the "ship everything"
    # baseline bench.py distexec measures wire bytes against).  Off
    # (False) is the only supported production value — pushdown=False
    # already restores the per-shard dispatch where every shard still
    # replies with its [G, W] map partial.
    ship_raw_series: bool = dataclasses.field(default=False, repr=False)


@dataclasses.dataclass
class QueryContext:
    """Per-query context threaded through planning + execution
    (ref: QueryContext.scala)."""
    query_id: str = ""
    submit_time_ms: int = 0
    origin: str = ""
    planner_params: PlannerParams = dataclasses.field(default_factory=PlannerParams)
    lookback_ms: int = 5 * 60 * 1000                # staleness window
    # end-to-end deadline (unix seconds; 0 = none): checked at every
    # exec-node boundary (execbase.execute_internal) and shrinking each
    # remote hop's socket timeout to the remaining budget — it rides the
    # wire with dispatched subtrees, so remote nodes enforce it too
    # (nodes share one clock here; document skew bounds for real WANs)
    deadline_unix_s: float = 0.0


def compute_deadline(pp: PlannerParams, default_timeout_s: float) -> float:
    """Absolute unix deadline for a request: an already-stamped deadline
    wins; otherwise the request's timeout_s CAPPED by the server default
    (a client can shrink its budget, never extend it); 0 = no deadline.
    The single home of the cap rule — the frontend (admission stamp) and
    the bare engine (execution-start stamp) must never drift."""
    if pp.deadline_unix_s:
        return pp.deadline_unix_s
    budget = pp.timeout_s or default_timeout_s
    if pp.timeout_s > 0 and default_timeout_s > 0:
        budget = min(pp.timeout_s, default_timeout_s)
    if budget <= 0:
        return 0.0
    import time as _t
    return _t.time() + budget


def remaining_budget(pp: Optional[PlannerParams], bound: float) -> float:
    """`bound` shrunk to the time left on pp's stamped deadline (floored
    at 0); `bound` unchanged when no deadline rides the params.  The
    single home of the wait-bound rule shared by the singleflight dedup
    wait, the scheduler queue wait, and the coalescer follower wait —
    every place a query BLOCKS must spend from the same budget the exec
    tree enforces (getattr: params serialized by an older peer may lack
    the field)."""
    dl = getattr(pp, "deadline_unix_s", 0.0) if pp is not None else 0.0
    if not dl:
        return bound
    import time as _t
    return min(bound, max(dl - _t.time(), 0.0))


def remove_nan_series(block: Optional[ResultBlock]) -> Optional[ResultBlock]:
    """Drop series that are NaN at every step (the reference filters
    all-NaN SerializedRangeVectors before responding)."""
    if block is None:
        return None
    vals = np.asarray(block.values)
    axis = tuple(range(1, vals.ndim))
    keep = ~np.isnan(vals).all(axis=axis)
    if keep.all():
        return block
    rows = np.flatnonzero(keep)
    if len(rows) == 0:
        return None
    return block.select(rows)
