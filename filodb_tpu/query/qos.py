"""Multi-tenant QoS: the weighted-fair query scheduler + read-side
load shedding + the shuffle-shard placement helper.

The frontend's original admission control was ONE global
BoundedSemaphore (query.max_concurrent_queries): fair only while every
tenant is polite.  A single abusive tenant — a dashboard storm, a
runaway notebook — fills all slots and every other tenant queues behind
it until their deadlines die (the noisy-neighbor brown-out the
reference's coordinator per-query limits exist to prevent, PAPER.md §1;
the Cortex/Thanos query-frontend fairness problem).  This module is the
fairness layer that replaces it:

  * WeightedFairScheduler — per-tenant (workspace) FIFO queues with
    configurable concurrency shares (`query.tenant_shares`, default
    equal) dispatched by deficit round robin: each round a tenant's
    deficit grows by its share and it may start floor(deficit) queries.
    Only tenants with QUEUED work participate in a round, so an idle
    tenant's share redistributes to the busy ones automatically — and a
    tenant that goes idle forfeits its banked deficit (no credit
    hoarding: returning after an idle spell earns fair share, not a
    burst).  Capacity is the same global bound as before; what changes
    is WHO gets the next free slot.
  * Adaptive read-side load shedding (the write side has had this
    stance since PR 7's `admit_ingest` → 429 + Retry-After): at
    admission the scheduler estimates this tenant's queue wait from its
    LIVE state — queued queries ahead, an EWMA of recent slot-hold
    times, the tenant's effective share of capacity — and rejects early
    with the structured `tenant_overloaded` error (HTTP 429 +
    Retry-After) when the predicted wait would blow the query's
    deadline budget, or when the tenant's queue is already at
    `query.tenant_max_queue_depth`.  A doomed query burning a queue
    slot until `query_timeout` helps nobody; a 429 with an honest
    Retry-After lets a compliant client back off.
  * `shuffle_shard_nodes` — the Cortex/Amazon shuffle-sharding
    primitive: a deterministic k-of-N node subset per tenant, so the
    replica-failover dispatcher can prefer each tenant's subset and a
    hot tenant's load lands on a bounded blast radius instead of every
    data node (replication/failover.py applies it to the owner lists
    from PR 11).

Internal workspaces (`_rules_`, `_self_`) are scheduled like any tenant
but NEVER shed — the ruler and the self-monitoring loop must not be
starved out of their own standing queries precisely under the overload
they exist to observe (same exemption as the scan-limit gate).
"""
from __future__ import annotations

import collections
import hashlib
import threading
import time
from typing import Deque, Dict, List, Optional, Sequence, Tuple


# shed verdicts are structured errors (the QueryError-taxonomy shape:
# clients route on the code before the colon); http/routes maps the
# code to 429 + Retry-After exactly like the write-side ingest limits
SHED_ERROR_CODE = "tenant_overloaded"


class Admission:
    """Outcome of one WeightedFairScheduler.admit() call.

    status:
      "acquired"  — the caller holds a slot; it MUST release(ws).
      "shed"      — rejected at admission (queue full / predicted wait
                    past the deadline); `reason` + `retry_after_s` say
                    why and when to come back.  No slot held.
      "cancelled" — the request's CancellationToken flipped while it
                    waited in its tenant queue.  No slot held.
      "timeout"   — the wait bound expired without a grant (and without
                    a stamped deadline to shed against).  No slot held;
                    the frontend preserves the pre-QoS stance of running
                    such queries unthrottled rather than failing them on
                    queue pressure alone.
    `waited_s` is the time spent queued — the queue_wait_s attribution
    every outcome carries (see account()).
    """

    __slots__ = ("status", "waited_s", "retry_after_s", "reason", "ws")

    def __init__(self, status: str, waited_s: float = 0.0,
                 retry_after_s: float = 0.0, reason: str = "",
                 ws: str = ""):
        self.status = status
        self.waited_s = waited_s
        self.retry_after_s = retry_after_s
        self.reason = reason
        # the (possibly overflow-folded) workspace this admission was
        # scheduled under — callers MUST tag metrics with this, not the
        # raw client-controlled ws (cardinality defense)
        self.ws = ws

    @property
    def acquired(self) -> bool:
        return self.status == "acquired"

    def shed_error(self) -> str:
        """The structured rejection string for a shed admission."""
        why = ("tenant scheduler queue is full"
               if self.reason == "queue_full" else
               "predicted queue wait would exceed the deadline budget")
        return (f"{SHED_ERROR_CODE}: {why} (predicted wait "
                f"{self.retry_after_s:.2f}s) — retry after "
                f"{self.retry_after_s:.2f}s")


class _Waiter:
    __slots__ = ("event", "granted", "ws")

    def __init__(self, ws: str):
        self.event = threading.Event()
        self.granted = False
        self.ws = ws


class WeightedFairScheduler:
    """Deficit-round-robin admission over per-tenant queues.

    One instance guards one frontend's execution capacity (the old
    semaphore's bound).  All state lives behind one lock; grants are
    handed to waiters by flipping their per-waiter Event, so a grant
    never requires the granted thread to win a lock race (no thundering
    herd on release).
    """

    # kill reaction bound while queued (the _acquire_cancellable
    # contract from PR 13: a killed request stops waiting within ~50 ms
    # and never holds the slot)
    _SLICE_S = 0.05

    # ws comes from client-controlled query text: distinct workspaces
    # past this cap fold into the overflow sentinel so hostile ws churn
    # cannot grow the scheduler's tables or the tenant_queue_depth /
    # queries_shed metric cardinality without bound (the same defense —
    # and the same cap — as usage.UsageAccountant.resolve)
    MAX_TENANTS = 512

    def __init__(self, capacity: int,
                 shares: Optional[Dict[str, float]] = None,
                 default_share: float = 1.0,
                 max_queue_depth: int = 0,
                 shed_enabled: bool = True):
        self.capacity = max(int(capacity), 1)
        self.shares = {str(k): max(float(v), 1e-6)
                       for k, v in (shares or {}).items()}
        self.default_share = max(float(default_share), 1e-6)
        self.max_queue_depth = max(int(max_queue_depth), 0)
        self.shed_enabled = bool(shed_enabled)
        self._lock = threading.Lock()
        self._queues: Dict[str, Deque[_Waiter]] = {}
        self._deficit: Dict[str, float] = {}
        # round-robin visit order over tenants with queued work, the
        # visit pointer, and the tenant currently mid-visit (topped up
        # this round; stays selected while its deficit lasts — THAT is
        # what makes a share of 3 worth 3 grants per round, not 1)
        self._order: List[str] = []
        self._rr = 0
        self._visit_ws: Optional[str] = None
        self._active: Dict[str, int] = {}
        self._total_active = 0
        # every distinct ws ever scheduled (bounded by MAX_TENANTS —
        # later strangers fold into the overflow sentinel)
        self._seen: set = set()
        # EWMA of slot-hold seconds (the service-time half of the wait
        # prediction); seeded pessimistically low so a cold scheduler
        # never sheds the first burst on a guess
        self._hold_ewma_s = 0.05
        self._hold_start: Dict[int, float] = {}
        # lifetime counters for snapshot()/CLI (metrics are incremented
        # by the frontend per shed with the reason tag)
        self.shed_total: Dict[str, int] = {}
        self.granted_total = 0

    # ------------------------------------------------------------ config

    def share_of(self, ws: str) -> float:
        return self.shares.get(ws, self.default_share)

    def _fold_locked(self, ws: str) -> str:
        """The workspace a request is scheduled under: itself while the
        tenant table has room, the overflow sentinel once MAX_TENANTS
        distinct workspaces have been seen."""
        if ws in self._seen or len(self._seen) < self.MAX_TENANTS:
            self._seen.add(ws)
            return ws
        from filodb_tpu.utils.usage import OVERFLOW_TENANT
        return OVERFLOW_TENANT[0]

    # --------------------------------------------------------- admission

    def admit(self, ws: str, timeout_s: float, tok=None,
              deadline_unix_s: float = 0.0) -> Admission:
        """Wait for a slot under weighted-fair dispatch, or shed.

        `tok` is the request's CancellationToken (None = unkillable);
        `deadline_unix_s` the end-to-end budget stamped at admission
        (0 = none) — the adaptive shed compares the PREDICTED queue wait
        against it before queueing at all.
        """
        from filodb_tpu.utils.usage import INTERNAL_WORKSPACES
        sheddable = self.shed_enabled and ws not in INTERNAL_WORKSPACES
        with self._lock:
            ws = self._fold_locked(ws)
            q = self._queues.get(ws)
            depth = len(q) if q is not None else 0
            if sheddable and self.max_queue_depth \
                    and depth >= self.max_queue_depth:
                self.shed_total[ws] = self.shed_total.get(ws, 0) + 1
                return Admission("shed",
                                 retry_after_s=self._predict_locked(
                                     ws, depth),
                                 reason="queue_full", ws=ws)
            if sheddable and deadline_unix_s:
                predicted = self._predict_locked(ws, depth)
                if time.time() + predicted >= deadline_unix_s:
                    self.shed_total[ws] = self.shed_total.get(ws, 0) + 1
                    return Admission("shed", retry_after_s=predicted,
                                     reason="deadline", ws=ws)
            w = _Waiter(ws)
            if q is None:
                q = self._queues[ws] = collections.deque()
            if not q and ws not in self._order:
                self._order.append(ws)
            q.append(w)
            self._dispatch_locked()
        t0 = time.perf_counter()
        deadline = t0 + max(timeout_s, 0.0)
        while True:
            if w.event.wait(timeout=min(
                    self._SLICE_S, max(deadline - time.perf_counter(),
                                       0.0))):
                waited = time.perf_counter() - t0
                with self._lock:
                    self._hold_start[id(w)] = time.perf_counter()
                return Admission("acquired", waited_s=waited, ws=ws)
            cancelled = tok is not None and tok.cancelled
            expired = time.perf_counter() >= deadline
            if cancelled or expired:
                waited = time.perf_counter() - t0
                with self._lock:
                    if w.granted:
                        # grant raced the cancel/timeout: the slot was
                        # handed to us — give it straight back and let
                        # the dispatcher pass it on
                        self._release_locked(ws, id(w))
                    else:
                        self._remove_locked(ws, w)
                return Admission("cancelled" if cancelled else "timeout",
                                 waited_s=waited, ws=ws)

    def release(self, ws: str, _wid: Optional[int] = None) -> None:
        """Release one slot of `ws` — pass the Admission's `ws` (the
        folded name), which `_fold_locked` reproduces stably anyway."""
        with self._lock:
            self._release_locked(self._fold_locked(ws), _wid)

    # --------------------------------------------- internal (lock held)

    def _release_locked(self, ws: str, wid: Optional[int] = None) -> None:
        left = max(self._active.get(ws, 1) - 1, 0)
        if left:
            self._active[ws] = left
        else:
            # drop zeroed rows: _active must not accumulate one entry
            # per workspace ever seen (cardinality hygiene, like the
            # empty-queue cleanup in _forget_idle_locked)
            self._active.pop(ws, None)
        self._total_active = max(self._total_active - 1, 0)
        if wid is not None:
            t0 = self._hold_start.pop(wid, None)
        elif self._hold_start:
            # released via the public release(ws): retire the OLDEST
            # open hold (FIFO is the common case; the EWMA only needs a
            # representative sample, not exact per-query pairing)
            t0 = self._hold_start.pop(next(iter(self._hold_start)))
        else:
            t0 = None
        if t0 is not None:
            held = time.perf_counter() - t0
            self._hold_ewma_s += 0.2 * (held - self._hold_ewma_s)
        self._dispatch_locked()

    def _remove_locked(self, ws: str, w: _Waiter) -> None:
        q = self._queues.get(ws)
        if q is not None:
            try:
                q.remove(w)
            except ValueError:
                pass
            if not q:
                self._forget_idle_locked(ws)

    def _forget_idle_locked(self, ws: str) -> None:
        """A tenant whose queue drained leaves the DRR rotation AND
        forfeits its banked deficit — the share-redistribution property:
        the remaining tenants' rounds no longer visit it, and it cannot
        hoard credit while idle to burst past its share later."""
        q = self._queues.get(ws)
        if q is not None and not q:
            del self._queues[ws]
        self._deficit.pop(ws, None)
        if self._visit_ws == ws:
            self._visit_ws = None
        if ws in self._order:
            i = self._order.index(ws)
            self._order.remove(ws)
            if i < self._rr:
                self._rr -= 1
            if self._order:
                self._rr %= len(self._order)
            else:
                self._rr = 0

    def _dispatch_locked(self) -> None:
        """Grant free slots to queued waiters by deficit round robin."""
        while self._total_active < self.capacity:
            ws = self._next_locked()
            if ws is None:
                return
            w = self._queues[ws].popleft()
            if not self._queues[ws]:
                self._forget_idle_locked(ws)
            w.granted = True
            self._active[ws] = self._active.get(ws, 0) + 1
            self._total_active += 1
            self.granted_total += 1
            w.event.set()

    def _next_locked(self) -> Optional[str]:
        """Next tenant owed a grant, or None when nothing is queued.
        Classic DRR over the tenants with queued work: VISITING a
        tenant tops its deficit up by its share once; while the deficit
        covers a query (unit cost) the visit pointer STAYS on it — a
        share of 3 is worth 3 back-to-back grants per round — and only
        an exhausted deficit advances the rotation."""
        if not self._order:
            return None
        # bound the scan: every tenant's deficit grows by >= its share
        # per full round, so within ceil(1/min_share) rounds SOME
        # deficit crosses 1.0 — the 64 cap is a safety net, after which
        # we grant the largest-deficit tenant outright
        for _ in range(64 * len(self._order)):
            ws = self._order[self._rr % len(self._order)]
            if self._visit_ws != ws:
                # first touch this round: top up the quantum
                self._visit_ws = ws
                self._deficit[ws] = self._deficit.get(ws, 0.0) \
                    + self.share_of(ws)
            d = self._deficit[ws]
            if d >= 1.0:
                self._deficit[ws] = d - 1.0
                return ws
            self._visit_ws = None
            self._rr = (self._rr + 1) % len(self._order)
        ws = max(self._order, key=lambda t: self._deficit.get(t, 0.0))
        self._deficit[ws] = 0.0
        return ws

    def _predict_locked(self, ws: str, depth: int) -> float:
        """Predicted queue wait for a NEW query of `ws` from live state:
        (queries ahead + 1) service times, at the tenant's effective
        slice of capacity.  Effective share counts only tenants with
        live demand — the same redistribution the dispatcher does."""
        demand = set(self._order)
        demand.update(t for t, n in self._active.items() if n > 0)
        demand.add(ws)
        total_share = sum(self.share_of(t) for t in demand)
        eff = self.capacity * self.share_of(ws) / max(total_share, 1e-9)
        ahead = depth + self._active.get(ws, 0)
        return (ahead + 1) * self._hold_ewma_s / max(eff, 1e-3)

    # ----------------------------------------------------- observability

    def predict_wait_s(self, ws: str) -> float:
        with self._lock:
            q = self._queues.get(ws)
            return self._predict_locked(ws, len(q) if q else 0)

    def queue_depths(self) -> Dict[str, int]:
        with self._lock:
            return {ws: len(q) for ws, q in self._queues.items() if q}

    def snapshot(self) -> List[dict]:
        """Per-tenant live rows for /admin/tenants and `filo-cli
        tenants`: share, running, queued, lifetime sheds."""
        with self._lock:
            ws_set = set(self._queues) | set(self._active) \
                | set(self.shed_total) | set(self.shares)
            out = []
            for ws in sorted(ws_set):
                q = self._queues.get(ws)
                out.append({
                    "ws": ws,
                    "share": self.share_of(ws),
                    "running": self._active.get(ws, 0),
                    "queued": len(q) if q else 0,
                    "shed": self.shed_total.get(ws, 0),
                })
            return out

    def refresh_gauges(self) -> None:
        """Publish per-tenant scheduler queue depth as
        `tenant_queue_depth{ws}` — refreshed at SCRAPE time like the
        shard and active-query gauges, so the admission hot path never
        touches the metric registry."""
        from filodb_tpu.utils.metrics import registry
        with self._lock:
            seen = set(self._queues) | set(self._active)
            depths = {ws: len(self._queues.get(ws) or ()) for ws in seen}
        for ws, d in depths.items():
            registry.gauge("tenant_queue_depth", ws=ws).update(d)


def account_wait(res, adm: Optional[Admission]) -> None:
    """THE admission-accounting helper: every serving outcome — ran,
    shed, killed-in-queue, timed-out-in-queue — attributes its scheduler
    wait through this one function, so the shed/killed/timeout paths can
    never drift from the happy path on queue_wait_s attribution (the
    four copy-pasted `+= waited` sites this replaced had exactly that
    failure mode)."""
    if res is not None and adm is not None:
        res.stats.queue_wait_s += adm.waited_s


# --------------------------------------------------- shuffle sharding


def shuffle_shard_nodes(tenant_ws: str, nodes: Sequence[str],
                        k: int) -> Tuple[str, ...]:
    """Deterministic k-of-N node subset for a tenant (the Cortex /
    Amazon shuffle-sharding primitive): rank every node by a stable
    hash of (tenant, node) and keep the first k.  Independent of list
    order, stable across processes (hashlib, not PYTHONHASHSEED), and
    overlapping subsets between two tenants shrink combinatorially as
    N grows — the bounded-blast-radius property."""
    uniq = sorted(set(nodes))
    if k <= 0 or k >= len(uniq):
        return tuple(uniq)
    ranked = sorted(
        uniq,
        key=lambda n: hashlib.blake2b(
            f"{tenant_ws}\x00{n}".encode(), digest_size=8).digest())
    return tuple(sorted(ranked[:k]))
