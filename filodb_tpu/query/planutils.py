"""LogicalPlan utilities: time-range math, plan rewriting, plan→PromQL.

Mirrors the reference's planner helpers:
  - time range + copy-with-time-range:
    ref: coordinator/.../queryplanner/LogicalPlanUtils.scala:230 (splitPlans,
    getTimeFromLogicalPlan, copyLogicalPlanWithUpdatedTimeRange)
  - plan → PromQL string (for shipping subqueries to remote clusters):
    ref: coordinator/.../queryplanner/LogicalPlanParser.scala (convertToQuery)
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from filodb_tpu.core.index import (ColumnFilter, Equals, EqualsRegex, In,
                                   NotEquals, NotEqualsRegex, NotIn, Prefix)
from filodb_tpu.query import logical as lp


@dataclasses.dataclass(frozen=True)
class TimeRange:
    start_ms: int
    end_ms: int


# --------------------------------------------------------------- time range

def get_time_range(plan: lp.LogicalPlan) -> TimeRange:
    """ref: LogicalPlanUtils.getTimeFromLogicalPlan."""
    if isinstance(plan, lp.PeriodicSeriesPlan):
        return TimeRange(plan.start_ms, plan.end_ms)
    if isinstance(plan, lp.RawSeries):
        return TimeRange(plan.range_selector.from_ms, plan.range_selector.to_ms)
    raise ValueError(f"no time range on {type(plan).__name__}")


def get_lookback_ms(plan: lp.LogicalPlan, default_ms: int) -> int:
    """Largest raw-data reach-back of any selector in the plan: window for
    range functions, staleness lookback otherwise
    (ref: LogicalPlanUtils.getLookBackMillis)."""
    out = [0]

    def walk(p):
        if isinstance(p, lp.PeriodicSeriesWithWindowing):
            out.append(p.window_ms)
            walk(p.series)
        elif isinstance(p, lp.PeriodicSeries):
            out.append(p.raw_series.lookback_ms or default_ms)
        elif isinstance(p, (lp.SubqueryWithWindowing,)):
            out.append(p.subquery_window_ms +
                       get_lookback_ms(p.inner, default_ms))
        elif isinstance(p, lp.TopLevelSubquery):
            walk(p.inner)
        elif dataclasses.is_dataclass(p):
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, lp.LogicalPlan):
                    walk(v)
        return
    walk(plan)
    return max(out)


def get_offset_ms(plan: lp.LogicalPlan) -> int:
    """Largest selector offset in the plan
    (ref: LogicalPlanUtils.getOffsetMillis)."""
    out = [0]

    def walk(p):
        if dataclasses.is_dataclass(p):
            off = getattr(p, "offset_ms", None)
            if off:
                out.append(off)
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, lp.LogicalPlan):
                    walk(v)
    walk(plan)
    return max(out)


def copy_with_time_range(plan: lp.LogicalPlan, tr: TimeRange) -> lp.LogicalPlan:
    """Rewrite every start/end (and nested RawSeries interval) to `tr`
    (ref: LogicalPlanUtils.copyLogicalPlanWithUpdatedTimeRange /
    copyWithUpdatedTimeRange)."""
    return _copy_tr(plan, tr)


def _copy_tr(p, tr: TimeRange):
    if isinstance(p, lp.ApplyAtTimestamp):
        # @ pins the inner evaluation time: only the OUTER (repeat) grid
        # retargets; rewriting the inner grid would destroy the pinning
        return dataclasses.replace(p, start_ms=tr.start_ms,
                                   end_ms=tr.end_ms)
    if isinstance(p, lp.RawSeries):
        return dataclasses.replace(
            p, range_selector=lp.IntervalSelector(tr.start_ms, tr.end_ms))
    if isinstance(p, lp.PeriodicSeries):
        raw = _copy_tr(p.raw_series, tr)
        return dataclasses.replace(p, raw_series=raw, start_ms=tr.start_ms,
                                   end_ms=tr.end_ms)
    if isinstance(p, lp.PeriodicSeriesWithWindowing):
        raw = _copy_tr(p.series,
                       TimeRange(tr.start_ms - p.window_ms, tr.end_ms))
        return dataclasses.replace(p, series=raw, start_ms=tr.start_ms,
                                   end_ms=tr.end_ms)
    if isinstance(p, (lp.SubqueryWithWindowing, lp.TopLevelSubquery)):
        # inner grids are anchored to the outer range; recompute conservatively
        win = getattr(p, "subquery_window_ms", 0)
        off = p.offset_ms or 0
        inner = _copy_tr(p.inner,
                         TimeRange(tr.start_ms - win - off, tr.end_ms - off))
        return dataclasses.replace(p, inner=inner, start_ms=tr.start_ms,
                                   end_ms=tr.end_ms)
    if isinstance(p, lp.ScalarVaryingDoublePlan):
        return dataclasses.replace(p, vectors=_copy_tr(p.vectors, tr))
    if isinstance(p, lp.VectorPlan):
        return dataclasses.replace(p, scalars=_copy_tr(p.scalars, tr))
    if isinstance(p, lp.ScalarBinaryOperation):
        lhs = _copy_tr(p.lhs, tr) if isinstance(p.lhs, lp.LogicalPlan) else p.lhs
        rhs = _copy_tr(p.rhs, tr) if isinstance(p.rhs, lp.LogicalPlan) else p.rhs
        return dataclasses.replace(p, lhs=lhs, rhs=rhs, start_ms=tr.start_ms,
                                   end_ms=tr.end_ms)
    if dataclasses.is_dataclass(p) and isinstance(p, lp.LogicalPlan):
        updates = {}
        for f in dataclasses.fields(p):
            v = getattr(p, f.name)
            if isinstance(v, lp.LogicalPlan):
                updates[f.name] = _copy_tr(v, tr)
        for name in ("start_ms", "end_ms"):
            if any(f.name == name for f in dataclasses.fields(p)):
                updates[name] = tr.start_ms if name == "start_ms" else tr.end_ms
        return dataclasses.replace(p, **updates) if updates else p
    return p


def split_plans(plan: lp.PeriodicSeriesPlan,
                split_size_ms: int) -> List[lp.PeriodicSeriesPlan]:
    """Split a long periodic plan into sequential time slices on the step
    grid (ref: LogicalPlanUtils.splitPlans:230)."""
    start, step, end = plan.start_ms, plan.step_ms, plan.end_ms
    if end - start <= split_size_ms:
        return [plan]
    out = []
    s = start
    while s <= end:
        e = min(s + split_size_ms, end)
        # snap the slice end onto the step grid
        e = s + ((e - s) // step) * step if e < end else end
        out.append(copy_with_time_range(plan, TimeRange(s, e)))
        if e >= end:
            break
        s = e + step
    return out


# --------------------------------------------------------------- filters

def get_raw_series_filters(plan: lp.LogicalPlan) -> List[Tuple[ColumnFilter, ...]]:
    """All RawSeries filter groups in the plan
    (ref: LogicalPlan.getRawSeriesFilters)."""
    out: List[Tuple[ColumnFilter, ...]] = []

    def walk(p):
        if isinstance(p, lp.RawSeries):
            out.append(p.filters)
        elif dataclasses.is_dataclass(p):
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, lp.LogicalPlan):
                    walk(v)
    walk(plan)
    return out


def rewrite_filters(plan: lp.LogicalPlan,
                    replace: Sequence[ColumnFilter]) -> lp.LogicalPlan:
    """Replace same-column filters on every RawSeries / metadata plan
    (ref: ShardKeyRegexPlanner's generateExec filter rewriting)."""
    cols = {f.column: f for f in replace}

    def walk(p):
        if isinstance(p, lp.RawSeries):
            newf = tuple(cols.get(f.column, f) for f in p.filters)
            # add filters for columns not present at all
            present = {f.column for f in newf}
            newf += tuple(f for c, f in cols.items() if c not in present)
            return dataclasses.replace(p, filters=newf)
        if dataclasses.is_dataclass(p) and isinstance(p, lp.LogicalPlan):
            updates = {}
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, lp.LogicalPlan):
                    updates[f.name] = walk(v)
            return dataclasses.replace(p, **updates) if updates else p
        return p
    return walk(plan)


# --------------------------------------------------------- plan → PromQL

def _esc(v: str) -> str:
    """Escape a literal label value for a double-quoted PromQL matcher."""
    return v.replace("\\", "\\\\").replace('"', '\\"')


def _matchers(filters: Sequence[ColumnFilter]) -> Tuple[str, List[str]]:
    """Returns (metric_name, label matcher strings)."""
    import re as _re
    metric = ""
    out: List[str] = []
    for f in filters:
        if f.column in ("_metric_", "__name__") and isinstance(f, Equals):
            metric = f.value
            continue
        if isinstance(f, Equals):
            out.append(f'{f.column}="{_esc(f.value)}"')
        elif isinstance(f, NotEquals):
            out.append(f'{f.column}!="{_esc(f.value)}"')
        elif isinstance(f, EqualsRegex):
            out.append(f'{f.column}=~"{_esc(f.pattern)}"')
        elif isinstance(f, NotEqualsRegex):
            out.append(f'{f.column}!~"{_esc(f.pattern)}"')
        elif isinstance(f, In):
            alts = "|".join(_re.escape(v) for v in sorted(f.values))
            out.append(f'{f.column}=~"{_esc(alts)}"')
        elif isinstance(f, NotIn):
            alts = "|".join(_re.escape(v) for v in sorted(f.values))
            out.append(f'{f.column}!~"{_esc(alts)}"')
        elif isinstance(f, Prefix):
            out.append(f'{f.column}=~"{_esc(_re.escape(f.prefix))}.*"')
        else:
            raise ValueError(f"cannot unparse filter {f}")
    return metric, out


def _selector(raw: lp.RawSeries, window_ms: Optional[int] = None,
              offset_ms: Optional[int] = None) -> str:
    metric, ms = _matchers(raw.filters)
    col = f"::{raw.columns[0]}" if raw.columns else ""
    s = metric + col + ("{" + ",".join(ms) + "}" if ms or not metric else "")
    if window_ms:
        s += f"[{_dur(window_ms)}]"
    off = offset_ms if offset_ms is not None else raw.offset_ms
    if off:
        s += f" offset {_dur(off)}"
    return s


def _dur(ms: int) -> str:
    for unit, span in (("d", 86_400_000), ("h", 3_600_000), ("m", 60_000),
                       ("s", 1000)):
        if ms % span == 0 and ms >= span:
            return f"{ms // span}{unit}"
    return f"{ms}ms"


def unparse(plan: lp.LogicalPlan) -> str:
    """LogicalPlan → PromQL string (ref: LogicalPlanParser.convertToQuery).
    Used by remote execs (HA / multi-partition routing) and by planner tests
    as a round-trip regression net."""
    u = unparse
    if isinstance(plan, lp.ApplyAtTimestamp):
        # re-attach the @ to the pinned selector/subquery text
        at_s = plan.inner.start_ms / 1000.0
        at_txt = f"{at_s:.3f}".rstrip("0").rstrip(".")
        inner = plan.inner
        if isinstance(inner, lp.PeriodicSeries):
            return (f"{_selector(inner.raw_series, offset_ms=inner.offset_ms)}"
                    f" @ {at_txt}")
        if isinstance(inner, lp.PeriodicSeriesWithWindowing):
            sel = _selector(inner.series, window_ms=inner.window_ms,
                            offset_ms=inner.offset_ms)
            args = [_num_str(a) for a in inner.function_args]
            return (f"{inner.function}("
                    f"{','.join(args + [sel + ' @ ' + at_txt])})")
        if isinstance(inner, lp.SubqueryWithWindowing):
            off = (f" offset {_dur(inner.offset_ms)}"
                   if inner.offset_ms else "")
            sq = (f"({u(inner.inner)})"
                  f"[{_dur(inner.subquery_window_ms)}:"
                  f"{_dur(inner.subquery_step_ms)}]{off} @ {at_txt}")
            args = [_num_str(a) for a in inner.function_args]
            return f"{inner.function}({','.join(args + [sq])})"
        if isinstance(inner, lp.TopLevelSubquery):
            step = inner.inner.step_ms
            win = (inner.start_ms - (inner.offset_ms or 0)
                   - inner.inner.start_ms)
            off = (f" offset {_dur(inner.offset_ms)}"
                   if inner.offset_ms else "")
            return (f"({u(inner.inner)})[{_dur(win)}:{_dur(step)}]{off}"
                    f" @ {at_txt}")
        raise ValueError(
            f"cannot unparse @ over {type(inner).__name__}")
    if isinstance(plan, lp.PeriodicSeries):
        return _selector(plan.raw_series, offset_ms=plan.offset_ms)
    if isinstance(plan, lp.PeriodicSeriesWithWindowing):
        if plan.window_is_lookback:
            # instant-vector timestamp(): round-trips WITHOUT a range so
            # the remote side re-resolves its own lookback
            inner = _selector(plan.series, offset_ms=plan.offset_ms)
            return f"{plan.function}({inner})"
        inner = _selector(plan.series, window_ms=plan.window_ms,
                          offset_ms=plan.offset_ms)
        args = [_num_str(a) for a in plan.function_args]
        return f"{plan.function}({','.join(args + [inner])})"
    if isinstance(plan, lp.Aggregate):
        clause = ""
        if plan.by:
            clause = f" by ({','.join(plan.by)})"
        elif plan.without:
            clause = f" without ({','.join(plan.without)})"
        args = [_num_str(a) if not isinstance(a, str) else f'"{a}"'
                for a in plan.params]
        return (f"{plan.operator}{clause}"
                f"({','.join(args + [u(plan.vectors)])})")
    if isinstance(plan, lp.BinaryJoin):
        op = plan.operator
        boolmod = ""
        if op.endswith("_bool"):
            op, boolmod = op[:-5], " bool"
        match = ""
        if plan.on is not None:
            match = f" on ({','.join(plan.on)})"
        elif plan.ignoring:
            match = f" ignoring ({','.join(plan.ignoring)})"
        grp = ""
        if plan.cardinality == "ManyToOne":
            grp = f" group_left ({','.join(plan.include)})"
        elif plan.cardinality == "OneToMany":
            grp = f" group_right ({','.join(plan.include)})"
        return f"({u(plan.lhs)} {op}{boolmod}{match}{grp} {u(plan.rhs)})"
    if isinstance(plan, lp.ScalarVectorBinaryOperation):
        op = plan.operator
        boolmod = ""
        if op.endswith("_bool"):
            op, boolmod = op[:-5], " bool"
        s, v = u(plan.scalar_arg), u(plan.vector)
        lhs, rhs = (s, v) if plan.scalar_is_lhs else (v, s)
        return f"({lhs} {op}{boolmod} {rhs})"
    if isinstance(plan, lp.ApplyInstantFunction):
        args = [_num_str(a) if isinstance(a, (int, float)) else u(a)
                for a in plan.function_args]
        return f"{plan.function}({','.join([u(plan.vectors)] + args)})"
    if isinstance(plan, lp.ApplyMiscellaneousFunction):
        args = [f'"{a}"' for a in plan.string_args]
        return f"{plan.function}({','.join([u(plan.vectors)] + args)})"
    if isinstance(plan, lp.ApplySortFunction):
        return f"{plan.function}({u(plan.vectors)})"
    if isinstance(plan, lp.ApplyAbsentFunction):
        # absent_over_time over a selector plans as ApplyAbsentFunction
        # (filters = the selector's matchers) over a present_over_time
        # windowing, possibly @-pinned (parser r4); unparse back to the
        # SURFACE form so a remote re-parse keeps the matcher labels —
        # absent(present_over_time(...)) re-parses with filters=().
        # Guarded on non-empty filters: a genuine user-written
        # absent(present_over_time(sel[w])) carries filters=() and must
        # NOT gain the selector's labels through a rewrite (review r4);
        # the subquery lowering also has filters=() and round-trips
        # structurally through the absent() rendering below.
        inner = plan.vectors
        look = (inner.inner if isinstance(inner, lp.ApplyAtTimestamp)
                else inner)
        if plan.filters \
                and isinstance(look, lp.PeriodicSeriesWithWindowing) \
                and look.function == "present_over_time":
            return "absent_over_time(" \
                + u(inner)[len("present_over_time("):]
        return f"absent({u(plan.vectors)})"
    if isinstance(plan, lp.ApplyLimitFunction):
        return f"limitk({plan.limit},{u(plan.vectors)})"
    if isinstance(plan, lp.ScalarFixedDoublePlan):
        return _num_str(plan.scalar)
    if isinstance(plan, lp.ScalarTimeBasedPlan):
        return f"{plan.function}()"
    if isinstance(plan, lp.ScalarVaryingDoublePlan):
        return f"scalar({u(plan.vectors)})"
    if isinstance(plan, lp.ScalarBinaryOperation):
        lhs = u(plan.lhs) if isinstance(plan.lhs, lp.LogicalPlan) \
            else _num_str(plan.lhs)
        rhs = u(plan.rhs) if isinstance(plan.rhs, lp.LogicalPlan) \
            else _num_str(plan.rhs)
        return f"({lhs} {plan.operator} {rhs})"
    if isinstance(plan, lp.VectorPlan):
        return f"vector({u(plan.scalars)})"
    if isinstance(plan, lp.TopLevelSubquery):
        step = plan.inner.step_ms
        # window from the inner grid anchor (end-start is 0 for @-pinned
        # plans): inner spans [start - window - offset, end - offset]
        win = plan.start_ms - (plan.offset_ms or 0) - plan.inner.start_ms
        off = f" offset {_dur(plan.offset_ms)}" if plan.offset_ms else ""
        return f"({u(plan.inner)})[{_dur(win)}:{_dur(step)}]{off}"
    if isinstance(plan, lp.SubqueryWithWindowing):
        off = f" offset {_dur(plan.offset_ms)}" if plan.offset_ms else ""
        sq = (f"({u(plan.inner)})"
              f"[{_dur(plan.subquery_window_ms)}:{_dur(plan.subquery_step_ms)}]"
              f"{off}")
        args = [_num_str(a) for a in plan.function_args]
        return f"{plan.function}({','.join(args + [sq])})"
    if isinstance(plan, lp.RawSeries):
        return _selector(plan)
    raise ValueError(f"cannot unparse {type(plan).__name__}")


def _num_str(x: float) -> str:
    xf = float(x)
    return str(int(xf)) if xf == int(xf) else repr(xf)
