"""RangeVectorTransformers: the per-plan result pipeline
(PeriodicSamplesMapper, aggregation map/present, instant functions,
label/sort/limit/scalar mappers).

Split from query/exec.py (round 4, no behavior change).
ref: query/.../exec/RangeVectorTransformer.scala:36,
AggrOverRangeVectors.scala, PeriodicSamplesMapper.scala.
"""
from __future__ import annotations

import math
import dataclasses
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax.numpy as jnp

from filodb_tpu.core.index import ColumnFilter, Equals
from filodb_tpu.ops import agg as agg_ops
from filodb_tpu.ops import hist as hist_ops
from filodb_tpu.ops.instant import (INSTANT_FUNCTIONS, ARITH_OPERATORS,
                                    COMPARISON_OPERATORS, apply_binary_op)
from filodb_tpu.ops import counter as counter_ops
from filodb_tpu.ops.rangefns import RANGE_FUNCTIONS, evaluate_range_function
from filodb_tpu.ops.timewindow import PAD_TS, to_offsets, make_window_ends
from filodb_tpu.query.rangevector import (QueryContext, QueryResult, QueryStats,
                                          RangeVectorKey, ResultBlock,
                                          concat_blocks, remove_nan_series)

from filodb_tpu.query.execbase import (
    AggPartial, Data, GroupCardinalityError, RawBlock, ScalarResult,
    _block_empty, _lru_touch, agg_token, present_partial)


# ------------------------------------------------------------- transformers


class RangeVectorTransformer:
    """ref: exec/RangeVectorTransformer.scala:36."""

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        raise NotImplementedError

    def args_str(self) -> str:
        return ""

    def __str__(self):
        return f"{type(self).__name__}({self.args_str()})"


@dataclasses.dataclass
class PeriodicSamplesMapper(RangeVectorTransformer):
    """Raw samples -> regular step grid, optional range function
    (ref: exec/PeriodicSamplesMapper.scala:27)."""
    start_ms: int
    step_ms: int
    end_ms: int
    window_ms: Optional[int] = None     # None => plain lookback sampling
    function: Optional[str] = None
    function_args: Tuple[float, ...] = ()
    offset_ms: int = 0
    lookback_ms: int = 5 * 60 * 1000

    def args_str(self):
        return (f"start={self.start_ms}, step={self.step_ms}, end={self.end_ms}, "
                f"window={self.window_ms}, functionId={self.function}, "
                f"offset={self.offset_ms}")

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        if data is None or (isinstance(data, RawBlock) and not data.keys):
            return _block_empty(wends)
        assert isinstance(data, RawBlock), "PeriodicSamplesMapper needs raw data"
        window = self.window_ms if self.window_ms else self.lookback_ms
        fn = self.function
        base = data.base_ms
        # timestamp(): the kernel computes f32 offset-seconds (exact for
        # query-sized ranges); the epoch base adds back below in f64 — f32
        # cannot hold epoch seconds to sub-minute precision
        kernel_base = 0 if fn == "timestamp" else base
        # offset: shift the window grid back, evaluate, keep original stamps
        eval_wends = wends - self.offset_ms
        wends_off = (eval_wends - base).astype(np.int32)
        vals = data.values
        vb = data.vbase
        # shared scrape grid: ship ONE [1, T] offset row and let it
        # broadcast through the kernel (exact for every range function —
        # window bounds come from row 0 and every gather takes the
        # column fast path).  Halves the general path's HBM timestamp
        # traffic and skips the S-fold ts transfer entirely.
        shared = data.shared_ts_row is not None
        ts_in = data.ts_off[:1] if shared else data.ts_off
        if vals.ndim == 3:
            S, T, B = vals.shape
            flat = np.moveaxis(vals, 2, 1).reshape(S * B, T)
            ts_rep = ts_in if shared else np.repeat(data.ts_off, B, axis=0)
            vb_flat = None if vb is None else jnp.asarray(vb).reshape(S * B)
            out = np.asarray(evaluate_range_function(
                jnp.asarray(ts_rep), jnp.asarray(flat),
                jnp.asarray(wends_off), window, fn,
                tuple(self.function_args), base_ms=kernel_base,
                vbase=vb_flat, precorrected=data.precorrected,
                shared_grid=shared, dense=data.dense))
            out = np.moveaxis(out.reshape(S, B, -1), 1, 2)     # [S, W, B]
        else:
            out = np.asarray(evaluate_range_function(
                jnp.asarray(ts_in), jnp.asarray(vals),
                jnp.asarray(wends_off), window, fn,
                tuple(self.function_args), base_ms=kernel_base,
                vbase=None if vb is None else jnp.asarray(vb),
                precorrected=data.precorrected, shared_grid=shared,
                dense=data.dense))
        if fn == "timestamp":
            out = out.astype(np.float64) + base / 1000.0
        return ResultBlock(data.keys, wends, out, data.bucket_les,
                           cache_token=getattr(data, "cache_token", None))


@dataclasses.dataclass
class RepeatToGridMapper(RangeVectorTransformer):
    """PromQL `@` modifier finisher: the upstream mapper evaluated on a
    single-step grid pinned at the @ timestamp; tile that one column
    across the query's output grid (Prometheus: the pinned value at every
    step)."""
    start_ms: int
    step_ms: int
    end_ms: int

    def args_str(self):
        return (f"start={self.start_ms}, step={self.step_ms}, "
                f"end={self.end_ms}")

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        if data is None:
            return None
        assert isinstance(data, ResultBlock), "@ repeat needs periodic data"
        vals = np.asarray(data.values)
        assert vals.shape[1] == 1, "@ inner grid must be single-step"
        reps = (1, len(wends)) + (1,) * (vals.ndim - 2)
        return ResultBlock(data.keys, wends, np.tile(vals, reps),
                           data.bucket_les,
                           cache_token=data.cache_token)


@dataclasses.dataclass
class InstantVectorFunctionMapper(RangeVectorTransformer):
    """ref: exec/RangeVectorTransformer.scala:61."""
    function: str
    args: Tuple = ()

    def args_str(self):
        return f"function={self.function}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if not isinstance(data, ResultBlock) or data.num_series == 0:
            return data
        vals = data.values
        if self.function in ("histogram_quantile", "histogram_max_quantile"):
            q = float(self._arg_value(self.args[0], source))
            if not data.is_histogram:
                # classic Prometheus histograms: `_bucket` series carrying
                # cumulative counts in `le` labels (upstream
                # promql/quantile.go bucketQuantile; the reference accepts
                # both forms, prometheus/.../PrometheusModel.scala)
                return self._classic_bucket_quantile(q, data)
            # no jnp pre-conversion: host [G, W, B] comps take the
            # numpy twin inside histogram_quantile (a device round trip
            # here cost a ~70 ms dispatch per quantile panel)
            out = np.asarray(hist_ops.histogram_quantile(
                q, vals, np.asarray(data.bucket_les)))
            return ResultBlock(data.keys, data.wends, out,
                               cache_token=data.cache_token)
        if self.function == "histogram_bucket":
            le = float(self._arg_value(self.args[0], source))
            out = np.asarray(hist_ops.histogram_bucket(
                le, jnp.asarray(vals), jnp.asarray(data.bucket_les)))
            return ResultBlock(data.keys, data.wends, out,
                               cache_token=data.cache_token)
        fn = INSTANT_FUNCTIONS[self.function]
        # elementwise functions broadcast per-step scalar args over [S, W]
        extra = [np.asarray(self._arg_value(a, source, per_step=True))
                 for a in self.args]
        out = np.asarray(fn(jnp.asarray(vals),
                            *[jnp.asarray(x) for x in extra]))
        return ResultBlock(data.keys, data.wends, out, data.bucket_les,
                           cache_token=data.cache_token)

    @staticmethod
    def _classic_bucket_quantile(q: float, data: ResultBlock) -> ResultBlock:
        """histogram_quantile over le-labeled `_bucket` series: group by
        the labels minus `le`, assemble each group's cumulative-count
        matrix in ascending le order, and reuse the native quantile
        kernel (it already applies the ensureMonotonic fixup and the
        first/+Inf-bucket edge rules).  Groups without a +Inf bucket are
        dropped, matching upstream.  Groups sharing one le ladder batch
        into a single [G, W, B] kernel call (the repo's batch-dense rule);
        an absent bucket sample (scrape gap / later-born bucket series)
        fills down from the bucket below — it contributes no extra
        observations instead of poisoning the group's quantile to NaN."""
        vals = np.asarray(data.values)
        groups: Dict[tuple, list] = {}
        for i, k in enumerate(data.keys):
            le_txt = k.labels_dict.get("le")
            if le_txt is None:
                continue
            try:
                le = float(le_txt)
            except ValueError:
                continue
            gk = k.without(("le", "_metric_", "__name__")).labels
            groups.setdefault(gk, []).append((le, i))
        by_ladder: Dict[tuple, list] = {}
        for gk, entries in sorted(groups.items()):
            entries.sort(key=lambda e: e[0])
            les = tuple(e[0] for e in entries)
            if len(les) < 2 or not math.isinf(les[-1]):
                continue                  # upstream requires an +Inf bucket
            mat = vals[[e[1] for e in entries]]           # [B, W]
            if np.isnan(mat).any():
                mat = mat.copy()
                mat[0] = np.where(np.isnan(mat[0]), 0.0, mat[0])
                for bi in range(1, mat.shape[0]):
                    mat[bi] = np.where(np.isnan(mat[bi]), mat[bi - 1],
                                       mat[bi])
            by_ladder.setdefault(les, []).append((gk, mat))
        keys, rows = [], []
        for les, members in by_ladder.items():
            stacked = np.stack([m.T for _, m in members])  # [G, W, B]
            out = np.asarray(hist_ops.histogram_quantile(
                q, stacked, np.array(les)))
            for (gk, _), row in zip(members, out):
                keys.append(RangeVectorKey(gk))
                rows.append(row)
        if not keys:
            return ResultBlock([], data.wends,
                               np.zeros((0, len(data.wends))))
        return ResultBlock(keys, data.wends, np.stack(rows))

    @staticmethod
    def _arg_value(a, source, per_step: bool = False):
        """Resolve a (possibly deferred) scalar argument.  per_step returns a
        [W] array for elementwise functions; otherwise a single float — a
        genuinely time-varying scalar is rejected rather than silently
        collapsed to its first step."""
        if hasattr(a, "resolve"):                 # deferred scalar subplan
            a = a.resolve(source)
        if isinstance(a, ScalarResult):
            if len(a.values) == 0:
                return np.nan
            if per_step:
                return a.values
            vals = a.values[~np.isnan(a.values)]
            if len(vals) and not np.all(vals == vals[0]):
                raise ValueError(
                    "time-varying scalar argument not supported for this "
                    "function")
            return a.values[0] if len(vals) == 0 else vals[0]
        return a


@dataclasses.dataclass
class ScalarOperationMapper(RangeVectorTransformer):
    """vector op scalar (ref: RangeVectorTransformer.scala:186)."""
    operator: str
    scalar: Union[float, ScalarResult]
    scalar_is_lhs: bool = False
    bool_modifier: bool = False

    def args_str(self):
        return f"operator={self.operator}, scalarOnLhs={self.scalar_is_lhs}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if not isinstance(data, ResultBlock) or data.num_series == 0:
            return data
        vals = np.asarray(data.values)
        scalar = self.scalar
        if hasattr(scalar, "resolve"):            # deferred scalar subplan
            scalar = scalar.resolve(source)
        if isinstance(scalar, ScalarResult):
            # empty scalar stream (e.g. scalar(absent-selector) across
            # shards) behaves as NaN, same as the 1-shard path
            sv = (scalar.values[None, :] if scalar.values.shape[0]
                  == vals.shape[1] else np.full((1, 1), np.nan))
        else:
            sv = np.full((1, 1), float(scalar))
        sv = np.broadcast_to(sv, vals.shape)
        a, b = (sv, vals) if self.scalar_is_lhs else (vals, sv)
        # comparison filtering keeps the VECTOR side's value
        out = np.asarray(apply_binary_op(
            jnp.asarray(a), jnp.asarray(b), op=self.operator,
            bool_modifier=self.bool_modifier,
            keep_side=("rhs" if self.scalar_is_lhs else "lhs")))
        return ResultBlock(data.keys, data.wends, out, data.bucket_les,
                           cache_token=data.cache_token)


def _group_ids(keys: Sequence[RangeVectorKey], by: Tuple[str, ...],
               without: Tuple[str, ...]) -> Tuple[np.ndarray, List[RangeVectorKey]]:
    """Host-side grouping: series key -> group key (by/without semantics)."""
    gmap: Dict[RangeVectorKey, int] = {}
    gids = np.empty(len(keys), dtype=np.int32)
    gkeys: List[RangeVectorKey] = []
    for i, k in enumerate(keys):
        if by:
            gk = k.only(by)
        elif without:
            gk = k.without(tuple(without) + ("_metric_", "__name__"))
        else:
            gk = RangeVectorKey(())
        gid = gmap.get(gk)
        if gid is None:
            gid = len(gkeys)
            gmap[gk] = gid
            gkeys.append(gk)
        gids[i] = gid
    return gids, gkeys


_CANDIDATE_OPS = {"topk", "bottomk", "count_values"}

# host group-id cache: (cache_token, by, without) -> (gids, gkeys).
# _group_ids is an O(S) Python loop (key.only() per series) that
# dominated warm general-path queries (~0.3s of 0.4s at 65k series,
# ~5s at 1M); the token (shard keys_serial, keys_epoch, pids bytes)
# identifies the key set exactly, so repeat dashboard queries do a
# dict hit instead.  Entries are treated as immutable.
_HOST_GROUP_CACHE: Dict[tuple, tuple] = {}
_HOST_GROUP_LOCK = threading.Lock()


def _group_ids_cached(token, keys, by, without):
    if token is None:
        return _group_ids(keys, by, without)
    k = (token, tuple(by), tuple(without))
    with _HOST_GROUP_LOCK:
        ent = _lru_touch(_HOST_GROUP_CACHE, k)
    if ent is not None and len(ent[0]) == len(keys):
        return ent
    gids, gkeys = _group_ids(keys, by, without)
    with _HOST_GROUP_LOCK:
        # entries from OLDER epochs of the same shard are dead — a
        # reclaimed pid may have been recycled for a different series.
        # Strictly older only: an in-flight query holding a pre-prune
        # token must not evict valid newer-epoch entries, nor install
        # its own never-hittable stale one.  Only LEAF tokens carry the
        # (serial, epoch:int, ...) shape this compares; derived tokens
        # (execbase.agg_token / _reduced_token) embed the leaf epoch
        # inside themselves — a prune mints a NEW token, and the stale
        # entry ages out through the LRU cap instead.
        if len(token) > 1 and isinstance(token[1], int):
            def _epoch(o):
                t = o[0]
                return (t[1] if t[0] == token[0] and len(t) > 1
                        and isinstance(t[1], int) else None)
            for old in [o for o in _HOST_GROUP_CACHE
                        if _epoch(o) is not None and _epoch(o) < token[1]]:
                del _HOST_GROUP_CACHE[old]
            if any(_epoch(o) is not None and _epoch(o) > token[1]
                   for o in _HOST_GROUP_CACHE):
                return gids, gkeys
        _HOST_GROUP_CACHE[k] = (gids, gkeys)
        while len(_HOST_GROUP_CACHE) > 8:
            _HOST_GROUP_CACHE.pop(next(iter(_HOST_GROUP_CACHE)))
    return gids, gkeys


@dataclasses.dataclass
class AggregateMapReduce(RangeVectorTransformer):
    """Map phase of 3-phase aggregation (ref: AggrOverRangeVectors.scala:76)."""
    op: str
    params: Tuple = ()
    by: Tuple[str, ...] = ()
    without: Tuple[str, ...] = ()

    def args_str(self):
        return (f"aggrOp={self.op}, aggrParams={list(self.params)}, "
                f"without={list(self.without)}, by={list(self.by)}")

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        assert isinstance(data, (ResultBlock, type(None)))
        if data is None or data.num_series == 0:
            return None
        vals = np.asarray(data.values)
        gids, gkeys = _group_ids_cached(
            getattr(data, "cache_token", None), data.keys, self.by,
            self.without)
        limit = ctx.planner_params.group_by_cardinality_limit
        if limit and len(gkeys) > limit:
            raise GroupCardinalityError(
                f"group-by cardinality limit {limit} exceeded "
                f"({len(gkeys)} groups)")
        if data.is_histogram and self.op == "sum":
            # histogram sum: elementwise over buckets — [G, W, B+1] where the
            # extra slot counts present series (empty-step masking)
            present = ~np.isnan(vals)
            comp = np.where(present, vals, 0.0)
            G = len(gkeys)
            S, W, B = vals.shape
            agg = np.zeros((G, W, B + 1))
            np.add.at(agg[..., :B], gids, comp)     # view write-through
            np.add.at(agg[..., B], gids, present.any(axis=2).astype(float))
            return AggPartial("hist_sum", gkeys, data.wends, comp=agg,
                              params=self.params, bucket_les=data.bucket_les,
                              cache_token=agg_token(
                                  "hist_sum", self.by, self.without,
                                  data.cache_token))
        if self.op == "quantile" and vals.ndim == 2:
            from filodb_tpu.ops import sketch as sketch_ops
            sk = sketch_ops.sketch_from_values(vals, gids, len(gkeys))
            return AggPartial(self.op, gkeys, data.wends, sketch=sk,
                              params=self.params,
                              cache_token=agg_token(
                                  self.op, self.by, self.without,
                                  data.cache_token))
        if self.op in _CANDIDATE_OPS or self.op == "quantile":
            cand_keys, cand_vals, cand_groups = self._candidates(
                data, vals, gids, len(gkeys))
            return AggPartial(self.op, gkeys, data.wends, cand_keys=cand_keys,
                              cand_vals=cand_vals, cand_groups=cand_groups,
                              params=self.params)
        comp = np.asarray(agg_ops.map_phase(
            self.op, jnp.asarray(vals), jnp.asarray(gids), len(gkeys)))
        return AggPartial(self.op, gkeys, data.wends, comp=comp,
                          params=self.params,
                          cache_token=agg_token(self.op, self.by,
                                                self.without,
                                                data.cache_token))

    def _candidates(self, data, vals, gids, num_groups):
        if self.op in ("topk", "bottomk"):
            k = int(self.params[0])
            mask = np.asarray(agg_ops.topk_mask(
                jnp.asarray(vals), jnp.asarray(gids), num_groups, k,
                largest=(self.op == "topk")))
            keep = mask.any(axis=1)
            rows = np.flatnonzero(keep)
        else:
            rows = np.arange(len(data.keys))
        return ([data.keys[int(r)] for r in rows], vals[rows], gids[rows])


class AggregatePresenter(RangeVectorTransformer):
    """Present phase (ref: AggrOverRangeVectors.scala:125)."""

    def __init__(self, op: str, params: Tuple = ()):
        self.op = op
        self.params = params

    def args_str(self):
        return f"aggrOp={self.op}, aggrParams={list(self.params)}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if data is None:
            return None
        assert isinstance(data, AggPartial)
        return present_partial(data)


@dataclasses.dataclass
class AbsentFunctionMapper(RangeVectorTransformer):
    """absent() (ref: RangeVectorTransformer.scala:340)."""
    filters: Tuple[ColumnFilter, ...]
    start_ms: int = 0
    step_ms: int = 0
    end_ms: int = 0

    def args_str(self):
        return "functionId=absent"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        wends = (data.wends if isinstance(data, ResultBlock)
                 else make_window_ends(self.start_ms, self.end_ms,
                                       max(self.step_ms, 1)))
        if isinstance(data, ResultBlock) and data.num_series:
            present = ~np.isnan(np.asarray(data.values)).all(axis=0)
        else:
            present = np.zeros(len(wends), dtype=bool)
        out = np.where(present, np.nan, 1.0)[None, :]
        labels = {f.column: f.value for f in self.filters
                  if isinstance(f, Equals)
                  and f.column not in ("__name__", "_metric_")}
        return ResultBlock([RangeVectorKey.make(labels)], wends, out)


@dataclasses.dataclass
class SortFunctionMapper(RangeVectorTransformer):
    """sort()/sort_desc() by mean value (ref: RangeVectorTransformer.scala:254)."""
    descending: bool = False

    def args_str(self):
        return f"function={'sort_desc' if self.descending else 'sort'}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if not isinstance(data, ResultBlock) or data.num_series <= 1:
            return data
        with np.errstate(invalid="ignore"):
            means = np.nanmean(np.asarray(data.values), axis=1)
        means = np.where(np.isnan(means), -np.inf if not self.descending else np.inf,
                         means)
        order = np.argsort(-means if self.descending else means, kind="stable")
        return data.select(order)


@dataclasses.dataclass
class MiscellaneousFunctionMapper(RangeVectorTransformer):
    """label_replace / label_join (ref: rangefn/MiscellaneousFunction.scala)."""
    function: str
    string_args: Tuple[str, ...] = ()

    def args_str(self):
        return f"function={self.function}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if not isinstance(data, ResultBlock):
            return data
        import re
        if self.function == "label_replace":
            dst, repl, src, regex = self.string_args
            pat = re.compile("^(?:" + regex + ")$")
            keys = []
            for k in data.keys:
                lbls = k.labels_dict
                m = pat.match(lbls.get(src, ""))
                if m:
                    val = m.expand(_dollar_to_backslash(repl))
                    if val:
                        lbls[dst] = val
                    else:
                        lbls.pop(dst, None)
                keys.append(RangeVectorKey.make(lbls))
            keys, vals = _merge_relabeled(keys, data, "label_replace")
            return ResultBlock(keys, data.wends, vals, data.bucket_les)
        if self.function == "label_join":
            dst, sep, *srcs = self.string_args
            keys = []
            for k in data.keys:
                lbls = k.labels_dict
                val = sep.join(lbls.get(s, "") for s in srcs)
                if val:
                    lbls[dst] = val
                else:
                    lbls.pop(dst, None)
                keys.append(RangeVectorKey.make(lbls))
            keys, vals = _merge_relabeled(keys, data, "label_join")
            return ResultBlock(keys, data.wends, vals, data.bucket_les)
        raise ValueError(f"unknown misc function {self.function}")


def _merge_relabeled(keys, data, fn_name: str):
    """Upstream semantics for relabeling that lands several series on
    one labelset: it is an ERROR only when the duplicates co-occur in
    the same evaluation step ("vector cannot contain metrics with the
    same labelset"); series whose samples never overlap (e.g. the two
    halves of a restart, absent-as-NaN here) MERGE into one series
    (ref: prometheus functions.go label_replace + per-step Series
    dedup).  Returns (keys, values) with disjoint duplicates merged."""
    groups: dict = {}
    for i, k in enumerate(keys):
        groups.setdefault(k.labels, []).append(i)
    if all(len(rows) == 1 for rows in groups.values()):
        return keys, data.values
    vals = np.asarray(data.values)
    out_keys, out_rows = [], []
    for sig, rows in groups.items():
        if len(rows) == 1:
            out_keys.append(keys[rows[0]])
            out_rows.append(vals[rows[0]])
            continue
        sub = vals[rows]                      # [d, W] or [d, W, B]
        # presence is NaN-only (the staleness convention everywhere else:
        # nonleaf dedup, absent()): +/-Inf is a legal sample value (1/0,
        # histogram_quantile overflow) and must collide/merge like any
        # other sample, not vanish (ADVICE r5, medium)
        present = ~np.isnan(sub)
        if sub.ndim == 3:
            present = present.any(axis=-1)
        if (present.sum(axis=0) > 1).any():
            raise ValueError(
                f"{fn_name}: vector cannot contain metrics with the "
                f"same labelset")
        merged = np.full(sub.shape[1:], np.nan, vals.dtype)
        for d in range(sub.shape[0]):
            m = present[d]
            merged[m] = sub[d][m]
        out_keys.append(keys[rows[0]])
        out_rows.append(merged)
    return out_keys, np.stack(out_rows)


def _dollar_to_backslash(repl: str) -> str:
    """PromQL uses $1; python re.expand uses \\1."""
    import re as _re
    return _re.sub(r"\$(\d+)", r"\\\1", repl)


@dataclasses.dataclass
class LimitFunctionMapper(RangeVectorTransformer):
    limit: int

    def args_str(self):
        return f"limit={self.limit}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if isinstance(data, ResultBlock) and data.num_series > self.limit:
            return data.select(np.arange(self.limit))
        return data


@dataclasses.dataclass
class ScalarFunctionMapper(RangeVectorTransformer):
    """scalar(vector): 1 series -> scalar stream, else NaN (ref:
    RangeVectorTransformer ScalarFunctionMapper)."""
    function: str = "scalar"

    def args_str(self):
        return f"function={self.function}"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        assert isinstance(data, (ResultBlock, type(None)))
        if data is None or data.num_series != 1:
            wends = data.wends if data is not None else np.zeros(0, np.int64)
            return ScalarResult(wends, np.full(len(wends), np.nan))
        return ScalarResult(data.wends, np.asarray(data.values)[0])


@dataclasses.dataclass
class VectorFunctionMapper(RangeVectorTransformer):
    """vector(scalar) (ref: RangeVectorTransformer VectorFunctionMapper)."""

    def args_str(self):
        return "function=vector"

    def apply(self, data: Data, ctx: QueryContext, stats: QueryStats,
              source=None) -> Data:
        if isinstance(data, ScalarResult):
            return ResultBlock([RangeVectorKey(())], data.wends,
                               data.values[None, :])
        return data

