"""Leaf exec plans: the shard-local gather + fused-path leaf and the
scalar generators.

Split from query/exec.py (round 4, no behavior change).
ref: query/.../exec/MultiSchemaPartitionsExec.scala,
TimeScalarGeneratorExec.scala.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax.numpy as jnp

from filodb_tpu.core.index import ColumnFilter, Equals
from filodb_tpu.ops import agg as agg_ops
from filodb_tpu.ops import hist as hist_ops
from filodb_tpu.ops.instant import (INSTANT_FUNCTIONS, ARITH_OPERATORS,
                                    COMPARISON_OPERATORS, apply_binary_op)
from filodb_tpu.ops import counter as counter_ops
from filodb_tpu.ops.rangefns import RANGE_FUNCTIONS, evaluate_range_function
from filodb_tpu.ops.timewindow import PAD_TS, to_offsets, make_window_ends
from filodb_tpu.query.rangevector import (QueryContext, QueryResult, QueryStats,
                                          RangeVectorKey, ResultBlock,
                                          concat_blocks, remove_nan_series)

from filodb_tpu.query.execbase import (
    AggPartial, GroupCardinalityError, LazyKeys, LeafExecPlan,
    QueryError, QueryResultLike, RawBlock, ScalarResult,
    _FUSED_CACHE_LOCK, _FUSED_MINMAX_PAD_CACHE, _FUSED_PLAN_CACHE,
    _FUSED_VALS_CACHE, _block_empty, _group_cache_insert,
    _group_cache_lookup, _lru_touch, _note_mirror_limit,
    _vals_cache_insert, agg_token)
from filodb_tpu.query.transformers import (
    AggregateMapReduce, PeriodicSamplesMapper, RangeVectorTransformer,
    _group_ids, _group_ids_cached)
from filodb_tpu.query.fusedbatch import FusedCall, finish_fused_calls


class MultiSchemaPartitionsExec(LeafExecPlan):
    """Leaf: index lookup + dense gather on the owning shard
    (ref: exec/MultiSchemaPartitionsExec.scala:27-60,
    SelectRawPartitionsExec.doExecute:125)."""

    def __init__(self, ctx: QueryContext, dataset: str, shard: int,
                 filters: Sequence[ColumnFilter], chunk_start_ms: int,
                 chunk_end_ms: int, columns: Sequence[str] = (),
                 schema: Optional[str] = None):
        super().__init__(ctx)
        self.dataset = dataset
        self.shard = shard
        self.filters = list(filters)
        self.chunk_start_ms = chunk_start_ms
        self.chunk_end_ms = chunk_end_ms
        self.columns = list(columns)
        self.schema = schema
        self._transformer_overrides: Dict[int, RangeVectorTransformer] = {}
        self._prefused = None

    def _execute_impl(self, source) -> QueryResultLike:
        # (wrapped by ExecPlan.execute_internal's resource tally)
        pre = getattr(self, "_prefused", None)
        if pre is not None:
            # phase-3 of engine.query_range_batch: the gather and fused
            # preflight already ran in prepare_fused (keeping this leaf's
            # _transformer_overrides), and the kernel work was batched
            self._prefused = None
            data, stats, fused = pre
            if isinstance(fused, FusedCall):
                # engine collected the call but never finished it (e.g. a
                # batch peer errored): run it standalone
                fused = self._finish_or_degrade(fused)
        else:
            self._transformer_overrides = {}
            self._fused_cache_key = None
            data, stats = self._do_execute(source)
            try:
                fused = self._try_fused(data, stats)
            except (GroupCardinalityError, QueryError):
                # real query errors (cardinality limit, cancellation)
                # must surface, never degrade to the general path
                raise
            except Exception as e:  # noqa: BLE001 — fusion is an optimization
                from filodb_tpu.utils.metrics import (log_fused_degradation,
                                                      registry)
                registry.counter("leaf_fused_errors").increment()
                log_fused_degradation("leaf", e)
                fused = None
        start = 0
        if fused is not None:
            data, start = fused, 2
        for i, t in enumerate(self.transformers[start:], start):
            t = self._transformer_overrides.get(i, t)
            data = t.apply(data, self.ctx, stats, source)
        return data, stats

    def prepare_fused(self, source):
        """Phase-1 of engine.query_range_batch: run the gather and the
        fused preflight, but NOT the kernel.  Returns a FusedCall when
        this leaf's kernel work can be merged with other panels'
        (finish_fused_calls), else None.  Either way the gathered data is
        parked on the leaf so phase-3 execution never re-gathers; the
        engine injects the finished AggPartial via inject_fused."""
        self._transformer_overrides = {}
        self._fused_cache_key = None
        data, stats = self._do_execute(source)
        try:
            pre = self._try_fused(data, stats, defer=True)
        except GroupCardinalityError:
            # real query error — park the gather anyway so phase-3
            # surfaces the SAME error from the general aggregate path
            # (transformers.py group limit) without paying the index
            # lookup + dense gather twice
            self._prefused = (data, stats, None)
            return None
        except QueryError:
            raise                        # cancellation must surface
        except Exception as e:  # noqa: BLE001 — fusion is an optimization
            from filodb_tpu.utils.metrics import (log_fused_degradation,
                                                  registry)
            registry.counter("leaf_fused_errors").increment()
            log_fused_degradation("leaf", e)
            pre = None
        self._prefused = (data, stats, pre)
        return pre if isinstance(pre, FusedCall) else None

    def inject_fused(self, partial) -> None:
        """Phase-2 handoff: replace the parked FusedCall with its batched
        result (an AggPartial)."""
        data, stats, _ = self._prefused
        self._prefused = (data, stats, partial)

    def _finish_or_degrade(self, fc):
        self._check_cancel("fused kernel dispatch")
        try:
            return finish_fused_calls([fc])[0]
        except QueryError:
            raise                        # cancellation must surface
        except Exception as e:  # noqa: BLE001 — fusion is an optimization
            from filodb_tpu.utils.metrics import (log_fused_degradation,
                                                  registry)
            registry.counter("leaf_fused_errors").increment()
            log_fused_degradation("leaf", e)
            return None

    def _try_fused(self, data, stats, defer: bool = False):
        """Peephole: PeriodicSamplesMapper(rate|increase|delta) followed by
        AggregateMapReduce(sum) over a shared-grid fully-finite working set
        collapses into the single-HBM-pass MXU kernel (ops/pallas_fused.py)
        — the leaf analogue of the reference pushing AggregateMapReduce to
        data nodes (ref: AggrOverRangeVectors.scala:76), fused one level
        further.  Returns the AggPartial or None (general path); with
        defer=True the matmul-kernel path returns a FusedCall instead so
        the engine can merge compatible panels into one dispatch."""
        if len(self.transformers) < 2 or not isinstance(data, RawBlock) \
                or not data.keys or data.shared_ts_row is None:
            return None
        t0 = self._transformer_overrides.get(0, self.transformers[0])
        t1 = self._transformer_overrides.get(1, self.transformers[1])
        if not isinstance(t0, PeriodicSamplesMapper) \
                or not isinstance(t1, AggregateMapReduce):
            return None
        from filodb_tpu.ops import pallas_fused as pf
        vals = data.values
        ndim = getattr(vals, "ndim", 0)
        is_hist = ndim == 3
        if ndim not in (2, 3) or t0.function_args or t1.params:
            return None
        if t0.window_ms is None:
            # instant-vector selector (`sum by (x) (metric)`): plain
            # lookback sampling IS last_over_time over the stale-lookback
            # window — the same normalization the general apply() does
            if t0.function is not None:
                return None
            t0 = dataclasses.replace(t0, window_ms=t0.lookback_ms,
                                     function="last_over_time")
        fn = t0.function or ""
        dense = data.dense
        if not pf.can_fuse(fn, t1.op, True, dense):
            return None
        if is_hist:
            # histogram buckets are counters too: flatten [S, T, B] into
            # S*B kernel rows with per-(group, bucket) slots — the hist
            # analogue (ref: HistogramQueryBenchmark's
            # sum(rate(..._bucket[5m])) + histogram_quantile).  Ragged
            # (NaN-holed) bucket rows ride the kernel's valid-boundary
            # machinery like scalar rows do (round-5 verdict item 5) —
            # each flattened bucket row finds its own boundaries
            if fn not in ("rate", "increase") or t1.op != "sum" \
                    or data.bucket_les is None:
                return None
        # host-only fast paths: under the dense shared grid every series
        # has IDENTICAL per-window sample counts, so count_over_time and
        # the count aggregate are pure host math — no device work at all
        if dense and not is_hist and fn == "count_over_time":
            return self._fused_count_over_time(data, t0, t1)
        if dense and not is_hist and t1.op == "count":
            return self._fused_count_agg(data, t0, t1)
        wends = make_window_ends(t0.start_ms, t0.end_ms, t0.step_ms)
        eval_wends = wends - t0.offset_ms - data.base_ms
        if eval_wends.size == 0 or abs(eval_wends).max() >= (1 << 30):
            return None
        routed = self._try_host_routed(data, t0, t1, wends, eval_wends,
                                       fn, dense, is_hist)
        if routed is not None:
            return routed
        if fn in pf.MINMAX_FNS:
            # pure-XLA reduce_window path — any backend, no Pallas
            return self._fused_minmax(data, t0, t1, wends, eval_wends)
        import jax
        backend = jax.default_backend()
        interpret = backend != "tpu"
        if interpret and not os.environ.get("FILODB_TPU_FUSED_INTERPRET"):
            return None                 # kernel is MXU-targeted
        if fn in ("rate", "increase") and not data.precorrected:
            return None
        # VMEM guard, part 1 (group count not yet known — use the minimum):
        # very long ranges with many windows must take the general path,
        # not fail at kernel lowering
        Tp = pf._pad_to(vals.shape[1], pf._LANE)
        Wp = pf._pad_to(eval_wends.size, pf._LANE)
        over_time = t0.function in pf.OVER_TIME_FNS
        ragged_rate = not dense and fn in ("rate", "increase", "delta")
        kind = fn if fn in pf.OVER_TIME_FNS else "rate_family"
        gather = pf.gather_default(kind)
        if pf.pick_block(Tp, Wp, 8, over_time, ragged_rate,
                         gather=gather) is None:
            return None
        from filodb_tpu.utils.metrics import registry
        # plan + prepared-input caches: a repeat query over an unchanged
        # snapshot (the dashboard-poll pattern) skips the selection-matrix
        # rebuild AND the full padded device copy (PreparedInputs contract)
        key = self._fused_cache_key
        plan = padded_vals = groups = gkeys = None
        if key is not None:
            plan_key = key[:3] + (t0.start_ms, t0.step_ms, t0.end_ms,
                                  t0.offset_ms, t0.window_ms, data.base_ms)
            with _FUSED_CACHE_LOCK:
                plan = _lru_touch(_FUSED_PLAN_CACHE, plan_key)
                padded_vals = _lru_touch(_FUSED_VALS_CACHE, key)
            groups, gkeys = _group_cache_lookup(key, t1.by, t1.without)
            if padded_vals is not None:
                registry.counter("leaf_fused_prep_hits").increment()
        if plan is None:
            plan = pf.build_plan(data.shared_ts_row.astype(np.int64),
                                 eval_wends, t0.window_ms)
            if key is not None:
                with _FUSED_CACHE_LOCK:
                    for k in [k for k in _FUSED_PLAN_CACHE
                              if k[0] == key[0] and k[1] != key[1]]:
                        del _FUSED_PLAN_CACHE[k]
                    _FUSED_PLAN_CACHE[plan_key] = plan
                    while len(_FUSED_PLAN_CACHE) > 8:
                        _FUSED_PLAN_CACHE.pop(next(iter(_FUSED_PLAN_CACHE)))
        if gkeys is None:
            gids, gkeys = _group_ids_cached(data.cache_token, data.keys,
                                            t1.by, t1.without)
        self._check_group_limit(gkeys)
        B = vals.shape[2] if is_hist else 1
        num_slots = len(gkeys) * B      # hist: one kernel group per (g, b)
        # VMEM guard, part 2: full estimate now that group count is known —
        # BEFORE the padded device copy, so diverted queries cost nothing
        # same padded group count _run will use — a gate tested on the
        # unpadded count could accept a shape _run then rejects
        if pf.pick_block(Tp, Wp, pf.pad_group_count(num_slots),
                         over_time, ragged_rate, gather=gather) is None:
            return None
        if padded_vals is None:
            vbase = data.vbase
            if is_hist:
                # [S, T, B] -> [S*B, T] rows (bucket-major within a series,
                # same layout PeriodicSamplesMapper flattens to)
                flat = jnp.moveaxis(jnp.asarray(vals), 2, 1) \
                    .reshape(vals.shape[0] * B, vals.shape[1])
                vb_flat = (np.zeros(flat.shape[0], np.float32)
                           if vbase is None
                           else jnp.asarray(vbase,
                                            jnp.float32).reshape(-1))
                padded_vals = pf.pad_values(flat, vb_flat, plan)
            else:
                if vbase is None:
                    vbase = np.zeros(vals.shape[0], np.float32)
                padded_vals = pf.pad_values(vals, vbase, plan)
            if key is not None:
                # a new snapshot generation obsoletes this mirror's older
                # entries — drop them NOW, not at LRU eviction: each pins a
                # full padded copy of the working set in HBM
                with _FUSED_CACHE_LOCK:
                    for k in [k for k in _FUSED_VALS_CACHE
                              if k[0] == key[0] and k[1] != key[1]]:
                        del _FUSED_VALS_CACHE[k]
                    _vals_cache_insert(key, padded_vals)
        if groups is None:
            if is_hist:
                gids_flat = (np.asarray(gids, np.int64)[:, None] * B
                             + np.arange(B)[None, :]).reshape(-1)
                groups = pf.pad_groups(gids_flat, vals.shape[0] * B,
                                       num_slots)
            else:
                groups = pf.pad_groups(gids, vals.shape[0], len(gkeys))
            _group_cache_insert(key, t1.by, t1.without, groups, gkeys)
        registry.counter("leaf_fused_kernel").increment()
        if not is_hist:
            # broadened matmul path: any fusable (fn, agg) combination,
            # ragged (validity-weighted) when the working set has NaN
            # holes.  Packaged as a FusedCall so engine.query_range_batch
            # can merge compatible panels into one kernel dispatch; the
            # single-query path finishes it immediately.
            ck = None if key is None else key + (
                t0.start_ms, t0.step_ms, t0.end_ms, t0.offset_ms,
                t0.window_ms, data.base_ms)
            fc = FusedCall(
                plan=plan, values=padded_vals, groups=groups, gkeys=gkeys,
                wends=wends, fn=fn, op=t1.op,
                precorrected=data.precorrected, interpret=interpret,
                ragged=not dense, num_series=vals.shape[0], cache_key=ck,
                cache_token=agg_token(t1.op, t1.by, t1.without,
                                      data.cache_token))
            if defer:
                return fc
            self._check_cancel("fused kernel dispatch")
            return finish_fused_calls([fc])[0]
        # histogram leaf (sum(rate(bucket_metric))): (group, bucket)
        # slots ride the same FusedCall machinery so quantile dashboards
        # batch too — identical panels (p50/p90/p99 over one metric)
        # dedup to ONE kernel run (fusedbatch finisher reshapes slots to
        # [G, W, B] and appends the present-series count)
        ck = None if key is None else key + (
            t0.start_ms, t0.step_ms, t0.end_ms, t0.offset_ms,
            t0.window_ms, data.base_ms, "hist", B)
        fc = FusedCall(
            plan=plan, values=padded_vals,
            groups=groups, gkeys=gkeys, wends=wends, fn=fn, op="sum",
            precorrected=data.precorrected, interpret=interpret,
            ragged=not dense, num_series=vals.shape[0] * B, cache_key=ck,
            bucket_les=data.bucket_les, num_buckets=B,
            cache_token=agg_token("hist_sum", t1.by, t1.without,
                                  data.cache_token))
        if defer:
            return fc
        self._check_cancel("fused hist kernel dispatch")
        return finish_fused_calls([fc])[0]

    def _try_host_routed(self, data, t0, t1, wends, eval_wends, fn,
                         dense, is_hist):
        """Cost-based host evaluation for small working sets (round-5
        verdict item 6; crossover/threshold: query.host_route_max_samples
        via RawBlock.route_host).  Returns an AggPartial or None to
        continue onto the device paths."""
        if not (data.route_host and dense and not is_hist
                and data.shared_ts_row is not None
                and t1.op in ("sum", "avg", "count", "min", "max")
                and isinstance(data.values, np.ndarray)):
            return None
        if fn in ("rate", "increase") and not data.precorrected:
            return None
        from filodb_tpu.ops import hostleaf
        from filodb_tpu.ops import pallas_fused as pf
        from filodb_tpu.utils.metrics import registry, span
        # batch-scoped FINISHED-partial memo: a dashboard repeats whole
        # subexpressions (sum by (ns)(rate(m[5m])) rides alone AND as a
        # ratio operand AND under topk), and within one gather-memo
        # scope an identical (working set, fn, op, grouping, grid) key
        # means identical inputs — so the evaluation is shared like the
        # scan.  Inert outside engine.query_range_batch's memo scope.
        mkey = None
        if data.cache_token is not None:
            mkey = ("hpartial", data.cache_token, fn, t1.op,
                    tuple(t1.by), tuple(t1.without), t0.start_ms,
                    t0.step_ms, t0.end_ms, t0.offset_ms, t0.window_ms,
                    data.base_ms)
            hit = hostleaf.memo_get(mkey)
            if hit is not None:
                self._check_group_limit(hit.group_keys)
                registry.counter("leaf_host_routed").increment()
                self.route = "host"
                return dataclasses.replace(hit)
        plan = pf.build_plan(
            np.asarray(data.shared_ts_row, np.int64), eval_wends,
            t0.window_ms)
        if plan.idx1 is None:
            return None
        # token-keyed group cache: the O(S) key.only() loop dominated
        # repeat host-routed leaves (same working set, new panel)
        gids, gkeys = _group_ids_cached(data.cache_token, data.keys,
                                        t1.by, t1.without)
        self._check_group_limit(gkeys)
        with span("leaf_host_routed", fn=fn, op=t1.op):
            comp = hostleaf.host_leaf_agg(
                plan, data.values, data.vbase, np.asarray(gids),
                len(gkeys), fn, t1.op)
        registry.counter("leaf_host_routed").increment()
        self.route = "host"
        p = AggPartial(t1.op, gkeys, wends, comp=comp,
                       cache_token=agg_token(t1.op, t1.by, t1.without,
                                             data.cache_token))
        if mkey is not None:
            hostleaf.memo_put(mkey, p)
        return p

    def args_str(self):
        fs = ",".join(str(f) for f in self.filters)
        route = getattr(self, "route", None)
        return (f"dataset={self.dataset}, shard={self.shard}, "
                f"chunkMethod=TimeRangeChunkScan({self.chunk_start_ms},"
                f"{self.chunk_end_ms}), filters=[{fs}], "
                f"colName={self.columns}"
                + (f", route={route}" if route else ""))

    def _window_counts_groups(self, data, t0, t1):
        """Shared host math for the no-device fast paths: per-window
        sample counts on the dense shared grid + grouping."""
        wends = make_window_ends(t0.start_ms, t0.end_ms, t0.step_ms)
        eval_wends = wends - t0.offset_ms - data.base_ms
        if eval_wends.size == 0 or abs(eval_wends).max() >= (1 << 30):
            return None
        from filodb_tpu.ops import pallas_fused as pf
        gids, gkeys = _group_ids_cached(data.cache_token, data.keys,
                                        t1.by, t1.without)
        self._check_group_limit(gkeys)
        n = pf.window_counts(data.shared_ts_row.astype(np.int64),
                             eval_wends, t0.window_ms).astype(np.float64)
        gsize = np.bincount(np.asarray(gids),
                            minlength=len(gkeys))[:len(gkeys)]
        return wends, gkeys, n, gsize.astype(np.float64)

    def _fused_count_over_time(self, data, t0, t1):
        """agg by (count_over_time(...)): under the shared dense grid every
        series has IDENTICAL per-window sample counts, so the whole result
        is host math over (gsize, n) — no device work at all.  Handles all
        five fusable aggregates: each series' value at window w is n[w]."""
        r = self._window_counts_groups(data, t0, t1)
        if r is None:
            return None
        wends, gkeys, n, gsize = r
        valid = (n >= 1).astype(np.float64)
        op = t1.op
        if op in ("sum", "avg"):
            comp = np.stack([gsize[:, None] * n[None, :] * valid,
                             gsize[:, None] * valid[None, :]], axis=-1)
        elif op == "count":
            comp = (gsize[:, None] * valid[None, :])[..., None]
        else:                            # min/max: every series agrees on n
            absent = np.inf if op == "min" else -np.inf
            per = np.where(valid > 0, n, absent)
            comp = np.stack(
                [np.broadcast_to(per[None, :], (len(gkeys), len(n))),
                 gsize[:, None] * valid[None, :]], axis=-1)
        from filodb_tpu.utils.metrics import registry
        registry.counter("leaf_fused_count_host").increment()
        return AggPartial(op, gkeys, wends, comp=comp,
                          cache_token=agg_token(op, t1.by, t1.without,
                                                data.cache_token))

    def _fused_count_agg(self, data, t0, t1):
        """count by (fn(...)) on a dense shared grid: the count of series
        emitting a value at window w is gsize * 1{n[w] >= min_samples} —
        host math, no device work (the value itself never matters)."""
        r = self._window_counts_groups(data, t0, t1)
        if r is None:
            return None
        wends, gkeys, n, gsize = r
        minsamp = 2 if t0.function in ("rate", "increase", "delta") else 1
        valid = (n >= minsamp).astype(np.float64)
        from filodb_tpu.utils.metrics import registry
        registry.counter("leaf_fused_count_host").increment()
        comp = (gsize[:, None] * valid[None, :])[..., None]
        return AggPartial("count", gkeys, wends, comp=comp,
                          cache_token=agg_token("count", t1.by, t1.without,
                                                data.cache_token))

    def _fused_minmax(self, data, t0, t1, wends, eval_wends):
        """min/max_over_time + any aggregate in one jit via the XLA
        reduce_window path (ops/pallas_fused.fused_minmax_agg) — one HBM
        pass, no host round trip of the [S, T] working set, any backend.
        Requires uniform window geometry; else the general path runs."""
        from filodb_tpu.ops import pallas_fused as pf
        ts_row0 = np.asarray(data.shared_ts_row)
        real = ts_row0[ts_row0 < PAD_TS]
        geom = pf.uniform_window_geometry(real.astype(np.int64),
                                          eval_wends, t0.window_ms)
        if geom is None:
            return None
        f0, stride, width, t_needed = geom
        if t_needed > 2 * real.size:
            # a grid hanging FAR past the data (end=now long after the last
            # scrape) would pad more columns than the data itself — the
            # general path handles that without materializing the padding
            return None
        # grouping: reuse the shared per-working-set group cache (the same
        # per-series label hashing the kernel path caches away)
        key = self._fused_cache_key
        groups_c, gkeys = _group_cache_lookup(key, t1.by, t1.without)
        if gkeys is None:
            gids, gkeys = _group_ids(data.keys, t1.by, t1.without)
            self._check_group_limit(gkeys)      # reject BEFORE caching
            _group_cache_insert(key, t1.by, t1.without,
                                pf.pad_groups(gids, len(data.keys),
                                              len(gkeys)), gkeys)
        else:
            self._check_group_limit(gkeys)
            gids = np.asarray(groups_c.gids_p[:len(data.keys), 0])
        vb = data.vbase
        vals = jnp.asarray(data.values)
        ragged = not data.dense
        if t_needed > real.size:
            # windows hang past the data's right edge (end=now queries):
            # extend with NaN columns so the ragged variant masks them —
            # cached per (working set, t_needed): the dashboard-poll shape
            # would otherwise re-copy the whole set on device every refresh
            pad_key = None if key is None else key + ("minmax_pad",
                                                      t_needed)
            padded = None
            if pad_key is not None:
                with _FUSED_CACHE_LOCK:
                    padded = _lru_touch(_FUSED_MINMAX_PAD_CACHE, pad_key)
            if padded is None:
                padded = jnp.pad(vals[:, :real.size],
                                 ((0, 0), (0, t_needed - real.size)),
                                 constant_values=np.nan)
                if pad_key is not None:
                    with _FUSED_CACHE_LOCK:
                        for k in [k for k in _FUSED_MINMAX_PAD_CACHE
                                  if k[0] == pad_key[0]
                                  and k[1] != pad_key[1]]:
                            del _FUSED_MINMAX_PAD_CACHE[k]
                        _FUSED_MINMAX_PAD_CACHE[pad_key] = padded
                        while len(_FUSED_MINMAX_PAD_CACHE) > 2:
                            _FUSED_MINMAX_PAD_CACHE.pop(
                                next(iter(_FUSED_MINMAX_PAD_CACHE)))
            vals = padded
            ragged = True
        _d0 = _time.perf_counter()
        comp = pf.fused_minmax_agg(
            vals, None if vb is None else jnp.asarray(vb),
            jnp.asarray(gids, jnp.int32), f0, stride, width,
            int(eval_wends.size), t0.function, t1.op, len(gkeys),
            ragged=ragged)
        comp_np = np.asarray(comp, np.float64)   # synchronizing readback
        from filodb_tpu.utils.devicetelem import telem
        telem.record_dispatch(
            "fused_minmax", device=pf._committed_device(vals),
            shape=f"S{vals.shape[0]}xW{int(eval_wends.size)}xG{len(gkeys)}",
            seconds=_time.perf_counter() - _d0,
            bytes_in=int(getattr(vals, "nbytes", 0)),
            bytes_out=comp_np.nbytes)
        from filodb_tpu.utils.metrics import registry
        registry.counter("leaf_fused_minmax").increment()
        return AggPartial(t1.op, gkeys, wends,
                          comp=comp_np,
                          cache_token=agg_token(t1.op, t1.by, t1.without,
                                                data.cache_token))

    def _check_group_limit(self, gkeys) -> None:
        limit = self.ctx.planner_params.group_by_cardinality_limit
        if limit and len(gkeys) > limit:
            raise GroupCardinalityError(
                f"group-by cardinality limit {limit} exceeded "
                f"({len(gkeys)} groups)")

    def _check_cancel(self, where: str) -> None:
        """Cooperative cancellation between the exec-node boundary
        checks: before device dispatches and around the paging loops, so
        a killed cold-tier scan stops mid-leaf instead of finishing a
        result nobody will read."""
        tok = getattr(self.ctx, "cancel", None)
        if tok is not None and tok.cancelled:
            tok.raise_if_cancelled(f"before {where} (shard {self.shard})")

    def _do_execute(self, source) -> QueryResultLike:
        stats = QueryStats(shards_queried=1)
        shard = source.get_shard(self.dataset, self.shard)
        if shard is None:
            return None, stats
        lookup = shard.lookup_partitions(self.filters, self.chunk_start_ms,
                                         self.chunk_end_ms)
        schema_name = self.schema or lookup.first_schema
        if schema_name is None:
            return None, stats
        pids = lookup.pids_by_schema.get(schema_name)
        if pids is None or pids.size == 0:
            return None, stats
        store = shard.stores[schema_name]
        rows = shard.rows_for(pids)

        # Cap data scanned BEFORE materializing (or paging) the [S, T]
        # matrix — a pathological selector must fail fast, not OOM first
        # (ref: OnDemandPagingShard.scala:55 capDataScannedPerShardCheck,
        # ExecPlan.scala:139-180 enforcedLimits).  The estimate clips each
        # series to the query's chunk range assuming uniform spacing (the
        # reference estimates from chunk metadata the same way); checked
        # against the resident data before ODP and again after paging.
        limit = self.ctx.planner_params.scan_limit
        enforced = limit and self.ctx.planner_params.enforced_limits

        def _check_scan_cap(when: str):
            if not enforced:
                return
            to_scan = _estimate_scan(store, rows, self.chunk_start_ms,
                                     self.chunk_end_ms)
            if to_scan > limit:
                raise ValueError(
                    f"shard {self.shard}: query would scan ~{to_scan} "
                    f"samples ({when}), over the scan limit {limit} — "
                    f"narrow the filters or time range")

        _check_scan_cap("resident")
        from filodb_tpu.core.shard import PagedLimitExceeded
        try:
            # the cancel callable rides into the per-partition paging
            # loop: a killed query stops paging history mid-scan (the
            # work already paged is kept — valid cache for a retry)
            tok = getattr(self.ctx, "cancel", None)
            paged = shard.ensure_paged_pids(
                schema_name, pids, self.chunk_start_ms, self.chunk_end_ms,
                max_samples=limit if enforced else None,
                cancel=(None if tok is None else
                        lambda: self._check_cancel("demand paging")))
        except PagedLimitExceeded as e:
            # structured query error, not a 500: the partial paging work
            # is kept (valid cache for a narrower retry) and the error
            # says how much was paged before the limit hit
            raise QueryError("paged_limit_exceeded", str(e)) from None
        stats.cold_tier = "hot"
        if paged:
            stats.samples_paged += int(paged)
            stats.cold_tier = "cold_paged"
            # ODP grew some series' extents, so the resident estimate is
            # stale; when nothing paged the second O(S) estimate would
            # be identical to the first — skip it (dashboard panels pay
            # this twice per panel otherwise)
            _check_scan_cap("after demand paging")
        schema = shard.schemas[schema_name]
        col_name = (self.columns[0] if self.columns
                    else schema.value_column)
        # schema-specific column + range-function substitution for the
        # downsample gauge schema: min_over_time reads the `min` column,
        # count_over_time becomes sum_over_time over `count`, etc.  Applied
        # as per-execution overrides so the plan stays reusable
        # (ref: MultiSchemaPartitionsExec.finalizePlan schema substitutions;
        # Schemas DS_GAUGE_FN_SUBSTITUTION)
        if schema.name == "ds-gauge" and not self.columns:
            from filodb_tpu.core.schemas import DS_GAUGE_FN_SUBSTITUTION
            for i, t in enumerate(self.transformers):
                if isinstance(t, PeriodicSamplesMapper):
                    sub = DS_GAUGE_FN_SUBSTITUTION.get(t.function)
                    if sub is not None:
                        col_name = sub[0]
                        if sub[1] != t.function:
                            self._transformer_overrides[i] = \
                                dataclasses.replace(t, function=sub[1])
                    break
        # counter semantics: counter-typed columns are reset-corrected in
        # f64 host-side (ops/counter.host_counter_correct) when the range
        # function has counter semantics, so post-rebase f32 deltas are
        # exact even across resets.  Non-counter functions on counter
        # columns (resets/delta/changes) need the RAW values and therefore
        # bypass the (pre-corrected) device mirror.
        col_def = next((c for c in schema.data_columns
                        if c.name == col_name), None)
        counter_col = col_def is not None and (col_def.detect_drops
                                               or col_def.counter)
        fn_is_counter = False
        for t in self.transformers:
            if isinstance(t, PeriodicSamplesMapper):
                spec = RANGE_FUNCTIONS.get(t.function or "")
                fn_is_counter = spec.is_counter if spec else False
                break
        # device-resident fast path: gather rows from the HBM mirror instead
        # of re-shipping the matrix every query (ref: block-memory working
        # set, BlockManager.scala; see core/devicecache.py)
        mirror = None
        # cost-based router (round-5 item 6): an estimated working set at
        # or below query.host_route_max_samples skips the device mirror —
        # the host gather is cheap at that size, and _try_fused then
        # evaluates in numpy instead of paying the dispatch floor
        route_host = False
        from filodb_tpu.config import settings as _settings
        _route_cap = _settings().query.host_route_max_samples
        if _route_cap > 0:
            # only where the per-dispatch floor exists: on the CPU
            # backend the "device" path is already host-side, and the
            # interpret-mode tests exercise the kernel deliberately
            import jax as _jax
            if _jax.default_backend() == "tpu" or os.environ.get(
                    "FILODB_TPU_FORCE_HOST_ROUTE"):
                est = _estimate_scan(store, rows, self.chunk_start_ms,
                                     self.chunk_end_ms)
                route_host = 0 < est <= _route_cap
        if (not route_host
                and getattr(shard.config.store, "device_mirror_enabled",
                            True)
                and (not counter_col or fn_is_counter)):
            mirror = getattr(store, "device_mirror", None)
            if mirror is None:
                from filodb_tpu.core.devicecache import (
                    DEFAULT_HBM_LIMIT_BYTES, DeviceMirror,
                    mirror_create_lock, placer, sharded_mirrors_enabled,
                    store_nbytes)
                limit = getattr(shard.config.store,
                                "device_mirror_hbm_limit",
                                DEFAULT_HBM_LIMIT_BYTES)
                # sharded mirror mode: pin this shard's mirror to its
                # placed device so the fused kernel dispatches THERE and
                # multi-shard queries fan out across chips (the
                # per-device dispatch contract, doc/multichip.md).
                # Creation is serialized: concurrent first queries each
                # calling placer.assign would double-book the device
                # until GC collects the losing mirror.
                with mirror_create_lock:
                    mirror = getattr(store, "device_mirror", None)
                    if mirror is None:
                        device, est = None, 0
                        if sharded_mirrors_enabled(shard.config.store):
                            est = store_nbytes(store)
                            device = placer.assign(self.shard, est, limit)
                        mirror = store.device_mirror = DeviceMirror(
                            limit, device=device, shard_num=self.shard,
                            reserved_bytes=est)
                        _note_mirror_limit(limit)

        # Mirror refresh (a full host->device upload) runs at most once per
        # query, under the write lock so it can't race a mutation; the
        # subsequent row gather reads only the immutable device copy.  The
        # host fallback copies out under the seqlock so a concurrent
        # ingest/flush can't hand the kernel a torn matrix.
        mirrored = snap = None
        if mirror is not None:
            ok = mirror.is_fresh(store)
            if not ok:
                bg = getattr(shard.config.store,
                             "mirror_background_rebuild", True)
                if mirror.can_update_inline(store) or not bg:
                    with shard._write_locked("mirror_refresh"):
                        # re-check under the lock: an eviction may bump
                        # shift_version between the unlocked check and
                        # lock acquisition, and the full rebuild must
                        # still not run on this query's critical path
                        if not bg or mirror.can_update_inline(store):
                            ok = mirror.ensure_fresh(store)
                if not ok and bg and not mirror.can_update_inline(store):
                    # eviction rearranged rows (shift_version moved): the
                    # full O(S*T) re-upload must not run on THIS query's
                    # critical path — rebuild in the background and serve
                    # this query via the host windowed gather below
                    # (eviction-proof serving; SOAK_LONG_r05's 752 s p99
                    # was one query paying this inline)
                    mirror.request_background_refresh(shard, store)
                    from filodb_tpu.utils.metrics import registry as _reg
                    _reg.counter(
                        "device_mirror_query_fallbacks").increment()
            if ok:
                # one snapshot read serves gather AND fused-eligibility:
                # pairing a newer snapshot's grid with an older one's values
                # would feed the kernel zero-padded phantom columns
                snap = mirror.snapshot()
                from filodb_tpu.utils.devicetelem import telem
                _g0 = _time.perf_counter()
                mirrored = mirror.gather_cached(rows, snap)
                telem.record_dispatch(
                    "mirror_gather", device=mirror.device,
                    shape=f"rows{len(rows)}",
                    seconds=_time.perf_counter() - _g0)
        # value column selection: histograms gather [S, T, B]
        shared_ts_row = None
        dense = True
        if mirrored is not None:
            ts_off, dev_cols, dev_vbases, base = mirrored
            vals = dev_cols[col_name]
            vbase = dev_vbases.get(col_name)
            counts = shard.snapshot_read(store,
                                         lambda: store.counts[rows].copy())
            precorrected = counter_col   # mirror corrects counter columns
            shared_ts_row = mirror.fused_eligible(col_name, snap,
                                                  allow_ragged=True)
            # col_dense is grid-independent (counted cells finite; pads are
            # excluded via PAD_TS), so a non-shared grid with finite values
            # keeps the cheap slot-boundary rate path
            dense = mirror.col_dense(col_name, snap)
            if shared_ts_row is not None:
                # cache identity for the fused path's prepared-input reuse
                # (mirror.serial, not id(): ids are reused after GC; raw
                # rows bytes, not their hash: a collision would silently
                # serve another row-set's values)
                self._fused_cache_key = (mirror.serial, snap.gen, col_name,
                                         rows.tobytes())
        else:
            # windowed gather: copy only the planner's chunk-scan span —
            # a fraction of the store's full time capacity, and far less
            # seqlock-tear exposure under live ingest (the r4 soak's 9x
            # under-ingest degradation was full-row gathers being torn
            # and retried against continuous appends)
            # batch gather memo (PR 17, ops/hostleaf.py): under a
            # query_range_batch prepare scope, N panels over one working
            # set share ONE windowed scan AND its post-processing — the
            # offset grid, the counter-corrected/rebased value matrix,
            # and the density verdict are all pure functions of the key
            # (exact row set, span, column, correction mode, keys
            # epoch), and every downstream consumer treats the arrays
            # as immutable.  Memoizing only the raw gather was measured
            # to leave ~80% of a repeat leaf's cost on the table —
            # host_counter_correct + to_offsets dominate the scan.
            from filodb_tpu.ops import hostleaf as _hostleaf
            precorrected = counter_col and fn_is_counter
            base = self.chunk_start_ms
            _memo_key = (shard.keys_serial, shard.keys_epoch, self.dataset,
                         self.shard, self.chunk_start_ms, self.chunk_end_ms,
                         col_name, precorrected, rows.tobytes())
            _hit = _hostleaf.memo_get(_memo_key)
            if _hit is not None:
                ts_off, vals, vbase, counts, dense = _hit
            else:
                # raw-gather sub-memo: panels that share the span but
                # differ in column/correction mode (e.g. a gauge window
                # next to a counter rate) still share the scan itself
                _raw_key = ("raw",) + _memo_key[:6] + (rows.tobytes(),)
                _raw = _hostleaf.memo_get(_raw_key)
                if _raw is not None:
                    ts, cols, counts = _raw
                    ts_off = _hostleaf.memo_get(("off",) + _raw_key[1:])
                else:
                    ts, cols, counts = shard.snapshot_read(
                        store, lambda: store.gather_rows(
                            rows, self.chunk_start_ms, self.chunk_end_ms))
                    _hostleaf.memo_put(_raw_key, (ts, cols, counts))
                    ts_off = None
                if ts_off is None:
                    ts_off = to_offsets(ts, counts, base)
                    _hostleaf.memo_put(("off",) + _raw_key[1:], ts_off)
                # correct (f64) + rebase so counter deltas stay exact on
                # chip
                vals, vbase = counter_ops.rebase_values(cols[col_name],
                                                        precorrected)
                # NaN anywhere (staleness markers or ragged-length
                # padding) routes the rate family onto its
                # valid-boundary variant
                dense = not bool(np.isnan(vals).any())
                _hostleaf.memo_put(_memo_key,
                                   (ts_off, vals, vbase, counts, dense))
        keys = LazyKeys(shard, pids)
        stats.series_scanned = int(pids.size)
        stats.samples_scanned = int(counts.sum())
        les = store.bucket_les if vals.ndim == 3 else None
        if route_host and shared_ts_row is None and isinstance(
                vals, np.ndarray):
            # the host path computed no shared-grid row; derive it the
            # same way the mirror does so small dense sets stay fusable
            # (identical offset rows across real samples)
            ts_np = np.asarray(ts_off)
            if ts_np.size and counts.size and \
                    (counts == counts[0]).all() and \
                    (ts_np[:, :max(int(counts[0]), 1)]
                     == ts_np[0, :max(int(counts[0]), 1)]).all():
                shared_ts_row = ts_np[0, :int(counts[0])]
        return RawBlock(keys, ts_off, vals, base, les,
                        samples=stats.samples_scanned, vbase=vbase,
                        precorrected=precorrected,
                        shared_ts_row=shared_ts_row, dense=dense,
                        cache_token=(shard.keys_serial, shard.keys_epoch,
                                     pids.tobytes()),
                        route_host=route_host), stats


class SelectPersistedSegmentsExec(MultiSchemaPartitionsExec):
    """Leaf for the persisted-segment (historical) tier: gathers rows from
    cold-region segment blocks instead of the shard's dense store, then
    runs the SAME transformer / fused pipeline as the hot leaf — cold
    scans take the device path, not `ensure_paged`'s host decode.

    `tier` is a persist.segments.PersistedTier bound at plan time by
    PersistedClusterPlanner (this tier is node-local: segment files +
    the cold DeviceMirror region live on the serving node)."""

    def __init__(self, ctx: QueryContext, dataset: str, shard: int,
                 filters: Sequence[ColumnFilter], chunk_start_ms: int,
                 chunk_end_ms: int, tier, columns: Sequence[str] = (),
                 schema: Optional[str] = None):
        super().__init__(ctx, dataset, shard, filters, chunk_start_ms,
                         chunk_end_ms, columns=columns, schema=schema)
        self.tier = tier

    def args_str(self):
        fs = ",".join(str(f) for f in self.filters)
        return (f"dataset={self.dataset}, shard={self.shard}, tier=cold, "
                f"chunkMethod=TimeRangeChunkScan({self.chunk_start_ms},"
                f"{self.chunk_end_ms}), filters=[{fs}]")

    def _do_execute(self, source) -> QueryResultLike:
        # disaggregated cold tier (persist/objectstore.py): a dead or
        # corrupt object store is a typed shard_unavailable — the leaf's
        # parent drops it under the partial-results gate (flagged
        # partial), exactly like a dead peer, never a hang
        try:
            return self._cold_execute(source)
        except Exception as e:  # noqa: BLE001 — re-raise non-store errors
            from filodb_tpu.persist.objectstore import ObjectStoreError
            if not isinstance(e, ObjectStoreError):
                raise
            raise QueryError(
                "shard_unavailable",
                f"shard {self.shard}: cold tier unavailable ({e})")

    def _cold_execute(self, source) -> QueryResultLike:
        stats = QueryStats(shards_queried=1)
        segs = self.tier.covering(self.shard, self.chunk_start_ms,
                                  self.chunk_end_ms, self.schema)
        if not segs:
            return None, stats
        by_schema: Dict[str, list] = {}
        for m in segs:
            by_schema.setdefault(m.schema_name, []).append(m)
        schema_name = self.schema or next(iter(by_schema))
        metas = sorted(by_schema.get(schema_name, ()),
                       key=lambda m: m.start_ms)
        if not metas:
            return None, stats
        schema = self.tier.schemas[schema_name]
        col_name = (self.columns[0] if self.columns
                    else schema.value_column)
        verdict = "cold_hit"
        picked = []                       # (block, rows)
        self._check_cancel("cold-segment page-in")
        if len(metas) > 1:
            # page the slice's segments in concurrently: decode + upload
            # overlap, so the cold wall is ~one segment, not the sum (the
            # per-column decode inside each is pooled too)
            import concurrent.futures

            def _fetch(m):
                # per-segment cancel check: a killed 30-day scan stops
                # between page-ins instead of decoding the whole slice
                self._check_cancel("cold-segment page-in")
                return self.tier.get_block(m)

            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(4, len(metas))) as pool:
                fetched = list(pool.map(_fetch, metas))
        else:
            fetched = [self.tier.get_block(metas[0])]
        self._check_cancel("cold-segment gather")
        for m, (block, v) in zip(metas, fetched):
            rows = block.match_rows(self.filters, self.chunk_start_ms,
                                    self.chunk_end_ms)
            if v == "cold_paged":
                verdict = "cold_paged"
                stats.samples_paged += int(block.counts.sum())
                stats.bytes_paged += int(block.nbytes)
            if rows.size:
                picked.append((block, rows))
        stats.cold_tier = verdict
        if not picked:
            return None, stats
        # scan cap over the FILTER-MATCHED rows (hot-leaf parity: the
        # estimate must reflect what this query scans, not the shard's
        # total segment volume), checked before the gather/merge
        # materializes anything; page-in granularity is the segment and
        # stays bounded by the cold region's byte budget either way
        limit = self.ctx.planner_params.scan_limit
        if limit and self.ctx.planner_params.enforced_limits:
            est = sum(int(b.counts[r].sum()) for b, r in picked)
            if est > limit:
                raise ValueError(
                    f"shard {self.shard}: persisted-tier query would scan "
                    f"~{est} samples, over the scan limit {limit} — "
                    f"narrow the filters or time range")
        base_ms = picked[0][0].meta.start_ms
        span = max(b.meta.end_ms for b, _ in picked) - base_ms
        if span >= (1 << 30):
            raise ValueError(
                "persisted-tier slice spans >2^30 ms — the planner must "
                "split long ranges (PersistedClusterPlanner.plan_split_ms)")
        raw = self._gather_cold(picked, schema, col_name, base_ms, stats)
        return raw, stats

    def _gather_cold(self, picked, schema, col_name: str, base_ms: int,
                     stats: QueryStats):
        from filodb_tpu.query.execbase import RawBlock
        counter_col = col_name in picked[0][0].counter_cols
        fn_is_counter = False
        for t in self.transformers:
            if isinstance(t, PeriodicSamplesMapper):
                spec = RANGE_FUNCTIONS.get(t.function or "")
                fn_is_counter = spec.is_counter if spec else False
                break
        if counter_col and not fn_is_counter:
            # resets/delta/changes need RAW counter values: re-decode the
            # segments host-side (uncached — this is the rare path), like
            # the hot leaf bypassing the pre-corrected mirror
            return self._gather_cold_raw(picked, col_name, base_ms, stats)
        host = any(b.is_host for b, _ in picked)
        seg_inputs = []
        for block, rows in picked:
            ts_off = block.ts_off
            vals = block.cols[col_name]
            if host:
                ts_off = np.asarray(ts_off)
                vals = np.asarray(vals)
            if host or isinstance(vals, np.ndarray):
                ts_g = np.asarray(ts_off)[rows]
                v_g = np.asarray(vals)[rows]
            else:
                idx = jnp.asarray(rows.astype(np.int32))
                ts_g = jnp.take(ts_off, idx, axis=0)
                v_g = jnp.take(vals, idx, axis=0)
            seg_inputs.append({
                "block": block, "rows": rows, "ts_off": ts_g, "vals": v_g,
                "counts": block.counts[rows],
                "t0": block.meta.start_ms,
                "vbase": block.vbase[col_name][rows],
            })
        samples = int(sum(int(si["counts"].sum()) for si in seg_inputs))
        stats.series_scanned = 0
        stats.samples_scanned = samples
        if len(seg_inputs) == 1:
            si = seg_inputs[0]
            block, rows = picked[0]
            keys = block.keys_for(rows)
            stats.series_scanned = int(rows.size)
            dense = block.dense.get(col_name, False)
            shared = block.ts_row0 if block.uniform else None
            self._fused_cache_key = (("cold", block.serial), 0, col_name,
                                     rows.tobytes())
            return RawBlock(keys, si["ts_off"], si["vals"], si["t0"],
                            None, samples=samples, vbase=si["vbase"],
                            precorrected=counter_col,
                            shared_ts_row=shared, dense=dense,
                            cache_token=("cold", block.serial,
                                         rows.tobytes()))
        return self._merge_cold(seg_inputs, picked, col_name, counter_col,
                                base_ms, stats, samples, host)

    def _merge_cold(self, seg_inputs, picked, col_name: str,
                    counter_col: bool, base_ms: int, stats, samples: int,
                    host: bool):
        """Stitch K time-ordered segment gathers into one packed [Su, Tt]
        RawBlock: union the row sets, chain counter corrections across
        segment boundaries, and pack each union row's samples contiguously
        (the general windowing path needs per-row-sorted offsets with pads
        only at the end)."""
        from filodb_tpu.query.execbase import RawBlock
        serials = tuple(b.serial for b, _ in picked)
        rows_token = b"".join(r.tobytes() for _, r in picked)
        mkey = (serials, col_name, rows_token, base_ms)
        cached = self.tier.merged_get(mkey)
        if cached is not None:
            # repeat query over the same cold row set: reuse the packed
            # merge (the cold analogue of the fused prepared-input cache)
            union_keys, ts_out, v_out, out_vbase, shared, dense, Su = cached
            stats.series_scanned = Su
            self._fused_cache_key = (("cold",) + serials, 0, col_name,
                                     rows_token)
            return RawBlock(union_keys, ts_out, v_out, base_ms, None,
                            samples=samples, vbase=out_vbase,
                            precorrected=counter_col, shared_ts_row=shared,
                            dense=dense,
                            cache_token=("cold", serials, rows_token))
        union: Dict[bytes, int] = {}
        union_keys = []
        urows_per = []
        for block, rows in picked:
            pk_bytes = block.identity.pk_bytes
            rl = rows.tolist()
            urows = np.empty(len(rl), dtype=np.int64)
            new_local = []
            for i, r in enumerate(rl):
                u = union.get(pk_bytes[r])
                if u is None:
                    u = union[pk_bytes[r]] = len(union)
                    new_local.append(i)
                urows[i] = u
            if new_local:
                union_keys.extend(
                    block.keys_for(rows[np.asarray(new_local)]))
            urows_per.append(urows)
        Su = len(union)
        stats.series_scanned = Su
        # per-union-row packed layout + cross-segment counter carry
        out_vbase = np.full(Su, np.nan)
        carry = np.zeros(Su)
        prev_last = np.full(Su, np.nan)
        flat_parts_ts, flat_parts_v = [], []
        flat_base = 0
        src_of: list = []                # (flat_base, Tk, urows, counts, adj)
        for si, ur in zip(seg_inputs, urows_per):
            block = si["block"]
            cnt = np.asarray(si["counts"], dtype=np.int64)
            vb = np.asarray(si["vbase"], np.float64)
            first_seen = np.isnan(out_vbase[ur])
            out_vbase[ur] = np.where(first_seen, vb, out_vbase[ur])
            if counter_col:
                fr = block.first_raw[col_name][si["rows"]]
                boundary = (~np.isnan(prev_last[ur])) & \
                    np.less(fr, prev_last[ur],
                            where=~np.isnan(fr) & ~np.isnan(prev_last[ur]),
                            out=np.zeros(len(ur), dtype=bool))
                carry[ur] += np.where(boundary, prev_last[ur], 0.0)
            adj = vb + carry[ur] - out_vbase[ur]          # f64 [Rk]
            if counter_col:
                carry[ur] += block.cum_drop[col_name][si["rows"]]
                lr = block.last_raw[col_name][si["rows"]]
                prev_last[ur] = np.where(np.isnan(lr), prev_last[ur], lr)
            Tk = int(np.asarray(si["ts_off"]).shape[1]) if host else \
                int(si["ts_off"].shape[1])
            delta = int(si["t0"] - base_ms)
            if host:
                ts_adj = np.asarray(si["ts_off"])
                ts_adj = np.where(ts_adj == PAD_TS, PAD_TS,
                                  ts_adj + np.int32(delta))
                src = np.asarray(si["vals"])
                v_adj = (src.astype(np.float64)
                         + adj[:, None]).astype(src.dtype)
            else:
                ts_adj = jnp.where(si["ts_off"] == PAD_TS, PAD_TS,
                                   si["ts_off"] + np.int32(delta))
                v_adj = si["vals"] + jnp.asarray(adj[:, None],
                                                 si["vals"].dtype)
            flat_parts_ts.append(ts_adj.reshape(-1))
            flat_parts_v.append(v_adj.reshape(-1))
            src_of.append((flat_base, Tk, ur, cnt))
            flat_base += len(ur) * Tk
        ct = np.zeros(Su, dtype=np.int64)
        for _, _, ur, cnt in src_of:
            ct[ur] += cnt
        Tmax = int(ct.max()) if Su else 0
        pad_pos = flat_base                    # one sentinel slot appended
        out_idx = np.full((Su, Tmax), pad_pos, dtype=np.int64)
        write_pos = np.zeros(Su, dtype=np.int64)
        for base_k, Tk, ur, cnt in src_of:
            jj = np.arange(Tk)
            valid = jj[None, :] < cnt[:, None]
            src = base_k + np.arange(len(ur))[:, None] * Tk + jj[None, :]
            rows_rep = np.repeat(ur, cnt)
            cols_rep = (write_pos[ur][:, None] + jj[None, :])[valid]
            out_idx[rows_rep, cols_rep] = src[valid]
            write_pos[ur] += cnt
        if host:
            flat_ts = np.concatenate(
                flat_parts_ts + [np.asarray([PAD_TS], np.int32)])
            flat_v = np.concatenate(
                flat_parts_v + [np.asarray([np.nan],
                                           flat_parts_v[0].dtype)])
            ts_out = flat_ts[out_idx]
            v_out = flat_v[out_idx]
        else:
            flat_ts = jnp.concatenate(
                flat_parts_ts + [jnp.asarray([PAD_TS], np.int32)])
            flat_v = jnp.concatenate(
                flat_parts_v
                + [jnp.asarray([np.nan], flat_parts_v[0].dtype)])
            idx_dev = jnp.asarray(out_idx)
            ts_out = jnp.take(flat_ts, idx_dev)
            v_out = jnp.take(flat_v, idx_dev)
        dense = all(b.dense.get(col_name, False) for b, _ in picked)
        # shared grid survives the merge only when every union row took
        # every segment's full uniform grid
        shared = None
        if all(b.uniform for b, _ in picked) \
                and all(len(ur) == Su for _, _, ur, _ in src_of) \
                and Su > 0 and (ct == ct[0]).all():
            parts = []
            for b, _ in picked:
                row0 = b.ts_row0[:int(b.counts[0])].astype(np.int64) \
                    + (b.meta.start_ms - base_ms)
                parts.append(row0.astype(np.int32))
            cat = np.concatenate(parts)
            if cat.size == Tmax:
                shared = cat
        self._fused_cache_key = (("cold",) + serials, 0, col_name,
                                 rows_token)
        self.tier.merged_put(mkey, (union_keys, ts_out, v_out, out_vbase,
                                    shared, dense, Su))
        return RawBlock(union_keys, ts_out, v_out, base_ms, None,
                        samples=samples, vbase=out_vbase,
                        precorrected=counter_col, shared_ts_row=shared,
                        dense=dense,
                        cache_token=("cold", serials, rows_token))

    def _gather_cold_raw(self, picked, col_name: str, base_ms: int,
                         stats):
        """Raw-value host path (non-counter function on a counter column):
        re-decode the segments and merge uncorrected values."""
        from filodb_tpu.query.execbase import RawBlock
        series: Dict[bytes, list] = {}
        keys: Dict[bytes, object] = {}
        for block, rows in picked:
            hdr, ts, cols = self.tier.store.load(block.meta)
            vals = cols.get(col_name)
            if vals is None:
                continue
            for r in rows.tolist():
                kb = block.part_keys[r].to_bytes()
                n = int(hdr["counts"][r])
                series.setdefault(kb, []).append((ts[r, :n], vals[r, :n]))
                keys.setdefault(kb, block.keys_for(np.asarray([r]))[0])
        if not series:
            return None
        Su = len(series)
        merged = []
        for kb, parts in series.items():
            parts.sort(key=lambda p: p[0][0] if len(p[0]) else 0)
            merged.append((np.concatenate([p[0] for p in parts]),
                           np.concatenate([p[1] for p in parts])))
        Tmax = max(len(t) for t, _ in merged)
        counts = np.asarray([len(t) for t, _ in merged], dtype=np.int64)
        ts_grid = np.zeros((Su, Tmax), dtype=np.int64)
        v_grid = np.full((Su, Tmax), np.nan)
        for i, (t, v) in enumerate(merged):
            ts_grid[i, :len(t)] = t
            v_grid[i, :len(v)] = v
        stats.series_scanned = Su
        stats.samples_scanned = int(counts.sum())
        ts_off = to_offsets(ts_grid, counts, base_ms)
        vals, vbase = counter_ops.rebase_values(v_grid, False)
        dense = not bool(np.isnan(
            vals[np.arange(Tmax)[None, :] < counts[:, None]]).any())
        return RawBlock(list(keys.values()), ts_off, vals, base_ms, None,
                        samples=stats.samples_scanned, vbase=vbase,
                        precorrected=False, shared_ts_row=None,
                        dense=dense)


def _estimate_scan(store, rows: np.ndarray, start_ms: int,
                   end_ms: int) -> int:
    """Estimated samples in [start_ms, end_ms] across the given store rows,
    from per-series extents under a uniform-spacing assumption — O(S), no
    [S, T] materialization."""
    cnt = store.counts[rows].astype(np.int64)
    if store.ts.shape[1] == 0 or not cnt.any():
        return 0
    first = store.ts[rows, 0]
    last = store.ts[rows, np.maximum(cnt - 1, 0)]
    lo = np.maximum(first, start_ms)
    hi = np.minimum(last, end_ms)
    span = np.maximum(last - first, 1).astype(np.float64)
    frac = np.clip((hi - lo).astype(np.float64) / span, 0.0, 1.0)
    est = np.where((cnt > 0) & (hi >= lo), np.maximum(cnt * frac, 1.0), 0.0)
    return int(est.sum())



# ------------------------------------------------------------- scalar execs


class TimeScalarGeneratorExec(LeafExecPlan):
    """time(), hour(), ... (ref: exec/TimeScalarGeneratorExec:84)."""

    def __init__(self, ctx, start_ms, step_ms, end_ms, function="time"):
        super().__init__(ctx)
        self.start_ms, self.step_ms, self.end_ms = start_ms, step_ms, end_ms
        self.function = function

    def args_str(self):
        return f"function={self.function}"

    def _do_execute(self, source) -> QueryResultLike:
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        secs = wends / 1000.0
        if self.function == "time":
            vals = secs
        else:
            # hour()/minute()/day_of_week()... on step timestamps: the date
            # INSTANT_FUNCTIONS already interpret values as epoch seconds
            vals = np.asarray(INSTANT_FUNCTIONS[self.function](jnp.asarray(secs)))
        return ScalarResult(wends, np.asarray(vals, dtype=float)), QueryStats()


class ScalarFixedDoubleExec(LeafExecPlan):
    """Literal scalar (ref: exec/ScalarFixedDoubleExec:76)."""

    def __init__(self, ctx, start_ms, step_ms, end_ms, value: float):
        super().__init__(ctx)
        self.start_ms, self.step_ms, self.end_ms = start_ms, step_ms, end_ms
        self.value = value

    def args_str(self):
        return f"value={self.value}"

    def _do_execute(self, source) -> QueryResultLike:
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        return ScalarResult(wends, np.full(len(wends), self.value)), QueryStats()


class ScalarBinaryOperationExec(LeafExecPlan):
    """scalar op scalar (ref: exec/ScalarBinaryOperationExec:72)."""

    def __init__(self, ctx, start_ms, step_ms, end_ms, operator, lhs, rhs):
        super().__init__(ctx)
        self.start_ms, self.step_ms, self.end_ms = start_ms, step_ms, end_ms
        self.operator = operator
        self.lhs = lhs          # float or ScalarBinaryOperationExec
        self.rhs = rhs

    def args_str(self):
        return f"operator={self.operator}"

    def _eval(self, x, source):
        if isinstance(x, ScalarBinaryOperationExec):
            return x._do_execute(source)[0].values
        return float(x)

    def _do_execute(self, source) -> QueryResultLike:
        wends = make_window_ends(self.start_ms, self.end_ms, self.step_ms)
        a = np.broadcast_to(self._eval(self.lhs, source), wends.shape).astype(float)
        b = np.broadcast_to(self._eval(self.rhs, source), wends.shape).astype(float)
        # scalar-scalar comparisons always behave as `bool` (PromQL requires it)
        out = np.asarray(apply_binary_op(
            jnp.asarray(a), jnp.asarray(b), op=self.operator,
            bool_modifier=True))
        return ScalarResult(wends, out), QueryStats()


