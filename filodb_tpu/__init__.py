"""filodb_tpu — a TPU-native, Prometheus-compatible distributed time-series database.

A ground-up rebuild of the capabilities of FiloDB (the Scala/Akka reference at
/root/reference) designed for TPU hardware: PromQL range functions run as vmap'd
JAX/XLA kernels over dense columnar chunk arrays, cross-series/cross-shard
aggregation uses mesh collectives (psum) instead of actor scatter-gather, and a
host-side Python/C++ runtime provides ingestion, the tag index, sharding,
persistence and recovery.

Layer map (mirrors SURVEY.md section 1):
  memory/    columnar chunk format + codecs (ref: memory/ module)
  core/      memstore, schemas, records, tag index (ref: core/ module)
  ops/       TPU kernels for range/instant/aggregate functions (ref: query/exec/rangefn)
  query/     LogicalPlan, ExecPlan, planners (ref: query/ + coordinator/queryplanner)
  promql/    PromQL parser -> AST -> LogicalPlan (ref: prometheus/ module)
  parallel/  shard mapping, device mesh execution, cluster controller (ref: coordinator/)
  http/      Prometheus-compatible HTTP API (ref: http/ module)
  ingest/    ingestion streams, gateway protocols (ref: kafka/ + gateway/)
  persist/   column store, checkpoints, recovery (ref: cassandra/ + MetaStore)
  downsample/ downsamplers + batch job (ref: core/downsample + spark-jobs/)
"""

__version__ = "0.1.0"
