"""Multi-host mesh building blocks — the NCCL/MPI-backend analogue.

The reference scales its comm backend across hosts with NCCL/MPI process
groups; the JAX equivalent is `jax.distributed` + one global
`('shard', 'time')` mesh whose collectives ride ICI within a slice and DCN
across slices (ref: SURVEY §2.9; the scaling-book recipe: pick a mesh,
annotate shardings, let XLA insert collectives).

SCOPE — read this before wiring a pod:

This module provides the verified building blocks (runtime join, global
mesh construction, per-host global-array assembly).  They degrade exactly
to the single-host path under one process, which is what CI exercises.
Driving `MeshExecutor` across processes additionally requires invariants
the CALLER must establish (single-host runs get them for free):

1. **Globally consistent group slots.**  `pack_shards` assigns
   aggregation-group slots from a local registry; every process must pack
   with the SAME key->slot mapping and the same num_groups, or the psum
   mixes unrelated groups.  Distribute the mapping via the cluster control
   plane (parallel/cluster.py) or derive it from a shared catalog before
   packing.
2. **Globally agreed static arguments.**  `precorrected` and the presence
   of `vbase` are static to the SPMD program; all processes must agree or
   they compile mismatched programs.  Agree on them from the dataset
   schema (which is global), not from locally-present columns.
3. **Process-aligned shard axis.**  Each process owns a contiguous block
   of the 'shard' axis covering exactly its addressable devices —
   global_mesh() enforces this alignment or raises.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from filodb_tpu.parallel.mesh import device_put_packed


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               auto: bool = False) -> None:
    """Join the multi-host runtime.

    auto=True calls jax.distributed.initialize() with no arguments, letting
    JAX auto-detect the pod topology from the platform's metadata (the
    normal mode on TPU pods).  Otherwise arguments default from
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID; with one
    process (or none of the variables set) this is a no-op so single-host
    tools run unchanged."""
    if auto:
        jax.distributed.initialize()
        return
    num = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "1"))
    if num <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address
        or os.environ.get("JAX_COORDINATOR_ADDRESS"),
        num_processes=num,
        process_id=process_id if process_id is not None else int(
            os.environ.get("JAX_PROCESS_ID", "0")))


def global_mesh(n_shard: Optional[int] = None, n_time: int = 1) -> Mesh:
    """('shard', 'time') mesh over ALL devices of every process (call after
    initialize()).  Devices are ordered process-major so each process's
    devices form contiguous 'shard' rows — the alignment assemble_global's
    per-process blocks rely on.  Raises if the shape would truncate a
    process's devices (harmless truncation is allowed only single-process)
    or split a shard row across processes."""
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    if n_shard is None:
        n_shard = len(devs) // n_time
    need = n_shard * n_time
    if len(devs) < need:
        raise ValueError(f"need {need} devices globally, have {len(devs)}")
    if jax.process_count() > 1:
        if need != len(devs):
            raise ValueError(
                f"mesh shape {n_shard}x{n_time} uses {need} of {len(devs)} "
                f"devices; multi-process meshes must cover every process")
        per_proc = len(devs) // jax.process_count()
        if per_proc % n_time != 0:
            raise ValueError(
                f"time axis {n_time} does not divide the {per_proc} devices "
                f"per process; a shard row would span two hosts")
    grid = np.array(devs[:need]).reshape(n_shard, n_time)
    return Mesh(grid, ("shard", "time"))


def assemble_global(mesh: Mesh, local: np.ndarray,
                    spec: Sequence[Optional[str]]) -> jax.Array:
    """Build one global array from this process's block of the data.

    `local` holds the slice this host owns along the sharded axes of
    `spec` (e.g. its shards' [D_local, S, T] block for spec
    ('shard', None, None)).  Under one process this is an ordinary
    device_put; under many, jax.make_array_from_process_local_data glues
    the per-host blocks into one global array without any host ever
    holding the whole tensor."""
    sharding = NamedSharding(mesh, P(*spec))
    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(sharding, local)


def device_put_packed_multihost(packed, mesh: Mesh):
    """Multi-host placement for a PackedShards whose arrays hold THIS
    process's shard block (D_local leading dim).  The caller owns the
    cross-process invariants listed in the module docstring (consistent
    group slots, agreed vbase/precorrected).  Single-process calls delegate
    to the local path so there is exactly one authoritative field list."""
    if jax.process_count() == 1:
        return device_put_packed(packed, mesh)
    import dataclasses
    data_spec = ("shard", None, None)
    row_spec = ("shard", None)
    return dataclasses.replace(
        packed,
        ts_off=assemble_global(mesh, packed.ts_off, data_spec),
        values=assemble_global(mesh, packed.values, data_spec),
        group_ids=assemble_global(mesh, packed.group_ids, row_spec),
        vbase=(assemble_global(mesh, packed.vbase, row_spec)
               if packed.vbase is not None else None))
