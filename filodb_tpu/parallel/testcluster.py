"""Shared two-node cluster builder for tests AND benchmarks.

Lives in the main package on purpose, like the reference keeping
TestTimeseriesProducer in src/main so jmh/stress reuse it (ref:
gateway/src/main/scala/filodb/timeseries/TestTimeseriesProducer.scala;
SURVEY §4 'shared fixtures').  One wiring of the cross-node transport
means the transport tests and the dispatch benchmark cannot drift.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterable, Optional

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.gateway.router import split_batch_by_shard
from filodb_tpu.parallel.shardmapper import (ShardEvent, ShardMapper,
                                             SpreadProvider)
from filodb_tpu.parallel.transport import (NodeQueryServer,
                                           RemoteNodeDispatcher)
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.planner import SingleClusterPlanner


@dataclasses.dataclass
class TwoNodeCluster:
    """Coordinator engine dispatching over TCP to two data nodes."""
    engine: QueryEngine
    mapper: ShardMapper
    stores: Dict[str, TimeSeriesMemStore]
    owner: Dict[int, str]
    servers: Dict[str, NodeQueryServer]
    truth: Optional[TimeSeriesMemStore]   # single store with ALL data

    def stop(self) -> None:
        for srv in self.servers.values():
            srv.stop()


@dataclasses.dataclass
class ReplicatedCluster:
    """N in-process nodes, every shard owned RF times: query servers on
    the real cross-node transport, replication doors on the real framed
    protocol, ingest fanned out by a ReplicationManager (distributor
    mode), queries planned through ReplicaFailoverDispatchers.  The
    shared fixture of the replication tests AND `bench.py replication`."""
    dataset: str
    engine: QueryEngine
    mapper: ShardMapper
    manager: "object"                     # ReplicationManager
    stores: Dict[str, TimeSeriesMemStore]
    query_servers: Dict[str, "NodeQueryServer"]
    repl_servers: Dict[str, "object"]     # ReplicationServer per node
    repl_clients: Dict[str, "object"]     # ReplicaClient per node
    truth: Optional[TimeSeriesMemStore]
    sm: "object"                          # ShardManager

    def ingest_grid(self, shard: int, schema: str, keys, ts, columns,
                    require_primary: bool = True):
        """One slab through the replicated ingest path (all owners) +
        the truth store when present."""
        res = self.manager.replicate(shard, schema, keys, ts, columns,
                                     require_primary=require_primary)
        if self.truth is not None:
            self.truth.get_shard(self.dataset, shard).ingest_columns(
                schema, keys, ts, columns)
        return res

    def kill(self, node: str) -> None:
        """In-process node death with the SIGKILL signature: live
        transport connections sever, new connects refuse."""
        self.query_servers[node].stop()
        self.repl_servers[node].stop()

    def stop(self) -> None:
        self.manager.stop()
        for srv in self.query_servers.values():
            try:
                srv.stop()
            except OSError:
                pass
        for srv in self.repl_servers.values():
            try:
                srv.stop()
            except OSError:
                pass


def make_replicated_cluster(nodes=("A", "B", "C"), num_shards: int = 4,
                            dataset: str = "prometheus",
                            replication_factor: int = 2,
                            ack_mode: str = "quorum",
                            with_truth: bool = False,
                            wal_root: Optional[str] = None
                            ) -> ReplicatedCluster:
    from filodb_tpu.config import ReplicationConfig
    from filodb_tpu.parallel.shardmanager import (DatasetResourceSpec,
                                                  ShardManager)
    from filodb_tpu.replication import (ReplicaClient, ReplicationManager,
                                        ReplicationServer,
                                        failover_dispatcher_factory)
    sm = ShardManager(replication_factor=replication_factor)
    for n in nodes:
        sm.add_member(n)
    mapper = sm.setup_dataset(
        dataset, DatasetResourceSpec(num_shards, len(nodes)))
    stores = {n: TimeSeriesMemStore() for n in nodes}
    wals: Dict[str, Dict] = {n: {} for n in nodes}
    if wal_root is not None:
        import os

        from filodb_tpu.wal import WalManager
        for n in nodes:
            wals[n] = {dataset: WalManager(
                os.path.join(wal_root, n), dataset)}
    for s in range(num_shards):
        for n in mapper.owners(s):
            stores[n].setup(dataset, s)
    # every owner copy is live from the start (in-process fixture — the
    # cluster path flips these through heartbeats)
    for s in range(num_shards):
        primary = mapper.node_for_shard(s)
        mapper.update_from_event(
            ShardEvent("IngestionStarted", dataset, s, primary))
        for n in list(mapper.replicas[s]):
            mapper.update_from_event(
                ShardEvent("ReplicaActive", dataset, s, n))
    query_servers = {n: NodeQueryServer(st).start()
                     for n, st in stores.items()}
    repl_servers = {n: ReplicationServer(stores[n], node=n,
                                         wals=wals[n]).start()
                    for n in nodes}
    repl_clients = {n: ReplicaClient(*srv.address)
                    for n, srv in repl_servers.items()}
    cfg = ReplicationConfig(enabled=True, factor=replication_factor,
                            ack_mode=ack_mode)
    manager = ReplicationManager(dataset, mapper,
                                 lambda n: repl_clients[n], config=cfg)
    dispatchers: Dict[str, RemoteNodeDispatcher] = {}

    def dispatcher_for(node: str) -> RemoteNodeDispatcher:
        d = dispatchers.get(node)
        if d is None:
            dispatchers[node] = d = RemoteNodeDispatcher(
                *query_servers[node].address)
        return d

    planner = SingleClusterPlanner(
        dataset, mapper, SpreadProvider(default_spread=1),
        dispatcher_factory=failover_dispatcher_factory(mapper,
                                                       dispatcher_for))
    engine = QueryEngine(dataset, TimeSeriesMemStore(), mapper,
                         planner=planner)
    truth = None
    if with_truth:
        truth = TimeSeriesMemStore()
        for s in range(num_shards):
            truth.setup(dataset, s)
    return ReplicatedCluster(dataset, engine, mapper, manager, stores,
                             query_servers, repl_servers, repl_clients,
                             truth, sm)


def make_fanout_cluster(batches: Iterable = (), num_shards: int = 4,
                        dataset: str = "prometheus",
                        default_spread: int = 1,
                        with_truth: bool = False,
                        nodes: Iterable = ("nodeA", "nodeB")
                        ) -> TwoNodeCluster:
    """N node processes (in-process servers), shards round-split across
    them, coordinator holding NO data with remote dispatchers — the
    multi-JVM IngestionAndRecoverySpec shape generalized for the
    distributed-execution fan-out bench (`bench.py distexec` drives a
    4-node shape through exactly this wiring)."""
    nodes = list(nodes)
    mapper = ShardMapper(num_shards)
    spread = SpreadProvider(default_spread=default_spread)
    stores = {n: TimeSeriesMemStore() for n in nodes}
    per = max(1, -(-num_shards // len(nodes)))      # ceil split, in order
    owner = {s: nodes[min(s // per, len(nodes) - 1)]
             for s in range(num_shards)}
    for s, node in owner.items():
        stores[node].setup(dataset, s)
        mapper.update_from_event(
            ShardEvent("IngestionStarted", dataset, s, node))
    truth = TimeSeriesMemStore() if with_truth else None
    truth_shards = ({s: truth.setup(dataset, s) for s in range(num_shards)}
                    if truth is not None else {})
    for batch in batches:
        for s, sub in split_batch_by_shard(batch, mapper, spread).items():
            stores[owner[s]].get_shard(dataset, s).ingest(sub)
            if truth is not None:
                truth_shards[s].ingest(sub)
    servers = {n: NodeQueryServer(st).start() for n, st in stores.items()}
    dispatchers = {n: RemoteNodeDispatcher(*srv.address)
                   for n, srv in servers.items()}
    planner = SingleClusterPlanner(
        dataset, mapper, spread,
        dispatcher_factory=lambda s: dispatchers[owner[s]])
    engine = QueryEngine(dataset, TimeSeriesMemStore(), mapper,
                         planner=planner)
    return TwoNodeCluster(engine, mapper, stores, owner, servers, truth)


@dataclasses.dataclass
class ColdReadCluster:
    """Coordinator + N query-capable nodes over ONE shared object store
    (persist/objectstore.py): the data node nominally owns every shard,
    query-only nodes own NOTHING — all of them serve cold leaves from
    the shared tier, walked round-robin by the cold dispatcher.  The
    shared fixture of the query-only-node tests AND the `bench.py
    objectstore` elastic-read gate."""
    dataset: str
    engine: QueryEngine
    mapper: ShardMapper
    object_store: "object"
    tier: "object"                        # object-store-backed query tier
    remote_store: "object"                # RemoteSegmentStore behind it
    servers: Dict[str, NodeQueryServer]
    query_nodes: tuple

    def stop(self) -> None:
        for srv in self.servers.values():
            try:
                srv.stop()
            except OSError:
                pass


def make_cold_read_cluster(object_store, num_shards: int = 4,
                           dataset: str = "prometheus",
                           data_nodes: Iterable = ("data0",),
                           query_nodes: Iterable = (),
                           schemas=None) -> ColdReadCluster:
    """Cold-read cluster over a pre-populated shared object store: call
    after segments + manifests are uploaded.  Every node (data-owning or
    query-only) is an in-process NodeQueryServer with an EMPTY memstore;
    decoded cold leaves rebind to the object-store query tier through
    the per-process tier registry, so this models N stateless readers
    paging one shared tier.  Query-only nodes register on the mapper
    (`register_query_node`) and the persisted planner routes through
    `cold_dispatcher_factory` — round-robin across all of them."""
    from filodb_tpu.persist.objectstore import make_query_tier
    from filodb_tpu.query.planners import PersistedClusterPlanner
    from filodb_tpu.replication.failover import cold_dispatcher_factory
    data_nodes = list(data_nodes)
    query_nodes = tuple(query_nodes)
    mapper = ShardMapper(num_shards)
    spread = SpreadProvider(default_spread=1)
    for s in range(num_shards):
        mapper.update_from_event(ShardEvent(
            "IngestionStarted", dataset, s,
            data_nodes[s % len(data_nodes)]))
    for q in query_nodes:
        mapper.register_query_node(q)
    stores = {n: TimeSeriesMemStore()
              for n in list(data_nodes) + list(query_nodes)}
    servers = {n: NodeQueryServer(st).start() for n, st in stores.items()}
    dispatchers: Dict[str, RemoteNodeDispatcher] = {}

    def dispatcher_for(node: str) -> RemoteNodeDispatcher:
        d = dispatchers.get(node)
        if d is None:
            dispatchers[node] = d = RemoteNodeDispatcher(
                *servers[node].address)
        return d

    # built LAST on purpose: the per-process tier registry resolves
    # decoded cold leaves to the most recent tier for the dataset, and
    # this in-process fixture wants that to be the object-store one
    tier, remote = make_query_tier(object_store, dataset, num_shards,
                                   schemas=schemas)
    planner = PersistedClusterPlanner(
        dataset, mapper, tier, spread_provider=spread,
        dispatcher_factory=cold_dispatcher_factory(mapper, dispatcher_for))
    engine = QueryEngine(dataset, TimeSeriesMemStore(), mapper,
                         planner=planner)
    return ColdReadCluster(dataset, engine, mapper, object_store, tier,
                           remote, servers, query_nodes)


@dataclasses.dataclass
class FederatedPair:
    """Two FULL FiloServer clusters federated over their doors, plus a
    single-store ground truth holding every series — the shared fixture
    of tests/test_federation.py AND `bench.py federation`.

    `east` owns region="east" series and is the coordinator the tests
    query; `west` owns region="west".  Each cluster's config declares
    the other via `federation.clusters` label matchers, so a query
    without a region selector fans out to both (west replying cluster
    partials for mergeable aggregates) and `{region="west"}` routes
    whole expressions across."""
    dataset: str
    metric: str
    east: "object"                        # FiloServer (coordinator)
    west: "object"                        # FiloServer (remote)
    truth: QueryEngine                    # all series in ONE store
    truth_store: TimeSeriesMemStore

    @property
    def engine(self) -> QueryEngine:
        return self.east.engines[self.dataset]

    @property
    def frontend(self):
        return self.east.api.frontends[self.dataset]

    def kill_west(self) -> None:
        """Cluster death with the SIGKILL signature, as east sees it:
        west's federation door severs live connections and refuses new
        ones.  (west's own engines keep running — a dead DOOR is what a
        dead cluster looks like from across the boundary.)"""
        self.west.federation_door.stop()

    def revive_west(self) -> None:
        """Bring west's door back on its ORIGINAL configured port
        (half-open breaker recovery needs the declared endpoint to
        answer again)."""
        self.west.federation_door.start()

    def stop(self) -> None:
        for srv in (self.east, self.west):
            try:
                srv.shutdown()
            except OSError:
                pass


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_federated_pair(num_series: int = 8, num_samples: int = 120,
                        num_shards: int = 2, dataset: str = "prometheus",
                        start_ms: int = 1_600_000_020_000,
                        step_ms: int = 10_000, metric: str = "fed_gauge",
                        push_partials: bool = True,
                        probe_interval_s: float = 0.2,
                        start: bool = True) -> FederatedPair:
    """Boot the two-cluster federation testbench: `num_series` integer-
    valued series per region, split by the `region` ownership label;
    the truth engine answers the same queries from one store holding
    everything (bit-identity oracle)."""
    from filodb_tpu.config import FilodbSettings
    from filodb_tpu.ingest.generator import region_gauge_batch
    from filodb_tpu.standalone import DatasetConfig, FiloServer
    ports = {"east": _free_port(), "west": _free_port()}

    def cfg(me: str, peer: str) -> FilodbSettings:
        c = FilodbSettings()
        f = c.federation
        f.enabled = True
        f.cluster_name = me
        f.door_port = ports[me]
        f.probe_interval_s = probe_interval_s
        f.probe_timeout_s = 1.0
        f.push_partials = push_partials
        f.clusters = {
            peer: {"host": "127.0.0.1", "port": ports[peer],
                   "match": {"region": peer}},
            me: {"local": True, "match": {"region": me}},
        }
        return c

    servers = {}
    batches = {}
    for i, (me, peer) in enumerate((("east", "west"), ("west", "east"))):
        srv = FiloServer([DatasetConfig(dataset, num_shards=num_shards)],
                         config=cfg(me, peer), http_port=0, node_name=me)
        batches[me] = region_gauge_batch(
            num_series, num_samples, region=me, start_ms=start_ms,
            step_ms=step_ms, metric=metric, seed=i + 1)
        spread = srv.spreads[dataset]
        for s, sub in split_batch_by_shard(batches[me],
                                           srv.mappers[dataset],
                                           spread).items():
            srv.memstore.get_shard(dataset, s).ingest(sub)
        servers[me] = srv
    truth_store = TimeSeriesMemStore()
    truth_mapper = ShardMapper(num_shards)
    truth_spread = SpreadProvider(default_spread=1)
    for s in range(num_shards):
        truth_store.setup(dataset, s)
        truth_mapper.update_from_event(
            ShardEvent("IngestionStarted", dataset, s, "truth"))
    for batch in batches.values():
        for s, sub in split_batch_by_shard(batch, truth_mapper,
                                           truth_spread).items():
            truth_store.get_shard(dataset, s).ingest(sub)
    truth = QueryEngine(dataset, truth_store, truth_mapper,
                        planner=SingleClusterPlanner(dataset, truth_mapper,
                                                     truth_spread))
    if start:
        for srv in servers.values():
            srv.start()
    return FederatedPair(dataset, metric, servers["east"],
                         servers["west"], truth, truth_store)


def make_two_node_cluster(batches: Iterable = (), num_shards: int = 4,
                          dataset: str = "prometheus",
                          default_spread: int = 1,
                          with_truth: bool = False) -> TwoNodeCluster:
    """Two node processes, shards split half/half — the original
    fixture shape, now a 2-node `make_fanout_cluster`."""
    return make_fanout_cluster(batches, num_shards, dataset,
                               default_spread, with_truth,
                               nodes=("nodeA", "nodeB"))
