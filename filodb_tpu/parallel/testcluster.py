"""Shared two-node cluster builder for tests AND benchmarks.

Lives in the main package on purpose, like the reference keeping
TestTimeseriesProducer in src/main so jmh/stress reuse it (ref:
gateway/src/main/scala/filodb/timeseries/TestTimeseriesProducer.scala;
SURVEY §4 'shared fixtures').  One wiring of the cross-node transport
means the transport tests and the dispatch benchmark cannot drift.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterable, Optional

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.gateway.router import split_batch_by_shard
from filodb_tpu.parallel.shardmapper import (ShardEvent, ShardMapper,
                                             SpreadProvider)
from filodb_tpu.parallel.transport import (NodeQueryServer,
                                           RemoteNodeDispatcher)
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.planner import SingleClusterPlanner


@dataclasses.dataclass
class TwoNodeCluster:
    """Coordinator engine dispatching over TCP to two data nodes."""
    engine: QueryEngine
    mapper: ShardMapper
    stores: Dict[str, TimeSeriesMemStore]
    owner: Dict[int, str]
    servers: Dict[str, NodeQueryServer]
    truth: Optional[TimeSeriesMemStore]   # single store with ALL data

    def stop(self) -> None:
        for srv in self.servers.values():
            srv.stop()


def make_two_node_cluster(batches: Iterable = (), num_shards: int = 4,
                          dataset: str = "prometheus",
                          default_spread: int = 1,
                          with_truth: bool = False) -> TwoNodeCluster:
    """Two node processes (in-process servers), shards split half/half,
    coordinator holding NO data with remote dispatchers — the multi-JVM
    IngestionAndRecoverySpec shape."""
    mapper = ShardMapper(num_shards)
    spread = SpreadProvider(default_spread=default_spread)
    stores = {"nodeA": TimeSeriesMemStore(), "nodeB": TimeSeriesMemStore()}
    owner = {s: ("nodeA" if s < num_shards // 2 else "nodeB")
             for s in range(num_shards)}
    for s, node in owner.items():
        stores[node].setup(dataset, s)
        mapper.update_from_event(
            ShardEvent("IngestionStarted", dataset, s, node))
    truth = TimeSeriesMemStore() if with_truth else None
    truth_shards = ({s: truth.setup(dataset, s) for s in range(num_shards)}
                    if truth is not None else {})
    for batch in batches:
        for s, sub in split_batch_by_shard(batch, mapper, spread).items():
            stores[owner[s]].get_shard(dataset, s).ingest(sub)
            if truth is not None:
                truth_shards[s].ingest(sub)
    servers = {n: NodeQueryServer(st).start() for n, st in stores.items()}
    dispatchers = {n: RemoteNodeDispatcher(*srv.address)
                   for n, srv in servers.items()}
    planner = SingleClusterPlanner(
        dataset, mapper, spread,
        dispatcher_factory=lambda s: dispatchers[owner[s]])
    engine = QueryEngine(dataset, TimeSeriesMemStore(), mapper,
                         planner=planner)
    return TwoNodeCluster(engine, mapper, stores, owner, servers, truth)
