"""Per-peer circuit breakers for the cross-node query transport.

A SIGKILLed or partitioned peer makes every scatter-gather that touches
its shards serialize a connect attempt (worst case the full connect
timeout, per child, per query).  The breaker converts that into a
microsecond fail-fast: after `failure_threshold` CONSECUTIVE
shard_unavailable/connect failures to one node address the breaker
opens, `RemoteNodeDispatcher` raises the same typed `shard_unavailable`
immediately, and the partial-result / re-plan machinery engages without
ever touching the socket.  Half-open probes with exponential backoff +
jitter detect recovery: one trial dispatch is let through per open
interval; success closes the breaker, failure re-opens it with a doubled
interval (ref: the standard Nygard circuit-breaker state machine — the
reference gets the equivalent for free from akka deathwatch marking the
member down; PAPERS.md Cortex/Thanos both ship per-store-gateway
breakers).

State is observable: `breaker_state` gauges (0 closed / 1 half-open /
2 open) and `breaker_transitions` counters at /metrics, a snapshot at
GET /admin/breakers.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_NUM = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """One peer's breaker.  Thread-safe; all transitions happen under
    the instance lock and are mirrored to the metrics registry."""

    def __init__(self, peer: str, failure_threshold: int = 3,
                 open_base_s: float = 1.0, open_max_s: float = 30.0,
                 jitter: float = 0.2):
        self.peer = peer
        self.failure_threshold = max(int(failure_threshold), 1)
        self.open_base_s = float(open_base_s)
        self.open_max_s = float(open_max_s)
        self.jitter = float(jitter)
        self._lock = threading.Lock()
        self.state = CLOSED
        self.consecutive_failures = 0
        self.open_until = 0.0
        self.opens = 0                   # total open transitions
        self.fail_fast = 0               # dispatches rejected while open
        self._backoff_s = self.open_base_s
        self._probe_inflight = False

    # ------------------------------------------------------------ events

    def allow(self) -> bool:
        """True = the dispatch may try the wire.  While open, exactly one
        caller per elapsed backoff interval is admitted as the half-open
        probe; everyone else fails fast."""
        with self._lock:
            if self.state == CLOSED:
                return True
            now = time.monotonic()
            if self.state == OPEN and now >= self.open_until:
                self._set_state(HALF_OPEN)
            if self.state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            self.fail_fast += 1
            from filodb_tpu.utils.metrics import registry
            registry.counter("breaker_fail_fast",
                             peer=self.peer).increment()
            return False

    def on_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            self._probe_inflight = False
            self._backoff_s = self.open_base_s
            if self.state != CLOSED:
                self._set_state(CLOSED)

    def on_failure(self) -> None:
        """A shard_unavailable/connect failure (only those count: a slow
        but alive peer — dispatch_timeout — is not a dead one)."""
        with self._lock:
            self.consecutive_failures += 1
            if self.state == HALF_OPEN:
                # failed probe: re-open with a doubled interval
                self._probe_inflight = False
                self._backoff_s = min(self._backoff_s * 2, self.open_max_s)
                self._open()
            elif self.state == CLOSED and \
                    self.consecutive_failures >= self.failure_threshold:
                self._open()

    def on_abort(self) -> None:
        """The dispatch ended with NO verdict on the peer's liveness (a
        deadline/ask timeout: the peer may be alive but slow).  Closed
        breakers are untouched; an admitted half-open probe must release
        its slot — without this, a probe that times out would leak
        `_probe_inflight` and wedge the breaker half-open FOREVER (found
        by the chaos stage: recovery never healed).  An inconclusive
        probe re-opens with a doubled interval, same as a failed one —
        optimistically closing on a timeout would thunder the herd onto
        a struggling peer."""
        with self._lock:
            if self.state == HALF_OPEN and self._probe_inflight:
                self._probe_inflight = False
                self._backoff_s = min(self._backoff_s * 2, self.open_max_s)
                self._open()

    # ----------------------------------------------------------- helpers

    def _open(self) -> None:
        span = self._backoff_s
        if self.jitter > 0:
            span *= 1.0 + random.uniform(-self.jitter, self.jitter)
        self.open_until = time.monotonic() + max(span, 0.0)
        self.opens += 1
        self._set_state(OPEN)

    def _set_state(self, state: str) -> None:
        from filodb_tpu.utils.metrics import registry
        if state != self.state:
            registry.counter("breaker_transitions", peer=self.peer,
                             to=state).increment()
            # the flight recorder sees every transition: "which peer
            # opened right before the partial-results spike" is one
            # GET /admin/events, correlated with slowlog by timestamp
            from filodb_tpu.utils.events import journal
            journal.emit(f"breaker_{state}", subsystem="peers",
                         peer=self.peer,
                         consecutive_failures=self.consecutive_failures)
        self.state = state
        registry.gauge("breaker_state",
                       peer=self.peer).update(_STATE_NUM[state])

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "peer": self.peer,
                "state": self.state,
                "consecutiveFailures": self.consecutive_failures,
                "opens": self.opens,
                "failFast": self.fail_fast,
                "backoffSeconds": round(self._backoff_s, 3),
                "openRemainingSeconds": round(
                    max(self.open_until - time.monotonic(), 0.0), 3)
                if self.state == OPEN else 0.0,
            }


class BreakerRegistry:
    """Process-wide breakers keyed by peer address; knobs resolve from
    `settings().breaker` at first use, overridable via configure() for
    tests (which also reset() between cases)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._overrides: Optional[dict] = None

    def configure(self, **kw) -> None:
        """Override breaker knobs for subsequently-created breakers
        (failure_threshold / open_base_s / open_max_s / jitter)."""
        with self._lock:
            self._overrides = kw or None

    def reset(self) -> None:
        with self._lock:
            self._breakers.clear()

    def enabled(self) -> bool:
        from filodb_tpu.config import settings
        return settings().breaker.enabled

    def get(self, peer: str) -> CircuitBreaker:
        br = self._breakers.get(peer)
        if br is None:
            with self._lock:
                br = self._breakers.get(peer)
                if br is None:
                    kw = self._overrides
                    if kw is None:
                        from filodb_tpu.config import settings
                        c = settings().breaker
                        kw = dict(failure_threshold=c.failure_threshold,
                                  open_base_s=c.open_base_s,
                                  open_max_s=c.open_max_s,
                                  jitter=c.jitter)
                    br = self._breakers[peer] = CircuitBreaker(peer, **kw)
        return br

    def snapshot(self) -> List[dict]:
        with self._lock:
            brs = list(self._breakers.values())
        return [b.snapshot() for b in brs]


breakers = BreakerRegistry()
