"""Cluster seed discovery — the akka-bootstrapper analogue.

ref: akka-bootstrapper/.../AkkaBootstrapper.scala:31-50 +
ClusterSeedDiscovery.scala:84 — a joining node discovers existing cluster
seeds via (a) an explicit seed list, (b) DNS SRV records, or (c) an HTTP
`/__members` endpoint served by live members; if nobody answers, it forms a
new cluster with itself as the first seed.

The TPU-native control plane uses the same shapes: `discover()` returns
live (host, port) coordinator addresses to hand to the ShardManager's
add_member, and `members_payload()` is what the HTTP layer serves at
/__members so later joiners find the cluster.
"""
from __future__ import annotations

import json
import socket
import urllib.request
from typing import List, Optional, Sequence, Tuple

Address = Tuple[str, int]


class ClusterSeedDiscovery:
    """ref: ClusterSeedDiscovery trait."""

    def discover(self) -> List[Address]:
        raise NotImplementedError


class ExplicitListSeedDiscovery(ClusterSeedDiscovery):
    """Static seed list (ref: ExplicitListClusterSeedDiscovery)."""

    def __init__(self, seeds: Sequence[Address]):
        self.seeds = list(seeds)

    def discover(self) -> List[Address]:
        return list(self.seeds)


class DnsSrvSeedDiscovery(ClusterSeedDiscovery):
    """DNS SRV lookup (ref: DnsSrvClusterSeedDiscovery.scala:122).  Uses a
    pluggable resolver because stdlib has no SRV client; deployments pass
    one backed by their resolver library."""

    def __init__(self, srv_name: str,
                 resolver=None):
        self.srv_name = srv_name
        self.resolver = resolver

    def discover(self) -> List[Address]:
        if self.resolver is None:
            raise RuntimeError("DNS SRV discovery needs a resolver callable "
                               "(srv_name -> [(host, port)])")
        return list(self.resolver(self.srv_name))


class HttpMembersSeedDiscovery(ClusterSeedDiscovery):
    """Ask candidate endpoints for their member list via /__members
    (ref: the seed HTTP endpoint AkkaBootstrapper exposes)."""

    def __init__(self, candidates: Sequence[Address], timeout_s: float = 5.0):
        self.candidates = list(candidates)
        self.timeout_s = timeout_s

    def discover(self) -> List[Address]:
        for host, port in self.candidates:
            try:
                with urllib.request.urlopen(
                        f"http://{host}:{port}/__members",
                        timeout=self.timeout_s) as r:
                    payload = json.loads(r.read())
                members = [(m["host"], int(m["port"]))
                           for m in payload.get("members", [])]
                if members:
                    return members
            except (OSError, ValueError, KeyError, TypeError,
                    AttributeError):
                # unreachable OR malformed answer: try the next candidate —
                # discovery must degrade to self-seeding, never crash
                continue
        return []


def bootstrap(discovery: ClusterSeedDiscovery, self_addr: Address,
              join_fn, retries: int = 3) -> List[Address]:
    """Join discovered seeds, or seed a new cluster with ourselves when no
    one answers (ref: AkkaBootstrapper.bootstrap: retry then
    joinSeedNodes(self))."""
    for _ in range(retries):
        seeds = [s for s in discovery.discover() if s != self_addr]
        if seeds:
            join_fn(seeds)
            return seeds
    join_fn([self_addr])
    return [self_addr]


def members_payload(members: Sequence[Address]) -> dict:
    """The /__members response body served by live nodes."""
    return {"members": [{"host": h, "port": p} for h, p in members]}
