"""Cluster seed discovery — the akka-bootstrapper analogue.

ref: akka-bootstrapper/.../AkkaBootstrapper.scala:31-50 +
ClusterSeedDiscovery.scala:84 — a joining node discovers existing cluster
seeds via (a) an explicit seed list, (b) DNS SRV records, or (c) an HTTP
`/__members` endpoint served by live members; if nobody answers, it forms a
new cluster with itself as the first seed.

The TPU-native control plane uses the same shapes: `discover()` returns
live (host, port) coordinator addresses to hand to the ShardManager's
add_member, and `members_payload()` is what the HTTP layer serves at
/__members so later joiners find the cluster.
"""
from __future__ import annotations

import json
import socket
import urllib.error
import urllib.request
from typing import List, Optional, Sequence, Tuple

Address = Tuple[str, int]


class ClusterSeedDiscovery:
    """ref: ClusterSeedDiscovery trait."""

    def discover(self) -> List[Address]:
        raise NotImplementedError


class ExplicitListSeedDiscovery(ClusterSeedDiscovery):
    """Static seed list (ref: ExplicitListClusterSeedDiscovery)."""

    def __init__(self, seeds: Sequence[Address]):
        self.seeds = list(seeds)

    def discover(self) -> List[Address]:
        return list(self.seeds)


class DnsSrvSeedDiscovery(ClusterSeedDiscovery):
    """DNS SRV lookup (ref: DnsSrvClusterSeedDiscovery.scala:122).  Uses a
    pluggable resolver because stdlib has no SRV client; deployments pass
    one backed by their resolver library."""

    def __init__(self, srv_name: str,
                 resolver=None):
        self.srv_name = srv_name
        self.resolver = resolver

    def discover(self) -> List[Address]:
        if self.resolver is None:
            raise RuntimeError("DNS SRV discovery needs a resolver callable "
                               "(srv_name -> [(host, port)])")
        return list(self.resolver(self.srv_name))


class HttpMembersSeedDiscovery(ClusterSeedDiscovery):
    """Ask candidate endpoints for their member list via /__members
    (ref: the seed HTTP endpoint AkkaBootstrapper exposes)."""

    def __init__(self, candidates: Sequence[Address], timeout_s: float = 5.0):
        self.candidates = list(candidates)
        self.timeout_s = timeout_s

    def discover(self) -> List[Address]:
        for host, port in self.candidates:
            try:
                with urllib.request.urlopen(
                        f"http://{host}:{port}/__members",
                        timeout=self.timeout_s) as r:
                    payload = json.loads(r.read())
                members = [(m["host"], int(m["port"]))
                           for m in payload.get("members", [])]
                if members:
                    return members
            except (OSError, ValueError, KeyError, TypeError,
                    AttributeError):
                # unreachable OR malformed answer: try the next candidate —
                # discovery must degrade to self-seeding, never crash
                continue
        return []


class ConsulSeedDiscovery(ClusterSeedDiscovery):
    """Consul-backed seed discovery (ref: akka-bootstrapper/.../
    ConsulClient.scala:29 + DnsSrvClusterSeedDiscovery.scala:95
    ConsulClusterSeedDiscovery): a joining node REGISTERS itself with the
    local Consul agent and discovers live seeds from Consul's catalog.
    The reference resolves seeds through Consul's DNS-SRV interface; this
    client uses the equivalent HTTP health API
    (GET /v1/health/service/<name>?passing=true) so no SRV resolver
    dependency is needed — same catalog, same liveness filter."""

    def __init__(self, service_name: str,
                 consul_host: str = "127.0.0.1", consul_port: int = 8500,
                 timeout_s: float = 5.0):
        self.service_name = service_name
        self.base = f"http://{consul_host}:{consul_port}"
        self.timeout_s = timeout_s
        self._service_id: Optional[str] = None

    def register(self, host: str, port: int) -> str:
        """PUT /v1/agent/service/register (ref: ConsulClient.register:38).
        Returns the service id used for deregistration."""
        service_id = f"{self.service_name}-{host}-{port}"
        payload = json.dumps({"id": service_id, "name": self.service_name,
                              "address": host, "port": port}).encode()
        req = urllib.request.Request(
            f"{self.base}/v1/agent/service/register", data=payload,
            method="PUT", headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
        except urllib.error.HTTPError as e:
            raise RuntimeError(
                f"consul registration failed: HTTP {e.code} {e.reason}"
            ) from e
        except OSError as e:             # agent unreachable / refused
            raise RuntimeError(f"consul agent unreachable: {e}") from e
        self._service_id = service_id
        return service_id

    def deregister(self) -> None:
        """PUT /v1/agent/service/deregister/<id> (ref:
        ConsulClient.deregister:50) — the reference runs this from a
        shutdown hook."""
        if self._service_id is None:
            return
        req = urllib.request.Request(
            f"{self.base}/v1/agent/service/deregister/{self._service_id}",
            data=b"", method="PUT")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
        except OSError:
            # shutdown-hook context: a down agent must not abort the rest
            # of shutdown; the registration expires with the agent anyway
            pass
        self._service_id = None

    def discover(self) -> List[Address]:
        try:
            with urllib.request.urlopen(
                    f"{self.base}/v1/health/service/{self.service_name}"
                    f"?passing=true", timeout=self.timeout_s) as r:
                entries = json.loads(r.read())
        except (OSError, ValueError):
            return []               # agent down: degrade to self-seeding
        out: List[Address] = []
        for e in entries:
            try:
                svc = e["Service"]
                host = svc.get("Address") or e.get("Node", {}).get("Address")
                if not host:
                    continue             # malformed entry: skip, don't crash
                out.append((host, int(svc["Port"])))
            except (KeyError, TypeError, ValueError):
                continue
        return out


def bootstrap(discovery: ClusterSeedDiscovery, self_addr: Address,
              join_fn, retries: int = 3) -> List[Address]:
    """Join discovered seeds, or seed a new cluster with ourselves when no
    one answers (ref: AkkaBootstrapper.bootstrap: retry then
    joinSeedNodes(self))."""
    for _ in range(retries):
        seeds = [s for s in discovery.discover() if s != self_addr]
        if seeds:
            join_fn(seeds)
            return seeds
    join_fn([self_addr])
    return [self_addr]


def members_payload(members: Sequence[Address]) -> dict:
    """The /__members response body served by live nodes."""
    return {"members": [{"host": h, "port": p} for h, p in members]}
