"""Wire serialization for exec-plan subtrees and query results.

The reference moves plan subtrees and results between nodes with Kryo over
Akka remoting (ref: coordinator/.../client/Serializer.scala:34-55,
FiloKryoSerializers.scala, exec/PlanDispatcher.scala:31-55; the README
calls SerializationSpec the regression net).  The TPU-native wire format is
a two-part frame:

  [u32 json_len][json tree][buffer table + raw array bytes]

The JSON tree captures structure; every numpy array node is a {"$nd": i}
reference into the binary section, so bulk result matrices cross the wire
as raw bytes with zero re-encoding.  Only classes in the explicit
registries below can be revived — no arbitrary-class instantiation (the
same closed-registry stance as the reference's registered Kryo serializers).
"""
from __future__ import annotations

import dataclasses
import json
import struct
from typing import Any, Dict, List, Tuple

import numpy as np

from filodb_tpu.core import index as index_mod
from filodb_tpu.query import exec as exec_mod
from filodb_tpu.query import logical as lp_mod
from filodb_tpu.query import rangevector as rv_mod

# ------------------------------------------------------------- registries

# dataclasses revivable by name (transformers, filters, result carriers;
# logical-plan dataclasses ride federation dispatches — the federated
# leaf ships the EXACT logical subtree instead of an unparse/re-parse
# round trip, so sub-second clamped grids and offsets survive the hop)
_DATACLASSES: Dict[str, type] = {}
for _m in (exec_mod, rv_mod, index_mod, lp_mod):
    for _name in dir(_m):
        _cls = getattr(_m, _name)
        if isinstance(_cls, type) and dataclasses.is_dataclass(_cls):
            _DATACLASSES[_cls.__name__] = _cls

# plain classes revived via constructor arg-name lists
_SIMPLE: Dict[str, Tuple[type, List[str]]] = {
    "AggregatePresenter": (exec_mod.AggregatePresenter, ["op", "params"]),
}

# leaf exec plans: (class, constructor attr names after ctx)
_LEAF_PLANS: Dict[str, Tuple[type, List[str]]] = {
    "MultiSchemaPartitionsExec": (
        exec_mod.MultiSchemaPartitionsExec,
        ["dataset", "shard", "filters", "chunk_start_ms", "chunk_end_ms",
         "columns", "schema"]),
    # cold-tier leaf (PR 17): `tier` crosses the wire as a dataset-name
    # marker and rebinds to the RECEIVING node's PersistedTier on decode
    # (persist.segments.query_tier) — so cold leaves can ride pushed
    # RemoteAggregateExec node groups like hot ones
    "SelectPersistedSegmentsExec": (
        exec_mod.SelectPersistedSegmentsExec,
        ["dataset", "shard", "filters", "chunk_start_ms", "chunk_end_ms",
         "tier", "columns", "schema"]),
    "LabelValuesExec": (
        exec_mod.LabelValuesExec,
        ["dataset", "shard", "filters", "labels", "start_ms", "end_ms"]),
    "PartKeysExec": (
        exec_mod.PartKeysExec,
        ["dataset", "shard", "filters", "start_ms", "end_ms"]),
    "TimeScalarGeneratorExec": (
        exec_mod.TimeScalarGeneratorExec,
        ["start_ms", "step_ms", "end_ms", "function"]),
    "ScalarFixedDoubleExec": (
        exec_mod.ScalarFixedDoubleExec,
        ["start_ms", "step_ms", "end_ms", "value"]),
}

# the ONLY non-leaf plans allowed over the wire: node-level aggregation
# pushdown subtrees (query/pushdown.py) whose children are themselves
# serializable leaves.  Everything else (joins, concats, stitches) keeps
# refusing — composition stays on the coordinator.
_PUSHDOWN_PLANS: Dict[str, Tuple[type, List[str]]] = {
    "RemoteAggregateExec": (exec_mod.RemoteAggregateExec, ["op", "params"]),
}


class NotSerializable(TypeError):
    pass


def register_leaf_plan(cls: type, attrs: List[str]) -> None:
    """Register an out-of-package leaf exec plan for wire revival — the
    closed-registry stance is kept (only explicit registrations revive);
    this lets higher layers (federation/exec.py FederatedLeafExec) ship
    their own leaves without a parallel→federation import cycle.  The
    class must construct as cls(ctx, **{attr: value}) like the built-in
    `_LEAF_PLANS` entries."""
    _LEAF_PLANS[cls.__name__] = (cls, list(attrs))


# --------------------------------------------------------------- encoding


class _Encoder:
    def __init__(self):
        self.buffers: List[np.ndarray] = []

    def enc(self, obj: Any):
        if obj is None or isinstance(obj, (bool, int, float, str)):
            return obj
        if isinstance(obj, (np.integer,)):
            return int(obj)
        if isinstance(obj, (np.floating,)):
            return float(obj)
        if isinstance(obj, np.ndarray):
            self.buffers.append(np.ascontiguousarray(obj))
            return {"$nd": len(self.buffers) - 1}
        if isinstance(obj, exec_mod.LazyKeys):
            # deferred key facades materialize at the wire: the remote's
            # shard/pid handles mean nothing on the coordinator (found by
            # the PR-4 partial-results tests: raw un-aggregated blocks
            # failed to dispatch remotely at all)
            return [self.enc(k) for k in obj]
        from filodb_tpu.persist.segments import PersistedTier
        if isinstance(obj, PersistedTier):
            # node-local (segment files + cold region): only the dataset
            # name crosses the wire; the decoder rebinds to the
            # receiving node's registered tier
            return {"$tier": obj.dataset}
        if isinstance(obj, tuple):
            return {"$t": [self.enc(x) for x in obj]}
        if isinstance(obj, list):
            return [self.enc(x) for x in obj]
        if isinstance(obj, dict):
            return {"$m": {k: self.enc(v) for k, v in obj.items()}}
        if isinstance(obj, exec_mod.ExecPlan):
            return self._enc_plan(obj)
        if dataclasses.is_dataclass(obj):
            name = type(obj).__name__
            if name not in _DATACLASSES:
                raise NotSerializable(f"unregistered dataclass {name}")
            # cache_token is PROCESS-LOCAL working-set identity (shard
            # keys_serial/keys_epoch/pid bytes): two processes can mint
            # colliding tokens for different key sets, so a token must
            # never cross the wire — the coordinator's group-id cache
            # would serve another node's group ids (PR 4 hardening)
            return {"$c": name,
                    "f": {f.name: self.enc(None if f.name == "cache_token"
                                           else getattr(obj, f.name))
                          for f in dataclasses.fields(obj)}}
        name = type(obj).__name__
        if name in _SIMPLE:
            _, attrs = _SIMPLE[name]
            return {"$s": name, "f": {a: self.enc(getattr(obj, a))
                                      for a in attrs}}
        raise NotSerializable(f"cannot serialize {type(obj)!r}")

    def _enc_plan(self, plan: exec_mod.ExecPlan):
        name = type(plan).__name__
        if name in _PUSHDOWN_PLANS:
            _, attrs = _PUSHDOWN_PLANS[name]
            return {"$plan": name,
                    "ctx": self.enc(plan.ctx),
                    "transformers": [self.enc(t) for t in plan.transformers],
                    "children": [self._enc_plan(c) for c in plan.children],
                    "f": {a: self.enc(getattr(plan, a)) for a in attrs}}
        if name not in _LEAF_PLANS:
            raise NotSerializable(
                f"plan {name} does not cross node boundaries — only leaf "
                f"subtrees and pushdown aggregation groups are dispatched "
                f"(ref: PlanDispatcher)")
        _, attrs = _LEAF_PLANS[name]
        return {"$plan": name,
                "ctx": self.enc(plan.ctx),
                "transformers": [self.enc(t) for t in plan.transformers],
                "f": {a: self.enc(getattr(plan, a)) for a in attrs}}


class _Decoder:
    def __init__(self, buffers: List[np.ndarray]):
        self.buffers = buffers

    def dec(self, node: Any):
        if node is None or isinstance(node, (bool, int, float, str)):
            return node
        if isinstance(node, list):
            return [self.dec(x) for x in node]
        if isinstance(node, dict):
            if "$nd" in node:
                return self.buffers[node["$nd"]]
            if "$tier" in node:
                from filodb_tpu.persist.segments import query_tier
                tier = query_tier(node["$tier"])
                if tier is None:
                    raise NotSerializable(
                        f"no persisted tier registered for dataset "
                        f"{node['$tier']!r} on this node")
                return tier
            if "$t" in node:
                return tuple(self.dec(x) for x in node["$t"])
            if "$m" in node:
                return {k: self.dec(v) for k, v in node["$m"].items()}
            if "$c" in node:
                cls = _DATACLASSES[node["$c"]]
                return cls(**{k: self.dec(v) for k, v in node["f"].items()})
            if "$s" in node:
                cls, _ = _SIMPLE[node["$s"]]
                return cls(**{k: self.dec(v) for k, v in node["f"].items()})
            if "$plan" in node:
                name = node["$plan"]
                if name in _PUSHDOWN_PLANS:
                    cls, attrs = _PUSHDOWN_PLANS[name]
                    ctx = self.dec(node["ctx"])
                    children = [self.dec(c) for c in node["children"]]
                    kwargs = {k: self.dec(v) for k, v in node["f"].items()}
                    # children revive with the default in-process
                    # dispatcher: on the data node the group executes as
                    # an ordinary local scatter-gather + reduce
                    plan = cls(ctx, children, **kwargs)
                    plan.transformers = [self.dec(t)
                                         for t in node["transformers"]]
                    return plan
                cls, attrs = _LEAF_PLANS[name]
                ctx = self.dec(node["ctx"])
                kwargs = {k: self.dec(v) for k, v in node["f"].items()}
                plan = cls(ctx, **kwargs)
                plan.transformers = [self.dec(t)
                                     for t in node["transformers"]]
                return plan
        raise NotSerializable(f"cannot decode node {node!r}")


def dumps(obj: Any) -> bytes:
    """Object → wire frame."""
    enc = _Encoder()
    tree = enc.enc(obj)
    blob = json.dumps(tree, separators=(",", ":")).encode()
    parts = [struct.pack("<I", len(blob)), blob,
             struct.pack("<I", len(enc.buffers))]
    for arr in enc.buffers:
        dt = str(arr.dtype).encode()
        shape = arr.shape
        parts.append(struct.pack("<H", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<H", len(shape)))
        parts.append(struct.pack(f"<{len(shape)}q", *shape))
        raw = arr.tobytes()
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def loads(data: bytes) -> Any:
    """Wire frame → object."""
    (jlen,) = struct.unpack_from("<I", data, 0)
    tree = json.loads(data[4:4 + jlen])
    pos = 4 + jlen
    (nbuf,) = struct.unpack_from("<I", data, pos)
    pos += 4
    buffers: List[np.ndarray] = []
    for _ in range(nbuf):
        (dlen,) = struct.unpack_from("<H", data, pos)
        pos += 2
        dtype = np.dtype(data[pos:pos + dlen].decode())
        pos += dlen
        (ndim,) = struct.unpack_from("<H", data, pos)
        pos += 2
        shape = struct.unpack_from(f"<{ndim}q", data, pos)
        pos += 8 * ndim
        (rlen,) = struct.unpack_from("<Q", data, pos)
        pos += 8
        # single copy: frombuffer(offset=) avoids a bytes-slice copy, and
        # .copy() makes the array writable for downstream consumers
        count = rlen // dtype.itemsize if dtype.itemsize else 0
        arr = np.frombuffer(data, dtype=dtype, count=count,
                            offset=pos).reshape(shape).copy()
        pos += rlen
        buffers.append(arr)
    return _Decoder(buffers).dec(tree)
