"""Cluster control plane: node registry, heartbeat liveness, assignment
distribution.

The reference runs a NodeClusterActor singleton whose ShardManager reacts to
akka-cluster membership (gossip) and deathwatch terminations (ref:
coordinator/.../ShardManager.scala:621 removeMember on Terminated,
doc/sharding.md:158-189).  The TPU rebuild keeps the same roles with explicit
wire machinery:

- ClusterCoordinator: one process owns the ShardManager; a framed-JSON TCP
  server accepts node registration, heartbeats (which double as the
  assignment feed), and state queries.  A liveness thread plays deathwatch:
  nodes that miss heartbeats past the timeout are removed and their shards
  reassigned to surviving capacity.
- NodeAgent: runs inside each node process; registers, heartbeats, applies
  assignment diffs via a callback (setup + recovery happen node-side), and
  reports which shards are actively ingesting so the coordinator can flip
  them Active in the shard map.
- ClusterClient: anyone (e.g. a query frontend) can fetch the current shard
  map + node addresses to build per-owner dispatchers.

The query data plane stays on transport.NodeQueryServer — this module is
control only.
"""
from __future__ import annotations

import json
import logging
import queue
import socket
import socketserver
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from filodb_tpu.parallel.shardmanager import (DatasetResourceSpec,
                                              ShardEvent, ShardManager)
from filodb_tpu.parallel.shardmapper import ShardMapper, ShardStatus
from filodb_tpu.parallel.transport import recv_json_frame, send_json_frame, _recv_frame, _send_frame

_log = logging.getLogger("filodb.cluster")


# shared frame codec (one copy next to the framing it wraps)
_send_json = send_json_frame
_recv_json = recv_json_frame


def _rpc(addr: Tuple[str, int], obj, timeout_s: float = 10.0):
    with socket.create_connection(tuple(addr), timeout=timeout_s) as s:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _send_json(s, obj)
        return _recv_json(s)


class ClusterCoordinator:
    """The NodeClusterActor-singleton analogue (control-plane server)."""

    def __init__(self, shard_manager: Optional[ShardManager] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 liveness_timeout_s: float = 5.0,
                 check_interval_s: float = 0.5,
                 replication_factor: int = 1):
        self.sm = shard_manager or ShardManager(
            replication_factor=replication_factor)
        self.liveness_timeout_s = liveness_timeout_s
        self.check_interval_s = check_interval_s
        self._lock = threading.RLock()
        # node -> {"query_addr": (h, p), "last_seen": t}
        self._nodes: Dict[str, Dict] = {}
        self._stop = threading.Event()
        self._liveness_thread: Optional[threading.Thread] = None
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        req = _recv_json(self.request)
                        try:
                            reply = outer._handle(req)
                        except Exception as e:  # noqa: BLE001
                            reply = {"ok": False,
                                     "error": f"{type(e).__name__}: {e}"}
                        _send_json(self.request, reply)
                except (ConnectionError, OSError, json.JSONDecodeError):
                    return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lifecycle

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def start(self) -> "ClusterCoordinator":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._liveness_thread = threading.Thread(target=self._liveness_loop,
                                                 daemon=True)
        self._liveness_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if self._liveness_thread:
            self._liveness_thread.join(timeout=5)

    def setup_dataset(self, dataset: str, num_shards: int,
                      min_num_nodes: int) -> None:
        with self._lock:
            self.sm.setup_dataset(
                dataset, DatasetResourceSpec(num_shards, min_num_nodes))

    # ------------------------------------------------------------- handlers

    def _assignments_for(self, node: str) -> Dict[str, List[int]]:
        """Shards `node` should hold a copy of: primaries AND replicas
        (the node-side contract is identical — set up the shard, ingest
        its stream; the coordinator's mapper keeps the roles)."""
        out = {}
        for ds in self.sm.datasets():
            m = self.sm.mapper(ds)
            shards = sorted(set(m.shards_for_node(node))
                            | set(m.replica_shards_for_node(node)))
            if shards:
                out[ds] = shards
        return out

    def _handle(self, req: Dict) -> Dict:
        cmd = req.get("cmd")
        with self._lock:
            if cmd == "register":
                node = req["node"]
                self._nodes[node] = {"query_addr": tuple(req["query_addr"]),
                                     "last_seen": time.time()}
                self.sm.add_member(node)
                from filodb_tpu.utils.events import journal
                journal.emit("node_joined", subsystem="cluster",
                             node=node, members=len(self.sm.members))
                _log.info("node %s registered (%d members)", node,
                          len(self.sm.members))
                return {"ok": True,
                        "assignments": self._assignments_for(node)}
            if cmd == "heartbeat":
                node = req["node"]
                info = self._nodes.get(node)
                if info is None:
                    # coordinator restarted or node was declared dead:
                    # tell it to re-register (reference: restart handshake)
                    return {"ok": False, "rejoin": True}
                info["last_seen"] = time.time()
                for ds, shards in (req.get("active") or {}).items():
                    mapper = self.sm.mapper(ds)
                    for s in shards:
                        if mapper.node_for_shard(s) == node and \
                                mapper.statuses[s] != ShardStatus.ACTIVE:
                            self.sm.on_shard_event(
                                ShardEvent("IngestionStarted", ds, s, node))
                        elif node in mapper.replicas[s] and \
                                mapper.owner_status(s, node) != \
                                ShardStatus.ACTIVE:
                            # a replica copy went live: it becomes a
                            # query-time failover target
                            self.sm.on_shard_event(
                                ShardEvent("ReplicaActive", ds, s, node))
                return {"ok": True,
                        "assignments": self._assignments_for(node)}
            if cmd == "state":
                return {"ok": True, "state": self._state()}
            return {"ok": False, "error": f"unknown cmd {cmd!r}"}

    def _state(self) -> Dict:
        nodes = {n: list(i["query_addr"]) for n, i in self._nodes.items()}
        datasets = {}
        for ds in self.sm.datasets():
            snap = self.sm.snapshot(ds)
            m = self.sm.mapper(ds)
            datasets[ds] = {
                "nodes": snap.nodes, "statuses": snap.statuses,
                # ordered replica owners + per-replica statuses, so a
                # ClusterClient can rebuild failover dispatchers
                "replicas": [list(r) for r in m.replicas],
                "replica_statuses": {
                    f"{s}:{n}": st.value
                    for (s, n), st in m.replica_statuses.items()}}
        return {"members": self.sm.members, "nodes": nodes,
                "datasets": datasets}

    # ------------------------------------------------------------- liveness

    def _liveness_loop(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            now = time.time()
            with self._lock:
                dead = [n for n, i in self._nodes.items()
                        if now - i["last_seen"] > self.liveness_timeout_s]
                for node in dead:
                    _log.warning("node %s missed heartbeats for %.1fs — "
                                 "removing and reassigning its shards",
                                 node, now - self._nodes[node]["last_seen"])
                    from filodb_tpu.utils.events import journal
                    journal.emit(
                        "node_dead", subsystem="cluster", node=node,
                        last_seen_ago_s=round(
                            now - self._nodes[node]["last_seen"], 2))
                    del self._nodes[node]
                    self.sm.remove_member(node)


class ClusterClient:
    """Control-plane client: state fetch + mapper/dispatcher construction."""

    def __init__(self, coordinator_addr: Tuple[str, int],
                 timeout_s: float = 10.0):
        self.addr = tuple(coordinator_addr)
        self.timeout_s = timeout_s

    def state(self) -> Dict:
        reply = _rpc(self.addr, {"cmd": "state"}, self.timeout_s)
        if not reply.get("ok"):
            raise RuntimeError(reply.get("error", "state failed"))
        return reply["state"]

    def mapper(self, dataset: str) -> Tuple[ShardMapper, Dict[str, Tuple[str, int]]]:
        """(ShardMapper, node -> query address) reflecting current state,
        including the replica tails of each shard's assignment list."""
        st = self.state()
        ds = st["datasets"][dataset]
        mapper = ShardMapper(len(ds["nodes"]))
        for shard, (node, status) in enumerate(zip(ds["nodes"],
                                                   ds["statuses"])):
            if node is None:
                continue
            mapper.register_node([shard], node)
            if status == ShardStatus.ACTIVE.value:
                mapper.update_from_event(
                    ShardEvent("IngestionStarted", dataset, shard, node))
        rstatus = ds.get("replica_statuses") or {}
        for shard, repls in enumerate(ds.get("replicas") or []):
            for node in repls:
                mapper.register_replica(
                    shard, node,
                    status=ShardStatus(rstatus.get(f"{shard}:{node}",
                                                   "Assigned")))
        addrs = {n: tuple(a) for n, a in st["nodes"].items()}
        return mapper, addrs


class NodeAgent:
    """Node-side membership: register, heartbeat, apply assignment diffs.

    `on_assign(dataset, shard)` runs once per newly-assigned shard (setup +
    recovery); when it returns the shard is reported active on subsequent
    heartbeats.  `on_unassign` is invoked for shards taken away."""

    def __init__(self, node_name: str, coordinator_addr: Tuple[str, int],
                 query_addr: Tuple[str, int],
                 on_assign: Callable[[str, int], None],
                 on_unassign: Optional[Callable[[str, int], None]] = None,
                 heartbeat_interval_s: float = 1.0):
        self.node = node_name
        self.coordinator_addr = tuple(coordinator_addr)
        self.query_addr = tuple(query_addr)
        self.on_assign = on_assign
        self.on_unassign = on_unassign
        self.heartbeat_interval_s = heartbeat_interval_s
        self._lock = threading.Lock()
        self._owned: Dict[str, set] = {}       # dataset -> recovered shards
        # (ds, shard) -> epoch of the CURRENT assignment attempt.  Epochs
        # defeat the revoke-then-reassign ABA: a recovery started under an
        # older epoch must neither claim ownership nor cancel the newer
        # attempt when it finally completes.
        self._scheduled: Dict[Tuple[str, int], int] = {}
        self._epoch = 0
        self._assign_q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._applier: Optional[threading.Thread] = None
        self.errors = 0

    def register(self) -> None:
        reply = _rpc(self.coordinator_addr,
                     {"cmd": "register", "node": self.node,
                      "query_addr": list(self.query_addr)})
        if not reply.get("ok"):
            raise RuntimeError(reply.get("error", "register failed"))
        self._apply(reply.get("assignments") or {})

    def _apply(self, assignments: Dict[str, List[int]]) -> None:
        """Diff assignments; recovery work (on_assign) runs on the applier
        thread so a long index recovery never starves heartbeats — the
        coordinator's deathwatch must not declare a RECOVERING node dead."""
        with self._lock:
            for ds, shards in assignments.items():
                for s in shards:
                    key = (ds, int(s))
                    if int(s) not in self._owned.get(ds, set()) \
                            and key not in self._scheduled:
                        self._epoch += 1
                        self._scheduled[key] = self._epoch
                        self._assign_q.put((key, self._epoch))
            # revocations: drop owned shards AND cancel ones still queued
            # or mid-recovery so the applier doesn't resurrect them
            for ds, owned in self._owned.items():
                now = set(assignments.get(ds, []))
                for s in sorted(owned - now):
                    if self.on_unassign is not None:
                        self.on_unassign(ds, int(s))
                    owned.discard(s)
            for key in list(self._scheduled):
                if key[1] not in set(assignments.get(key[0], [])):
                    del self._scheduled[key]

    def _applier_loop(self) -> None:
        while not self._stop.is_set():
            try:
                (ds, s), epoch = self._assign_q.get(timeout=0.2)
            except queue.Empty:
                continue
            with self._lock:
                if self._scheduled.get((ds, s)) != epoch:
                    continue            # revoked/superseded while queued
            try:
                self.on_assign(ds, s)
                with self._lock:
                    # only claim ownership if THIS attempt is still the
                    # current one — a revocation (or a newer reassignment)
                    # mid-recovery means this work must be torn down
                    survived = self._scheduled.get((ds, s)) == epoch
                    if survived:
                        self._owned.setdefault(ds, set()).add(s)
                        del self._scheduled[(ds, s)]
                if not survived and self.on_unassign is not None:
                    self.on_unassign(ds, s)
            except Exception:  # noqa: BLE001
                self.errors += 1
                _log.exception("shard assignment failed: %s/%d", ds, s)
                with self._lock:
                    if self._scheduled.get((ds, s)) == epoch:
                        del self._scheduled[(ds, s)]

    def start(self) -> "NodeAgent":
        self._applier = threading.Thread(target=self._applier_loop,
                                         daemon=True)
        self._applier.start()
        self.register()
        self._thread = threading.Thread(target=self._heartbeat_loop,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in (self._thread, self._applier):
            if t:
                t.join(timeout=5)

    @property
    def owned(self) -> Dict[str, List[int]]:
        with self._lock:
            return {ds: sorted(s) for ds, s in self._owned.items()}

    def _heartbeat_loop(self) -> None:
        from filodb_tpu.utils.faults import faults
        while not self._stop.wait(self.heartbeat_interval_s):
            try:
                faults.fire("cluster.heartbeat")
                reply = _rpc(self.coordinator_addr,
                             {"cmd": "heartbeat", "node": self.node,
                              "active": self.owned},  # locked snapshot
                             timeout_s=self.heartbeat_interval_s * 4)
                if reply.get("rejoin"):
                    self.register()
                elif reply.get("ok"):
                    self._apply(reply.get("assignments") or {})
            except (OSError, RuntimeError, json.JSONDecodeError):
                self.errors += 1
