"""Distributed query execution over a JAX device mesh.

This is the TPU-native replacement for the reference's distributed exec tree:
where FiloDB dispatches serialized ExecPlan subtrees to shard-owner nodes via
Akka and tree-reduces partial aggregates through ReduceAggregateExec
(ref: query/.../exec/PlanDispatcher.scala:20-57, exec/AggrOverRangeVectors.scala
:51-123, doc/query-engine.md:90-155), we lay the per-shard dense series arrays
out on a device mesh and let XLA collectives do the reduce:

  mesh axes:  ('shard', 'time')
    - 'shard': data parallelism over series — each device (or device column)
      owns the series of one FiloDB shard, the moral equivalent of
      1 shard = 1 node (ref: doc/sharding.md:23-56).
    - 'time':  sequence parallelism over the *output window grid* — each
      device row computes a contiguous slice of the PromQL step grid, the
      TPU analogue of the planner's time-range splitting + StitchRvsExec
      (ref: SingleClusterPlanner.scala:91-117).

  collectives: the 3-phase aggregate contract (map/reduce/present,
  doc/query-engine.md:311-330) maps onto shard_map as
      map_phase on-device per shard  ->  psum/pmin/pmax over the 'shard'
      axis (ICI)  ->  present host-side,
  so cross-shard aggregation rides ICI instead of Kryo-over-TCP.

All shapes are static under jit: shards are padded to a uniform
[series_per_shard, time] block and padded rows carry NaN values, which the
map phase masks out (same trick the single-shard path uses for ragged data).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from filodb_tpu.ops import agg as agg_ops
from filodb_tpu.ops.rangefns import evaluate_range_function
from filodb_tpu.ops.timewindow import PAD_TS


# --------------------------------------------------------------------- mesh

def make_mesh(n_shard: int, n_time: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ('shard', 'time') mesh from the first n_shard*n_time devices."""
    devs = list(devices if devices is not None else jax.devices())
    need = n_shard * n_time
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    grid = np.array(devs[:need]).reshape(n_shard, n_time)
    return Mesh(grid, ("shard", "time"))


# ---------------------------------------------------------------- packing

@dataclasses.dataclass
class PackedShards:
    """Host-side uniform pack of per-shard series blocks.

    ts_off  [D, S, T] int32 window-offset timestamps (PAD_TS past each row)
    values  [D, S, T] float  (NaN for padded rows)
    group_ids [D, S] int32   global aggregation-group slot per series row
    num_groups               static group count (for segment reductions)
    group_labels             slot -> label dict (for presenting results)
    base_ms                  common timestamp base
    n_series                 true (unpadded) series count per shard
    """
    ts_off: np.ndarray
    values: np.ndarray
    group_ids: np.ndarray
    num_groups: int
    group_labels: List[Dict[str, str]]
    base_ms: int
    n_series: np.ndarray
    # per-series value base subtracted host-side in f64 (ops/counter.
    # rebase_values) so counter deltas survive the f32 device downcast —
    # same contract as the single-shard leaf path (RawBlock.vbase)
    vbase: Optional[np.ndarray] = None      # [D, S]
    precorrected: bool = False

    @property
    def n_shards(self) -> int:
        return self.ts_off.shape[0]


def pack_shards(blocks: Sequence[Tuple],
                by: Sequence[str] = (), without: Sequence[str] = (),
                base_ms: int = 0,
                pad_series_to: Optional[int] = None,
                pad_time_to: Optional[int] = None,
                precorrected: bool = False) -> PackedShards:
    """Pack per-shard (ts_off [S,T], vals [S,T], series label dicts[,
    vbase [S]]) into the uniform [D, S, T] layout, assigning
    globally-consistent group slots.

    Group identity follows the reference's by/without label semantics
    (ref: exec/AggrOverRangeVectors.scala AggregateMapReduce grouping):
    group key = labels restricted to `by` (or all minus `without`).
    """
    D = len(blocks)
    S = pad_series_to or max((b[0].shape[0] for b in blocks), default=1)
    T = pad_time_to or max((b[0].shape[1] for b in blocks), default=1)
    S, T = max(S, 1), max(T, 1)

    group_slot: Dict[Tuple[Tuple[str, str], ...], int] = {}
    group_labels: List[Dict[str, str]] = []

    ts = np.full((D, S, T), PAD_TS, dtype=np.int32)
    vals = np.full((D, S, T), np.nan, dtype=np.float64)
    gids = np.zeros((D, S), dtype=np.int32)
    nser = np.zeros(D, dtype=np.int32)
    vbase = np.zeros((D, S), dtype=np.float64)
    any_vbase = False

    for d, blk in enumerate(blocks):
        t, v, labels = blk[0], blk[1], blk[2]
        if len(blk) > 3 and blk[3] is not None:
            vbase[d, :len(blk[3])] = blk[3]
            any_vbase = True
        s, tt = t.shape
        ts[d, :s, :tt] = t
        vals[d, :s, :tt] = v
        nser[d] = s
        for i, lab in enumerate(labels):
            if by:
                kept = {k: lab[k] for k in by if k in lab}
            elif without:
                drop = set(without) | {"_metric_", "__name__"}
                kept = {k: x for k, x in lab.items() if k not in drop}
            else:
                kept = {}              # aggregate over everything -> 1 group
            key = tuple(sorted(kept.items()))
            slot = group_slot.get(key)
            if slot is None:
                slot = len(group_labels)
                group_slot[key] = slot
                group_labels.append(dict(kept))
            gids[d, i] = slot

    return PackedShards(ts, vals, gids, max(len(group_labels), 1),
                        group_labels, base_ms, nser,
                        vbase=vbase if any_vbase else None,
                        precorrected=precorrected)


def device_put_packed(packed: PackedShards, mesh: Mesh) -> PackedShards:
    """Place packed arrays on the mesh: series data sharded over 'shard',
    replicated over 'time' (each time-row needs the full series to evaluate
    any window slice — windows reach back `range` into the data)."""
    data_spec = NamedSharding(mesh, P("shard", None, None))
    gid_spec = NamedSharding(mesh, P("shard", None))
    return dataclasses.replace(
        packed,
        ts_off=jax.device_put(packed.ts_off, data_spec),
        values=jax.device_put(packed.values, data_spec),
        group_ids=jax.device_put(packed.group_ids, gid_spec),
        vbase=(None if packed.vbase is None
               else jax.device_put(packed.vbase, gid_spec)))


# ------------------------------------------------------------ SPMD kernels

def distributed_window_agg(mesh: Mesh, ts_off, values, group_ids, wends, *,
                           range_ms, fn_name, params=(), agg_op="sum",
                           num_groups=1, base_ms=0, vbase=None,
                           precorrected=False):
    """Eager wrapper: floats base_ms before the jit boundary (epoch-ms ints
    overflow int32 canonicalization on no-x64 TPU; see rangefns)."""
    if vbase is None:
        vbase = jnp.zeros(values.shape[:2], values.dtype)
    return _distributed_window_agg(mesh, ts_off, values, group_ids, wends,
                                   vbase,
                                   range_ms=range_ms, fn_name=fn_name,
                                   params=params, agg_op=agg_op,
                                   num_groups=num_groups,
                                   base_ms=float(base_ms),
                                   precorrected=precorrected)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "fn_name", "params", "agg_op", "num_groups",
                     "precorrected"))
def _distributed_window_agg(mesh: Mesh,
                           ts_off: jax.Array, values: jax.Array,
                           group_ids: jax.Array, wends: jax.Array,
                           vbase: jax.Array,
                           *, range_ms: int, fn_name: Optional[str],
                           params: Tuple[float, ...] = (),
                           agg_op: str = "sum", num_groups: int = 1,
                           base_ms: int = 0,
                           precorrected: bool = False) -> jax.Array:
    """Full distributed query step: windowed range function + cross-shard
    aggregate, SPMD over the ('shard', 'time') mesh.

    ts_off/values [D, S, T] sharded over 'shard'; wends [W] sharded over
    'time'.  Returns partial components [G, W, C] (replicated over 'shard',
    sharded over 'time') — call agg_ops.present() to finish.
    """
    combiner = agg_ops.AGGREGATORS[agg_op].combiner

    def step(ts_blk, val_blk, gid_blk, wends_blk, vbase_blk):
        # ts_blk [1, S, T] — this device column's shard; wends_blk [W/nt]
        res = evaluate_range_function(ts_blk[0], val_blk[0], wends_blk,
                                      range_ms, fn_name, params, base_ms,
                                      vbase=vbase_blk[0],
                                      precorrected=precorrected)
        part = agg_ops.map_phase(agg_op, res, gid_blk[0], num_groups)
        if combiner == "sum":
            part = jax.lax.psum(part, "shard")
        elif combiner == "min":
            part = jax.lax.pmin(part, "shard")
        else:
            part = jax.lax.pmax(part, "shard")
        return part

    return jax.shard_map(
        step, mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", None, None),
                  P("shard", None), P("time"), P("shard", None)),
        out_specs=P(None, "time", None))(ts_off, values, group_ids, wends,
                                         vbase)


def distributed_window_raw(mesh: Mesh, ts_off, values, wends, *, range_ms,
                           fn_name, params=(), base_ms=0, vbase=None,
                           precorrected=False):
    """Eager wrapper: floats base_ms (see distributed_window_agg)."""
    if vbase is None:
        vbase = jnp.zeros(values.shape[:2], values.dtype)
    return _distributed_window_raw(mesh, ts_off, values, wends, vbase,
                                   range_ms=range_ms, fn_name=fn_name,
                                   params=params, base_ms=float(base_ms),
                                   precorrected=precorrected)


@functools.partial(
    jax.jit, static_argnames=("mesh", "fn_name", "params", "precorrected"))
def _distributed_window_raw(mesh: Mesh,
                           ts_off: jax.Array, values: jax.Array,
                           wends: jax.Array, vbase: jax.Array,
                           *, range_ms: int,
                           fn_name: Optional[str],
                           params: Tuple[float, ...] = (),
                           base_ms: int = 0,
                           precorrected: bool = False) -> jax.Array:
    """Un-aggregated distributed evaluation -> [D, S, W] (the DistConcatExec
    analogue: per-shard results stay sharded; host gathers lazily)."""

    def step(ts_blk, val_blk, wends_blk, vbase_blk):
        res = evaluate_range_function(ts_blk[0], val_blk[0], wends_blk,
                                      range_ms, fn_name, params, base_ms,
                                      vbase=vbase_blk[0],
                                      precorrected=precorrected)
        return res[None]

    return jax.shard_map(
        step, mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", None, None), P("time"),
                  P("shard", None)),
        out_specs=P("shard", None, "time"))(ts_off, values, wends, vbase)


# ----------------------------------------------------------- executor glue

class MeshExecutor:
    """Bridges a multi-shard TimeSeriesMemStore to the mesh SPMD path.

    The moral equivalent of the reference's QueryActor + ActorPlanDispatcher
    wiring, minus the actors: shard lookup happens host-side per shard (the
    Lucene-analogue index), data ships to mesh devices once, and the
    aggregate executes as one SPMD program.
    """

    def __init__(self, memstore, dataset: str, mesh: Mesh):
        self.memstore = memstore
        self.dataset = dataset
        self.mesh = mesh
        self.n_shard = mesh.shape["shard"]

    def lookup_and_pack(self, filters, start_ms: int, end_ms: int,
                        by: Sequence[str] = (),
                        without: Sequence[str] = (),
                        fn_name: Optional[str] = None
                        ) -> Optional[PackedShards]:
        """fn_name (the range function the pack will feed) selects counter
        semantics: counter columns are reset-corrected host-side in f64 so
        f32 deltas on device are exact — same contract as the leaf exec."""
        from filodb_tpu.ops.counter import rebase_values
        from filodb_tpu.ops.rangefns import RANGE_FUNCTIONS
        from filodb_tpu.ops.timewindow import to_offsets
        spec = RANGE_FUNCTIONS.get(fn_name or "")
        fn_is_counter = spec.is_counter if spec else False
        blocks = []
        precorrected = True
        for shard in self.memstore.shards_for(self.dataset):
            lookup = shard.lookup_partitions(filters, start_ms, end_ms)
            schema_name = lookup.first_schema
            parts = (lookup.parts_by_schema.get(schema_name, [])
                     if schema_name else [])
            if not parts:
                blocks.append((np.full((1, 1), PAD_TS, np.int32),
                               np.full((1, 1), np.nan), []))
                continue
            shard.ensure_paged(parts, start_ms, end_ms)
            ts, cols, counts, store = shard.gather_series(parts)
            schema = shard.schemas[schema_name]
            col_def = next((c for c in schema.data_columns
                            if c.name == schema.value_column), None)
            counter_col = col_def is not None and (col_def.detect_drops
                                                   or col_def.counter)
            correct = counter_col and fn_is_counter
            precorrected = precorrected and correct
            vals, vbase = rebase_values(cols[schema.value_column], correct)
            ts_off = to_offsets(ts, counts, start_ms)
            labels = [{**p.part_key.tags_dict, "_metric_": p.part_key.metric}
                      for p in parts]
            blocks.append((ts_off, vals, labels, vbase))
        if not blocks:
            return None
        if len(blocks) > self.n_shard:
            raise ValueError(
                f"memstore has {len(blocks)} shards but mesh shard axis is "
                f"{self.n_shard}; data would be silently dropped")
        # pad shard list to mesh size
        while len(blocks) < self.n_shard:
            blocks.append((np.full((1, 1), PAD_TS, np.int32),
                           np.full((1, 1), np.nan), []))
        packed = pack_shards(blocks, by=by, without=without, base_ms=start_ms,
                             precorrected=precorrected)
        return device_put_packed(packed, self.mesh)

    def run_agg(self, packed: PackedShards, wends: np.ndarray, *,
                range_ms: int, fn_name: Optional[str], agg_op: str,
                params: Tuple[float, ...] = ()) -> Tuple[np.ndarray, List[Dict[str, str]]]:
        """Returns (final [G, W] values, group label dicts).

        wends are ABSOLUTE ms (same clock as lookup_and_pack's time range);
        they are rebased onto the pack's offset base here."""
        wends = np.asarray(wends, np.int64) - packed.base_ms
        if wends.size and (wends.max() >= (1 << 30) or
                           wends.min() <= -(1 << 30)):
            raise ValueError("window ends more than ~12 days from the packed "
                             "base; split the query by time range")
        wends = wends.astype(np.int32)
        W = wends.shape[0]
        n_time = self.mesh.shape["time"]
        # pad the window grid to a multiple of the time axis; padded windows
        # end before all data (-PAD_TS) so they are empty, not garbage
        Wp = -(-W // n_time) * n_time
        if Wp != W:
            wends = np.concatenate(
                [wends, np.full(Wp - W, -PAD_TS, np.int32)])
        wends_dev = jax.device_put(
            wends, NamedSharding(self.mesh, P("time")))
        partials = distributed_window_agg(
            self.mesh, packed.ts_off, packed.values, packed.group_ids,
            wends_dev, range_ms=range_ms, fn_name=fn_name, params=params,
            agg_op=agg_op, num_groups=packed.num_groups,
            base_ms=packed.base_ms, vbase=packed.vbase,
            precorrected=packed.precorrected)
        out = agg_ops.present(agg_op, partials)
        return np.asarray(out)[:, :W], packed.group_labels
