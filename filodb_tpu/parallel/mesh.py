"""Distributed query execution over a JAX device mesh.

This is the TPU-native replacement for the reference's distributed exec tree:
where FiloDB dispatches serialized ExecPlan subtrees to shard-owner nodes via
Akka and tree-reduces partial aggregates through ReduceAggregateExec
(ref: query/.../exec/PlanDispatcher.scala:20-57, exec/AggrOverRangeVectors.scala
:51-123, doc/query-engine.md:90-155), we lay the per-shard dense series arrays
out on a device mesh and let XLA collectives do the reduce:

  mesh axes:  ('shard', 'time')
    - 'shard': data parallelism over series — each device (or device column)
      owns the series of one FiloDB shard, the moral equivalent of
      1 shard = 1 node (ref: doc/sharding.md:23-56).
    - 'time':  sequence parallelism over the *output window grid* — each
      device row computes a contiguous slice of the PromQL step grid, the
      TPU analogue of the planner's time-range splitting + StitchRvsExec
      (ref: SingleClusterPlanner.scala:91-117).

  collectives: the 3-phase aggregate contract (map/reduce/present,
  doc/query-engine.md:311-330) maps onto shard_map as
      map_phase on-device per shard  ->  psum/pmin/pmax over the 'shard'
      axis (ICI)  ->  present host-side,
  so cross-shard aggregation rides ICI instead of Kryo-over-TCP.

All shapes are static under jit: shards are padded to a uniform
[series_per_shard, time] block and padded rows carry NaN values, which the
map phase masks out (same trick the single-shard path uses for ragged data).
"""
from __future__ import annotations

import dataclasses
import functools
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from filodb_tpu.ops import agg as agg_ops
from filodb_tpu.ops.rangefns import evaluate_range_function
from filodb_tpu.ops.timewindow import PAD_TS
from filodb_tpu.utils.jaxcompat import has_ici, shard_map


# --------------------------------------------------------------------- mesh

def make_mesh(n_shard: int, n_time: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ('shard', 'time') mesh from the first n_shard*n_time devices.

    Devices beyond n_shard*n_time are left out of the mesh; that
    truncation used to be silent — an operator sizing a pod for 8-way
    scaling with a 6-shard dataset would quietly idle 2 chips.  The
    unused count is logged once and the chosen shape exposed as gauges
    (`mesh_shard_axis` / `mesh_time_axis` / `mesh_unused_devices`)."""
    from filodb_tpu.utils.metrics import log_error_once, registry
    devs = list(devices if devices is not None else jax.devices())
    need = n_shard * n_time
    if len(devs) < need:
        raise ValueError(f"need {need} devices, have {len(devs)}")
    if len(devs) > need:
        log_error_once(
            "mesh_unused_devices",
            RuntimeWarning(
                f"mesh ({n_shard} shard x {n_time} time) uses {need} of "
                f"{len(devs)} devices; {len(devs) - need} idle — resize "
                f"the mesh axes to cover the pod"))
    registry.gauge("mesh_shard_axis").update(n_shard)
    registry.gauge("mesh_time_axis").update(n_time)
    registry.gauge("mesh_unused_devices").update(len(devs) - need)
    grid = np.array(devs[:need]).reshape(n_shard, n_time)
    return Mesh(grid, ("shard", "time"))


# ---------------------------------------------------------------- packing

@dataclasses.dataclass
class PackedShards:
    """Host-side uniform pack of per-shard series blocks.

    ts_off  [D, S, T] int32 window-offset timestamps (PAD_TS past each row)
    values  [D, S, T] float  (NaN for padded rows)
    group_ids [D, S] int32   global aggregation-group slot per series row
    num_groups               static group count (for segment reductions)
    group_labels             slot -> label dict (for presenting results)
    base_ms                  common timestamp base
    n_series                 true (unpadded) series count per shard
    """
    ts_off: np.ndarray
    values: np.ndarray
    group_ids: np.ndarray
    num_groups: int
    group_labels: List[Dict[str, str]]
    base_ms: int
    n_series: np.ndarray
    # per-series value base subtracted host-side in f64 (ops/counter.
    # rebase_values) so counter deltas survive the f32 device downcast —
    # same contract as the single-shard leaf path (RawBlock.vbase)
    vbase: Optional[np.ndarray] = None      # [D, S]
    precorrected: bool = False
    # fused-kernel eligibility (ops/pallas_fused.py): when every real row
    # of every shard shares ONE scrape grid, the shared row (int32 [T],
    # PAD_TS tail) — else None.  Computed at pack time; `dense` qualifies
    # whether values are hole-free (dense kernel) or NaN-holed (ragged
    # kernel variant).
    shared_ts_row: Optional[np.ndarray] = None
    # series per aggregation group over REAL rows (for present-count math)
    gsize: Optional[np.ndarray] = None
    # False when any counted cell is non-finite: the rate family then runs
    # its valid-boundary variant (staleness markers are absent samples).
    # Computed ONCE at pack time on the HOST arrays (packs are cached, so
    # the boolean scan amortizes; post-device_put the values are sharded
    # device arrays a lazy scan would have to transfer back).
    dense: bool = True
    # host-side per-shard pid arrays in pack-row order (None for empty
    # shards): lets run_agg_batch recompute OTHER groupings over the SAME
    # rows without re-gathering (the mesh analogue of the leaf path's
    # PaddedValues/PaddedGroups split)
    pids_by_shard: Optional[List[np.ndarray]] = None
    # host-side views of the packed arrays, kept on backends without an
    # MXU (device_put_packed): the per-device dispatcher's host fused
    # route (ops/hostleaf) reads these instead of pulling device copies
    # back per query.  None on TPU — there the kernel path serves.
    host_values: Optional[np.ndarray] = None
    host_vbase: Optional[np.ndarray] = None
    host_group_ids: Optional[np.ndarray] = None

    @property
    def n_shards(self) -> int:
        return self.ts_off.shape[0]


class GroupRegistry:
    """Global aggregation-group slot assignment shared across shards (and
    across queries, when cached by MeshExecutor): group key -> stable slot.
    Group identity follows by/without label semantics (ref:
    exec/AggrOverRangeVectors.scala AggregateMapReduce grouping)."""

    def __init__(self, by: Sequence[str] = (), without: Sequence[str] = ()):
        self.by = frozenset(by) if by else None
        self.drop = (set(without) | {"_metric_", "__name__"}) if without else None
        self.slot_of: Dict[Tuple[Tuple[str, str], ...], int] = {}
        self.labels: List[Dict[str, str]] = []

    def slot_for(self, items: Tuple[Tuple[str, str], ...]) -> int:
        """items: the series' sorted (label, value) tuple."""
        if self.by is not None:
            key = tuple((k, v) for k, v in items if k in self.by)
        elif self.drop is not None:
            key = tuple((k, v) for k, v in items if k not in self.drop)
        else:
            key = ()
        slot = self.slot_of.get(key)
        if slot is None:
            slot = len(self.labels)
            self.slot_of[key] = slot
            self.labels.append(dict(key))
        return slot


def pack_shards(blocks: Sequence[Tuple],
                by: Sequence[str] = (), without: Sequence[str] = (),
                base_ms: int = 0,
                pad_series_to: Optional[int] = None,
                pad_time_to: Optional[int] = None,
                precorrected: bool = False,
                group_labels: Optional[List[Dict[str, str]]] = None
                ) -> PackedShards:
    """Pack per-shard (ts_off [S,T], vals [S,T], series label dicts[,
    vbase [S]]) into the uniform [D, S, T] layout, assigning
    globally-consistent group slots.

    Group identity follows the reference's by/without label semantics
    (ref: exec/AggrOverRangeVectors.scala AggregateMapReduce grouping):
    group key = labels restricted to `by` (or all minus `without`).

    Each block's third element is either a per-series label sequence
    (dicts or sorted (k, v) tuples) grouped here, or a precomputed int32
    gid array already compacted to [0, len(group_labels)) — the cached
    fast path that avoids per-series Python work entirely (see
    MeshExecutor._gids_for, which also does the per-query compaction).
    """
    D = len(blocks)
    S = pad_series_to or max((b[0].shape[0] for b in blocks), default=1)
    T = pad_time_to or max((b[0].shape[1] for b in blocks), default=1)
    S, T = max(S, 1), max(T, 1)

    reg = GroupRegistry(by, without)

    ts = np.full((D, S, T), PAD_TS, dtype=np.int32)
    vals = np.full((D, S, T), np.nan, dtype=np.float64)
    gids = np.zeros((D, S), dtype=np.int32)
    nser = np.zeros(D, dtype=np.int32)
    vbase = np.zeros((D, S), dtype=np.float64)
    any_vbase = False

    for d, blk in enumerate(blocks):
        t, v, labels = blk[0], blk[1], blk[2]
        if len(blk) > 3 and blk[3] is not None:
            vbase[d, :len(blk[3])] = blk[3]
            any_vbase = True
        s, tt = t.shape
        ts[d, :s, :tt] = t
        vals[d, :s, :tt] = v
        # real series = labeled rows; empty-shard placeholder blocks carry
        # a single all-PAD row with NO labels — that row is padding, not
        # data (it must not count toward group sizes or grid uniformity)
        if isinstance(labels, np.ndarray):
            nser[d] = labels.shape[0]
            gids[d, :labels.shape[0]] = labels
        else:
            nser[d] = min(s, len(labels))
            for i, lab in enumerate(labels):
                items = (lab if isinstance(lab, tuple)
                         else tuple(sorted(lab.items())))
                gids[d, i] = reg.slot_for(items)

    labels_out = group_labels if group_labels is not None else list(reg.labels)
    num_groups = max(len(labels_out), 1)
    # fused-kernel eligibility: one shared grid across every real row.
    # Per-shard views with early exit — no [N, T] fancy-index copies (packs
    # run for every query shape, most of which can't fuse anyway).
    shared_row = None
    ref = None
    for d in range(D):
        n = nser[d]
        if n == 0:
            continue
        if ref is None:
            ref = ts[d, 0]
        rows = ts[d, :n]
        if not (rows == ref[None, :]).all():
            ref = None
            break
    if ref is not None:
        shared_row = ref.copy()
    gsize = np.zeros(num_groups, dtype=np.int64)
    for d in range(D):
        if nser[d]:
            gsize += np.bincount(gids[d, :nser[d]],
                                 minlength=num_groups)[:num_groups]
    # dense = every counted cell finite.  Tracked SEPARATELY from grid
    # sharing (r4): a uniform-grid pack with NaN holes keeps its
    # shared_ts_row and runs the RAGGED fused kernel variant.  isfinite,
    # not isnan: an inf sample would be clamped by the dense kernel
    # wrapper's nan_to_num and silently change query results.
    dense = all(
        nser[d] == 0
        or bool((np.isfinite(vals[d, :nser[d]])
                 | (ts[d, :nser[d]] >= PAD_TS)).all())
        for d in range(D))
    return PackedShards(ts, vals, gids, num_groups,
                        labels_out, base_ms, nser,
                        vbase=vbase if any_vbase else None,
                        precorrected=precorrected,
                        shared_ts_row=shared_row, gsize=gsize,
                        dense=dense)


def device_put_packed(packed: PackedShards, mesh: Mesh) -> PackedShards:
    """Place packed arrays on the mesh: series data sharded over 'shard',
    replicated over 'time' (each time-row needs the full series to evaluate
    any window slice — windows reach back `range` into the data)."""
    data_spec = NamedSharding(mesh, P("shard", None, None))
    gid_spec = NamedSharding(mesh, P("shard", None))
    # host-side views feed only the host fused route, which serves dense
    # packs exclusively — keeping them for ragged packs would hold a
    # full extra [D, S, T] copy per cache entry that nothing ever reads
    keep_host = jax.default_backend() != "tpu" and packed.dense
    return dataclasses.replace(
        packed,
        ts_off=jax.device_put(packed.ts_off, data_spec),
        values=jax.device_put(packed.values, data_spec),
        group_ids=jax.device_put(packed.group_ids, gid_spec),
        vbase=(None if packed.vbase is None
               else jax.device_put(packed.vbase, gid_spec)),
        host_values=(np.asarray(packed.values) if keep_host else None),
        host_vbase=(np.asarray(packed.vbase)
                    if keep_host and packed.vbase is not None else None),
        host_group_ids=(np.asarray(packed.group_ids)
                        if keep_host else None))


# ------------------------------------------------------------ SPMD kernels

@functools.partial(jax.jit, static_argnames=(
    "G", "S", "T", "Tp", "gather", "is_counter", "is_rate", "interpret",
    "kind", "ragged"))
def _pad_run_single(v, vb, g, mats, *, G: int, S: int, T: int, Tp: int,
                    gather: bool, is_counter: bool, is_rate: bool,
                    interpret: bool, kind: str, ragged: bool):
    """Pad ONE device's [S, T] values + [S, P] grouping (P > 1:
    run_agg_batch panels over disjoint group-id ranges, multi-hot kernel
    epilogue) to kernel tile shapes and run the single-chip kernel — the
    shared map-phase body of the per-device dispatch
    (_device_fused_call) and the legacy fused-in-shard_map A/B probe
    (_mesh_fused_call), so their padding semantics can never diverge.

    Dense packs: NaN cells are exactly pad rows / beyond-count columns,
    zeroed they contribute nothing (pack pad rows carry gid 0 but add +0
    to its sums).  Ragged packs keep their NaNs — the kernel's fill
    scans treat them as absent samples; pad rows become all-NaN rows
    whose presence is 0.  with_drops is always False here: counter
    functions require a precorrected pack."""
    from filodb_tpu.ops import pallas_fused as pf
    Gp = pf.pad_group_count(G)
    Sp = pf.pad_series_count(S)
    v = v.astype(jnp.float32)
    if ragged:
        v = jnp.pad(v, ((0, Sp - S), (0, Tp - T)), constant_values=np.nan)
    else:
        v = jnp.pad(jnp.nan_to_num(v), ((0, Sp - S), (0, Tp - T)))
    vb = jnp.pad(vb.astype(jnp.float32), (0, Sp - S))[:, None]
    g = jnp.pad(g.astype(jnp.int32), ((0, Sp - S), (0, 0)),
                constant_values=-1)
    return pf.run_kernel(v, vb, g, *mats, gather=gather, num_groups=Gp,
                         is_counter=is_counter, is_rate=is_rate,
                         with_drops=False, interpret=interpret, kind=kind,
                         ragged=ragged)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "G", "S", "T", "Tp", "is_counter", "is_rate", "interpret",
    "kind", "ragged"))
def _mesh_fused_call(mesh: Mesh, values, group_ids, vbase,
                     o1, o2, l1, l2, t1, t2, n, ws, we, ts, i1, i2, *,
                     G: int, S: int, T: int, Tp: int,
                     is_counter: bool, is_rate: bool, interpret: bool,
                     kind: str = "rate_family", ragged: bool = False):
    """LEGACY A/B path: the Pallas fused kernel traced INSIDE shard_map.

    Kept only for measurement tooling (tools/tpu_extra.py, the driver
    dryrun, bench.py multichip's inversion probe): on a multi-device
    mesh this composition collapses ~30x vs the general path
    (MULTICHIP_r05.json) because the kernel re-traces and schedules per
    mesh program.  Production queries route through the per-device
    dispatch below (_device_fused_call + merge_device_partials), which
    never puts the kernel under shard_map; see doc/multichip.md."""
    from filodb_tpu.ops import pallas_fused as pf
    gather = pf.gather_default(kind)

    def step(val_blk, gid_blk, vb_blk, *mat_blks):
        res = _pad_run_single(val_blk[0], vb_blk[0], gid_blk[0],
                              tuple(m[0] for m in mat_blks), G=G, S=S,
                              T=T, Tp=Tp, gather=gather,
                              is_counter=is_counter, is_rate=is_rate,
                              interpret=interpret, kind=kind,
                              ragged=ragged)
        if ragged:
            sums, cnts = res
            return (jax.lax.psum(sums[:G], "shard"),
                    jax.lax.psum(cnts[:G], "shard"))
        return jax.lax.psum(res[:G], "shard")          # [G, Wlp]

    return shard_map(
        step, mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", None, None),
                  P("shard", None)) + (P("time", None, None),) * 12,
        out_specs=((P(None, "time"), P(None, "time")) if ragged
                   else P(None, "time")),
        # pallas_call's out_shape carries no varying-mesh-axes info, which
        # trips shard_map's vma checker; the psum makes the output
        # replicated over 'shard' by construction
        check_vma=False)(values, group_ids, vbase,
                         o1, o2, l1, l2, t1, t2, n, ws, we, ts, i1, i2)


# ------------------------------------------------ per-device fused dispatch
#
# The multi-chip fused scan.  Tracing the Pallas kernel INSIDE shard_map
# (the _mesh_fused_call path above, kept for A/B tooling) inverted the
# kernel's single-chip win ~30x on an 8-device mesh (MULTICHIP_r05.json:
# warm 25.3 s fused vs 0.88 s general): the kernel + its grid loop were
# re-traced and scheduled per mesh program instead of dispatched as the
# single-chip binary.  The production path below never puts the kernel
# under shard_map: device (s, t) runs the SINGLE-CHIP kernel over its
# committed [S, T] shard block with time-slice t's plan, and only the
# [G, Wl] group partials cross chips — one tiny psum collective on ICI,
# a host-side ops/agg.reduce_phase merge otherwise.  That is exactly the
# reference's 3-phase map/reduce/present contract (doc/query-engine.md
# :311-330) with the map phase on-chip and the reduce over partials only.

@functools.partial(jax.jit, static_argnames=(
    "G", "S", "T", "Tp", "is_counter", "is_rate", "interpret", "kind",
    "ragged"))
def _device_fused_call(values, group_ids, vbase, o1, o2, l1, l2, t1, t2,
                       n, ws, we, ts, i1, i2, *, G: int, S: int, T: int,
                       Tp: int, is_counter: bool, is_rate: bool,
                       interpret: bool, kind: str = "rate_family",
                       ragged: bool = False):
    """One device's share of the multi-chip fused scan: the single-chip
    Pallas kernel over this device's [1, S, T] shard block.  Every
    operand is committed to the owning device, so the jit executes THERE
    (device-pinned dispatch — never inside shard_map) and only the
    [G, Wlp] group partials leave the chip.  The leading shard axis is
    kept so the pack's addressable shards feed straight in."""
    from filodb_tpu.ops import pallas_fused as pf
    res = _pad_run_single(values[0], vbase[0], group_ids[0],
                          (o1, o2, l1, l2, t1, t2, n, ws, we, ts, i1, i2),
                          G=G, S=S, T=T, Tp=Tp,
                          gather=pf.gather_default(kind),
                          is_counter=is_counter, is_rate=is_rate,
                          interpret=interpret, kind=kind, ragged=ragged)
    if ragged:
        return res[0][:G], res[1][:G]
    return res[:G]


@functools.partial(jax.jit, static_argnames=("mesh", "comb"))
def _merge_partials_collective(mesh: Mesh, x, *, comb: str = "sum"):
    """The cross-chip reduce of the 3-phase contract as ONE tiny jitted
    collective over group partials [D, G, n_time, Wlp] (psum/pmin/pmax
    over 'shard'; the [S, T] series blocks never ride a collective)."""
    def step(blk):
        p = blk[0]
        if comb == "sum":
            return jax.lax.psum(p, "shard")
        return (jax.lax.pmin if comb == "min" else jax.lax.pmax)(p, "shard")
    return shard_map(step, mesh=mesh,
                     in_specs=P("shard", None, "time", None),
                     out_specs=P(None, "time", None))(x)


def merge_device_partials(parts: Dict[Tuple[int, int], jax.Array],
                          mesh: Mesh, comb: str = "sum",
                          collective: Optional[bool] = None) -> np.ndarray:
    """Merge per-device [G, Wlp] partials -> [G, n_time * Wlp] float64.

    parts[(s, t)] is mesh device (s, t)'s partial (shard s, time-slice
    t).  With ICI (TPU backend) the merge is one jitted collective over
    the partials only; host platforms emulate collectives through host
    memory anyway, so there the partials come host-side in one
    device_get and merge with ops/agg.reduce_phase combiner semantics in
    ascending shard order — deterministic, and bit-stable across runs."""
    n_shard, n_time = mesh.shape["shard"], mesh.shape["time"]
    G, Wlp = parts[(0, 0)].shape
    if collective is None:
        collective = has_ici()
    if collective and n_shard > 1:
        pieces = [jnp.reshape(parts[(s, t)], (1, G, 1, Wlp))
                  for s in range(n_shard) for t in range(n_time)]
        sh = NamedSharding(mesh, P("shard", None, "time", None))
        glob = jax.make_array_from_single_device_arrays(
            (n_shard, G, n_time, Wlp), sh, pieces)
        from filodb_tpu.utils.metrics import registry
        registry.counter("mesh_partials_collective_merge").increment()
        out = np.asarray(_merge_partials_collective(mesh, glob, comb=comb),
                         dtype=np.float64)
        return out.reshape(G, n_time * Wlp)
    ordered = [parts[(s, t)] for t in range(n_time)
               for s in range(n_shard)]
    host = [np.asarray(a, np.float64) for a in jax.device_get(ordered)]
    from filodb_tpu.utils.metrics import registry
    registry.counter("mesh_partials_host_merge").increment()
    cols = []
    for t in range(n_time):
        acc = host[t * n_shard]
        for s in range(1, n_shard):
            nxt = host[t * n_shard + s]
            if comb == "sum":
                acc = acc + nxt
            elif comb == "min":
                acc = np.minimum(acc, nxt)
            else:
                acc = np.maximum(acc, nxt)
        cols.append(acc)
    return np.concatenate(cols, axis=1)


def distributed_window_agg(mesh: Mesh, ts_off, values, group_ids, wends, *,
                           range_ms, fn_name, params=(), agg_op="sum",
                           num_groups=1, base_ms=0, vbase=None,
                           precorrected=False, dense=True):
    """Eager wrapper: floats base_ms before the jit boundary (epoch-ms ints
    overflow int32 canonicalization on no-x64 TPU; see rangefns)."""
    if vbase is None:
        vbase = jnp.zeros(values.shape[:2], values.dtype)
    return _distributed_window_agg(mesh, ts_off, values, group_ids, wends,
                                   vbase,
                                   range_ms=range_ms, fn_name=fn_name,
                                   params=params, agg_op=agg_op,
                                   num_groups=num_groups,
                                   base_ms=float(base_ms),
                                   precorrected=precorrected, dense=dense)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "fn_name", "params", "agg_op", "num_groups",
                     "precorrected", "dense"))
def _distributed_window_agg(mesh: Mesh,
                           ts_off: jax.Array, values: jax.Array,
                           group_ids: jax.Array, wends: jax.Array,
                           vbase: jax.Array,
                           *, range_ms: int, fn_name: Optional[str],
                           params: Tuple[float, ...] = (),
                           agg_op: str = "sum", num_groups: int = 1,
                           base_ms: int = 0,
                           precorrected: bool = False,
                           dense: bool = True) -> jax.Array:
    """Full distributed query step: windowed range function + cross-shard
    aggregate, SPMD over the ('shard', 'time') mesh.

    ts_off/values [D, S, T] sharded over 'shard'; wends [W] sharded over
    'time'.  Returns partial components [G, W, C] (replicated over 'shard',
    sharded over 'time') — call agg_ops.present() to finish.
    """
    def _collective(comb, x):
        if comb == "sum":
            return jax.lax.psum(x, "shard")
        return (jax.lax.pmin if comb == "min" else jax.lax.pmax)(x, "shard")

    def step(ts_blk, val_blk, gid_blk, wends_blk, vbase_blk):
        # ts_blk [1, S, T] — this device column's shard; wends_blk [W/nt]
        res = evaluate_range_function(ts_blk[0], val_blk[0], wends_blk,
                                      range_ms, fn_name, params, base_ms,
                                      vbase=vbase_blk[0],
                                      precorrected=precorrected,
                                      dense=dense)
        part = agg_ops.map_phase(agg_op, res, gid_blk[0], num_groups)
        combs = agg_ops.combiners_for(agg_op, part.shape[-1])
        if len(set(combs)) == 1:
            return _collective(combs[0], part)
        return jnp.stack([_collective(c, part[..., i])
                          for i, c in enumerate(combs)], axis=-1)

    return shard_map(
        step, mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", None, None),
                  P("shard", None), P("time"), P("shard", None)),
        out_specs=P(None, "time", None))(ts_off, values, group_ids, wends,
                                         vbase)


def distributed_window_raw(mesh: Mesh, ts_off, values, wends, *, range_ms,
                           fn_name, params=(), base_ms=0, vbase=None,
                           precorrected=False, dense=True):
    """Eager wrapper: floats base_ms (see distributed_window_agg)."""
    if vbase is None:
        vbase = jnp.zeros(values.shape[:2], values.dtype)
    return _distributed_window_raw(mesh, ts_off, values, wends, vbase,
                                   range_ms=range_ms, fn_name=fn_name,
                                   params=params, base_ms=float(base_ms),
                                   precorrected=precorrected, dense=dense)


@functools.partial(
    jax.jit, static_argnames=("mesh", "fn_name", "params", "precorrected",
                              "dense"))
def _distributed_window_raw(mesh: Mesh,
                           ts_off: jax.Array, values: jax.Array,
                           wends: jax.Array, vbase: jax.Array,
                           *, range_ms: int,
                           fn_name: Optional[str],
                           params: Tuple[float, ...] = (),
                           base_ms: int = 0,
                           precorrected: bool = False,
                           dense: bool = True) -> jax.Array:
    """Un-aggregated distributed evaluation -> [D, S, W] (the DistConcatExec
    analogue: per-shard results stay sharded; host gathers lazily)."""

    def step(ts_blk, val_blk, wends_blk, vbase_blk):
        res = evaluate_range_function(ts_blk[0], val_blk[0], wends_blk,
                                      range_ms, fn_name, params, base_ms,
                                      vbase=vbase_blk[0],
                                      precorrected=precorrected,
                                      dense=dense)
        return res[None]

    return shard_map(
        step, mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", None, None), P("time"),
                  P("shard", None)),
        out_specs=P("shard", None, "time"))(ts_off, values, wends, vbase)


def _host_counts(gsize: np.ndarray, wvalid: np.ndarray) -> np.ndarray:
    """Dense-pack present counts: every REAL series emits a value exactly
    where the shared window is valid — counts[g, w] = |group g| * valid[w].
    The single home of the formula for both the kernel-route epilogue and
    the dense count panels (_finish_count_panels)."""
    return gsize[:, None] * wvalid[None, :].astype(np.float64)


# ----------------------------------------------------------- executor glue

class MeshExecutor:
    """Bridges a multi-shard TimeSeriesMemStore to the mesh SPMD path.

    The moral equivalent of the reference's QueryActor + ActorPlanDispatcher
    wiring, minus the actors: shard lookup happens host-side per shard (the
    Lucene-analogue index), data ships to mesh devices once, and the
    aggregate executes as one SPMD program.
    """

    def __init__(self, memstore, dataset: str, mesh: Mesh):
        self.memstore = memstore
        self.dataset = dataset
        self.mesh = mesh
        self.n_shard = mesh.shape["shard"]
        # (by, without) -> (GroupRegistry, per-shard pid->slot arrays).
        # Slots are assigned once per series lifetime; repeat queries map
        # pids to group slots with one numpy gather instead of per-series
        # label work (ref: the reference re-groups every query — this is
        # a deliberate TPU-side improvement for the 1M-series target).
        self._group_caches: Dict[Tuple, Tuple[GroupRegistry, Dict[int, np.ndarray]]] = {}
        # Device-resident pack cache: the mesh analogue of the leaf path's
        # DeviceMirror (core/devicecache.py).  A pack is revalidated by
        # every shard's (partition count, store generations) signature —
        # unchanged data means repeat queries skip the host gather AND the
        # host->device transfer entirely; any ingest invalidates it and the
        # next query pays one re-upload (never worse than uncached).
        self._pack_cache: Dict[Tuple, Dict] = {}
        self._pack_cache_max = 8
        # packing-LAYOUT memo, validated against the actual per-shard
        # pid sets the index lookup returns: survives value-level
        # invalidations of _pack_cache, so live-ingest re-polls
        # re-upload values but never repack the layout (see
        # lookup_and_pack; mesh_pack_memo_hits counts the wins)
        self._pack_layout_memo: Dict[Tuple, Dict] = {}
        # fused-path plan/mats cache: (shared_ts_row, wends, range) ->
        # (device selection matrices, wvalid); see _run_agg_fused
        self._fused_plan_cache: Dict[Tuple, Tuple] = {}
        # run_agg_batch merged-gid cache: (id(pack), panels, fn) -> the
        # device-resident [D, S, P] grouping matrix (+ the pack ref to
        # pin identity), so a dashboard refresh loop over a warm pack
        # skips the per-panel host remaps AND the gid upload.  Panel-
        # grouping entries live in their own dict: with one shared dict
        # a gids_dev insert (cap 4) could purge recently cached panel
        # groupings (cap 8) and defeat the dashboard-refresh warm path
        self._batch_gid_cache: Dict[Tuple, Dict] = {}
        self._panel_group_cache: Dict[Tuple, Dict] = {}
        # queries can reach the executor from HTTP worker threads (same
        # contract as the leaf caches' _FUSED_CACHE_LOCK in query/exec.py):
        # every cache read-modify-write below holds this lock; device work
        # runs outside it
        self._cache_lock = threading.Lock()

    def _cluster_sig(self) -> Tuple:
        return tuple(
            (sh.shard_num, len(sh.partitions),
             tuple((name, st.generation)
                   for name, st in sorted(sh.stores.items())))
            for sh in self.memstore.shards_for(self.dataset))

    def _gids_for(self, shard, pids: np.ndarray,
                  by: Sequence[str], without: Sequence[str]
                  ) -> Tuple[np.ndarray, GroupRegistry]:
        ck = (tuple(by), tuple(without))
        # the whole resolve runs under the lock: GroupRegistry.slot_for is
        # check-then-insert (a race would assign one group key two slots and
        # permanently split its aggregates) and the per-shard array is
        # read-modify-written; keys_for is a fast snapshot read
        with self._cache_lock:
            entry = self._group_caches.get(ck)
            if entry is None:
                entry = (GroupRegistry(by, without), {})
                self._group_caches[ck] = entry
            reg, per_shard = entry
            arr = per_shard.get(shard.shard_num)
            n = len(shard.partitions)
            if arr is None:
                arr = np.full(n, -1, dtype=np.int32)
            elif arr.shape[0] < n:
                arr = np.concatenate(
                    [arr, np.full(n - arr.shape[0], -1, dtype=np.int32)])
            need = arr[pids] < 0
            if need.any():
                new_pids = pids[need]
                keys = shard.keys_for(new_pids)
                for pid, key in zip(new_pids.tolist(), keys):
                    arr[pid] = reg.slot_for(key.labels)
            per_shard[shard.shard_num] = arr
            return arr[pids], reg

    def lookup_and_pack(self, filters, start_ms: int, end_ms: int,
                        by: Sequence[str] = (),
                        without: Sequence[str] = (),
                        fn_name: Optional[str] = None
                        ) -> Optional[PackedShards]:
        """fn_name (the range function the pack will feed) selects counter
        semantics: counter columns are reset-corrected host-side in f64 so
        f32 deltas on device are exact — same contract as the leaf exec.

        Packs are cached on device: a repeat query over unchanged data
        (validated by per-shard generation signatures) reuses the resident
        arrays — run_agg rebases any window grid onto the pack's base, so
        the cache serves rolling windows too, as long as the requested
        start doesn't reach below what the pack was paged for."""
        from filodb_tpu.ops.counter import rebase_values
        from filodb_tpu.ops.rangefns import RANGE_FUNCTIONS
        from filodb_tpu.ops.timewindow import to_offsets
        from filodb_tpu.utils.metrics import registry as metrics_registry
        ck = (tuple(str(f) for f in filters), tuple(by), tuple(without),
              fn_name)
        sig = self._cluster_sig()
        with self._cache_lock:
            # stale entries pin device memory for nothing — drop eagerly
            for k in [k for k, e in self._pack_cache.items()
                      if e["sig"] != sig]:
                del self._pack_cache[k]
            ent = self._pack_cache.get(ck)
            # a hit needs the requested range INSIDE the cached one: the
            # index prunes series by time, so a later end could admit
            # series the cached pack never gathered
            if ent is not None and ent["start_ms"] <= start_ms \
                    and ent["end_ms"] >= end_ms:
                metrics_registry.counter("mesh_pack_cache_hits").increment()
                self._pack_cache[ck] = self._pack_cache.pop(ck)  # LRU touch
                return ent["packed"]
        spec = RANGE_FUNCTIONS.get(fn_name or "")
        fn_is_counter = spec.is_counter if spec else False
        shards = list(self.memstore.shards_for(self.dataset))
        if not shards:
            return None

        def gather_block(shard, pids, schema_name, state):
            """Value-level (re)gather for one shard's memoized row set."""
            shard.ensure_paged_pids(schema_name, pids, start_ms, end_ms)
            store = shard.stores[schema_name]
            rows = shard.rows_for(pids)
            ts, cols, counts = shard.snapshot_read(
                store, lambda: store.gather_rows(rows))
            schema = shard.schemas[schema_name]
            col_def = next((c for c in schema.data_columns
                            if c.name == schema.value_column), None)
            counter_col = col_def is not None and (col_def.detect_drops
                                                   or col_def.counter)
            correct = counter_col and fn_is_counter
            state["precorrected"] = state["precorrected"] and correct
            vals, vbase = rebase_values(cols[schema.value_column], correct)
            return to_offsets(ts, counts, start_ms), vals, vbase

        # Packing LAYOUT memo: the row order, group-slot arrays, labels
        # and schema routing depend only on the per-shard pid SETS the
        # index lookup returns — so a re-poll whose lookup yields the
        # SAME pid sets (the common live-ingest case: values appended,
        # no index change admitting or pruning different series for the
        # new range) reuses the memoized grouping/labels and skips the
        # per-series Python of group resolution + slot compaction.
        # Validity is checked against the ACTUAL lookup result, never
        # inferred from generation counters: new-series ingest and
        # time-range drift both change the pid sets without necessarily
        # moving keys_serial/keys_epoch.  lookup_partitions is itself
        # memoized per (filters, range, index.mutations, keys_epoch)
        # (core/shard.py), so the guard costs one cached lookup + pid
        # array compare per shard.
        lookups: List[Tuple[Optional[np.ndarray], Optional[str]]] = []
        for shard in shards:
            lookup = shard.lookup_partitions(filters, start_ms, end_ms)
            schema_name = lookup.first_schema
            pids = (lookup.pids_by_schema.get(schema_name)
                    if schema_name else None)
            if pids is None or pids.size == 0:
                lookups.append((None, None))
            else:
                lookups.append((np.asarray(pids), schema_name))

        def _memo_valid(memo):
            if len(memo["pids"]) != len(lookups):
                return False
            return all(
                sch == msch and ((pids is None and mp is None)
                                 or (pids is not None and mp is not None
                                     and np.array_equal(pids, mp)))
                for (pids, sch), mp, msch in zip(lookups, memo["pids"],
                                                 memo["schemas"]))

        with self._cache_lock:
            memo = self._pack_layout_memo.get(ck)
            if memo is not None and _memo_valid(memo):
                self._pack_layout_memo[ck] = self._pack_layout_memo.pop(ck)
            else:
                memo = None
        state = {"precorrected": True}
        blocks = []
        pids_by_shard = []
        if memo is not None:
            metrics_registry.counter("mesh_pack_memo_hits").increment()
            for shard, (pids, schema_name), gids in zip(
                    shards, lookups, memo["gids"]):
                if pids is None:
                    blocks.append((np.full((1, 1), PAD_TS, np.int32),
                                   np.full((1, 1), np.nan), []))
                    pids_by_shard.append(None)
                    continue
                pids_by_shard.append(pids)
                ts_off, vals, vbase = gather_block(shard, pids,
                                                   schema_name, state)
                blocks.append((ts_off, vals, gids, vbase))
            labels = memo["labels"]
        else:
            metrics_registry.counter("mesh_pack_memo_misses").increment()
            registry = None
            schemas_by_shard: List[Optional[str]] = []
            for shard, (pids, schema_name) in zip(shards, lookups):
                if pids is None:
                    blocks.append((np.full((1, 1), PAD_TS, np.int32),
                                   np.full((1, 1), np.nan), []))
                    pids_by_shard.append(None)
                    schemas_by_shard.append(None)
                    continue
                pids_by_shard.append(pids)
                schemas_by_shard.append(schema_name)
                ts_off, vals, vbase = gather_block(shard, pids,
                                                   schema_name, state)
                gids, registry = self._gids_for(shard, pids, by, without)
                blocks.append((ts_off, vals, gids, vbase))
            # Compact global registry slots to this query's groups only,
            # so a narrow filter never emits phantom groups from earlier
            # queries and num_groups (-> jit shapes) doesn't grow
            # unboundedly.
            labels = None
            if registry is not None:
                arrs = [b[2] for b in blocks
                        if isinstance(b[2], np.ndarray)]
                uniq = (np.unique(np.concatenate(arrs)) if arrs
                        else np.zeros(0, dtype=np.int32))
                labels = [registry.labels[int(g)] for g in uniq]
                blocks = [(b[0], b[1],
                           (np.searchsorted(uniq, b[2]).astype(np.int32)
                            if isinstance(b[2], np.ndarray) else b[2]),
                           *b[3:]) for b in blocks]
            with self._cache_lock:
                self._pack_layout_memo[ck] = {
                    "pids": list(pids_by_shard),
                    "gids": [(b[2] if isinstance(b[2], np.ndarray)
                              else None) for b in blocks],
                    "schemas": schemas_by_shard,
                    "labels": labels}
                while len(self._pack_layout_memo) > 8:
                    self._pack_layout_memo.pop(
                        next(iter(self._pack_layout_memo)))
        precorrected = state["precorrected"]
        if len(blocks) > self.n_shard:
            raise ValueError(
                f"memstore has {len(blocks)} shards but mesh shard axis is "
                f"{self.n_shard}; data would be silently dropped")
        # pad shard list to mesh size
        while len(blocks) < self.n_shard:
            blocks.append((np.full((1, 1), PAD_TS, np.int32),
                           np.full((1, 1), np.nan), []))
        packed = pack_shards(blocks, by=by, without=without, base_ms=start_ms,
                             precorrected=precorrected, group_labels=labels)
        packed.pids_by_shard = pids_by_shard
        packed = device_put_packed(packed, self.mesh)
        # cache under the PRE-gather signature: a concurrent ingest landing
        # mid-gather then invalidates the entry (over-invalidation is safe;
        # re-reading the signature here could cache a pack MISSING those
        # samples under the post-ingest generation and serve it as fresh).
        # ODP during the first gather also bumps generations, so the second
        # query re-packs once and stabilizes from the third on.
        with self._cache_lock:
            self._pack_cache[ck] = {"sig": sig,
                                    "start_ms": start_ms, "end_ms": end_ms,
                                    "packed": packed}
            while len(self._pack_cache) > self._pack_cache_max:
                self._pack_cache.pop(next(iter(self._pack_cache)))
        metrics_registry.counter("mesh_pack_cache_misses").increment()
        return packed

    def _prep_wends(self, packed: PackedShards, wends: np.ndarray
                    ) -> Tuple[np.ndarray, int]:
        """Rebase absolute window ends onto the pack's offset base and pad
        the grid to a multiple of the time axis; padded windows end before
        all data (-PAD_TS) so they are empty, not garbage."""
        wends = np.asarray(wends, np.int64) - packed.base_ms
        if wends.size and (wends.max() >= (1 << 30) or
                           wends.min() <= -(1 << 30)):
            raise ValueError("window ends more than ~12 days from the packed "
                             "base; split the query by time range")
        wends = wends.astype(np.int32)
        W = wends.shape[0]
        n_time = self.mesh.shape["time"]
        Wp = -(-W // n_time) * n_time
        if Wp != W:
            wends = np.concatenate(
                [wends, np.full(Wp - W, -PAD_TS, np.int32)])
        return wends, W

    def run_agg_batch(self, filters, start_ms: int, end_ms: int,
                      wends: np.ndarray, *, range_ms: int,
                      fn_name: Optional[str],
                      panels) -> List[Tuple[np.ndarray, List[Dict[str, str]]]]:
        """A dashboard's panels over one packed working set: panels is
        [(by, without, agg_op)]; returns [(values [G, W], labels)] in
        panel order.

        The mesh analogue of engine.query_range_batch: the values are
        packed ONCE (grouping recomputed per panel over the same rows via
        pids_by_shard), and every fused-eligible panel merges into ONE
        shard_map kernel dispatch over disjoint group-id ranges
        (_run_agg_fused_multi multi-hot epilogue).  Ineligible panels —
        and all panels when the shared fused gate rejects — fall back to
        run_agg per panel, where the pack cache still dedups the gather
        for repeated groupings."""
        by0, wo0, _ = panels[0]
        packed = self.lookup_and_pack(filters, start_ms, end_ms, by=by0,
                                      without=wo0, fn_name=fn_name)
        results: List = [None] * len(panels)
        if packed is None:
            # no shards for the dataset: keep the declared contract —
            # one (empty values, no labels) tuple per panel
            empty = np.zeros((0, np.asarray(wends).shape[0]))
            return [(empty, []) for _ in panels]
        panels_key = tuple((tuple(by), tuple(wo), op)
                           for by, wo, op in panels)
        merged_key = (id(packed), panels_key, fn_name)
        with self._cache_lock:
            cached = self._panel_group_cache.get(merged_key)
        if cached is not None and cached["packed"] is packed:
            kpanels, kmap, klabels = cached["kpanels"], cached["kmap"], \
                cached["klabels"]
        else:
            kpanels, kmap, klabels = self._panel_groupings(packed, panels)
            with self._cache_lock:
                self._panel_group_cache[merged_key] = {
                    "packed": packed, "kpanels": kpanels, "kmap": kmap,
                    "klabels": klabels}
                while len(self._panel_group_cache) > 8:
                    self._panel_group_cache.pop(
                        next(iter(self._panel_group_cache)))
        if kpanels:
            wends_p, W = self._prep_wends(packed, wends)
            try:
                fused = self._run_agg_fused_multi(
                    packed, wends_p, W, range_ms, fn_name, kpanels,
                    merged_key=merged_key)
            except Exception as e:  # noqa: BLE001 — fusion is optional
                from filodb_tpu.utils.metrics import (
                    log_fused_degradation, registry as mreg)
                mreg.counter("mesh_fused_errors").increment()
                log_fused_degradation("mesh", e)
                fused = None
            if fused is not None:
                for arr, idx, labels in zip(fused, kmap, klabels):
                    results[idx] = (arr, labels)
        for idx, (by, wo, op) in enumerate(panels):
            if results[idx] is None:
                pk = self.lookup_and_pack(filters, start_ms, end_ms,
                                          by=by, without=wo,
                                          fn_name=fn_name)
                results[idx] = self.run_agg(pk, np.asarray(wends),
                                            range_ms=range_ms,
                                            fn_name=fn_name, agg_op=op)
        return results

    def run_binop_agg(self, filters_l, filters_r, start_ms: int,
                      end_ms: int, wends: np.ndarray, *, range_ms: int,
                      fn_name: Optional[str], op: str,
                      agg_op_l: str = "sum", agg_op_r: str = "sum",
                      by=(), without=(), bool_modifier: bool = False
                      ) -> Tuple[np.ndarray, List[Dict[str, str]]]:
        """Mesh-wide vector-matching binary op between two aggregated
        expressions: ``aggL by(...)(fnL(selL)) <op> aggR by(...)(selR)``
        matched on the (shared) group labels.  Returns
        (values [P, W], per-pair label dicts).

        Whole-expression dispatch (PR 17): when both sides select the
        SAME working set the two panels ride ONE run_agg_batch — one
        pack, one merged kernel dispatch across the mesh; otherwise each
        side runs its own fused scan.  Either way only the two sides'
        [G, W] partials cross chips; the label match resolves host-side
        into index maps and the op itself is one jitted gather+binop
        program (ops/select.gather_binop)."""
        from filodb_tpu.ops.select import gather_binop
        by, without = tuple(by), tuple(without)
        if list(filters_l) == list(filters_r):
            (lv, ll), (rv, rl) = self.run_agg_batch(
                filters_l, start_ms, end_ms, wends, range_ms=range_ms,
                fn_name=fn_name,
                panels=[(by, without, agg_op_l), (by, without, agg_op_r)])
        else:
            pl = self.lookup_and_pack(filters_l, start_ms, end_ms, by=by,
                                      without=without, fn_name=fn_name)
            pr = self.lookup_and_pack(filters_r, start_ms, end_ms, by=by,
                                      without=without, fn_name=fn_name)
            W = np.asarray(wends).shape[0]
            lv, ll = ((np.zeros((0, W)), []) if pl is None else
                      self.run_agg(pl, np.asarray(wends), range_ms=range_ms,
                                   fn_name=fn_name, agg_op=agg_op_l))
            rv, rl = ((np.zeros((0, W)), []) if pr is None else
                      self.run_agg(pr, np.asarray(wends), range_ms=range_ms,
                                   fn_name=fn_name, agg_op=agg_op_r))
        # group labels are unique per side: one-to-one match on the
        # label dict (both sides grouped by the same by/without)
        rindex = {tuple(sorted(d.items())): j for j, d in enumerate(rl)}
        pairs = [(i, rindex[tuple(sorted(d.items()))])
                 for i, d in enumerate(ll)
                 if tuple(sorted(d.items())) in rindex]
        W = lv.shape[1] if lv.ndim == 2 else np.asarray(wends).shape[0]
        if not pairs:
            return np.zeros((0, W)), []
        mi = np.asarray([p[0] for p in pairs], np.int64)
        oi = np.asarray([p[1] for p in pairs], np.int64)
        import time as _time

        from filodb_tpu.utils.devicetelem import telem
        _b0 = _time.perf_counter()
        out = np.asarray(gather_binop(
            jnp.asarray(np.asarray(lv)), jnp.asarray(np.asarray(rv)),
            jnp.asarray(mi), jnp.asarray(oi), op=op,
            bool_modifier=bool_modifier, keep_side="lhs"))
        telem.record_dispatch(
            "gather_binop", shape=f"P{len(pairs)}xW{W}:{op}",
            seconds=_time.perf_counter() - _b0, bytes_out=int(out.nbytes))
        return out, [ll[i] for i, _ in pairs]

    def _panel_groupings(self, packed: PackedShards, panels):
        """Per-panel (gids, G, op, gsize) + labels over the pack's rows —
        the host remap work run_agg_batch caches per (pack, panels)."""
        kpanels, kmap, klabels = [], [], []
        shards = list(self.memstore.shards_for(self.dataset))
        D, S, _ = packed.ts_off.shape
        for idx, (by, wo, op) in enumerate(panels):
            if op not in ("sum", "avg", "count"):
                continue
            if idx == 0:
                kpanels.append((None, packed.num_groups, op, packed.gsize))
                kmap.append(idx)
                klabels.append(packed.group_labels)
                continue
            if packed.pids_by_shard is None:
                continue          # pack built outside lookup_and_pack
            garrs, registry = [], None
            for shard, pids in zip(shards, packed.pids_by_shard):
                if pids is None:
                    garrs.append(None)
                    continue
                g, registry = self._gids_for(shard, pids, tuple(by),
                                             tuple(wo))
                garrs.append(np.asarray(g, np.int64))
            real = [g for g in garrs if g is not None]
            uniq = (np.unique(np.concatenate(real)) if real
                    else np.zeros(0, np.int64))
            labels = ([registry.labels[int(x)] for x in uniq]
                      if registry is not None else [])
            G = max(len(labels), 1)
            gids = np.full((D, S), -1, np.int32)
            gsize = np.zeros(G, np.int64)
            for d, g in enumerate(garrs):
                if g is None:
                    continue
                cg = np.searchsorted(uniq, g).astype(np.int32)
                gids[d, :len(cg)] = cg
                gsize += np.bincount(cg, minlength=G)[:G]
            kpanels.append((gids, G, op, gsize))
            kmap.append(idx)
            klabels.append(labels)
        return kpanels, kmap, klabels

    def run_agg(self, packed: PackedShards, wends: np.ndarray, *,
                range_ms: int, fn_name: Optional[str], agg_op: str,
                params: Tuple[float, ...] = ()) -> Tuple[np.ndarray, List[Dict[str, str]]]:
        """Returns (final [G, W] values, group label dicts).

        wends are ABSOLUTE ms (same clock as lookup_and_pack's time range);
        they are rebased onto the pack's offset base here."""
        wends, W = self._prep_wends(packed, wends)
        if agg_op in ("sum", "avg", "count") and not params:
            try:
                fused = self._run_agg_fused(packed, wends, W, range_ms,
                                            fn_name, agg_op)
            except Exception as e:  # noqa: BLE001 — fusion is optional
                from filodb_tpu.utils.metrics import (
                    log_fused_degradation, registry)
                registry.counter("mesh_fused_errors").increment()
                log_fused_degradation("mesh", e)
                fused = None
            if fused is not None:
                return fused, packed.group_labels
        wends_dev = jax.device_put(
            wends, NamedSharding(self.mesh, P("time")))
        partials = distributed_window_agg(
            self.mesh, packed.ts_off, packed.values, packed.group_ids,
            wends_dev, range_ms=range_ms, fn_name=fn_name, params=params,
            agg_op=agg_op, num_groups=packed.num_groups,
            base_ms=packed.base_ms, vbase=packed.vbase,
            precorrected=packed.precorrected,
            dense=(packed.dense
                   if fn_name in ("rate", "increase", "delta",
                                  "irate", "idelta") else True))
        out = agg_ops.present(agg_op, partials)
        return np.asarray(out)[:, :W], packed.group_labels

    def _run_agg_fused(self, packed: PackedShards, wends_p: np.ndarray,
                       W: int, range_ms: int, fn_name: Optional[str],
                       agg_op: str = "sum") -> Optional[np.ndarray]:
        """Single-panel form of _run_agg_fused_multi (see below)."""
        res = self._run_agg_fused_multi(
            packed, wends_p, W, range_ms, fn_name,
            [(None, packed.num_groups, agg_op, packed.gsize)])
        return None if res is None else res[0]

    def _run_agg_fused_multi(self, packed: PackedShards,
                             wends_p: np.ndarray, W: int, range_ms: int,
                             fn_name: Optional[str],
                             kpanels,
                             merged_key: Optional[Tuple] = None
                             ) -> Optional[List[np.ndarray]]:
        """sum/avg/count(rate|increase|delta|*_over_time) over a
        uniform-grid pack via PER-DEVICE dispatch of the single-chip MXU
        kernel (ops/pallas_fused.py): device (s, t) runs the kernel over
        its committed shard block with time-slice t's selection-matrix
        plan, and only the [G] group partials merge across chips
        (merge_device_partials — psum collective on ICI, host reduce
        otherwise).  The kernel is NEVER traced inside shard_map: that
        composition inverted the single-chip win ~30x (MULTICHIP_r05).
        One HBM pass per device instead of the general path's several.
        NaN-holed (ragged) packs run the kernel's valid-boundary variant
        with per-cell presence merged as a second partial (r4).  On a
        dense pack count needs NO device work (identical per-window
        counts); avg divides sums by counts.  Backends without an MXU
        dispatch ops/hostleaf per shard instead (same merge contract).

        kpanels: [(gids [D, S] int32 or None for the pack's own grouping,
        G, agg_op, gsize [G])] — multiple panels (run_agg_batch) merge
        into ONE kernel dispatch over disjoint group-id ranges, the mesh
        analogue of the leaf path's fused_leaf_agg_batch.  Returns the
        finished [G, W] arrays in panel order, or None when the shared
        gate rejects (callers then take the general path per panel)."""
        import os

        from filodb_tpu.ops import pallas_fused as pf
        shared = packed.shared_ts_row is not None and packed.gsize is not None
        dense = packed.dense
        for _, _, op, _ in kpanels:
            if not pf.can_fuse(fn_name or "", op, shared, dense):
                return None
        if fn_name in pf.MINMAX_FNS:
            # reduce_window kinds run through the general mesh path (XLA
            # fuses them fine); the matmul kernel has no min/max kind
            return None
        ragged = not dense
        if ragged and fn_name in ("last_over_time", "count_over_time"):
            # slot-semantics kinds: their kernel presence counts grid
            # SLOTS, and mesh pack padding rows carry gid 0 (unlike the
            # leaf path's -1) — they would inflate group 0.  General path.
            return None
        minsamp = 2 if fn_name in ("rate", "increase", "delta") else 1
        over_time = fn_name in pf.OVER_TIME_FNS

        out: List[Optional[np.ndarray]] = [None] * len(kpanels)
        # dense count panels: every REAL series emits a value exactly
        # where the shared window is valid — pure host math
        kidx = [i for i, (_, _, op, _) in enumerate(kpanels)
                if not (op == "count" and dense)]
        if kidx:
            if fn_name in ("rate", "increase") and not packed.precorrected:
                return None
            n_time = self.mesh.shape["time"]
            Wp = wends_p.shape[0]
            Wl = Wp // n_time
            D, S, T = packed.ts_off.shape
            Tp = pf._pad_to(T, pf._LANE)
            Wlp = pf._pad_to(max(Wl, 1), pf._LANE)
            offsets, Gtot = [], 0
            for i in kidx:
                offsets.append(Gtot)
                Gtot += kpanels[i][1]
            # padded group count, matching _run's recomputation exactly
            kind_k = fn_name if over_time else "rate_family"
            if pf.pick_block(
                    Tp, Wlp, pf.pad_group_count(Gtot),
                    over_time,
                    ragged and fn_name in ("rate", "increase", "delta"),
                    panels=max(len(kidx), 1),
                    gather=pf.gather_default(kind_k)) is None:
                return None
            interpret = jax.default_backend() != "tpu"
            if interpret and not os.environ.get("FILODB_TPU_FUSED_INTERPRET"):
                # no MXU here: the per-device unit becomes the host fused
                # leaf (ops/hostleaf), same dispatch + partial-merge shape
                # — the single-chip cost-based router's host path scaled
                # out over shards.  Ragged sets have no host variant.
                host_out = self._run_agg_fused_host(
                    packed, wends_p, W, range_ms, fn_name, kpanels, kidx)
                if host_out is None:
                    return None
                for i, arr in zip(kidx, host_out):
                    out[i] = arr
                return self._finish_count_panels(packed, wends_p, W,
                                                 range_ms, kpanels, out,
                                                 minsamp)
            # plan cache: per-time-slice plans; the per-(plan, device)
            # selection-matrix uploads live in pallas_fused's own cache
            # (plan_device_mats), keyed by these pinned plan objects
            plan_key = (packed.shared_ts_row.tobytes(), wends_p.tobytes(),
                        range_ms)
            from filodb_tpu.query.exec import _lru_touch
            with self._cache_lock:
                ent = _lru_touch(self._fused_plan_cache, plan_key)
            if ent is None:
                ts_row = packed.shared_ts_row.astype(np.int64)
                plans = [pf.build_plan(
                    ts_row, wends_p[i * Wl:(i + 1) * Wl].astype(np.int64),
                    range_ms) for i in range(n_time)]
                ent = (plans,
                       np.concatenate([p.wvalid for p in plans]),
                       np.concatenate([p.wvalid1 for p in plans]))
                with self._cache_lock:
                    self._fused_plan_cache[plan_key] = ent
                    while len(self._fused_plan_cache) > 4:
                        self._fused_plan_cache.pop(
                            next(iter(self._fused_plan_cache)))
            plans, wvalid, wvalid1 = ent
            vbase = packed.vbase
            if vbase is None:
                vbase = jax.device_put(
                    np.zeros((D, S), np.float32),
                    NamedSharding(self.mesh, P("shard", None)))
                # the pack is cached across queries — keep the device zeros
                # with it so repeats skip this alloc + transfer (also
                # serves the general path, which otherwise re-zeros)
                packed.vbase = vbase
            if len(kidx) == 1 and kpanels[kidx[0]][0] is None:
                gids_dev = packed.group_ids[..., None]
            else:
                gids_dev = None
                if merged_key is not None:
                    with self._cache_lock:
                        ent2 = self._batch_gid_cache.get(merged_key)
                    if ent2 is not None and ent2["packed"] is packed:
                        gids_dev = ent2["gids_dev"]
                if gids_dev is None:
                    cols = []
                    for j, i in enumerate(kidx):
                        g = kpanels[i][0]
                        if g is None:
                            g = np.asarray(packed.group_ids)
                        # pack pad rows carry gid 0 over zeroed/NaN
                        # values: offset keeps them harmless (+0 sums,
                        # 0 presence)
                        cols.append(np.where(g >= 0, g + offsets[j], -1)
                                    .astype(np.int32))
                    gids_dev = jax.device_put(
                        np.stack(cols, axis=-1),
                        NamedSharding(self.mesh, P("shard", None, None)))
                    if merged_key is not None:
                        with self._cache_lock:
                            self._batch_gid_cache[merged_key] = {
                                "packed": packed, "gids_dev": gids_dev}
                            while len(self._batch_gid_cache) > 4:
                                self._batch_gid_cache.pop(
                                    next(iter(self._batch_gid_cache)))
            # per-device dispatch: device (s, t) runs the SINGLE-CHIP
            # kernel over its committed shard block with time-slice t's
            # plan — all D*n_time dispatches are issued before any
            # result is touched, so the chips compute concurrently; only
            # the [Gtot, Wlp] partials then merge (collective on ICI,
            # host reduce otherwise).  The kernel never traces inside
            # shard_map (the MULTICHIP_r05 30x inversion).
            gather = pf.gather_default(kind_k)
            is_counter = fn_name in ("rate", "increase")
            vblocks = {s.device: s.data
                       for s in packed.values.addressable_shards}
            grid = self.mesh.devices
            if any(dev not in vblocks for dev in grid.flat):
                # multi-host mesh: remote devices' blocks are not
                # addressable from this process, so per-device dispatch
                # cannot read them — route the general SPMD path (the
                # multi-host-correct shard_map composition) instead of
                # raising a KeyError per query
                from filodb_tpu.utils.metrics import registry
                registry.counter("mesh_fused_unaddressable").increment()
                return None
            gblocks = {s.device: s.data
                       for s in gids_dev.addressable_shards}
            vbblocks = {s.device: s.data
                        for s in vbase.addressable_shards}
            parts_sums: Dict[Tuple[int, int], jax.Array] = {}
            parts_cnts: Dict[Tuple[int, int], jax.Array] = {}
            import time as _time

            from filodb_tpu.utils.devicetelem import telem, watched_call
            sig = (f"S{S}xT{T}xG{Gtot}:{kind_k}"
                   + (":ragged" if ragged else ""))
            for si in range(D):
                for ti in range(n_time):
                    dev = grid[si, ti]
                    mats_d = pf._kernel_mats(plans[ti], over_time, gather,
                                             device=dev)
                    _d0 = _time.perf_counter()
                    res = watched_call(
                        "mesh_fused", _device_fused_call, sig,
                        lambda: _device_fused_call(
                            vblocks[dev], gblocks[dev], vbblocks[dev],
                            *mats_d, G=Gtot, S=S, T=T, Tp=Tp,
                            is_counter=is_counter,
                            is_rate=(fn_name == "rate"),
                            interpret=interpret,
                            kind=kind_k, ragged=ragged),
                        device=dev)
                    # per-chip ledger entry per dispatch: the seconds here
                    # are issue wall only (the chips compute concurrently;
                    # the synchronizing merge below carries the wait), but
                    # the COUNTS reconcile 1:1 with
                    # mesh_fused_perdevice_dispatches
                    telem.record_dispatch(
                        "mesh_fused", device=dev, shape=sig,
                        seconds=_time.perf_counter() - _d0,
                        bytes_in=int(getattr(vblocks[dev], "nbytes", 0)))
                    if ragged:
                        parts_sums[(si, ti)], parts_cnts[(si, ti)] = res
                    else:
                        parts_sums[(si, ti)] = res
            _m0 = _time.perf_counter()
            merged = merge_device_partials(parts_sums, self.mesh, "sum")

            def unslice(a):
                return a.reshape(Gtot, n_time, Wlp)[:, :, :Wl] \
                    .reshape(Gtot, Wp)[:, :W]

            if ragged:
                all_out = unslice(merged)
                all_counts = unslice(
                    merge_device_partials(parts_cnts, self.mesh, "sum"))
            else:
                all_out, all_counts = unslice(merged), None
            # the merge is where the dispatches above synchronize: its
            # wall is the fleet's compute+reduce wait, attributed as one
            # ledger entry so QueryStats.device_seconds covers the mesh
            # path end to end
            telem.record_dispatch(
                "mesh_merge", shape=f"D{D}xG{Gtot}",
                seconds=_time.perf_counter() - _m0,
                bytes_out=int(all_out.nbytes))
            from filodb_tpu.utils.metrics import registry
            registry.counter("mesh_fused_kernel").increment()
            registry.counter("mesh_fused_perdevice_dispatches") \
                .increment(D * n_time)
            if len(kidx) > 1:
                registry.counter("mesh_fused_batch_panels") \
                    .increment(len(kidx))
            for j, i in enumerate(kidx):
                _, G, op, gsize = kpanels[i]
                lo = offsets[j]
                pout = all_out[lo:lo + G]
                counts = (all_counts[lo:lo + G] if ragged
                          else _host_counts(gsize,
                                            wvalid1 if over_time
                                            else wvalid)[:, :W])
                if op == "count":             # ragged: kernel presence
                    out[i] = np.where(counts > 0,
                                      counts.astype(np.float64), np.nan)
                    continue
                if op == "avg":
                    with np.errstate(invalid="ignore", divide="ignore"):
                        pout = np.asarray(pout, np.float64) \
                            / np.maximum(counts, 1.0)
                out[i] = pf.present_sum(pout, counts)
        return self._finish_count_panels(packed, wends_p, W, range_ms,
                                         kpanels, out, minsamp)

    def _finish_count_panels(self, packed: PackedShards,
                             wends_p: np.ndarray, W: int, range_ms: int,
                             kpanels, out: List[Optional[np.ndarray]],
                             minsamp: int) -> List[np.ndarray]:
        """Dense count panels: every REAL series emits a value exactly
        where the shared window is valid — pure host math, no device
        work (shared epilogue of the kernel and host dispatch routes)."""
        from filodb_tpu.ops import pallas_fused as pf
        valid = None                          # panel-independent; lazy
        for i, (_, _, op, gsize) in enumerate(kpanels):
            if out[i] is None:                # dense count: host math
                if valid is None:
                    n = pf.window_counts(
                        packed.shared_ts_row.astype(np.int64),
                        wends_p[:W].astype(np.int64), range_ms)
                    valid = (n >= minsamp).astype(np.float64)
                counts = _host_counts(gsize, valid)
                from filodb_tpu.utils.metrics import registry
                registry.counter("mesh_fused_count_host").increment()
                out[i] = np.where(counts > 0, counts, np.nan)
        return out

    def _host_plan(self, packed: PackedShards, wends_p: np.ndarray,
                   W: int, range_ms: int):
        """Full-grid FusedPlan for the host dispatch route, cached next
        to the per-slice device plans."""
        from filodb_tpu.ops import pallas_fused as pf
        from filodb_tpu.query.exec import _lru_touch
        plan_key = ("host", packed.shared_ts_row.tobytes(),
                    wends_p[:W].tobytes(), range_ms)
        with self._cache_lock:
            plan = _lru_touch(self._fused_plan_cache, plan_key)
        if plan is None:
            plan = pf.build_plan(packed.shared_ts_row.astype(np.int64),
                                 wends_p[:W].astype(np.int64), range_ms)
            with self._cache_lock:
                self._fused_plan_cache[plan_key] = plan
                while len(self._fused_plan_cache) > 4:
                    self._fused_plan_cache.pop(
                        next(iter(self._fused_plan_cache)))
        return plan

    def _run_agg_fused_host(self, packed: PackedShards,
                            wends_p: np.ndarray, W: int, range_ms: int,
                            fn_name: Optional[str], kpanels, kidx
                            ) -> Optional[List[np.ndarray]]:
        """Per-shard HOST fused evaluation (ops/hostleaf) with the same
        dispatch + partial-merge shape as the per-device kernel path —
        the dispatch unit on backends without an MXU, mirroring the
        single-chip cost-based router's host route.  Dense shared-grid
        packs only (hostleaf has no ragged variant); partials merge in
        ascending shard order via the sum combiner (ops/agg.reduce_phase
        semantics).  Returns finished [G, W] arrays in kidx order, or
        None to divert to the general path."""
        if not packed.dense or packed.host_values is None:
            return None
        from filodb_tpu.ops import hostleaf
        plan = self._host_plan(packed, wends_p, W, range_ms)
        if plan.idx1 is None:
            return None
        hv = packed.host_values
        hvb = packed.host_vbase
        hg = packed.host_group_ids
        outs: List[np.ndarray] = []
        for i in kidx:
            g, G, op, _ = kpanels[i]
            comp = None
            for d in range(hv.shape[0]):
                nser = int(packed.n_series[d])
                if nser == 0:
                    continue
                gids_d = (hg[d, :nser] if g is None
                          else np.asarray(g[d, :nser]))
                vb_d = None if hvb is None else hvb[d, :nser]
                c = hostleaf.host_leaf_agg(plan, hv[d, :nser], vb_d,
                                           gids_d, G, fn_name, op)
                comp = c if comp is None else comp + c
            if comp is None:
                comp = np.zeros((G, W, 2))
            s, cnt = comp[..., 0], comp[..., 1]
            vals = s / np.maximum(cnt, 1.0) if op == "avg" else s
            outs.append(np.where(cnt > 0, vals, np.nan))
        from filodb_tpu.utils.metrics import registry
        registry.counter("mesh_fused_host").increment()
        registry.counter("mesh_partials_host_merge").increment()
        return outs
