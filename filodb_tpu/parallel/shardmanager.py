"""ShardManager — the cluster-singleton shard coordinator.

Mirrors the reference's NodeClusterActor + ShardManager pair (ref:
coordinator/.../NodeClusterActor.scala:469 area, ShardManager.scala:621,
doc/sharding.md:57-189):

  - owns the authoritative ShardMapper for every dataset
  - assigns shards to nodes via a stateless even-spread strategy, in
    reverse deploy order so rolling upgrades drain the oldest nodes last
    (ref: ShardAssignmentStrategy.scala:113, doc/sharding.md:87-103)
  - reacts to node join/leave: reassigns a downed node's shards to
    remaining capacity, rate-limited per shard by
    `reassignment-min-interval` (ref: filodb-defaults.conf:208-211,
    doc/sharding.md:158-167)
  - publishes ShardEvents to subscribers, who first receive a full
    CurrentShardSnapshot (ref: ShardSubscriptions.scala:59)
  - recovers its state after singleton failover by replaying dataset
    configs from the MetaStore-analogue plus node-local snapshots
    (ref: doc/sharding.md:177-189)

The TPU-native control plane is an in-process state machine with pluggable
node handles (strings); cross-host transports (gRPC/HTTP) call these same
entry points.  Time is injectable for deterministic failover tests.
"""
from __future__ import annotations

import dataclasses
import math
import time as _time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from filodb_tpu.parallel.shardmapper import (ShardEvent, ShardMapper,
                                             ShardStatus)


@dataclasses.dataclass(frozen=True)
class DatasetResourceSpec:
    """ref: NodeClusterActor.SetupDataset resources."""
    num_shards: int
    min_num_nodes: int


@dataclasses.dataclass
class ShardSnapshot:
    """ref: CurrentShardSnapshot sent to new subscribers."""
    dataset: str
    nodes: List[Optional[str]]
    statuses: List[str]


class ShardAssignmentStrategy:
    """ref: ShardAssignmentStrategy.scala trait.  `exclude` removes shards
    from the candidate pool BEFORE capacity truncation, so an ineligible
    shard (rate-limited, error-pinned) never occupies a proposal slot."""

    def shards_for_node(self, node: str, dataset: str,
                        resources: DatasetResourceSpec,
                        mapper: ShardMapper,
                        exclude: frozenset = frozenset()) -> List[int]:
        raise NotImplementedError


class DefaultShardAssignmentStrategy(ShardAssignmentStrategy):
    """Stateless even spread: each node takes up to
    ceil(numShards / minNumNodes) shards from the unassigned pool
    (ref: DefaultShardAssignmentStrategy, doc/sharding.md:87-103)."""

    def shards_for_node(self, node, dataset, resources, mapper,
                        exclude=frozenset()):
        assigned_to_node = mapper.shards_for_node(node)
        capacity = math.ceil(resources.num_shards / resources.min_num_nodes)
        room = capacity - len(assigned_to_node)
        if room <= 0:
            return []
        unassigned = [s for s in range(mapper.num_shards)
                      if mapper.node_for_shard(s) is None
                      and s not in exclude]
        return unassigned[:room]


Subscriber = Callable[[object], None]       # receives ShardSnapshot | ShardEvent


class ShardManager:

    def __init__(self,
                 strategy: Optional[ShardAssignmentStrategy] = None,
                 reassignment_min_interval_s: float = 2 * 3600.0,
                 clock: Callable[[], float] = _time.time,
                 replication_factor: int = 1):
        self.strategy = strategy or DefaultShardAssignmentStrategy()
        self.reassignment_min_interval_s = reassignment_min_interval_s
        self.clock = clock
        # owners per shard (primary + replicas); 1 = replication off —
        # everything below then behaves exactly as before the
        # replication layer (doc/replication.md)
        self.replication_factor = max(int(replication_factor), 1)
        # deploy order: index = join order (reverse-deploy assignment walks
        # from the most recently joined, ref: ShardManager.addMember)
        self._members: List[str] = []
        self._datasets: Dict[str, DatasetResourceSpec] = {}
        self._mappers: Dict[str, ShardMapper] = {}
        self._subscribers: Dict[str, List[Subscriber]] = {}
        # (dataset, shard) -> last reassignment time; only shards that have
        # been assigned before are rate-limited — first assignment is free
        self._last_reassign: Dict[Tuple[str, int], float] = {}
        self._ever_assigned: set = set()
        # (dataset, shard) -> node the shard last errored on, to keep an
        # erroring shard from flapping straight back
        self._error_node: Dict[Tuple[str, int], str] = {}

    # ------------------------------------------------------------ accessors

    @property
    def members(self) -> List[str]:
        return list(self._members)

    def mapper(self, dataset: str) -> ShardMapper:
        return self._mappers[dataset]

    def datasets(self) -> List[str]:
        return list(self._datasets)

    def snapshot(self, dataset: str) -> ShardSnapshot:
        m = self._mappers[dataset]
        return ShardSnapshot(dataset, list(m.nodes),
                             [s.value for s in m.statuses])

    # --------------------------------------------------------- subscriptions

    def subscribe(self, dataset: str, sub: Subscriber) -> None:
        """New subscribers first get the full snapshot
        (ref: ShardSubscriptions.subscribe)."""
        self._subscribers.setdefault(dataset, []).append(sub)
        if dataset in self._mappers:
            sub(self.snapshot(dataset))

    def _publish(self, ev: ShardEvent) -> None:
        for sub in self._subscribers.get(ev.dataset, []):
            sub(ev)

    # ------------------------------------------------------------- datasets

    def setup_dataset(self, dataset: str, resources: DatasetResourceSpec,
                      ) -> ShardMapper:
        """ref: NodeClusterActor.SetupDataset → ShardManager.addDataset."""
        if dataset in self._datasets:
            return self._mappers[dataset]
        self._datasets[dataset] = resources
        mapper = ShardMapper(resources.num_shards,
                             replication_factor=self.replication_factor)
        self._mappers[dataset] = mapper
        for node in reversed(self._members):
            self._assign_to(node, dataset)
        self._assign_replicas(dataset)
        return mapper

    # --------------------------------------------------------------- members

    def add_member(self, node: str) -> Dict[str, List[int]]:
        """Node joined: give it unassigned shards of every dataset
        (ref: ShardManager.addMember)."""
        if node in self._members:
            return {}
        self._members.append(node)
        out = {}
        for dataset in self._datasets:
            got = self._assign_to(node, dataset)
            if got:
                out[dataset] = got
            self._assign_replicas(dataset)
        return out

    def remove_member(self, node: str) -> Dict[str, List[int]]:
        """Node left/died: mark its shards Down, then reassign to surviving
        capacity subject to the per-shard rate limit
        (ref: ShardManager.removeMember + rate limit doc/sharding.md:158-167)."""
        if node not in self._members:
            return {}
        self._members.remove(node)
        affected: Dict[str, List[int]] = {}
        for dataset, mapper in self._mappers.items():
            shards = mapper.shards_for_node(node)
            replica_shards = mapper.replica_shards_for_node(node)
            if shards:
                affected[dataset] = list(shards)
            for s in shards:
                # RF >= 2: a live replica is promoted IN PLACE of the
                # dead primary — the shard never goes Down, queries fail
                # over without a gap (the point of the replication
                # layer); the dead node leaves the owner list entirely
                live = [n for n in mapper.replicas[s]
                        if mapper.owner_status(s, n).query_ready]
                if live:
                    mapper.promote_replica(s, live[0], demote_old=False)
                    ev = ShardEvent("ReplicaPromoted", dataset, s, live[0])
                    self._publish(ev)
                    continue
                mapper.update_from_event(
                    ShardEvent("ShardDown", dataset, s, node))
                self._publish(ShardEvent("ShardDown", dataset, s, node))
            for s in replica_shards:
                mapper.unassign_replica(s, node)
                self._publish(ShardEvent("ReplicaDown", dataset, s, node))
            self._reassign_down_shards(dataset)
            self._assign_replicas(dataset)
        return affected

    # ------------------------------------------------------------ assignment

    def _assign_to(self, node: str, dataset: str) -> List[int]:
        """Assign unassigned shards to `node` up to its capacity, skipping
        shards that moved within the rate-limit interval or that last errored
        on this very node."""
        resources = self._datasets[dataset]
        mapper = self._mappers[dataset]
        now = self.clock()
        assigned: List[int] = []
        skipped: set = set()
        # re-ask the strategy after every assignment/skip so an ineligible
        # proposal (rate-limited / error-pinned) is replaced by the next
        # eligible shard instead of wasting the node's capacity slot
        while True:
            proposals = self.strategy.shards_for_node(
                node, dataset, resources, mapper, exclude=frozenset(skipped))
            if not proposals:
                break
            s = proposals[0]
            key = (dataset, s)
            if self._error_node.get(key) == node:
                skipped.add(s)
                continue
            if key in self._ever_assigned:
                last = self._last_reassign.get(key)
                if last is not None and \
                        now - last < self.reassignment_min_interval_s:
                    skipped.add(s)
                    continue
                self._last_reassign[key] = now
            self._ever_assigned.add(key)
            self._error_node.pop(key, None)
            mapper.register_node([s], node)
            ev = ShardEvent("ShardAssignmentStarted", dataset, s, node)
            mapper.update_from_event(ev)
            self._publish(ev)
            assigned.append(s)
        return assigned

    def _reassign_down_shards(self, dataset: str) -> List[int]:
        """Give Down/Unassigned shards to nodes with spare capacity, newest
        member first."""
        moved = []
        for node in reversed(self._members):
            moved.extend(self._assign_to(node, dataset))
        return moved

    # -------------------------------------------------------------- replicas

    def _assign_replicas(self, dataset: str) -> List[Tuple[int, str]]:
        """Fill every shard's assignment list to `replication_factor`
        owners: replicas are never co-located with the primary (or each
        other), and spread by current replica load, least-loaded node
        first.  No-op at RF 1.  Returns [(shard, node)] newly assigned."""
        rf = self.replication_factor
        if rf <= 1 or len(self._members) < 2:
            return []
        mapper = self._mappers[dataset]
        load = {n: len(mapper.shards_for_node(n))
                + len(mapper.replica_shards_for_node(n))
                for n in self._members}
        added: List[Tuple[int, str]] = []
        for s in range(mapper.num_shards):
            primary = mapper.node_for_shard(s)
            if primary is None:
                continue            # replicas follow a placed primary
            while len(mapper.owners(s)) < rf:
                taken = set(mapper.owners(s))
                candidates = sorted(
                    (n for n in self._members if n not in taken),
                    key=lambda n: (load[n], self._members.index(n)))
                if not candidates:
                    break           # not enough nodes for full RF
                node = candidates[0]
                mapper.register_replica(s, node)
                load[node] += 1
                ev = ShardEvent("ReplicaAssigned", dataset, s, node)
                self._publish(ev)
                added.append((s, node))
        return added

    # -------------------------------------------------------- ingest events

    def on_shard_event(self, ev: ShardEvent) -> None:
        """Node-local ingestion lifecycle events flow up to the singleton and
        fan out to subscribers (ref: ShardManager.updateFromShardEvent)."""
        mapper = self._mappers.get(ev.dataset)
        if mapper is None:
            return
        mapper.update_from_event(ev)
        self._publish(ev)
        if ev.kind in ("IngestionStopped", "IngestionError"):
            # stopped/errored shards go back to the pool for reassignment;
            # an errored shard avoids the node it just failed on
            if ev.kind == "IngestionError" and ev.node is not None:
                self._error_node[(ev.dataset, ev.shard)] = ev.node
            mapper.unassign(ev.shard)
            self._reassign_down_shards(ev.dataset)

    # --------------------------------------------------------------- recovery

    def recover(self, datasets: Dict[str, DatasetResourceSpec],
                members: Sequence[str],
                snapshots: Dict[str, ShardSnapshot]) -> None:
        """Rebuild singleton state after failover: dataset configs from the
        metastore-analogue, member list from the cluster, shard maps from
        node-local snapshots (ref: doc/sharding.md:177-189 recovery protocol)."""
        self._members = list(members)
        for name, res in datasets.items():
            self._datasets[name] = res
            mapper = ShardMapper(res.num_shards)
            snap = snapshots.get(name)
            if snap is not None:
                for s, (node, status) in enumerate(zip(snap.nodes,
                                                       snap.statuses)):
                    if node is not None:
                        mapper.register_node([s], node)
                    mapper.statuses[s] = ShardStatus(status)
            self._mappers[name] = mapper
            # anything left unassigned gets assigned now
            for node in reversed(self._members):
                self._assign_to(node, name)
