"""Standalone cluster node process.

The multi-process analogue of the reference's FiloDB standalone node
(ref: standalone/.../FiloServer.scala + multi-jvm IngestionAndRecoverySpec):
one process = memstore + query-plan server + cluster agent (register /
heartbeat / assignment application with index recovery), plus a small
framed-JSON control socket the test harness uses as its ingest feed (the
Kafka-consumer stand-in: every node sees the full stream and ingests only
the shards it owns).

Run: python -m filodb_tpu.parallel.nodeapp --name A \
         --coordinator 127.0.0.1:9999 --data-dir /tmp/filodb [--platform cpu]

Prints one JSON line {"ready": true, "query_port": N, "control_port": N}
on stdout once serving.
"""
from __future__ import annotations

import argparse
import json
import socketserver
import sys
import threading


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--name", required=True)
    ap.add_argument("--coordinator", required=True, help="host:port")
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--store-url", default="",
                    help="host:port of a chunk service (persist/netstore) — "
                         "the node then needs NO shared filesystem; default "
                         "is the local-disk store in --data-dir")
    ap.add_argument("--platform", default="",
                    help="pin jax platform (e.g. cpu) BEFORE package import")
    ap.add_argument("--heartbeat-interval", type=float, default=0.5)
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    from filodb_tpu.utils import metrics as _metrics
    _metrics.NODE_NAME = args.name       # stamp this node on trace spans
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.gateway.influx import influx_lines_to_batches
    from filodb_tpu.gateway.router import split_batch_by_shard
    from filodb_tpu.parallel.cluster import (ClusterClient, NodeAgent,
                                             _recv_json, _send_json)
    from filodb_tpu.parallel.shardmapper import SpreadProvider
    from filodb_tpu.parallel.transport import NodeQueryServer
    from filodb_tpu.persist.localstore import (LocalDiskColumnStore,
                                               LocalDiskMetaStore)

    host, port = args.coordinator.rsplit(":", 1)
    coord_addr = (host, int(port))
    if args.store_url:
        # shared NETWORK store (ref: CassandraColumnStore — a remote
        # service every node reads through; failover recovery included)
        from filodb_tpu.persist.netstore import (RemoteColumnStore,
                                                 RemoteMetaStore)
        s_host, s_port = args.store_url.rsplit(":", 1)
        column_store = RemoteColumnStore(s_host, int(s_port))
        meta_store = RemoteMetaStore(s_host, int(s_port))
    else:
        column_store = LocalDiskColumnStore(args.data_dir)
        meta_store = LocalDiskMetaStore(args.data_dir)
    memstore = TimeSeriesMemStore(column_store=column_store,
                                  meta_store=meta_store)
    qsrv = NodeQueryServer(memstore).start()
    # replication door (filodb_tpu/replication): peers fan ingest slabs
    # here, and a joining replica streams WAL segments / handoff
    # snapshots out of it
    from filodb_tpu.replication import ReplicationServer
    rsrv = ReplicationServer(memstore, node=args.name).start()

    def on_assign(dataset: str, shard: int) -> None:
        sh = memstore.get_shard(dataset, shard) or \
            memstore.setup(dataset, shard)
        # recovery-by-replay: rebuild the index from persisted part keys;
        # historical chunk data pages in on demand at query time
        sh.recover_index()

    agent = NodeAgent(args.name, coord_addr, qsrv.address, on_assign,
                      heartbeat_interval_s=args.heartbeat_interval)
    client = ClusterClient(coord_addr)
    spread = SpreadProvider(default_spread=1)

    class _Control(socketserver.BaseRequestHandler):
        def handle(self):
            try:
                while True:
                    req = _recv_json(self.request)
                    try:
                        reply = _control(req)
                    except Exception as e:  # noqa: BLE001
                        reply = {"ok": False,
                                 "error": f"{type(e).__name__}: {e}"}
                    _send_json(self.request, reply)
            except (ConnectionError, OSError, json.JSONDecodeError):
                return

    def _control(req):
        cmd = req.get("cmd")
        if cmd == "ping":
            return {"ok": True, "owned": agent.owned}
        if cmd == "ingest_lines":
            dataset = req.get("dataset", "prometheus")
            mapper, _ = client.mapper(dataset)
            n = 0
            for batch in influx_lines_to_batches(req["lines"]):
                routed = split_batch_by_shard(batch, mapper, spread)
                for shard_num, sub in routed.items():
                    sh = memstore.get_shard(dataset, shard_num)
                    if sh is not None and \
                            shard_num in agent.owned.get(dataset, []):
                        n += sh.ingest(sub, offset=int(req.get("offset", -1)))
            return {"ok": True, "ingested": n}
        if cmd == "flush":
            n = 0
            for ds, shards in agent.owned.items():
                for s in shards:
                    sh = memstore.get_shard(ds, s)
                    if sh is not None:
                        n += sh.flush_all_groups()
            return {"ok": True, "chunks": n}
        if cmd == "stop":
            threading.Thread(target=_shutdown, daemon=True).start()
            return {"ok": True}
        return {"ok": False, "error": f"unknown cmd {cmd!r}"}

    class _Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    ctrl = _Server(("127.0.0.1", 0), _Control)
    stop_evt = threading.Event()

    def _shutdown():
        agent.stop()
        qsrv.stop()
        rsrv.stop()
        ctrl.shutdown()
        stop_evt.set()

    agent.start()
    t = threading.Thread(target=ctrl.serve_forever, daemon=True)
    t.start()
    print(json.dumps({"ready": True, "query_port": qsrv.address[1],
                      "control_port": ctrl.server_address[1],
                      "replication_port": rsrv.address[1],
                      "node": args.name}), flush=True)
    try:
        stop_evt.wait()
    except KeyboardInterrupt:
        _shutdown()


if __name__ == "__main__":
    main()
