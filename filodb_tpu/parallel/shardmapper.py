"""ShardMapper — shard routing with spread (hot-key splitting).

Reproduces the reference's shard math exactly (ref: coordinator/.../
ShardMapper.scala:26-120, doc/sharding.md:23-56):

  - numShards is a power of 2.
  - shardKeyHash (hash of _ws_/_ns_/_metric_) selects a contiguous run of
    2^spread shards; partitionHash selects within the run:
        shardHash = (shardKeyHash & ~mask) | (partHash & mask)
        shard     = shardHash & (numShards - 1),  mask = (1<<spread) - 1
    ...expressed upstream as the upper bits from the shard key and the lower
    `spread` bits from the partition hash.
  - queryShards(shardKeyHash, spread) = all shards the key can land on.

Shard status tracking mirrors ShardStatus + ShardMapper.updateFromEvent.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Tuple


class ShardStatus(enum.Enum):
    """ref: coordinator/ShardStatus.scala."""
    UNASSIGNED = "Unassigned"
    ASSIGNED = "Assigned"
    RECOVERY = "Recovery"
    ACTIVE = "Active"
    ERROR = "Error"
    STOPPED = "Stopped"
    DOWN = "Down"

    @property
    def query_ready(self) -> bool:
        return self in (ShardStatus.ACTIVE, ShardStatus.RECOVERY)


@dataclasses.dataclass
class ShardEvent:
    """ref: coordinator/ShardEvent ADT (IngestionStarted, RecoveryInProgress,
    IngestionStopped, ShardDown...)."""
    kind: str
    dataset: str
    shard: int
    node: Optional[str] = None
    progress_pct: int = 0


_EVENT_STATUS = {
    "ShardAssignmentStarted": ShardStatus.ASSIGNED,
    "IngestionStarted": ShardStatus.ACTIVE,
    "RecoveryInProgress": ShardStatus.RECOVERY,
    "RecoveryStarted": ShardStatus.RECOVERY,
    "IngestionStopped": ShardStatus.STOPPED,
    "IngestionError": ShardStatus.ERROR,
    "ShardDown": ShardStatus.DOWN,
}

# replica-copy lifecycle events (replication layer, doc/replication.md):
# they address ONE owner in the shard's ordered assignment list, never
# the shard's primary status column
_REPLICA_EVENT_STATUS = {
    "ReplicaAssigned": ShardStatus.ASSIGNED,
    "ReplicaRecovery": ShardStatus.RECOVERY,
    "ReplicaActive": ShardStatus.ACTIVE,
    "ReplicaDown": ShardStatus.DOWN,
}


class ShardMapper:
    """Tracks shard -> ordered owner list (primary + replicas) with
    per-owner status, and does spread-based shard math.

    RF-1 view (the pre-replication API) is unchanged: `nodes` /
    `statuses` are the PRIMARY columns.  Replication adds an ordered
    replica list per shard (`owners(s)` = [primary] + replicas) with
    per-replica statuses, and `promote_replica` for the atomic cutover
    a query-time failover or live handoff rides on."""

    def __init__(self, num_shards: int, replication_factor: int = 1):
        assert num_shards > 0 and (num_shards & (num_shards - 1)) == 0, \
            "numShards must be a power of 2"
        self.num_shards = num_shards
        self.nodes: List[Optional[str]] = [None] * num_shards
        self.statuses: List[ShardStatus] = [ShardStatus.UNASSIGNED] * num_shards
        # intended owners per shard (1 = unreplicated); the health
        # evaluator compares live owners against it
        self.replication_factor = max(int(replication_factor), 1)
        # ordered NON-primary owners per shard (assignment-list tail)
        self.replicas: List[List[str]] = [[] for _ in range(num_shards)]
        self.replica_statuses: Dict[Tuple[int, str], ShardStatus] = {}
        # stateless query-only nodes (persist/objectstore.py): own ZERO
        # shards, serve COLD leaves from the shared object tier — extra
        # query-capable targets for the cold-leaf failover walk, never
        # ingest/upload owners
        self.query_nodes: List[str] = []

    # ------------------------------------------------------------ shard math

    def _mask(self, spread: int) -> int:
        """spread clamped so 2^spread never exceeds numShards
        (the reference requires spread <= log2(numShards))."""
        return min((1 << spread) - 1, self.num_shards - 1)

    def ingestion_shard(self, shard_key_hash: int, partition_hash: int,
                        spread: int) -> int:
        """ref: ShardMapper.ingestionShard:108-120 — upper bits from the
        shard-key hash, lower `spread` bits from the partition hash."""
        mask = self._mask(spread)
        h = (shard_key_hash & ~mask) | (partition_hash & mask)
        return h & (self.num_shards - 1)

    def query_shards(self, shard_key_hash: int, spread: int) -> List[int]:
        """ref: ShardMapper.queryShards:93 — every shard 2^spread wide run."""
        mask = self._mask(spread)
        base = shard_key_hash & ~mask & (self.num_shards - 1)
        return [base | i for i in range(mask + 1)]

    def all_shards(self) -> List[int]:
        return list(range(self.num_shards))

    # --------------------------------------------------------- status state

    def update_from_event(self, ev: ShardEvent) -> None:
        if ev.kind == "ReplicaPromoted":
            if ev.node is not None and ev.node != self.nodes[ev.shard]:
                self.promote_replica(ev.shard, ev.node, demote_old=False)
            return
        rst = _REPLICA_EVENT_STATUS.get(ev.kind)
        if rst is not None:
            if ev.node is None:
                raise ValueError(f"replica event {ev.kind} needs a node")
            if rst == ShardStatus.DOWN:
                self.unassign_replica(ev.shard, ev.node)
            else:
                self.register_replica(ev.shard, ev.node, status=rst)
            return
        st = _EVENT_STATUS.get(ev.kind)
        if st is None:
            raise ValueError(f"unknown shard event {ev.kind}")
        if ev.node is not None and ev.node != self.nodes[ev.shard] \
                and ev.node in self.replicas[ev.shard]:
            # a primary-lifecycle event addressed to a REPLICA owner
            # (e.g. ShardDown for a dead replica node) touches only that
            # owner's column, never the primary's
            if st in (ShardStatus.DOWN, ShardStatus.UNASSIGNED):
                self.unassign_replica(ev.shard, ev.node)
            else:
                self.replica_statuses[(ev.shard, ev.node)] = st
            return
        self.statuses[ev.shard] = st
        if ev.node is not None:
            self.nodes[ev.shard] = ev.node
        if st in (ShardStatus.DOWN, ShardStatus.UNASSIGNED):
            self.nodes[ev.shard] = None

    def register_node(self, shards: Sequence[int], node: str) -> None:
        for s in shards:
            self.nodes[s] = node
            if self.statuses[s] == ShardStatus.UNASSIGNED:
                self.statuses[s] = ShardStatus.ASSIGNED

    def unassign(self, shard: int) -> None:
        self.nodes[shard] = None
        self.statuses[shard] = ShardStatus.UNASSIGNED

    def node_for_shard(self, shard: int) -> Optional[str]:
        return self.nodes[shard]

    def shards_for_node(self, node: str) -> List[int]:
        return [i for i, n in enumerate(self.nodes) if n == node]

    @property
    def num_assigned(self) -> int:
        return sum(1 for n in self.nodes if n is not None)

    def active_shards(self, shards: Optional[Sequence[int]] = None) -> List[int]:
        shards = shards if shards is not None else range(self.num_shards)
        return [s for s in shards if self.statuses[s].query_ready]

    def status_snapshot(self) -> Dict[int, Tuple[Optional[str], str]]:
        return {i: (self.nodes[i], self.statuses[i].value)
                for i in range(self.num_shards)}

    # ------------------------------------------------------------- replicas

    def register_replica(self, shard: int, node: str,
                         status: ShardStatus = ShardStatus.ASSIGNED) -> None:
        """Append `node` to the shard's ordered assignment-list tail.
        Registering the current primary is a no-op; re-registering an
        existing replica only refreshes its status."""
        if node == self.nodes[shard]:
            return
        if node not in self.replicas[shard]:
            self.replicas[shard].append(node)
        self.replica_statuses[(shard, node)] = status

    def unassign_replica(self, shard: int, node: str) -> None:
        if node in self.replicas[shard]:
            self.replicas[shard].remove(node)
        self.replica_statuses.pop((shard, node), None)

    def register_query_node(self, node: str) -> None:
        """Register a stateless query-only node (cold-capable dispatch
        target; owns no shards).  Idempotent."""
        if node not in self.query_nodes:
            self.query_nodes.append(node)
            from filodb_tpu.utils.events import journal
            journal.emit("query_node_registered", subsystem="cluster",
                         node=node)

    def unregister_query_node(self, node: str) -> None:
        if node in self.query_nodes:
            self.query_nodes.remove(node)

    def owners(self, shard: int) -> List[str]:
        """Ordered assignment list: primary first, then replicas."""
        head = [self.nodes[shard]] if self.nodes[shard] is not None else []
        return head + list(self.replicas[shard])

    def owner_status(self, shard: int, node: str) -> ShardStatus:
        if node == self.nodes[shard]:
            return self.statuses[shard]
        return self.replica_statuses.get((shard, node),
                                         ShardStatus.UNASSIGNED)

    def live_owners(self, shard: int) -> List[str]:
        return [n for n in self.owners(shard)
                if self.owner_status(shard, n).query_ready]

    def replica_shards_for_node(self, node: str) -> List[int]:
        return [s for s in range(self.num_shards)
                if node in self.replicas[s]]

    def promote_replica(self, shard: int, node: str,
                        demote_old: bool = True) -> Optional[str]:
        """Atomic cutover: `node` (a registered replica) becomes the
        shard's primary; the old primary (returned) becomes the FIRST
        replica when `demote_old` (failover promotion — its copy is
        still the freshest fallback) or leaves the owner list entirely
        (handoff tombstone path).  The shard's primary status carries
        the promoted owner's replica status so an ACTIVE replica yields
        an immediately query-ready primary."""
        if node not in self.replicas[shard]:
            raise ValueError(
                f"cannot promote {node!r}: not a replica of shard {shard}")
        old = self.nodes[shard]
        old_status = self.statuses[shard]
        new_status = self.replica_statuses.get(
            (shard, node), ShardStatus.ASSIGNED)
        self.replicas[shard].remove(node)
        self.replica_statuses.pop((shard, node), None)
        self.nodes[shard] = node
        self.statuses[shard] = new_status
        if old is not None and demote_old:
            self.replicas[shard].insert(0, old)
            self.replica_statuses[(shard, old)] = old_status
        return old

    def assignment_table(self) -> List[Dict]:
        """Per-shard assignment/status rows for GET /admin/shards."""
        out = []
        for s in range(self.num_shards):
            out.append({
                "shard": s,
                "primary": self.nodes[s],
                "status": self.statuses[s].value,
                "replicas": [
                    {"node": n,
                     "status": self.owner_status(s, n).value}
                    for n in self.replicas[s]],
                "liveOwners": len(self.live_owners(s)),
            })
        return out

    def query_node_table(self) -> List[Dict]:
        """Query-only node rows for GET /admin/shards."""
        return [{"node": n, "role": "query-only"}
                for n in self.query_nodes]


@dataclasses.dataclass(frozen=True)
class SpreadChange:
    """Spread override by shard-key filters (ref: filodb-defaults.conf:157-161
    + SpreadProvider)."""
    shard_key: Dict[str, str]
    spread: int


class SpreadProvider:
    """ref: coordinator SpreadProvider/FilodbSpreadMap."""

    def __init__(self, default_spread: int = 1,
                 overrides: Sequence[SpreadChange] = ()):
        self.default_spread = default_spread
        self.overrides = list(overrides)

    def spread_for(self, shard_key: Dict[str, str]) -> int:
        for ov in self.overrides:
            if all(shard_key.get(k) == v for k, v in ov.shard_key.items()):
                return ov.spread
        return self.default_spread
