"""ShardMapper — shard routing with spread (hot-key splitting).

Reproduces the reference's shard math exactly (ref: coordinator/.../
ShardMapper.scala:26-120, doc/sharding.md:23-56):

  - numShards is a power of 2.
  - shardKeyHash (hash of _ws_/_ns_/_metric_) selects a contiguous run of
    2^spread shards; partitionHash selects within the run:
        shardHash = (shardKeyHash & ~mask) | (partHash & mask)
        shard     = shardHash & (numShards - 1),  mask = (1<<spread) - 1
    ...expressed upstream as the upper bits from the shard key and the lower
    `spread` bits from the partition hash.
  - queryShards(shardKeyHash, spread) = all shards the key can land on.

Shard status tracking mirrors ShardStatus + ShardMapper.updateFromEvent.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Tuple


class ShardStatus(enum.Enum):
    """ref: coordinator/ShardStatus.scala."""
    UNASSIGNED = "Unassigned"
    ASSIGNED = "Assigned"
    RECOVERY = "Recovery"
    ACTIVE = "Active"
    ERROR = "Error"
    STOPPED = "Stopped"
    DOWN = "Down"

    @property
    def query_ready(self) -> bool:
        return self in (ShardStatus.ACTIVE, ShardStatus.RECOVERY)


@dataclasses.dataclass
class ShardEvent:
    """ref: coordinator/ShardEvent ADT (IngestionStarted, RecoveryInProgress,
    IngestionStopped, ShardDown...)."""
    kind: str
    dataset: str
    shard: int
    node: Optional[str] = None
    progress_pct: int = 0


_EVENT_STATUS = {
    "ShardAssignmentStarted": ShardStatus.ASSIGNED,
    "IngestionStarted": ShardStatus.ACTIVE,
    "RecoveryInProgress": ShardStatus.RECOVERY,
    "RecoveryStarted": ShardStatus.RECOVERY,
    "IngestionStopped": ShardStatus.STOPPED,
    "IngestionError": ShardStatus.ERROR,
    "ShardDown": ShardStatus.DOWN,
}


class ShardMapper:
    """Tracks shard -> (node, status) and does spread-based shard math."""

    def __init__(self, num_shards: int):
        assert num_shards > 0 and (num_shards & (num_shards - 1)) == 0, \
            "numShards must be a power of 2"
        self.num_shards = num_shards
        self.nodes: List[Optional[str]] = [None] * num_shards
        self.statuses: List[ShardStatus] = [ShardStatus.UNASSIGNED] * num_shards

    # ------------------------------------------------------------ shard math

    def _mask(self, spread: int) -> int:
        """spread clamped so 2^spread never exceeds numShards
        (the reference requires spread <= log2(numShards))."""
        return min((1 << spread) - 1, self.num_shards - 1)

    def ingestion_shard(self, shard_key_hash: int, partition_hash: int,
                        spread: int) -> int:
        """ref: ShardMapper.ingestionShard:108-120 — upper bits from the
        shard-key hash, lower `spread` bits from the partition hash."""
        mask = self._mask(spread)
        h = (shard_key_hash & ~mask) | (partition_hash & mask)
        return h & (self.num_shards - 1)

    def query_shards(self, shard_key_hash: int, spread: int) -> List[int]:
        """ref: ShardMapper.queryShards:93 — every shard 2^spread wide run."""
        mask = self._mask(spread)
        base = shard_key_hash & ~mask & (self.num_shards - 1)
        return [base | i for i in range(mask + 1)]

    def all_shards(self) -> List[int]:
        return list(range(self.num_shards))

    # --------------------------------------------------------- status state

    def update_from_event(self, ev: ShardEvent) -> None:
        st = _EVENT_STATUS.get(ev.kind)
        if st is None:
            raise ValueError(f"unknown shard event {ev.kind}")
        self.statuses[ev.shard] = st
        if ev.node is not None:
            self.nodes[ev.shard] = ev.node
        if st in (ShardStatus.DOWN, ShardStatus.UNASSIGNED):
            self.nodes[ev.shard] = None

    def register_node(self, shards: Sequence[int], node: str) -> None:
        for s in shards:
            self.nodes[s] = node
            if self.statuses[s] == ShardStatus.UNASSIGNED:
                self.statuses[s] = ShardStatus.ASSIGNED

    def unassign(self, shard: int) -> None:
        self.nodes[shard] = None
        self.statuses[shard] = ShardStatus.UNASSIGNED

    def node_for_shard(self, shard: int) -> Optional[str]:
        return self.nodes[shard]

    def shards_for_node(self, node: str) -> List[int]:
        return [i for i, n in enumerate(self.nodes) if n == node]

    @property
    def num_assigned(self) -> int:
        return sum(1 for n in self.nodes if n is not None)

    def active_shards(self, shards: Optional[Sequence[int]] = None) -> List[int]:
        shards = shards if shards is not None else range(self.num_shards)
        return [s for s in shards if self.statuses[s].query_ready]

    def status_snapshot(self) -> Dict[int, Tuple[Optional[str], str]]:
        return {i: (self.nodes[i], self.statuses[i].value)
                for i in range(self.num_shards)}


@dataclasses.dataclass(frozen=True)
class SpreadChange:
    """Spread override by shard-key filters (ref: filodb-defaults.conf:157-161
    + SpreadProvider)."""
    shard_key: Dict[str, str]
    spread: int


class SpreadProvider:
    """ref: coordinator SpreadProvider/FilodbSpreadMap."""

    def __init__(self, default_spread: int = 1,
                 overrides: Sequence[SpreadChange] = ()):
        self.default_spread = default_spread
        self.overrides = list(overrides)

    def spread_for(self, shard_key: Dict[str, str]) -> int:
        for ov in self.overrides:
            if all(shard_key.get(k) == v for k, v in ov.shard_key.items()):
                return ov.spread
        return self.default_spread
