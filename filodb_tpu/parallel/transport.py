"""Cross-node query transport: dispatch serialized plan subtrees over TCP.

The reference's data plane sends Kryo'd ExecPlan subtrees to the shard's
owning node with the Akka ask pattern and gets Kryo'd QueryResults back
(ref: exec/PlanDispatcher.scala:31-55 ActorPlanDispatcher,
doc/query-engine.md:90-155 scatter-gather).  Here the frame protocol is
length-prefixed request/response over a plain TCP socket; the node side
executes against its local memstore source, so the coordinator's
NonLeafExecPlan scatter-gathers across machines exactly like the
single-process path.
"""
from __future__ import annotations

import json
import socket
import json
import socketserver
import struct
import threading
from typing import Callable, Optional, Tuple

from filodb_tpu.parallel import serialize
from filodb_tpu.query.exec import PlanDispatcher, QueryResultLike
from filodb_tpu.query.rangevector import QueryStats

_MAGIC = b"FQ01"


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_MAGIC + struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        got = sock.recv(min(n, 1 << 20))
        if not got:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(got)
        n -= len(got)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> bytes:
    hdr = _recv_exact(sock, 12)
    if hdr[:4] != _MAGIC:
        raise ConnectionError(f"bad frame magic {hdr[:4]!r}")
    (ln,) = struct.unpack("<Q", hdr[4:])
    return _recv_exact(sock, ln)


def send_json_frame(sock: socket.socket, obj) -> None:
    """One JSON message as one frame — the shared control-plane encoding
    (cluster coordination, node control sockets, the chunk service)."""
    _send_frame(sock, json.dumps(obj).encode("utf-8"))


def recv_json_frame(sock: socket.socket):
    return json.loads(_recv_frame(sock).decode("utf-8"))


class NodeQueryServer:
    """Executes dispatched leaf plans against this node's source
    (the QueryActor receive loop, ref: coordinator/.../QueryActor.scala:119)."""

    def __init__(self, source, host: str = "127.0.0.1", port: int = 0):
        self.source = source
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        payload = _recv_frame(self.request)
                        try:
                            from filodb_tpu.utils.metrics import (
                                collector, span, trace_context)
                            plan = serialize.loads(payload)
                            tid = getattr(plan.ctx, "query_id", "")
                            # execute under the CALLER's trace id so this
                            # node's spans stitch into the same trace; ship
                            # them back with the reply (the Kamon-context-
                            # over-Akka analogue, ref: ExecPlan.scala:102)
                            with trace_context(tid),                                     span("remote_exec",
                                         plan=type(plan).__name__):
                                data, stats = plan.execute_internal(
                                    outer.source)
                            reply = serialize.dumps(
                                {"ok": True, "data": data, "stats": stats,
                                 "spans": (collector.take(tid)
                                           if tid else [])})
                        except Exception as e:  # noqa: BLE001 — errors ride the wire
                            reply = serialize.dumps(
                                {"ok": False,
                                 "error": f"{type(e).__name__}: {e}"})
                        _send_frame(self.request, reply)
                except (ConnectionError, OSError):
                    return              # client went away

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def start(self) -> "NodeQueryServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)


class RemoteNodeDispatcher(PlanDispatcher):
    """Coordinator-side dispatcher for one remote node; keeps one pooled
    connection per thread (ref: ActorPlanDispatcher ask-pattern send)."""

    def __init__(self, host: str, port: int,
                 timeout_s: Optional[float] = None):
        self.host, self.port = host, port
        if timeout_s is None:
            # the ask-timeout knob (ref: filodb-defaults.conf
            # query.ask-timeout; PlanDispatcher.scala:31 Akka ask)
            from filodb_tpu.config import settings
            timeout_s = settings().query.ask_timeout_s
        self.timeout_s = timeout_s
        self._tls = threading.local()

    def _sock(self) -> Tuple[socket.socket, bool]:
        """Returns (socket, fresh): `fresh` distinguishes a just-opened
        connection from a pooled one that may have gone stale."""
        s = getattr(self._tls, "sock", None)
        if s is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._tls.sock = s
            return s, True
        return s, False

    def _reset(self) -> None:
        s = getattr(self._tls, "sock", None)
        if s is not None:
            try:
                s.close()
            finally:
                self._tls.sock = None

    def dispatch(self, plan, source) -> QueryResultLike:
        import time as _time

        from filodb_tpu.query.execbase import QueryError
        payload = serialize.dumps(plan)
        where = f"{self.host}:{self.port}"
        t_wire0 = _time.perf_counter()
        try:
            sock, fresh = self._sock()
        except OSError as e:
            # connect refused/unreachable: the owner is gone (SIGKILL,
            # network partition) — the taxonomy's shard_unavailable
            raise QueryError("shard_unavailable",
                             f"node {where} unreachable: {e}") from e
        try:
            _send_frame(sock, payload)
            raw = _recv_frame(sock)
            reply = serialize.loads(raw)
        except socket.timeout as e:
            # NEVER retry a timeout: the remote may still be executing the
            # plan, and a re-send would run the query twice
            self._reset()
            raise QueryError(
                "dispatch_timeout",
                f"node {where} gave no reply within {self.timeout_s}s "
                f"(not retried: the remote may still be executing)") from e
        except (ConnectionError, OSError) as e:
            self._reset()
            if fresh:
                raise QueryError("shard_unavailable",
                                 f"node {where} died mid-dispatch: "
                                 f"{e}") from e
            # pooled socket had gone stale — one retry on a fresh one.
            # The CONNECT is classified separately: a connect timeout
            # means the node is unreachable (shard_unavailable, same as
            # the first-attempt path), not "accepted but silent"
            try:
                sock, _ = self._sock()
            except OSError as e2:
                raise QueryError("shard_unavailable",
                                 f"node {where} unreachable: "
                                 f"{e2}") from e2
            try:
                _send_frame(sock, payload)
                raw = _recv_frame(sock)
                reply = serialize.loads(raw)
            except socket.timeout as e2:
                self._reset()
                raise QueryError(
                    "dispatch_timeout",
                    f"node {where} gave no reply within "
                    f"{self.timeout_s}s") from e2
            except (ConnectionError, OSError) as e2:
                self._reset()
                raise QueryError("shard_unavailable",
                                 f"node {where} died mid-dispatch: "
                                 f"{e2}") from e2
        if not reply["ok"]:
            raise QueryError("remote_failure",
                             f"node {where} failed: {reply['error']}")
        # stitch the remote node's spans into the caller's trace (they
        # arrive stamped with the remote NODE_NAME)
        spans = reply.get("spans")
        if spans:
            from filodb_tpu.utils.metrics import collector
            tid = getattr(plan.ctx, "query_id", "")
            for ev in spans:
                if isinstance(ev, dict):
                    collector.record(tid, ev)
        stats = reply["stats"] or QueryStats()
        # resource attribution across the wire (PR 3): the remote's own
        # phase seconds arrived inside `stats`; the round trip minus the
        # remote's busy time is serialization + network — transfer.  The
        # whole round trip is credited as CHILD wall so the coordinator
        # node's exclusive cpu_seconds never claims the network wait.
        from filodb_tpu.utils.metrics import exec_tally
        wire_wall = _time.perf_counter() - t_wire0
        exec_tally.child_wall += wire_wall
        remote_busy = (stats.cpu_seconds + stats.device_seconds
                       + stats.transfer_s)
        stats.transfer_s += max(wire_wall - remote_busy, 0.0)
        stats.bytes_transferred += len(payload) + len(raw)
        return reply["data"], stats
