"""Cross-node query transport: dispatch serialized plan subtrees over TCP.

The reference's data plane sends Kryo'd ExecPlan subtrees to the shard's
owning node with the Akka ask pattern and gets Kryo'd QueryResults back
(ref: exec/PlanDispatcher.scala:31-55 ActorPlanDispatcher,
doc/query-engine.md:90-155 scatter-gather).  Here the frame protocol is
length-prefixed request/response over a plain TCP socket; the node side
executes against its local memstore source, so the coordinator's
NonLeafExecPlan scatter-gathers across machines exactly like the
single-process path.

Replies bigger than `query.stream_frame_bytes` stream as multiple
CRC-framed row slices (PR 15, parallel/streams.py): the coordinator
merges them incrementally (preallocated assembly, or the parent's
map+reduce fold), the query deadline applies PER frame, kills land
between frames, and a torn stream is the typed remote_failure — see
doc/query-engine.md "Aggregation pushdown & streaming".
"""
from __future__ import annotations

import json
import socket
import json
import socketserver
import struct
import threading
import zlib
from typing import Callable, Optional, Tuple

from filodb_tpu.parallel import serialize
from filodb_tpu.query.exec import PlanDispatcher, QueryResultLike
from filodb_tpu.query.rangevector import QueryStats

_MAGIC = b"FQ01"
# control-plane kill frame: payloads with this prefix carry a JSON kill
# request ({"id", "reason"}) instead of a serialized plan — recognized
# BEFORE serialize.loads, so a kill lands on a node whose handler
# threads are all busy executing (ThreadingTCPServer: the kill arrives
# on its own fresh connection)
_KILL_MAGIC = b"FKILL1"
# plan-request envelope (PR 15): _PLAN_MAGIC + u32 flags + plan bytes.
# Bit 0 of flags = the caller accepts a streamed (multi-frame) reply.
# Bare payloads without the envelope remain valid requests and get the
# legacy single-frame reply, so an old CLIENT can talk to a new server;
# new clients always envelope, so data nodes must upgrade before
# coordinators in a rolling deploy.
_PLAN_MAGIC = b"FPLN2"
_REQ_FLAG_STREAM = 1
# control-plane liveness/identity probe: payloads with this prefix get
# the server's `ping_info()` dict back (federation health probes read
# cluster identity + per-dataset data tokens through it).  Handled
# before serialize.loads, like kills, so a probe answers even while
# every handler thread is executing plans.
_PING_MAGIC = b"FPING1"
# streamed-reply frame: _STREAM_MAGIC + u8 flags (bit 0 = last frame) +
# u32 seq + u32 crc32(body) + body.  Non-last bodies carry {"begin"} /
# {"piece"} chunks (parallel/streams.py); the last frame carries the
# usual reply dict (ok/stats/spans or the typed error) — the per-frame
# CRC is the WAL's torn-write stance applied to the query wire.
_STREAM_MAGIC = b"FSTR1"
_STREAM_FLAG_LAST = 1
_STREAM_HDR = len(_STREAM_MAGIC) + 9


def _pack_stream_frame(seq: int, body: bytes, last: bool) -> bytes:
    return (_STREAM_MAGIC
            + struct.pack("<BII", _STREAM_FLAG_LAST if last else 0,
                          seq & 0xFFFFFFFF, zlib.crc32(body) & 0xFFFFFFFF)
            + body)


def _unpack_stream_frame(raw: bytes) -> Tuple[bool, int, bytes]:
    """(last, seq, body) — raises ValueError on a short header or a CRC
    mismatch (the caller maps that to the typed remote_failure)."""
    if len(raw) < _STREAM_HDR:
        raise ValueError("stream frame shorter than its header")
    flags, seq, crc = struct.unpack_from("<BII", raw, len(_STREAM_MAGIC))
    body = raw[_STREAM_HDR:]
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise ValueError(f"stream frame {seq} CRC mismatch")
    return bool(flags & _STREAM_FLAG_LAST), seq, body


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_MAGIC + struct.pack("<Q", len(payload)) + payload)


def _attach_registration(plan, ent) -> None:
    """Stamp the local registry entry's kill token onto EVERY ctx in a
    dispatched subtree: serialization gives each exec node its own
    QueryContext, and for a pushed-down group (RemoteAggregateExec) it
    is the per-shard LEAVES whose exec-boundary cancel checks actually
    stop the scans — a token only on the group root would let every
    shard run to completion after a kill."""
    stack = [plan]
    while stack:
        node = stack.pop()
        node.ctx.cancel = ent.token
        node.ctx.active = ent
        stack.extend(getattr(node, "children", ()) or ())


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        got = sock.recv(min(n, 1 << 20))
        if not got:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(got)
        n -= len(got)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> bytes:
    hdr = _recv_exact(sock, 12)
    if hdr[:4] != _MAGIC:
        raise ConnectionError(f"bad frame magic {hdr[:4]!r}")
    (ln,) = struct.unpack("<Q", hdr[4:])
    return _recv_exact(sock, ln)


def send_json_frame(sock: socket.socket, obj) -> None:
    """One JSON message as one frame — the shared control-plane encoding
    (cluster coordination, node control sockets, the chunk service)."""
    _send_frame(sock, json.dumps(obj).encode("utf-8"))


def recv_json_frame(sock: socket.socket):
    return json.loads(_recv_frame(sock).decode("utf-8"))


class NodeQueryServer:
    """Executes dispatched leaf plans against this node's source
    (the QueryActor receive loop, ref: coordinator/.../QueryActor.scala:119)."""

    def __init__(self, source, host: str = "127.0.0.1", port: int = 0,
                 ping_info: Optional[Callable[[], dict]] = None):
        self.source = source
        # optional identity payload for FPING probes (federation doors
        # answer cluster name + per-dataset data tokens through this)
        self._ping_info = ping_info
        # live handler connections: stop() severs them so a stopped
        # in-proc node looks EXACTLY like a SIGKILLed one to peers with
        # pooled sockets (shutdown() alone only stops accepting; pooled
        # dispatcher connections would keep being served by the handler
        # threads, hiding the death from failure-domain tests)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def setup(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)

            def finish(self):
                with outer._conns_lock:
                    outer._conns.discard(self.request)

            def handle(self):
                try:
                    while True:
                        payload = _recv_frame(self.request)
                        if payload.startswith(_KILL_MAGIC):
                            # cross-node cooperative cancellation: flip
                            # every token registered under the id on
                            # THIS node (idempotent; an already-
                            # completed child answers killed=False)
                            _send_frame(self.request,
                                        outer._handle_kill(payload))
                            continue
                        if payload.startswith(_PING_MAGIC):
                            _send_frame(self.request,
                                        outer._handle_ping())
                            continue
                        stream_ok = False
                        ent = None
                        verdict = "completed"
                        plan = None
                        try:
                            try:
                                from filodb_tpu.query.activequeries import \
                                    active_queries
                                from filodb_tpu.utils.metrics import (
                                    collector, span, trace_context)
                                # envelope parse INSIDE the try: a
                                # truncated FPLN2 header answers typed
                                # on a live connection, never a torn
                                # socket the coordinator misreads as a
                                # dead node
                                if payload.startswith(_PLAN_MAGIC):
                                    (rflags,) = struct.unpack_from(
                                        "<I", payload, len(_PLAN_MAGIC))
                                    stream_ok = bool(rflags
                                                     & _REQ_FLAG_STREAM)
                                    payload = payload[len(_PLAN_MAGIC)
                                                      + 4:]
                                plan = serialize.loads(payload)
                                tid = getattr(plan.ctx, "query_id", "")
                                # register the dispatched subtree in the
                                # LOCAL active-query registry under the
                                # coordinator's query id: one id names the
                                # whole distributed query, and a kill frame
                                # keyed by it stops this leaf's scan
                                if tid:
                                    ent = active_queries.register(
                                        tid,
                                        promql=(f"[remote] "
                                                f"{type(plan).__name__}"
                                                f"({plan.args_str()})")[:300],
                                        origin="remote", role="remote")
                                    if ent is not None:
                                        _attach_registration(plan, ent)
                                        ent.set_phase("executing")
                                # execute under the CALLER's trace id so this
                                # node's spans stitch into the same trace; ship
                                # them back with the reply (the Kamon-context-
                                # over-Akka analogue, ref: ExecPlan.scala:102)
                                with trace_context(tid),                                         span("remote_exec",
                                             plan=type(plan).__name__):
                                    data, stats = plan.execute_internal(
                                        outer.source)
                                spans = collector.take(tid) if tid else []
                            except Exception as e:  # noqa: BLE001 — errors ride the wire
                                from filodb_tpu.query.execbase import \
                                    QueryError
                                if isinstance(e, QueryError):
                                    # preserve the typed code across the
                                    # wire: a deadline expiring on THIS node
                                    # must surface at the coordinator as
                                    # query_timeout, not remote_failure
                                    err = {"ok": False, "error_code": e.code,
                                           "error": str(e)}
                                    verdict = ("killed"
                                               if e.code == "query_canceled"
                                               else "deadline"
                                               if e.code == "query_timeout"
                                               else "error")
                                else:
                                    err = {"ok": False,
                                           "error": f"{type(e).__name__}: {e}"}
                                    verdict = "error"
                                outer._send_error(self.request, stream_ok,
                                                  err)
                            else:
                                # reply while the registration is alive:
                                # a kill frame landing mid-STREAM must
                                # still find this entry's token
                                try:
                                    verdict = outer._send_reply(
                                        self.request, stream_ok, plan,
                                        data, stats, spans) or verdict
                                except (ConnectionError, OSError):
                                    raise       # client went away
                                except Exception as e:  # noqa: BLE001
                                    # reply serialization failed (e.g.
                                    # NotSerializable): answer typed —
                                    # tearing the connection would make
                                    # the client retry a stale socket
                                    # and re-execute the plan
                                    outer._send_error(
                                        self.request, stream_ok,
                                        {"ok": False,
                                         "error":
                                         f"{type(e).__name__}: {e}"})
                                    verdict = "error"
                        finally:
                            if ent is not None:
                                from filodb_tpu.query.activequeries \
                                    import active_queries
                                active_queries.deregister(ent, verdict)
                except (ConnectionError, OSError):
                    return              # client went away

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _handle_kill(payload: bytes) -> bytes:
        """Serve one kill frame: flip the tokens registered under the
        query id and report what happened (killed=False for an unknown
        or already-completed id — the idempotent contract)."""
        from filodb_tpu.query.activequeries import active_queries
        try:
            req = json.loads(payload[len(_KILL_MAGIC):].decode("utf-8"))
            out = active_queries.kill(str(req.get("id", "")),
                                      reason=str(req.get("reason",
                                                         "admin")),
                                      detail="kill frame from coordinator")
            return serialize.dumps({"ok": True, "data": out,
                                    "stats": None})
        except Exception as e:  # noqa: BLE001 — a bad kill frame must not
            return serialize.dumps(  # kill the handler connection
                {"ok": False, "error": f"{type(e).__name__}: {e}"})

    def _handle_ping(self) -> bytes:
        """Serve one liveness/identity probe frame."""
        try:
            info = self._ping_info() if self._ping_info is not None else {}
            return serialize.dumps({"ok": True, "data": info,
                                    "stats": None})
        except Exception as e:  # noqa: BLE001 — a bad probe must not
            return serialize.dumps(  # kill the handler connection
                {"ok": False, "error": f"{type(e).__name__}: {e}"})

    @staticmethod
    def _send_error(sock: socket.socket, stream_ok: bool, err: dict) -> None:
        body = serialize.dumps(err)
        if stream_ok:
            _send_frame(sock, _pack_stream_frame(0, body, last=True))
        else:
            _send_frame(sock, body)

    @staticmethod
    def _send_reply(sock: socket.socket, stream_ok: bool, plan, data,
                    stats, spans) -> Optional[str]:
        """Send one success reply — single-frame (legacy / small) or a
        chunked stream of CRC-framed row slices (parallel/streams.py)
        when the caller accepts it and the payload is big enough.
        Between piece frames the plan's cancellation token and deadline
        are re-checked, so a kill or an expired budget cuts the stream
        short with a typed error frame instead of pushing megabytes
        nobody is waiting for.  Returns a verdict override for the
        active-query registry ('killed'/'deadline') or None."""
        if not stream_ok:
            _send_frame(sock, serialize.dumps(
                {"ok": True, "data": data, "stats": stats, "spans": spans}))
            return None
        from filodb_tpu.config import settings
        from filodb_tpu.parallel import streams
        frame_bytes = settings().query.stream_frame_bytes
        split = (streams.split_for_stream(data, frame_bytes)
                 if frame_bytes > 0 else None)
        if split is None:
            _send_frame(sock, _pack_stream_frame(0, serialize.dumps(
                {"ok": True, "data": data, "stats": stats,
                 "spans": spans}), last=True))
            return None
        import time as _time
        begin, pieces = split
        seq = 0
        _send_frame(sock, _pack_stream_frame(
            seq, serialize.dumps({"begin": begin}), last=False))
        tok = getattr(plan.ctx, "cancel", None)
        dl = getattr(plan.ctx, "deadline_unix_s", 0.0)
        for piece in pieces:
            code = None
            if tok is not None and tok.cancelled:
                code, why = "query_canceled", "query killed mid-stream"
            elif dl and _time.time() >= dl:
                code, why = "query_timeout", "deadline expired mid-stream"
            if code is not None:
                seq += 1
                _send_frame(sock, _pack_stream_frame(seq, serialize.dumps(
                    {"ok": False, "error_code": code,
                     "error": f"{why} after {seq - 1} frames"}), last=True))
                return "killed" if code == "query_canceled" else "deadline"
            seq += 1
            _send_frame(sock, _pack_stream_frame(
                seq, serialize.dumps({"piece": piece}), last=False))
        seq += 1
        _send_frame(sock, _pack_stream_frame(seq, serialize.dumps(
            {"ok": True, "data": None, "streamed": True, "stats": stats,
             "spans": spans}), last=True))
        return None

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def start(self) -> "NodeQueryServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self._thread:
            self._thread.join(timeout=5)


def send_kill(host: str, port: int, query_id: str, reason: str = "admin",
              timeout_s: float = 2.0) -> dict:
    """Ship one kill frame to a remote node on a FRESH connection (the
    pooled dispatcher sockets are per-thread and may be blocked inside
    the very round-trip the kill is meant to cut short).  Returns the
    node's kill verdict dict; raises on transport failure (the caller
    counts propagation errors — a dead child needs no kill)."""
    payload = _KILL_MAGIC + json.dumps(
        {"id": query_id, "reason": reason}).encode("utf-8")
    with socket.create_connection((host, port), timeout=timeout_s) as s:
        s.settimeout(timeout_s)
        _send_frame(s, payload)
        reply = serialize.loads(_recv_frame(s))
    if not reply.get("ok"):
        raise ConnectionError(f"kill frame rejected: {reply.get('error')}")
    return reply.get("data") or {}


def send_ping(host: str, port: int, timeout_s: float = 2.0) -> dict:
    """One FPING probe on a fresh connection: returns the server's
    `ping_info()` dict (federation health probes carry cluster identity
    + per-dataset data tokens in it).  Raises on transport failure."""
    with socket.create_connection((host, port), timeout=timeout_s) as s:
        s.settimeout(timeout_s)
        _send_frame(s, _PING_MAGIC)
        reply = serialize.loads(_recv_frame(s))
    if not reply.get("ok"):
        raise ConnectionError(f"ping rejected: {reply.get('error')}")
    return reply.get("data") or {}


class RemoteNodeDispatcher(PlanDispatcher):
    """Coordinator-side dispatcher for one remote node; keeps one pooled
    connection per thread (ref: ActorPlanDispatcher ask-pattern send).

    `peer` renames the endpoint for breaker keying and error text: the
    federation layer passes `cluster:<name>` so a remote CLUSTER's
    breaker rows and degradation warnings carry the cluster name, not a
    bare host:port (kill fan-out still records the raw address)."""

    def __init__(self, host: str, port: int,
                 timeout_s: Optional[float] = None,
                 peer: Optional[str] = None):
        self.host, self.port = host, port
        self.peer = peer
        from filodb_tpu.config import settings
        q = settings().query
        if timeout_s is None:
            # the ask-timeout knob (ref: filodb-defaults.conf
            # query.ask-timeout; PlanDispatcher.scala:31 Akka ask)
            timeout_s = q.ask_timeout_s
        self.timeout_s = timeout_s
        # fraction of the REMAINING deadline budget one hop may spend
        # when partial results are allowed — without it a wedged peer
        # (accepts, never replies) consumes the whole budget and the
        # query times out even though degradation was allowed
        self.deadline_share = q.peer_deadline_share
        self._tls = threading.local()

    def pushdown_target(self) -> "RemoteNodeDispatcher":
        """This dispatcher IS a node address — aggregation pushdown can
        group same-node leaves behind it (query/pushdown.py)."""
        return self

    def _sock(self, timeout_s: Optional[float] = None
              ) -> Tuple[socket.socket, bool]:
        """Returns (socket, fresh): `fresh` distinguishes a just-opened
        connection from a pooled one that may have gone stale.  The
        timeout (per-hop ask timeout shrunk to the query's remaining
        deadline budget) applies to connect AND subsequent frame I/O."""
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        s = getattr(self._tls, "sock", None)
        if s is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._tls.sock = s
            return s, True
        s.settimeout(timeout_s)
        return s, False

    def _reset(self) -> None:
        s = getattr(self._tls, "sock", None)
        if s is not None:
            try:
                s.close()
            finally:
                self._tls.sock = None

    def _roundtrip(self, sock: socket.socket, payload: bytes) -> bytes:
        """One framed request/response, with the transport fault points:
        `transport.send` fires before the plan frame is written (corrupt
        plans mutate the payload the server will fail to decode), and
        `transport.recv` fires on the raw reply bytes."""
        from filodb_tpu.utils.faults import faults
        _send_frame(sock, faults.fire("transport.send", payload))
        return faults.fire("transport.recv", _recv_frame(sock))

    def dispatch(self, plan, source) -> QueryResultLike:
        import time as _time

        from filodb_tpu.parallel.breaker import breakers
        from filodb_tpu.query.execbase import QueryError
        addr = f"{self.host}:{self.port}"
        # breaker key + error-text identity: the federation layer names
        # the remote `cluster:<name>`; node fan-out keeps host:port
        where = self.peer or addr
        # record the child node on the query's live registry entry
        # BEFORE any wire I/O: a kill issued while this hop is blocked
        # in its round-trip must know where to send the kill frame (the
        # RAW address — kill frames dial it directly)
        act = getattr(plan.ctx, "active", None)
        if act is not None:
            act.note_remote(addr)
        dl = getattr(plan.ctx, "deadline_unix_s", 0.0)
        allow_partial = getattr(plan.ctx.planner_params,
                                "allow_partial_results", False)

        def _hop_timeout(what: str):
            """(socket timeout, budget_bounded) for one hop: the per-hop
            ask timeout, shrunk to the query's REMAINING deadline budget
            — each hop of a deep scatter spends from one end-to-end
            budget, not a fresh 120 s — and, when partial results are
            allowed, to the deadline SHARE (query.peer_deadline_share):
            one wedged peer may spend at most that fraction of the
            remainder, so its expiry is a droppable dispatch_timeout
            while the survivors still have budget.  Raises query_timeout
            when nothing remains."""
            t = self.timeout_s
            bounded = False
            remaining = dl - _time.time()
            if remaining <= 0:
                raise QueryError("query_timeout",
                                 f"no budget left {what} {where}")
            cap = remaining
            if allow_partial and 0 < self.deadline_share < 1:
                cap = remaining * self.deadline_share
            if cap < t:
                t = cap
                bounded = True
            return t, bounded

        # effective timeout derived BEFORE the breaker so an already-
        # expired query can never consume (and then strand) a half-open
        # probe slot.
        timeout_s = self.timeout_s
        budget_bounded = False
        if dl:
            timeout_s, budget_bounded = _hop_timeout("before dispatch to")
        # serialize BEFORE the breaker admits us: a NotSerializable (or
        # any unexpected dumps failure) after allow() granted the half-
        # open probe slot would bypass every on_success/on_failure/
        # on_abort path and wedge the breaker half-open forever
        from filodb_tpu.config import settings as _settings
        stream_req = _settings().query.stream_frame_bytes > 0
        payload = (_PLAN_MAGIC
                   + struct.pack("<I",
                                 _REQ_FLAG_STREAM if stream_req else 0)
                   + serialize.dumps(plan))
        # per-peer circuit breaker: a peer that keeps failing
        # shard_unavailable is failed FAST (microseconds, no socket) so
        # the partial-result path engages immediately instead of every
        # query serializing connect attempts to a dead node
        br = breakers.get(where) if breakers.enabled() else None
        if br is not None and not br.allow():
            raise QueryError(
                "shard_unavailable",
                f"node {where} circuit open "
                f"({br.consecutive_failures} consecutive failures; "
                f"failing fast until the half-open probe succeeds)")

        def _timeout_err(e):
            # classified by the CLOCK, not by which cap bounded the
            # wait: expiry at/after the global deadline IS the query's
            # deadline expiring (query_timeout — never dropped, the
            # budget is global); a wait the deadline SHARE cut short
            # leaves the survivors their budget, so it is the taxonomy's
            # droppable dispatch_timeout, exactly like an ask-bounded
            # wait.  Neither is EVER retried: the remote may still be
            # executing, and a re-send would run the query twice.  The
            # breaker learns NOTHING about liveness from a timeout — but
            # an admitted half-open probe must release its slot
            # (on_abort), or the breaker wedges.
            self._reset()
            if br is not None:
                br.on_abort()
            if dl and _time.time() >= dl:
                return QueryError(
                    "query_timeout",
                    f"node {where} gave no reply within the remaining "
                    f"deadline budget ({timeout_s:.3f}s)")
            return QueryError(
                "dispatch_timeout",
                f"node {where} gave no reply within {timeout_s:.3f}s "
                f"(not retried: the remote may still be executing)")

        def _unavailable(e, what):
            if br is not None:
                br.on_failure()
            return QueryError("shard_unavailable",
                              f"node {where} {what}: {e}")

        t_wire0 = _time.perf_counter()
        try:
            sock, fresh = self._sock(timeout_s)
        except socket.timeout as e:
            # connect timeout: unreachable (same class as refused) — but
            # a budget-bounded connect wait expired by the deadline or
            # its share teaches the breaker nothing about liveness
            if budget_bounded:
                raise _timeout_err(e) from e
            raise _unavailable(e, "unreachable") from e
        except OSError as e:
            # connect refused/unreachable: the owner is gone (SIGKILL,
            # network partition) — the taxonomy's shard_unavailable
            raise _unavailable(e, "unreachable") from e
        try:
            raw = self._roundtrip(sock, payload)
        except socket.timeout as e:
            raise _timeout_err(e) from e
        except (ConnectionError, OSError) as e:
            self._reset()
            if fresh:
                raise _unavailable(e, "died mid-dispatch") from e
            # pooled socket had gone stale — one retry on a fresh one,
            # counted + tagged so chaos runs can tell stale-pool churn
            # from real peer death.  The CONNECT is classified
            # separately: a connect timeout means the node is
            # unreachable (shard_unavailable, same as the first-attempt
            # path), not "accepted but silent"
            from filodb_tpu.utils.metrics import registry, span
            registry.counter("transport_stale_socket_retries").increment()
            # re-derive the remaining budget for the retry: the first
            # attempt may have burned most of it before dying, and
            # reusing the stale value could block up to 2x the deadline
            if dl:
                try:
                    timeout_s, budget_bounded = _hop_timeout(
                        "to retry stale socket to")
                except QueryError:
                    # release an admitted half-open probe slot before
                    # bailing (every exit path must: a leaked slot
                    # wedges the breaker half-open forever)
                    if br is not None:
                        br.on_abort()
                    raise
            try:
                with span("transport_reconnect", peer=where,
                          reason="stale_pool"):
                    sock, _ = self._sock(timeout_s)
            except socket.timeout as e2:
                # same classification as the first-attempt connect: a
                # budget-bounded connect timeout is the deadline (or its
                # share) expiring, NOT evidence of peer death — it must
                # not feed the breaker's failure count
                if budget_bounded:
                    raise _timeout_err(e2) from e2
                raise _unavailable(e2, "unreachable") from e2
            except OSError as e2:
                raise _unavailable(e2, "unreachable") from e2
            try:
                raw = self._roundtrip(sock, payload)
            except socket.timeout as e2:
                raise _timeout_err(e2) from e2
            except (ConnectionError, OSError) as e2:
                self._reset()
                raise _unavailable(e2, "died mid-dispatch") from e2
        if br is not None:
            # a reply frame arrived: the peer is alive (even a
            # remote_failure reply resets the consecutive-failure run)
            br.on_success()
        total_raw = len(raw)
        frames = 0
        assembler = None
        if stream_req and raw.startswith(_STREAM_MAGIC):
            # streamed (multi-frame) reply: fold each CRC-checked row
            # slice into the preallocated assembler as it arrives —
            # bounded coordinator memory per child regardless of range.
            # The deadline applies PER FRAME (a stalled peer expires by
            # the clock like any hop) and the query's own kill token is
            # re-checked between frames.  A torn stream is the typed
            # remote_failure, never a hang and never a silent partial
            # (the assembler refuses to finish() short).
            from filodb_tpu.parallel import streams
            frames = 1
            tok = getattr(plan.ctx, "cancel", None)
            reply = None
            try:
                while True:
                    last, _seq, body = _unpack_stream_frame(raw)
                    msg = serialize.loads(body)
                    if last:
                        reply = msg
                        break
                    if "begin" in msg:
                        # a parent that can merge row slices in place
                        # (ReduceAggregateExec's map+reduce fold) gets
                        # each piece as a mini block and the child is
                        # NEVER materialized whole on the coordinator
                        ff = getattr(plan, "_stream_fold", None)
                        if ff is not None and \
                                msg["begin"].get("type") == "ResultBlock":
                            assembler = streams.StreamFold(msg["begin"],
                                                           ff())
                        else:
                            assembler = streams.StreamAssembler(
                                msg["begin"])
                    elif "piece" in msg:
                        if assembler is None:
                            raise ValueError("stream piece before begin")
                        assembler.add(msg["piece"])
                    else:
                        raise ValueError(
                            f"unknown stream frame keys {sorted(msg)}")
                    if tok is not None and tok.cancelled:
                        # the stream is mid-flight: the pooled socket is
                        # out of sync with the peer — drop it
                        self._reset()
                        tok.raise_if_cancelled(
                            f"mid-stream from node {where}")
                    if dl:
                        left = dl - _time.time()
                        if left <= 0:
                            self._reset()
                            raise QueryError(
                                "query_timeout",
                                f"deadline expired mid-stream from node "
                                f"{where} ({frames} frames in)")
                        # same share cap as the initial hop: under
                        # partial results one stalled peer may burn at
                        # most its deadline SHARE of the remainder per
                        # frame wait (a droppable dispatch_timeout),
                        # never the survivors' whole budget
                        if allow_partial and 0 < self.deadline_share < 1:
                            left *= self.deadline_share
                        sock.settimeout(min(self.timeout_s, left))
                    raw = _recv_frame(sock)
                    frames += 1
                    total_raw += len(raw)
            except QueryError:
                raise
            except streams.FoldError as fe:
                # application error inside the parent's fold (group-by
                # cardinality limit, ...): the socket is out of sync
                # mid-stream — drop it, but surface the REAL error
                self._reset()
                raise fe.cause
            except socket.timeout as e:
                self._reset()
                if dl and _time.time() >= dl:
                    raise QueryError(
                        "query_timeout",
                        f"node {where} stalled mid-stream past the "
                        f"remaining deadline budget") from e
                raise QueryError(
                    "dispatch_timeout",
                    f"node {where} stalled mid-stream (not retried: the "
                    f"remote may still be sending)") from e
            except (ConnectionError, OSError) as e:
                self._reset()
                raise QueryError(
                    "remote_failure",
                    f"node {where} stream torn mid-frame after {frames} "
                    f"frames: {type(e).__name__}: {e}") from e
            except Exception as e:  # noqa: BLE001 — CRC/decode garbage
                self._reset()
                raise QueryError(
                    "remote_failure",
                    f"node {where} sent a corrupt stream frame: "
                    f"{type(e).__name__}: {e}") from e
        else:
            try:
                reply = serialize.loads(raw)
            except Exception as e:  # noqa: BLE001 — garbage frame, any shape
                # corrupt reply: the stream may be out of sync — drop the
                # pooled connection; NOT retried (the remote did execute)
                self._reset()
                raise QueryError(
                    "remote_failure",
                    f"node {where} sent a corrupt reply frame: "
                    f"{type(e).__name__}: {e}") from e
        if not reply["ok"]:
            # a typed QueryError that fired ON the remote keeps its code
            # (query_timeout stays errorType "timeout" at the HTTP edge;
            # a nested shard_unavailable stays retry/drop-eligible) —
            # everything else is the taxonomy's remote_failure
            code = reply.get("error_code")
            detail = reply["error"]
            if code:
                if detail.startswith(code + ":"):
                    detail = detail[len(code) + 1:].strip()
                raise QueryError(code, f"(via node {where}) {detail}")
            raise QueryError("remote_failure",
                             f"node {where} failed: {detail}")
        # stitch the remote node's spans into the caller's trace (they
        # arrive stamped with the remote NODE_NAME)
        spans = reply.get("spans")
        if spans:
            from filodb_tpu.utils.metrics import collector
            tid = getattr(plan.ctx, "query_id", "")
            for ev in spans:
                if isinstance(ev, dict):
                    collector.record(tid, ev)
        stats = reply["stats"] or QueryStats()
        # live-counter mirror: the remote leaf's scan work lands on the
        # coordinator's registry entry too (its own entry on the remote
        # node deregisters with the reply), so /admin/queries on the
        # coordinator shows the whole distributed query's burn
        if act is not None:
            act.add(samples=stats.samples_scanned,
                    paged_samples=stats.samples_paged,
                    paged_bytes=stats.bytes_paged)
        # resource attribution across the wire (PR 3): the remote's own
        # phase seconds arrived inside `stats`; the round trip minus the
        # remote's busy time is serialization + network — transfer.  The
        # whole round trip is credited as CHILD wall so the coordinator
        # node's exclusive cpu_seconds never claims the network wait.
        from filodb_tpu.utils.metrics import exec_tally
        wire_wall = _time.perf_counter() - t_wire0
        exec_tally.child_wall += wire_wall
        remote_busy = (stats.cpu_seconds + stats.device_seconds
                       + stats.transfer_s)
        stats.transfer_s += max(wire_wall - remote_busy, 0.0)
        stats.bytes_transferred += len(payload) + total_raw
        # true wire attribution (PR 15): bytes_transferred above also
        # counts host→device uploads the remote's stats brought along,
        # so the slowlog/?stats=true wire column gets its own counter
        stats.wire_bytes += len(payload) + total_raw
        data_out = reply["data"]
        if reply.get("streamed"):
            from filodb_tpu.utils.metrics import registry
            registry.counter("transport_stream_frames").increment(frames)
            stats.streamed_frames += frames
            if assembler is None:
                raise QueryError(
                    "remote_failure",
                    f"node {where} flagged a streamed reply without a "
                    f"begin frame")
            from filodb_tpu.parallel import streams
            try:
                data_out = assembler.finish()
            except streams.FoldError as fe:
                raise fe.cause
            except ValueError as e:
                # a short stream must NEVER pass as a full result
                raise QueryError(
                    "remote_failure",
                    f"node {where} stream incomplete: {e}") from e
        return data_out, stats
