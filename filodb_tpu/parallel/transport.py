"""Cross-node query transport: dispatch serialized plan subtrees over TCP.

The reference's data plane sends Kryo'd ExecPlan subtrees to the shard's
owning node with the Akka ask pattern and gets Kryo'd QueryResults back
(ref: exec/PlanDispatcher.scala:31-55 ActorPlanDispatcher,
doc/query-engine.md:90-155 scatter-gather).  Here the frame protocol is
length-prefixed request/response over a plain TCP socket; the node side
executes against its local memstore source, so the coordinator's
NonLeafExecPlan scatter-gathers across machines exactly like the
single-process path.
"""
from __future__ import annotations

import json
import socket
import json
import socketserver
import struct
import threading
from typing import Callable, Optional, Tuple

from filodb_tpu.parallel import serialize
from filodb_tpu.query.exec import PlanDispatcher, QueryResultLike
from filodb_tpu.query.rangevector import QueryStats

_MAGIC = b"FQ01"
# control-plane kill frame: payloads with this prefix carry a JSON kill
# request ({"id", "reason"}) instead of a serialized plan — recognized
# BEFORE serialize.loads, so a kill lands on a node whose handler
# threads are all busy executing (ThreadingTCPServer: the kill arrives
# on its own fresh connection)
_KILL_MAGIC = b"FKILL1"


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_MAGIC + struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        got = sock.recv(min(n, 1 << 20))
        if not got:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(got)
        n -= len(got)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> bytes:
    hdr = _recv_exact(sock, 12)
    if hdr[:4] != _MAGIC:
        raise ConnectionError(f"bad frame magic {hdr[:4]!r}")
    (ln,) = struct.unpack("<Q", hdr[4:])
    return _recv_exact(sock, ln)


def send_json_frame(sock: socket.socket, obj) -> None:
    """One JSON message as one frame — the shared control-plane encoding
    (cluster coordination, node control sockets, the chunk service)."""
    _send_frame(sock, json.dumps(obj).encode("utf-8"))


def recv_json_frame(sock: socket.socket):
    return json.loads(_recv_frame(sock).decode("utf-8"))


class NodeQueryServer:
    """Executes dispatched leaf plans against this node's source
    (the QueryActor receive loop, ref: coordinator/.../QueryActor.scala:119)."""

    def __init__(self, source, host: str = "127.0.0.1", port: int = 0):
        self.source = source
        # live handler connections: stop() severs them so a stopped
        # in-proc node looks EXACTLY like a SIGKILLed one to peers with
        # pooled sockets (shutdown() alone only stops accepting; pooled
        # dispatcher connections would keep being served by the handler
        # threads, hiding the death from failure-domain tests)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def setup(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)

            def finish(self):
                with outer._conns_lock:
                    outer._conns.discard(self.request)

            def handle(self):
                try:
                    while True:
                        payload = _recv_frame(self.request)
                        if payload.startswith(_KILL_MAGIC):
                            # cross-node cooperative cancellation: flip
                            # every token registered under the id on
                            # THIS node (idempotent; an already-
                            # completed child answers killed=False)
                            _send_frame(self.request,
                                        outer._handle_kill(payload))
                            continue
                        ent = None
                        verdict = "completed"
                        try:
                            from filodb_tpu.query.activequeries import \
                                active_queries
                            from filodb_tpu.utils.metrics import (
                                collector, span, trace_context)
                            plan = serialize.loads(payload)
                            tid = getattr(plan.ctx, "query_id", "")
                            # register the dispatched subtree in the
                            # LOCAL active-query registry under the
                            # coordinator's query id: one id names the
                            # whole distributed query, and a kill frame
                            # keyed by it stops this leaf's scan
                            if tid:
                                ent = active_queries.register(
                                    tid,
                                    promql=(f"[remote] "
                                            f"{type(plan).__name__}"
                                            f"({plan.args_str()})")[:300],
                                    origin="remote", role="remote")
                                if ent is not None:
                                    plan.ctx.cancel = ent.token
                                    plan.ctx.active = ent
                                    ent.set_phase("executing")
                            # execute under the CALLER's trace id so this
                            # node's spans stitch into the same trace; ship
                            # them back with the reply (the Kamon-context-
                            # over-Akka analogue, ref: ExecPlan.scala:102)
                            with trace_context(tid),                                     span("remote_exec",
                                         plan=type(plan).__name__):
                                data, stats = plan.execute_internal(
                                    outer.source)
                            reply = serialize.dumps(
                                {"ok": True, "data": data, "stats": stats,
                                 "spans": (collector.take(tid)
                                           if tid else [])})
                        except Exception as e:  # noqa: BLE001 — errors ride the wire
                            from filodb_tpu.query.execbase import \
                                QueryError
                            if isinstance(e, QueryError):
                                # preserve the typed code across the
                                # wire: a deadline expiring on THIS node
                                # must surface at the coordinator as
                                # query_timeout, not remote_failure
                                reply = serialize.dumps(
                                    {"ok": False, "error_code": e.code,
                                     "error": str(e)})
                                verdict = ("killed"
                                           if e.code == "query_canceled"
                                           else "deadline"
                                           if e.code == "query_timeout"
                                           else "error")
                            else:
                                reply = serialize.dumps(
                                    {"ok": False,
                                     "error": f"{type(e).__name__}: {e}"})
                                verdict = "error"
                        finally:
                            if ent is not None:
                                from filodb_tpu.query.activequeries \
                                    import active_queries
                                active_queries.deregister(ent, verdict)
                        _send_frame(self.request, reply)
                except (ConnectionError, OSError):
                    return              # client went away

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _handle_kill(payload: bytes) -> bytes:
        """Serve one kill frame: flip the tokens registered under the
        query id and report what happened (killed=False for an unknown
        or already-completed id — the idempotent contract)."""
        from filodb_tpu.query.activequeries import active_queries
        try:
            req = json.loads(payload[len(_KILL_MAGIC):].decode("utf-8"))
            out = active_queries.kill(str(req.get("id", "")),
                                      reason=str(req.get("reason",
                                                         "admin")),
                                      detail="kill frame from coordinator")
            return serialize.dumps({"ok": True, "data": out,
                                    "stats": None})
        except Exception as e:  # noqa: BLE001 — a bad kill frame must not
            return serialize.dumps(  # kill the handler connection
                {"ok": False, "error": f"{type(e).__name__}: {e}"})

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def start(self) -> "NodeQueryServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self._thread:
            self._thread.join(timeout=5)


def send_kill(host: str, port: int, query_id: str, reason: str = "admin",
              timeout_s: float = 2.0) -> dict:
    """Ship one kill frame to a remote node on a FRESH connection (the
    pooled dispatcher sockets are per-thread and may be blocked inside
    the very round-trip the kill is meant to cut short).  Returns the
    node's kill verdict dict; raises on transport failure (the caller
    counts propagation errors — a dead child needs no kill)."""
    payload = _KILL_MAGIC + json.dumps(
        {"id": query_id, "reason": reason}).encode("utf-8")
    with socket.create_connection((host, port), timeout=timeout_s) as s:
        s.settimeout(timeout_s)
        _send_frame(s, payload)
        reply = serialize.loads(_recv_frame(s))
    if not reply.get("ok"):
        raise ConnectionError(f"kill frame rejected: {reply.get('error')}")
    return reply.get("data") or {}


class RemoteNodeDispatcher(PlanDispatcher):
    """Coordinator-side dispatcher for one remote node; keeps one pooled
    connection per thread (ref: ActorPlanDispatcher ask-pattern send)."""

    def __init__(self, host: str, port: int,
                 timeout_s: Optional[float] = None):
        self.host, self.port = host, port
        from filodb_tpu.config import settings
        q = settings().query
        if timeout_s is None:
            # the ask-timeout knob (ref: filodb-defaults.conf
            # query.ask-timeout; PlanDispatcher.scala:31 Akka ask)
            timeout_s = q.ask_timeout_s
        self.timeout_s = timeout_s
        # fraction of the REMAINING deadline budget one hop may spend
        # when partial results are allowed — without it a wedged peer
        # (accepts, never replies) consumes the whole budget and the
        # query times out even though degradation was allowed
        self.deadline_share = q.peer_deadline_share
        self._tls = threading.local()

    def _sock(self, timeout_s: Optional[float] = None
              ) -> Tuple[socket.socket, bool]:
        """Returns (socket, fresh): `fresh` distinguishes a just-opened
        connection from a pooled one that may have gone stale.  The
        timeout (per-hop ask timeout shrunk to the query's remaining
        deadline budget) applies to connect AND subsequent frame I/O."""
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        s = getattr(self._tls, "sock", None)
        if s is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._tls.sock = s
            return s, True
        s.settimeout(timeout_s)
        return s, False

    def _reset(self) -> None:
        s = getattr(self._tls, "sock", None)
        if s is not None:
            try:
                s.close()
            finally:
                self._tls.sock = None

    def _roundtrip(self, sock: socket.socket, payload: bytes) -> bytes:
        """One framed request/response, with the transport fault points:
        `transport.send` fires before the plan frame is written (corrupt
        plans mutate the payload the server will fail to decode), and
        `transport.recv` fires on the raw reply bytes."""
        from filodb_tpu.utils.faults import faults
        _send_frame(sock, faults.fire("transport.send", payload))
        return faults.fire("transport.recv", _recv_frame(sock))

    def dispatch(self, plan, source) -> QueryResultLike:
        import time as _time

        from filodb_tpu.parallel.breaker import breakers
        from filodb_tpu.query.execbase import QueryError
        where = f"{self.host}:{self.port}"
        # record the child node on the query's live registry entry
        # BEFORE any wire I/O: a kill issued while this hop is blocked
        # in its round-trip must know where to send the kill frame
        act = getattr(plan.ctx, "active", None)
        if act is not None:
            act.note_remote(where)
        dl = getattr(plan.ctx, "deadline_unix_s", 0.0)
        allow_partial = getattr(plan.ctx.planner_params,
                                "allow_partial_results", False)

        def _hop_timeout(what: str):
            """(socket timeout, budget_bounded) for one hop: the per-hop
            ask timeout, shrunk to the query's REMAINING deadline budget
            — each hop of a deep scatter spends from one end-to-end
            budget, not a fresh 120 s — and, when partial results are
            allowed, to the deadline SHARE (query.peer_deadline_share):
            one wedged peer may spend at most that fraction of the
            remainder, so its expiry is a droppable dispatch_timeout
            while the survivors still have budget.  Raises query_timeout
            when nothing remains."""
            t = self.timeout_s
            bounded = False
            remaining = dl - _time.time()
            if remaining <= 0:
                raise QueryError("query_timeout",
                                 f"no budget left {what} {where}")
            cap = remaining
            if allow_partial and 0 < self.deadline_share < 1:
                cap = remaining * self.deadline_share
            if cap < t:
                t = cap
                bounded = True
            return t, bounded

        # effective timeout derived BEFORE the breaker so an already-
        # expired query can never consume (and then strand) a half-open
        # probe slot.
        timeout_s = self.timeout_s
        budget_bounded = False
        if dl:
            timeout_s, budget_bounded = _hop_timeout("before dispatch to")
        # serialize BEFORE the breaker admits us: a NotSerializable (or
        # any unexpected dumps failure) after allow() granted the half-
        # open probe slot would bypass every on_success/on_failure/
        # on_abort path and wedge the breaker half-open forever
        payload = serialize.dumps(plan)
        # per-peer circuit breaker: a peer that keeps failing
        # shard_unavailable is failed FAST (microseconds, no socket) so
        # the partial-result path engages immediately instead of every
        # query serializing connect attempts to a dead node
        br = breakers.get(where) if breakers.enabled() else None
        if br is not None and not br.allow():
            raise QueryError(
                "shard_unavailable",
                f"node {where} circuit open "
                f"({br.consecutive_failures} consecutive failures; "
                f"failing fast until the half-open probe succeeds)")

        def _timeout_err(e):
            # classified by the CLOCK, not by which cap bounded the
            # wait: expiry at/after the global deadline IS the query's
            # deadline expiring (query_timeout — never dropped, the
            # budget is global); a wait the deadline SHARE cut short
            # leaves the survivors their budget, so it is the taxonomy's
            # droppable dispatch_timeout, exactly like an ask-bounded
            # wait.  Neither is EVER retried: the remote may still be
            # executing, and a re-send would run the query twice.  The
            # breaker learns NOTHING about liveness from a timeout — but
            # an admitted half-open probe must release its slot
            # (on_abort), or the breaker wedges.
            self._reset()
            if br is not None:
                br.on_abort()
            if dl and _time.time() >= dl:
                return QueryError(
                    "query_timeout",
                    f"node {where} gave no reply within the remaining "
                    f"deadline budget ({timeout_s:.3f}s)")
            return QueryError(
                "dispatch_timeout",
                f"node {where} gave no reply within {timeout_s:.3f}s "
                f"(not retried: the remote may still be executing)")

        def _unavailable(e, what):
            if br is not None:
                br.on_failure()
            return QueryError("shard_unavailable",
                              f"node {where} {what}: {e}")

        t_wire0 = _time.perf_counter()
        try:
            sock, fresh = self._sock(timeout_s)
        except socket.timeout as e:
            # connect timeout: unreachable (same class as refused) — but
            # a budget-bounded connect wait expired by the deadline or
            # its share teaches the breaker nothing about liveness
            if budget_bounded:
                raise _timeout_err(e) from e
            raise _unavailable(e, "unreachable") from e
        except OSError as e:
            # connect refused/unreachable: the owner is gone (SIGKILL,
            # network partition) — the taxonomy's shard_unavailable
            raise _unavailable(e, "unreachable") from e
        try:
            raw = self._roundtrip(sock, payload)
        except socket.timeout as e:
            raise _timeout_err(e) from e
        except (ConnectionError, OSError) as e:
            self._reset()
            if fresh:
                raise _unavailable(e, "died mid-dispatch") from e
            # pooled socket had gone stale — one retry on a fresh one,
            # counted + tagged so chaos runs can tell stale-pool churn
            # from real peer death.  The CONNECT is classified
            # separately: a connect timeout means the node is
            # unreachable (shard_unavailable, same as the first-attempt
            # path), not "accepted but silent"
            from filodb_tpu.utils.metrics import registry, span
            registry.counter("transport_stale_socket_retries").increment()
            # re-derive the remaining budget for the retry: the first
            # attempt may have burned most of it before dying, and
            # reusing the stale value could block up to 2x the deadline
            if dl:
                try:
                    timeout_s, budget_bounded = _hop_timeout(
                        "to retry stale socket to")
                except QueryError:
                    # release an admitted half-open probe slot before
                    # bailing (every exit path must: a leaked slot
                    # wedges the breaker half-open forever)
                    if br is not None:
                        br.on_abort()
                    raise
            try:
                with span("transport_reconnect", peer=where,
                          reason="stale_pool"):
                    sock, _ = self._sock(timeout_s)
            except socket.timeout as e2:
                # same classification as the first-attempt connect: a
                # budget-bounded connect timeout is the deadline (or its
                # share) expiring, NOT evidence of peer death — it must
                # not feed the breaker's failure count
                if budget_bounded:
                    raise _timeout_err(e2) from e2
                raise _unavailable(e2, "unreachable") from e2
            except OSError as e2:
                raise _unavailable(e2, "unreachable") from e2
            try:
                raw = self._roundtrip(sock, payload)
            except socket.timeout as e2:
                raise _timeout_err(e2) from e2
            except (ConnectionError, OSError) as e2:
                self._reset()
                raise _unavailable(e2, "died mid-dispatch") from e2
        if br is not None:
            # a reply frame arrived: the peer is alive (even a
            # remote_failure reply resets the consecutive-failure run)
            br.on_success()
        try:
            reply = serialize.loads(raw)
        except Exception as e:  # noqa: BLE001 — garbage frame, any shape
            # corrupt reply: the stream may be out of sync — drop the
            # pooled connection; NOT retried (the remote did execute)
            self._reset()
            raise QueryError(
                "remote_failure",
                f"node {where} sent a corrupt reply frame: "
                f"{type(e).__name__}: {e}") from e
        if not reply["ok"]:
            # a typed QueryError that fired ON the remote keeps its code
            # (query_timeout stays errorType "timeout" at the HTTP edge;
            # a nested shard_unavailable stays retry/drop-eligible) —
            # everything else is the taxonomy's remote_failure
            code = reply.get("error_code")
            detail = reply["error"]
            if code:
                if detail.startswith(code + ":"):
                    detail = detail[len(code) + 1:].strip()
                raise QueryError(code, f"(via node {where}) {detail}")
            raise QueryError("remote_failure",
                             f"node {where} failed: {detail}")
        # stitch the remote node's spans into the caller's trace (they
        # arrive stamped with the remote NODE_NAME)
        spans = reply.get("spans")
        if spans:
            from filodb_tpu.utils.metrics import collector
            tid = getattr(plan.ctx, "query_id", "")
            for ev in spans:
                if isinstance(ev, dict):
                    collector.record(tid, ev)
        stats = reply["stats"] or QueryStats()
        # live-counter mirror: the remote leaf's scan work lands on the
        # coordinator's registry entry too (its own entry on the remote
        # node deregisters with the reply), so /admin/queries on the
        # coordinator shows the whole distributed query's burn
        if act is not None:
            act.add(samples=stats.samples_scanned,
                    paged_samples=stats.samples_paged,
                    paged_bytes=stats.bytes_paged)
        # resource attribution across the wire (PR 3): the remote's own
        # phase seconds arrived inside `stats`; the round trip minus the
        # remote's busy time is serialization + network — transfer.  The
        # whole round trip is credited as CHILD wall so the coordinator
        # node's exclusive cpu_seconds never claims the network wait.
        from filodb_tpu.utils.metrics import exec_tally
        wire_wall = _time.perf_counter() - t_wire0
        exec_tally.child_wall += wire_wall
        remote_busy = (stats.cpu_seconds + stats.device_seconds
                       + stats.transfer_s)
        stats.transfer_s += max(wire_wall - remote_busy, 0.0)
        stats.bytes_transferred += len(payload) + len(raw)
        return reply["data"], stats
