"""Row-sliced streaming of query result payloads over the node transport.

The single-frame reply protocol buffers an entire serialized result —
for a 30-day cold-tier block that is the whole [S, W] matrix TWICE on
the coordinator (raw reply bytes + decoded arrays) before the exec tree
even sees it.  This module is the chunking half of the streamed reply
path (parallel/transport.py): the data node splits a result into
bounded row slices, and the coordinator's `StreamAssembler` writes each
slice into preallocated arrays as its frame arrives — peak memory is
the result itself plus ONE frame, regardless of range.

The begin/piece shape is deliberately dumb: a `begin` dict carries the
constant fields plus per-array dtype/shape templates, every `piece`
carries a row offset and the row slices.  `finish()` refuses to hand
back a block whose rows were not all filled — a torn stream can never
be silently treated as a full result (the transport layer raises the
typed `remote_failure` before that, but the assembler is the last
line).

Splittable payloads: RawBlock / ResultBlock (row axis = series) and
AggPartial (row axis = groups for the component/sketch forms, candidate
rows for the topk/count_values form).  Everything else rides inline in
the final frame.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from filodb_tpu.query.execbase import AggPartial, RawBlock
from filodb_tpu.query.rangevector import ResultBlock

# type name -> (list-valued row fields, array-valued row fields,
# constant fields).  Optional row arrays (vbase, comp vs sketch) are
# simply absent from a begin's templates when None.
_SPECS: Dict[str, Tuple[Tuple[str, ...], Tuple[str, ...], Tuple[str, ...]]] = {
    "RawBlock": (("keys",), ("ts_off", "values", "vbase"),
                 ("base_ms", "bucket_les", "samples", "precorrected",
                  "shared_ts_row", "dense", "route_host")),
    "ResultBlock": (("keys",), ("values",), ("wends", "bucket_les")),
    # component / sketch forms: rows are groups
    "AggPartial": (("group_keys",), ("comp", "sketch"),
                   ("op", "wends", "params", "bucket_les")),
    # candidate form: rows are candidate series, groups ride whole
    "AggPartialCand": (("cand_keys",), ("cand_vals", "cand_groups"),
                       ("op", "wends", "params", "bucket_les",
                        "group_keys")),
}

_CLASSES = {"RawBlock": RawBlock, "ResultBlock": ResultBlock,
            "AggPartial": AggPartial, "AggPartialCand": AggPartial}


def _spec_for(data) -> Optional[Tuple[str, int]]:
    """(spec name, row count) for a splittable payload, else None."""
    if isinstance(data, RawBlock):
        return "RawBlock", int(np.asarray(data.ts_off).shape[0])
    if isinstance(data, AggPartial):
        if data.cand_vals is not None:
            return "AggPartialCand", int(np.asarray(data.cand_vals).shape[0])
        return "AggPartial", len(data.group_keys)
    if isinstance(data, ResultBlock):
        return "ResultBlock", int(np.asarray(data.values).shape[0])
    return None


def split_for_stream(data, max_bytes: int):
    """(begin, [piece, ...]) when `data` is a splittable payload bigger
    than `max_bytes`, else None (the reply rides inline in one frame).

    Pieces slice ONLY along the row axis so the receiving assembler can
    preallocate from the begin templates and fill slices in place."""
    if max_bytes <= 0:
        return None
    found = _spec_for(data)
    if found is None:
        return None
    name, nrows = found
    if nrows <= 1:
        return None
    list_fields, arr_fields, const_fields = _SPECS[name]
    arrays: Dict[str, np.ndarray] = {}
    for f in arr_fields:
        v = getattr(data, f, None)
        if v is not None:
            a = np.asarray(v)
            if a.shape and a.shape[0] == nrows:
                arrays[f] = a
    lists: Dict[str, List] = {}
    for f in list_fields:
        v = getattr(data, f, None)
        lists[f] = list(v) if v is not None else []  # LazyKeys materialize
    total = sum(a.nbytes for a in arrays.values())
    if total <= max_bytes or not arrays:
        return None
    row_bytes = max(total / nrows, 1.0)
    step = max(1, int(max_bytes // row_bytes))
    begin = {
        "type": name,
        "rows": nrows,
        "fields": {f: {"dtype": str(a.dtype), "shape": list(a.shape)}
                   for f, a in arrays.items()},
        "lists": sorted(lists),
        "const": {f: getattr(data, f, None) for f in const_fields},
    }
    pieces = []
    for r0 in range(0, nrows, step):
        r1 = min(r0 + step, nrows)
        pieces.append({
            "r0": r0, "n": r1 - r0,
            # row slices stay VIEWS: a row slice of a contiguous array
            # is contiguous, so the serializer's ascontiguousarray is a
            # no-op and the only per-frame copy is tobytes() at send
            # time — the sender never holds a second full copy
            "arrays": {f: a[r0:r1] for f, a in arrays.items()},
            "lists": {f: l[r0:r1] for f, l in lists.items()},
        })
    return begin, pieces


def piece_block(begin: dict, piece: dict):
    """Materialize ONE piece as a standalone payload of the begin's type
    (a row-slice mini block) — the incremental-fold path: a parent that
    can merge row slices directly (ReduceAggregateExec's map+reduce
    fold) consumes each frame and never holds the child whole."""
    name = begin.get("type")
    if name not in _SPECS:
        raise ValueError(f"unknown stream payload type {name!r}")
    cls = _CLASSES[name]
    kwargs = dict(begin.get("const") or {})
    n = int(piece["n"])
    for f, arr in (piece.get("arrays") or {}).items():
        a = np.asarray(arr)
        if not a.shape or a.shape[0] != n:
            raise ValueError(f"stream piece field {f} does not lead with "
                             f"its row count {n}")
        kwargs[f] = a
    for f, items in (piece.get("lists") or {}).items():
        if len(items) != n:
            raise ValueError(f"stream piece list {f} has {len(items)} "
                             f"items for {n} rows")
        kwargs[f] = list(items)
    if isinstance(kwargs.get("params"), list):
        kwargs["params"] = tuple(kwargs["params"])
    field_names = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kwargs.items() if k in field_names})


class FoldError(Exception):
    """An APPLICATION error raised inside a parent's fold (e.g. the
    group-by cardinality limit) — distinct from protocol/shape errors so
    the transport can surface the real error instead of remote_failure.
    The original exception rides in `cause`."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


class StreamFold:
    """Incremental consumer: each piece becomes a mini block handed to
    the parent-provided fold object (`fold.add(block)` / `fold.result()`)
    as its frame arrives.  Row accounting matches the assembler's — a
    short stream still refuses to finish."""

    def __init__(self, begin: dict, fold):
        if begin.get("type") not in _SPECS:
            raise ValueError(
                f"unknown stream payload type {begin.get('type')!r}")
        self._begin = begin
        self._fold = fold
        self._rows = int(begin["rows"])
        self._filled = 0

    def add(self, piece: dict) -> None:
        # pieces are emitted in strict row order — continuity closes
        # duplicated/overlapping/reordered frames, which would otherwise
        # double-fold rows while still satisfying the row count
        if int(piece["r0"]) != self._filled:
            raise ValueError(
                f"stream piece rows start at {piece['r0']}, expected "
                f"{self._filled} (out-of-order or duplicated frame)")
        blk = piece_block(self._begin, piece)
        try:
            self._fold.add(blk)
        except Exception as e:  # noqa: BLE001 — app error, not protocol
            raise FoldError(e) from e
        self._filled += int(piece["n"])

    def finish(self):
        if self._filled != self._rows:
            raise ValueError(
                f"short stream: {self._filled}/{self._rows} rows arrived")
        try:
            return self._fold.result()
        except Exception as e:  # noqa: BLE001 — app error, not protocol
            raise FoldError(e) from e


class StreamAssembler:
    """Coordinator-side incremental reassembly: preallocates the row
    arrays from the begin frame's templates and writes each piece's row
    slice in place as its frame arrives."""

    def __init__(self, begin: dict):
        name = begin.get("type")
        if name not in _SPECS:
            raise ValueError(f"unknown stream payload type {name!r}")
        self._name = name
        self._rows = int(begin["rows"])
        if self._rows <= 0:
            raise ValueError("stream begin frame with no rows")
        self._arrays: Dict[str, np.ndarray] = {}
        for f, t in (begin.get("fields") or {}).items():
            shape = tuple(int(x) for x in t["shape"])
            if not shape or shape[0] != self._rows:
                raise ValueError(f"stream field {f} shape {shape} does not "
                                 f"lead with the row count {self._rows}")
            self._arrays[f] = np.empty(shape, dtype=np.dtype(t["dtype"]))
        self._lists: Dict[str, List] = {
            f: [None] * self._rows for f in (begin.get("lists") or [])}
        self._const = dict(begin.get("const") or {})
        self._filled = 0

    def add(self, piece: dict) -> None:
        r0 = int(piece["r0"])
        n = int(piece["n"])
        if r0 < 0 or n <= 0 or r0 + n > self._rows:
            raise ValueError(f"stream piece rows [{r0}, {r0 + n}) outside "
                             f"[0, {self._rows})")
        # pieces are emitted in strict row order — continuity means a
        # duplicated or dropped frame can NEVER leave np.empty garbage
        # rows behind a satisfied row count
        if r0 != self._filled:
            raise ValueError(
                f"stream piece rows start at {r0}, expected "
                f"{self._filled} (out-of-order or duplicated frame)")
        for f, arr in (piece.get("arrays") or {}).items():
            dst = self._arrays.get(f)
            if dst is None:
                raise ValueError(f"stream piece carries undeclared field {f}")
            a = np.asarray(arr)
            if a.shape != (n,) + dst.shape[1:] or a.dtype != dst.dtype:
                raise ValueError(
                    f"stream piece field {f} shape/dtype mismatch "
                    f"({a.dtype}{a.shape} vs {dst.dtype}"
                    f"{(n,) + dst.shape[1:]})")
            dst[r0:r0 + n] = a
        for f, items in (piece.get("lists") or {}).items():
            dst_l = self._lists.get(f)
            if dst_l is None:
                raise ValueError(f"stream piece carries undeclared list {f}")
            if len(items) != n:
                raise ValueError(f"stream piece list {f} has {len(items)} "
                                 f"items for {n} rows")
            dst_l[r0:r0 + n] = items
        self._filled += n

    def finish(self):
        """Build the payload — refuses a short stream (`finish` on fewer
        filled rows than declared can NEVER pass a partial off as full)."""
        if self._filled != self._rows:
            raise ValueError(
                f"short stream: {self._filled}/{self._rows} rows arrived")
        cls = _CLASSES[self._name]
        kwargs = dict(self._const)
        kwargs.update(self._arrays)
        kwargs.update(self._lists)
        if isinstance(kwargs.get("params"), list):
            kwargs["params"] = tuple(kwargs["params"])
        field_names = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in kwargs.items() if k in field_names}
        return cls(**kwargs)
