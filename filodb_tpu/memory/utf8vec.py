"""UTF8 string vectors and dictionary encoding.

The reference's UTF8Vector stores length-prefixed strings back to back;
DictUTF8Vector adds a sorted dictionary of unique strings plus a
bit-packed code per row, which is how low-cardinality label columns
(job, instance, namespace...) collapse to a couple of bits per entry
(ref: memory/.../format/vectors/UTF8Vector.scala:1-400,
DictUTF8Vector.scala:132, ZeroCopyBinary.scala).

TPU-native role: strings never reach the device — labels live host-side
in the tag index and on the wire.  These codecs serve the *bulk*
surfaces: batch export bundles (jobs/batch_io.py label tables) and any
snapshot format where per-row label dicts would otherwise repeat the
same few values thousands of times.

Layouts (little-endian):
  UTF8 blob vector:   u32 n, then n x (u32 len, bytes)
  Dict vector:        u32 dict_n, UTF8-blob of dict (sorted, unique),
                      intvec-packed codes (one per row)
  Label table:        u32 nrows, u32 ncols, per col: (u32 keylen,
                      key bytes, u32 bitmaplen, presence bitmap
                      (LSB-first), u32 bodylen, dict-vector body);
                      absent keys are marked in the bitmap (their code
                      slot holds ""), so "" values round-trip exactly.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as np

from filodb_tpu.memory import intvec

_U32 = struct.Struct("<I")


def pack_utf8(strings: List[bytes]) -> bytes:
    parts = [_U32.pack(len(strings))]
    for s in strings:
        parts.append(_U32.pack(len(s)))
        parts.append(s)
    return b"".join(parts)


def unpack_utf8(data: bytes, off: int = 0) -> Tuple[List[bytes], int]:
    """-> (strings, next offset)."""
    (n,) = _U32.unpack_from(data, off)
    off += 4
    out: List[bytes] = []
    for _ in range(n):
        (ln,) = _U32.unpack_from(data, off)
        off += 4
        out.append(data[off:off + ln])
        off += ln
    return out, off


def pack_dict_utf8(strings: List[bytes]) -> bytes:
    """Dictionary-encode: sorted unique dictionary + bit-packed codes."""
    uniq = sorted(set(strings))
    index = {s: i for i, s in enumerate(uniq)}
    codes = np.fromiter((index[s] for s in strings), dtype=np.int64,
                        count=len(strings))
    return (_U32.pack(len(strings)) + pack_utf8(uniq)
            + intvec.pack_ints(codes))


def unpack_dict_utf8(data: bytes) -> List[bytes]:
    (n,) = _U32.unpack_from(data)
    uniq, off = unpack_utf8(data, 4)
    codes = intvec.unpack_ints(data[off:], n)
    return [uniq[c] for c in codes.tolist()]


def dict_cardinality(data: bytes) -> int:
    (_,) = _U32.unpack_from(data)
    (dn,) = _U32.unpack_from(data, 4)
    return dn


def pack_label_table(rows: List[Dict[str, str]]) -> bytes:
    """Columnar dict-encoded table of label dicts.  A per-column presence
    bitmap distinguishes an absent key from an explicitly-empty value, so
    the round trip is exact."""
    keys = sorted({k for r in rows for k in r})
    parts = [_U32.pack(len(keys))]
    for k in keys:
        kb = k.encode("utf-8")
        present = np.fromiter((k in r for r in rows), dtype=bool,
                              count=len(rows))
        bitmap = np.packbits(present, bitorder="little").tobytes()
        col = [r.get(k, "").encode("utf-8") for r in rows]
        body = pack_dict_utf8(col)
        parts += [_U32.pack(len(kb)), kb,
                  _U32.pack(len(bitmap)), bitmap,
                  _U32.pack(len(body)), body]
    return _U32.pack(len(rows)) + b"".join(parts)


def unpack_label_table(data: bytes) -> List[Dict[str, str]]:
    (nrows,) = _U32.unpack_from(data)
    (ncols,) = _U32.unpack_from(data, 4)
    off = 8
    rows: List[Dict[str, str]] = [dict() for _ in range(nrows)]
    for _ in range(ncols):
        (klen,) = _U32.unpack_from(data, off)
        off += 4
        key = data[off:off + klen].decode("utf-8")
        off += klen
        (blen,) = _U32.unpack_from(data, off)
        off += 4
        bitmap = np.frombuffer(data, dtype=np.uint8, count=blen, offset=off)
        present = np.unpackbits(bitmap, count=nrows, bitorder="little")
        off += blen
        (blen,) = _U32.unpack_from(data, off)
        off += 4
        col = unpack_dict_utf8(data[off:off + blen])
        off += blen
        for r, p, v in zip(rows, present, col):
            if p:
                r[key] = v.decode("utf-8")
    return rows
