"""Predictive NibblePack codec.

Storage scheme per the reference spec (ref: doc/compression.md:33-90,
memory/src/main/scala/filodb.memory/format/NibblePack.scala): groups of 8
u64 values are encoded as

  +0  u8 bitmask (bit i set => value i nonzero; LSB = first value)
  +1  u8: bits 0-3 = trailing zero nibbles, bits 4-7 = numNibbles-1
      (skipped when bitmask == 0)
  +2  packed nibble stream, LSB-first per value, for each nonzero value
      (skipped when bitmask == 0)

This is the host-side wire/storage codec; decoded data lives as dense arrays
for the TPU.  Pure-Python with integer ops (a C fast path can override it);
used for timestamps (after delta-delta), doubles (after XOR predictor) and
histogram bucket deltas.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

# C fast path (filodb_tpu/native); None -> pure-Python implementations
try:
    from filodb_tpu.native import lib as _native
except Exception:  # pragma: no cover
    _native = None

_M64 = 0xFFFFFFFFFFFFFFFF


def _trailing_zero_nibbles(x: int) -> int:
    if x == 0:
        return 16
    n = 0
    while (x & 0xF) == 0:
        x >>= 4
        n += 1
    return n


def _leading_zero_nibbles(x: int) -> int:
    if x == 0:
        return 16
    return 16 - ((x.bit_length() + 3) // 4)


def pack(values: np.ndarray) -> bytes:
    """Pack an array of uint64 into NibblePack bytes.  Length is encoded by the
    caller (chunk metadata holds numRows); trailing group is zero-padded."""
    if _native is not None:
        return _native.nibble_pack(values)
    return _pack_py(values)


def _pack_py(values: np.ndarray) -> bytes:
    vals = np.asarray(values, dtype=np.uint64)
    n = len(vals)
    out = bytearray()
    ngroups = (n + 7) // 8
    padded = np.zeros(ngroups * 8, dtype=np.uint64)
    padded[:n] = vals
    for g in range(ngroups):
        group = [int(v) for v in padded[g * 8:(g + 1) * 8]]
        bitmask = 0
        for i, v in enumerate(group):
            if v != 0:
                bitmask |= 1 << i
        out.append(bitmask)
        if bitmask == 0:
            continue
        trailing = min(_trailing_zero_nibbles(v) for v in group if v != 0)
        leading = min(_leading_zero_nibbles(v) for v in group if v != 0)
        num_nibbles = 16 - leading - trailing
        out.append((trailing & 0xF) | ((num_nibbles - 1) << 4))
        # Pack nibbles LSB-first across all nonzero values.
        acc = 0
        acc_bits = 0
        for v in group:
            if v == 0:
                continue
            shifted = v >> (trailing * 4)
            acc |= (shifted & ((1 << (num_nibbles * 4)) - 1)) << acc_bits
            acc_bits += num_nibbles * 4
        while acc_bits > 0:
            out.append(acc & 0xFF)
            acc >>= 8
            acc_bits -= 8
    return bytes(out)


def unpack(data: bytes, count: int) -> np.ndarray:
    """Unpack `count` uint64 values from NibblePack bytes."""
    if _native is not None:
        return _native.nibble_unpack(data, count)
    return _unpack_py(data, count)


def _unpack_py(data: bytes, count: int) -> np.ndarray:
    out = np.zeros(count, dtype=np.uint64)
    idx = 0
    pos = 0
    while idx < count:
        # bounds contract matches the C implementation: truncated input is
        # a ValueError, never a silent zero-pad (divergent decodes across
        # nodes with/without the native lib would corrupt results)
        if pos >= len(data):
            raise ValueError("nibble_unpack: truncated input")
        bitmask = data[pos]
        pos += 1
        if bitmask == 0:
            idx += 8
            continue
        if pos >= len(data):
            raise ValueError("nibble_unpack: truncated input")
        hdr = data[pos]
        pos += 1
        trailing = hdr & 0xF
        num_nibbles = (hdr >> 4) + 1
        nonzero = bin(bitmask).count("1")
        total_nibbles = num_nibbles * nonzero
        nbytes = (total_nibbles + 1) // 2
        if pos + nbytes > len(data):
            raise ValueError("nibble_unpack: truncated input")
        acc = int.from_bytes(data[pos:pos + nbytes], "little")
        pos += nbytes
        mask_bits = (1 << (num_nibbles * 4)) - 1
        acc_shift = 0
        for i in range(8):
            if bitmask & (1 << i):
                v = ((acc >> acc_shift) & mask_bits) << (trailing * 4)
                acc_shift += num_nibbles * 4
                if idx + i < count:
                    out[idx + i] = v & _M64
        idx += 8
    return out


def zigzag_encode(values: np.ndarray) -> np.ndarray:
    """int64 -> uint64 zigzag (small magnitudes -> small codes)."""
    v = np.asarray(values, dtype=np.int64)
    return ((v << np.int64(1)) ^ (v >> np.int64(63))).astype(np.uint64)


def zigzag_decode(codes: np.ndarray) -> np.ndarray:
    u = np.asarray(codes, dtype=np.uint64)
    return ((u >> np.uint64(1)).astype(np.int64)
            ^ -(u & np.uint64(1)).astype(np.int64))


def pack_i64(values: np.ndarray) -> bytes:
    return pack(zigzag_encode(values))


def unpack_i64(data: bytes, count: int) -> np.ndarray:
    return zigzag_decode(unpack(data, count))


def pack_f64_xor(values: np.ndarray) -> bytes:
    """Gorilla-style XOR-predictor + NibblePack for doubles (ref:
    doc/compression.md:25-31; the reference stores doubles raw or as
    delta-delta longs, XOR+NibblePack gives strictly better wire size)."""
    bits = np.asarray(values, dtype=np.float64).view(np.uint64)
    prev = np.concatenate([[np.uint64(0)], bits[:-1]])
    return pack(bits ^ prev)


def unpack_f64_xor(data: bytes, count: int) -> np.ndarray:
    xored = unpack(data, count)
    bits = np.bitwise_xor.accumulate(xored)
    return bits.view(np.float64)


def delta_delta_encode(ts: np.ndarray) -> Tuple[int, int, np.ndarray]:
    """Timestamp compression: sloped line + per-sample deviations (ref:
    memory/.../format/vectors/DeltaDeltaVector.scala:28 'delta-delta').

    Returns (base, slope, deltas) where ts[i] == base + slope*i + deltas[i].
    A constant-interval series yields all-zero deltas (the const-slope case
    that occupies ~0 bytes/sample after NibblePack).
    """
    t = np.asarray(ts, dtype=np.int64)
    n = len(t)
    base = int(t[0]) if n else 0
    slope = int(round((int(t[-1]) - base) / (n - 1))) if n > 1 else 0
    line = base + slope * np.arange(n, dtype=np.int64)
    return base, slope, (t - line)


def delta_delta_decode(base: int, slope: int, deltas: np.ndarray) -> np.ndarray:
    n = len(deltas)
    return (base + slope * np.arange(n, dtype=np.int64)
            + np.asarray(deltas, dtype=np.int64))


def pack_timestamps(ts: np.ndarray) -> Tuple[int, int, bytes]:
    base, slope, deltas = delta_delta_encode(ts)
    return base, slope, pack_i64(deltas)


def unpack_timestamps(base: int, slope: int, data: bytes, count: int) -> np.ndarray:
    return delta_delta_decode(base, slope, unpack_i64(data, count))
