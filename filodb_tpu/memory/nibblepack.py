"""Predictive NibblePack codec.

Storage scheme per the reference spec (ref: doc/compression.md:33-90,
memory/src/main/scala/filodb.memory/format/NibblePack.scala): groups of 8
u64 values are encoded as

  +0  u8 bitmask (bit i set => value i nonzero; LSB = first value)
  +1  u8: bits 0-3 = trailing zero nibbles, bits 4-7 = numNibbles-1
      (skipped when bitmask == 0)
  +2  packed nibble stream, LSB-first per value, for each nonzero value
      (skipped when bitmask == 0)

This is the host-side wire/storage codec; decoded data lives as dense arrays
for the TPU.  Three interchangeable implementations, all bit-exact:

  - C (filodb_tpu/native), used when the shared lib is built;
  - vectorized NumPy (_pack_vec/_unpack_vec): group-wise uint64 ops over
    ALL groups at once — no Python loop per group — the default fallback;
  - pure-Python reference (_pack_py/_unpack_py): the readable spec,
    kept as the parity oracle and for tiny inputs where NumPy dispatch
    overhead exceeds the loop cost.

Used for timestamps (after delta-delta), doubles (after XOR predictor) and
histogram bucket deltas.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

# C fast path (filodb_tpu/native); None -> NumPy/pure-Python implementations
try:
    from filodb_tpu.native import lib as _native
except Exception:  # pragma: no cover
    _native = None

_M64 = 0xFFFFFFFFFFFFFFFF

# below this many values the pure-Python loop beats NumPy dispatch overhead
# (measured crossover ~3 groups on this host; see tests/test_nibblepack.py
# parity fuzz for the bit-exactness contract that makes the switch safe)
_VEC_MIN_VALUES = 32

# popcount LUT for uint8 bitmasks (np.bitwise_count needs numpy>=2.0;
# a 256-entry gather is just as fast for our [G] masks and always there)
_POPCNT8 = np.array([bin(i).count("1") for i in range(256)], dtype=np.uint8)

# _KTH8[mask, k] = bit index of the k-th set bit of `mask` (0 when absent):
# maps "k-th nonzero value of the group" back to its slot 0..7
_KTH8 = np.zeros((256, 8), dtype=np.uint8)
for _m in range(256):
    _set = [i for i in range(8) if _m & (1 << i)]
    for _k, _i in enumerate(_set):
        _KTH8[_m, _k] = _i
del _m, _set

# payload-nibble q of a group with nn nibbles/value belongs to nonzero
# value q//nn, nibble q%nn — tabulated so the hot loop gathers instead of
# integer-dividing [G, 128] arrays (row nn=0 is never consulted: tn==0)
_QDIV = np.zeros((17, 128), dtype=np.uint8)
_QMOD = np.zeros((17, 128), dtype=np.uint8)
for _nn in range(1, 17):
    _q = np.arange(128)
    _QDIV[_nn] = np.minimum(_q // _nn, 7)
    _QMOD[_nn] = _q % _nn
del _nn, _q


def _trailing_zero_nibbles(x: int) -> int:
    if x == 0:
        return 16
    n = 0
    while (x & 0xF) == 0:
        x >>= 4
        n += 1
    return n


def _leading_zero_nibbles(x: int) -> int:
    if x == 0:
        return 16
    return 16 - ((x.bit_length() + 3) // 4)


def pack(values: np.ndarray) -> bytes:
    """Pack an array of uint64 into NibblePack bytes.  Length is encoded by the
    caller (chunk metadata holds numRows); trailing group is zero-padded."""
    if _native is not None:
        return _native.nibble_pack(values)
    if len(values) < _VEC_MIN_VALUES:
        return _pack_py(values)
    return _pack_vec(values)


def _pack_py(values: np.ndarray) -> bytes:
    vals = np.asarray(values, dtype=np.uint64)
    n = len(vals)
    out = bytearray()
    ngroups = (n + 7) // 8
    padded = np.zeros(ngroups * 8, dtype=np.uint64)
    padded[:n] = vals
    for g in range(ngroups):
        group = [int(v) for v in padded[g * 8:(g + 1) * 8]]
        bitmask = 0
        for i, v in enumerate(group):
            if v != 0:
                bitmask |= 1 << i
        out.append(bitmask)
        if bitmask == 0:
            continue
        trailing = min(_trailing_zero_nibbles(v) for v in group if v != 0)
        leading = min(_leading_zero_nibbles(v) for v in group if v != 0)
        num_nibbles = 16 - leading - trailing
        out.append((trailing & 0xF) | ((num_nibbles - 1) << 4))
        # Pack nibbles LSB-first across all nonzero values.
        acc = 0
        acc_bits = 0
        for v in group:
            if v == 0:
                continue
            shifted = v >> (trailing * 4)
            acc |= (shifted & ((1 << (num_nibbles * 4)) - 1)) << acc_bits
            acc_bits += num_nibbles * 4
        while acc_bits > 0:
            out.append(acc & 0xFF)
            acc >>= 8
            acc_bits -= 8
    return bytes(out)


def _nibble_geometry(v: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-value (trailing_zero_nibbles, nibble_length) for a uint64 array,
    via branch-free binary descent (vectorized steps instead of a Python
    while-loop per value).  Zero values report (15, 0) — callers mask them
    out before taking group minima.  The descent runs at the narrowest
    dtype covering the batch's max value (delta-delta payloads are tiny,
    and uint64 passes would quadruple the memory traffic for them);
    accumulators are uint8 since counts never exceed 16."""
    vmax = int(v.max()) if v.size else 0
    if vmax < (1 << 16):
        x0, rounds = v.astype(np.uint16), ((8, 2), (4, 1))
    elif vmax < (1 << 32):
        x0, rounds = v.astype(np.uint32), ((16, 4), (8, 2), (4, 1))
    else:
        x0, rounds = v, ((32, 8), (16, 4), (8, 2), (4, 1))
    dt = x0.dtype.type
    tz = np.zeros(v.shape, dtype=np.uint8)
    nl = np.zeros(v.shape, dtype=np.uint8)
    x_tz = x0.copy()
    x_nl = x0.copy()
    for bits, nibs in rounds:
        b = dt(bits)
        lowmask = dt((1 << bits) - 1)
        m = (x_tz & lowmask) == 0
        tz += np.where(m, np.uint8(nibs), np.uint8(0))
        x_tz = np.where(m, x_tz >> b, x_tz)
        hi = (x_nl >> b) != 0
        nl += np.where(hi, np.uint8(nibs), np.uint8(0))
        x_nl = np.where(hi, x_nl >> b, x_nl)
    nl += (x_nl != 0)
    return tz, nl


def _pack_vec(values: np.ndarray) -> bytes:
    """Vectorized NumPy pack: bit-exact with _pack_py / the C codec, but
    every step operates on ALL 8-value groups at once.  Per-group rows of
    [bitmask | header | payload bytes] are assembled in a [G, width]
    matrix and the variable-width byte stream falls out of one row-major
    boolean compaction.  Intermediate work stays in uint8/int32 (the
    nibble matrix comes from a little-endian byte VIEW of the shifted
    values, not 16 uint64 shift+masks) so memory traffic, not dtype
    width, bounds the cost."""
    vals = np.asarray(values, dtype=np.uint64)
    n = len(vals)
    if n == 0:
        return b""
    G = (n + 7) // 8
    if not vals.any():
        # all-zero input (constant-slope timestamps after delta-delta):
        # G empty-bitmask groups, nothing else to compute
        return b"\x00" * G
    v = np.zeros(G * 8, dtype=np.uint64)
    v[:n] = vals
    v = v.reshape(G, 8)
    nzmask = v != 0
    bitmask = np.packbits(nzmask, axis=1, bitorder="little")[:, 0]   # [G]
    nz = _POPCNT8[bitmask].astype(np.int32)                          # [G]
    has = nz > 0

    tz, nl = _nibble_geometry(v)
    sentinel = np.uint8(63)
    trailing = np.where(nzmask, tz, sentinel).min(axis=1)
    leading = np.where(nzmask, np.uint8(16) - nl, sentinel).min(axis=1)
    trailing = np.where(has, trailing, np.uint8(0)).astype(np.int32)
    leading = np.where(has, leading, np.uint8(0)).astype(np.int32)
    nn = np.where(has, 16 - leading - trailing, 0)     # nibbles per value

    # layout: 1 bitmask byte (+ 1 header + ceil(nibbles/2) when nonzero)
    tn = nz * nn                                       # nibbles per group
    payload_bytes = (tn + 1) // 2
    gsize = 1 + np.where(has, 1 + payload_bytes, 0)

    # nibble stream: per nonzero value, nn LSB-first nibbles of v >> 4*tz.
    # Little-endian byte view of the shifted values = the 16 nibbles of
    # each value, so splitting bytes gives the nibble matrix in two
    # uint8 ops instead of sixteen uint64 shift+masks.
    shifted = v >> (trailing.astype(np.uint64) * np.uint64(4))[:, None]
    b8 = shifted.astype("<u8", copy=False).view(np.uint8).reshape(G, 8, 8)
    # only the first ceil(max nn / 2) bytes of each value can be consulted
    # below — build that many nibble columns, not all 16
    maxnn = int(nn.max())
    nbytes_v = (maxnn + 1) >> 1
    nib = np.empty((G, 8, 2 * nbytes_v), dtype=np.uint8)
    nib[:, :, 0::2] = b8[:, :, :nbytes_v] & 0xF
    nib[:, :, 1::2] = b8[:, :, :nbytes_v] >> 4
    # group payload nibble q = nibble q%nn of the (q//nn)-th NONZERO value
    # (nn is uniform within a group) — two LUT gathers replace per-nibble
    # index arithmetic, and per-group rows assemble in one shot
    Q = int(tn.max())
    if Q:
        Qe = Q + (Q & 1)
        grow = np.arange(G, dtype=np.intp)[:, None]
        qcols = np.arange(Q, dtype=np.int32)
        k = _QDIV[nn[:, None], qcols[None, :]]          # [G, Q] value rank
        jn = _QMOD[nn[:, None], qcols[None, :]]         # [G, Q] nibble no.
        vi = _KTH8[bitmask[:, None], k]                 # [G, Q] value slot
        paynib = np.zeros((G, Qe), dtype=np.uint8)
        # q >= tn[g] gathers a neighbor's nibble — zero it so an odd tail
        # byte's high nibble matches the reference's zero fill
        np.multiply(nib[grow, vi, jn], qcols[None, :] < tn[:, None],
                    out=paynib[:, :Q])
        paybytes = paynib[:, 0::2] | (paynib[:, 1::2] << 4)
    else:
        paybytes = np.zeros((G, 0), dtype=np.uint8)
    # row-major boolean compaction of [bitmask | header | payload...]
    # yields the final byte stream directly — no scatter, no repeat
    mat = np.zeros((G, 2 + paybytes.shape[1]), dtype=np.uint8)
    mat[:, 0] = bitmask
    mat[:, 1] = np.where(has, (trailing & 0xF) | ((nn - 1) << 4), 0)
    mat[:, 2:] = paybytes
    keep = np.arange(mat.shape[1], dtype=np.int32)[None, :] < gsize[:, None]
    out = mat[keep]
    return out.tobytes()


def unpack(data: bytes, count: int) -> np.ndarray:
    """Unpack `count` uint64 values from NibblePack bytes."""
    if _native is not None:
        return _native.nibble_unpack(data, count)
    if count < _VEC_MIN_VALUES:
        return _unpack_py(data, count)
    return _unpack_vec(data, count)


def _unpack_vec(data: bytes, count: int) -> np.ndarray:
    """Vectorized NumPy unpack.  The only sequential dependency in the
    format is the group-boundary chain (each group's size is read from its
    own first two bytes); it is resolved with pointer doubling — log2(G)
    vectorized gathers over a per-position "size if a group started here"
    table — after which extraction is pure array math.  Truncated input is
    a ValueError, exactly like the Python and C implementations."""
    if count == 0:
        return np.zeros(0, dtype=np.uint64)
    buf = np.frombuffer(data, dtype=np.uint8)
    L = len(buf)
    if L == 0:
        raise ValueError("nibble_unpack: truncated input")
    G = (count + 7) // 8
    if not buf[:G].any():
        # all-zero bitmasks (the constant-slope timestamp shape: every
        # delta-delta group empty) — G one-byte groups, nothing to decode
        if L < G:
            raise ValueError("nibble_unpack: truncated input")
        return np.zeros(count, dtype=np.uint64)
    # per-position group size, assuming a group starts at that byte —
    # all-uint8 in-place arithmetic (tn <= 128 fits), one int32 pass at
    # the end; sizes are data, so this is the only full-buffer stage
    size_at = np.empty(L, dtype=np.uint8)
    np.right_shift(buf[1:], 4, out=size_at[:L - 1])
    size_at[L - 1] = 0
    size_at += 1
    size_at *= _POPCNT8[buf]                       # total nibbles if nonzero
    size_at += 1
    size_at >>= 1                                  # ceil(nibbles / 2)
    size_at += 2
    np.place(size_at, buf == 0, 1)
    # next-group position from each byte, clamped to the L sentinel
    nxt = np.empty(L + 1, dtype=np.int32)
    np.add(np.arange(L, dtype=np.int32), size_at, out=nxt[:L])
    np.minimum(nxt[:L], L, out=nxt[:L])
    nxt[L] = L
    # group offsets: the one sequential dependency in the format.  Pointer
    # doubling resolves it with vectorized gathers; the jump table stops
    # doubling at 32 steps (each doubling costs a full-buffer gather) and
    # the tail splices 32 groups per shot — control flow touches Python
    # once per 256 values, every byte-level op stays vectorized.
    offsets = np.empty(G, dtype=np.int32)
    offsets[0] = 0
    have = 1
    stride = 1
    stride_cap = max(32, G >> 6)     # ~64 tail splices, whatever the size
    step = nxt                       # position after `stride` steps
    while have < G:
        take = min(stride, G - have)
        offsets[have:have + take] = \
            step[offsets[have - stride:have - stride + take]]
        have += take
        if stride < stride_cap and stride <= have and have < G:
            step = step[step]
            stride *= 2
    if offsets[-1] >= L:             # a group's bitmask byte ran past the end
        raise ValueError("nibble_unpack: truncated input")
    bm = buf[offsets]
    has = bm != 0
    # nonzero groups need their header byte and full payload in-bounds
    if (has & (offsets + 1 >= L)).any():
        raise ValueError("nibble_unpack: truncated input")
    if (offsets + size_at[offsets] > L).any():
        raise ValueError("nibble_unpack: truncated input")

    hdr = np.where(has, buf[np.minimum(offsets + 1, L - 1)], 0)
    nn = (hdr >> 4).astype(np.int32) + 1               # [G]
    bits = ((bm[:, None] >> np.arange(8, dtype=np.uint8)) & 1)  # [G, 8]
    # rank*nn <= 7*16 fits uint8 — keep the per-value index math narrow
    rank = np.cumsum(bits, axis=1, dtype=np.uint8) - bits       # set bits below
    # Each value's nibbles occupy payload nibble range [rank*nn, rank*nn+nn)
    # — i.e. a window of at most 9 bytes starting at byte rank*nn >> 1.
    # Gather a fixed-width byte window per value and let a little-endian
    # integer VIEW fuse it; a half-nibble shift re-aligns odd starts.  The
    # window narrows to 2/4 bytes when the largest nn allows (delta-delta
    # payloads are 1-3 nibbles/value — 4x less gather traffic), and only
    # the 17-nibble case (nn=16, odd start) consults a 9th byte.
    # Everything past the [G, 8, W] gather runs at [G, 8] scale.
    maxnn = int(nn[has].max()) if has.any() else 1
    W, dt = ((2, "<u2") if maxnn <= 3 else
             (4, "<u4") if maxnn <= 7 else (8, "<u8"))
    bufp = np.zeros(L + 16, dtype=np.uint8)            # window overshoot pad
    bufp[:L] = buf
    pn = rank * nn[:, None].astype(np.uint8)           # payload nibble start
    bstart = (offsets + 2)[:, None] + (pn >> 1)        # [G, 8]
    if W == 2:
        # two [G, 8] gathers beat building a [G, 8, 2] index tensor
        lo = (bufp[bstart].astype(np.uint16)
              | (bufp[bstart + 1].astype(np.uint16) << 8))
    else:
        win = bufp[bstart[:, :, None] + np.arange(W, dtype=np.int32)]
        lo = win.reshape(G * 8, W).view(dt).reshape(G, 8)
    odd = (pn & 1).astype(lo.dtype)
    vals = lo >> (odd << 2)                            # drop odd-start nibble
    if W < 8:                                          # 4*nn < window bits
        mask4 = np.left_shift(np.int64(1), 4 * nn) - 1
        vals = (vals & mask4.astype(lo.dtype)[:, None]).astype(np.uint64)
    else:
        vals = vals.astype(np.uint64, copy=False)
        if maxnn == 16:                # 17-nibble span: top nibble from b9
            b9 = bufp[bstart + 8].astype(np.uint64)
            vals |= np.where((pn & 1) == 1,
                             (b9 & np.uint64(0xF)) << np.uint64(60),
                             np.uint64(0))
        nibmask = _M64 >> (np.uint64(64)
                           - nn.astype(np.uint64) * np.uint64(4))
        vals &= nibmask[:, None]
    trail4 = (hdr & 0xF).astype(np.uint64)
    if trail4.any():                 # skip the pass when no group shifts
        vals <<= trail4[:, None] * np.uint64(4)
    vals[bits == 0] = 0              # zero-slot scatter, not a full mask pass
    flat = vals.reshape(-1)
    return flat if len(flat) == count else flat[:count].copy()


def _unpack_py(data: bytes, count: int) -> np.ndarray:
    out = np.zeros(count, dtype=np.uint64)
    idx = 0
    pos = 0
    while idx < count:
        # bounds contract matches the C implementation: truncated input is
        # a ValueError, never a silent zero-pad (divergent decodes across
        # nodes with/without the native lib would corrupt results)
        if pos >= len(data):
            raise ValueError("nibble_unpack: truncated input")
        bitmask = data[pos]
        pos += 1
        if bitmask == 0:
            idx += 8
            continue
        if pos >= len(data):
            raise ValueError("nibble_unpack: truncated input")
        hdr = data[pos]
        pos += 1
        trailing = hdr & 0xF
        num_nibbles = (hdr >> 4) + 1
        nonzero = bin(bitmask).count("1")
        total_nibbles = num_nibbles * nonzero
        nbytes = (total_nibbles + 1) // 2
        if pos + nbytes > len(data):
            raise ValueError("nibble_unpack: truncated input")
        acc = int.from_bytes(data[pos:pos + nbytes], "little")
        pos += nbytes
        mask_bits = (1 << (num_nibbles * 4)) - 1
        acc_shift = 0
        for i in range(8):
            if bitmask & (1 << i):
                v = ((acc >> acc_shift) & mask_bits) << (trailing * 4)
                acc_shift += num_nibbles * 4
                if idx + i < count:
                    out[idx + i] = v & _M64
        idx += 8
    return out


def zigzag_encode(values: np.ndarray) -> np.ndarray:
    """int64 -> uint64 zigzag (small magnitudes -> small codes)."""
    v = np.asarray(values, dtype=np.int64)
    return ((v << np.int64(1)) ^ (v >> np.int64(63))).astype(np.uint64)


def zigzag_decode(codes: np.ndarray) -> np.ndarray:
    u = np.asarray(codes, dtype=np.uint64)
    return ((u >> np.uint64(1)).astype(np.int64)
            ^ -(u & np.uint64(1)).astype(np.int64))


def pack_i64(values: np.ndarray) -> bytes:
    return pack(zigzag_encode(values))


def unpack_i64(data: bytes, count: int) -> np.ndarray:
    return zigzag_decode(unpack(data, count))


def pack_f64_xor(values: np.ndarray) -> bytes:
    """Gorilla-style XOR-predictor + NibblePack for doubles (ref:
    doc/compression.md:25-31; the reference stores doubles raw or as
    delta-delta longs, XOR+NibblePack gives strictly better wire size)."""
    bits = np.asarray(values, dtype=np.float64).view(np.uint64)
    prev = np.concatenate([[np.uint64(0)], bits[:-1]])
    return pack(bits ^ prev)


def unpack_f64_xor(data: bytes, count: int) -> np.ndarray:
    xored = unpack(data, count)
    bits = np.bitwise_xor.accumulate(xored)
    return bits.view(np.float64)


def delta_delta_encode(ts: np.ndarray) -> Tuple[int, int, np.ndarray]:
    """Timestamp compression: sloped line + per-sample deviations (ref:
    memory/.../format/vectors/DeltaDeltaVector.scala:28 'delta-delta').

    Returns (base, slope, deltas) where ts[i] == base + slope*i + deltas[i].
    A constant-interval series yields all-zero deltas (the const-slope case
    that occupies ~0 bytes/sample after NibblePack).
    """
    t = np.asarray(ts, dtype=np.int64)
    n = len(t)
    base = int(t[0]) if n else 0
    slope = int(round((int(t[-1]) - base) / (n - 1))) if n > 1 else 0
    line = base + slope * np.arange(n, dtype=np.int64)
    return base, slope, (t - line)


def delta_delta_decode(base: int, slope: int, deltas: np.ndarray) -> np.ndarray:
    n = len(deltas)
    return (base + slope * np.arange(n, dtype=np.int64)
            + np.asarray(deltas, dtype=np.int64))


def pack_timestamps(ts: np.ndarray) -> Tuple[int, int, bytes]:
    base, slope, deltas = delta_delta_encode(ts)
    return base, slope, pack_i64(deltas)


def unpack_timestamps(base: int, slope: int, data: bytes, count: int) -> np.ndarray:
    return delta_delta_decode(base, slope, unpack_i64(data, count))
