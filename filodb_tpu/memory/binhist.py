"""BinaryHistogram wire blobs + section-based appendable histogram storage.

Wire-format parity with the reference's ingest blob (ref:
memory/src/main/scala/filodb.memory/format/vectors/HistogramVector.scala:17-34
BinaryHistogram):

    +0000  u16  total length of this BinaryHistogram (excluding these 2B)
    +0002  u8   format code:
                  0x00 empty  0x03 geometric+NP-delta-long
                  0x04 geometric_1+NP-delta-long  0x05 custom+NP-delta-long
                  0x08 geometric+NP-XOR-double    0x0a custom+NP-XOR-double
    +0003  u16  bucket-definition length
    +0005  [u8] bucket definition (first u16 = numBuckets; geometric adds
                f64 firstBucket + f64 multiplier; custom adds NP-XOR les)
    +...   NibblePacked values (zigzag deltas of increasing cumulative
                counts for the long formats; XOR stream for the doubles)

All integers little-endian (the reference's buffers are native-order on
x86; the explicit LITTLE_ENDIAN puts in GeometricBuckets.serialize:457).

The section-based appendable vector mirrors AppendableSectDeltaHistVector
(ref: HistogramVector.scala:427): histograms append as blobs; each
SECTION starts with an absolute histogram and subsequent entries are
NibblePacked deltas AGAINST THE SECTION START, so random access within a
section costs one unpack + one add, and counter drops reset sections.
The dense [T, B] matrix codec in memory/histogram.py remains the
query-side layout; this is the ingest/storage-side parity component.
"""
from __future__ import annotations

import dataclasses
import struct
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from filodb_tpu.memory import nibblepack

HIST_FORMAT_NULL = 0x00
HIST_FORMAT_GEOMETRIC_DELTA = 0x03
HIST_FORMAT_GEOMETRIC1_DELTA = 0x04
HIST_FORMAT_CUSTOM_DELTA = 0x05
HIST_FORMAT_GEOMETRIC_XOR = 0x08
HIST_FORMAT_CUSTOM_XOR = 0x0A


@dataclasses.dataclass(frozen=True)
class GeometricScheme:
    """le[i] = first * multiplier^i (+ adjustment -1 when minus_one;
    ref: Histogram.scala:448 GeometricBuckets)."""
    first: float
    multiplier: float
    num_buckets: int
    minus_one: bool = False

    def les(self) -> np.ndarray:
        tops = self.first * self.multiplier ** np.arange(self.num_buckets)
        return tops - (1.0 if self.minus_one else 0.0)

    def serialize(self) -> bytes:
        return struct.pack("<HHdd", 2 + 8 + 8, self.num_buckets,
                           self.first, self.multiplier)


@dataclasses.dataclass(frozen=True)
class CustomScheme:
    """Explicit le bounds, NibblePack-XOR packed on the wire
    (ref: Histogram.scala:480 CustomBuckets.serialize)."""
    les_arr: Tuple[float, ...]

    def les(self) -> np.ndarray:
        return np.asarray(self.les_arr, np.float64)

    @property
    def num_buckets(self) -> int:
        return len(self.les_arr)

    def serialize(self) -> bytes:
        packed = nibblepack.pack_f64_xor(self.les())
        return struct.pack("<HH", 2 + len(packed), self.num_buckets) + packed


Scheme = Union[GeometricScheme, CustomScheme]


def detect_scheme(les: np.ndarray) -> Scheme:
    """Prefer the 20-byte geometric definition when the les really are a
    geometric series (the reference's preferred prom scheme); otherwise a
    custom scheme (handles +Inf tops)."""
    les = np.asarray(les, np.float64)
    if len(les) >= 2 and np.isfinite(les).all() and (les > 0).all():
        mult = les[1] / les[0]
        if mult > 1 and np.allclose(les, les[0] * mult **
                                    np.arange(len(les)), rtol=1e-9):
            return GeometricScheme(float(les[0]), float(mult), len(les))
    return CustomScheme(tuple(float(x) for x in les))


def _parse_scheme(code: int, defn: bytes) -> Scheme:
    # defn = [u16 def-length][u16 numBuckets][scheme details...]
    num = struct.unpack_from("<H", defn, 2)[0]
    if code in (HIST_FORMAT_GEOMETRIC_DELTA, HIST_FORMAT_GEOMETRIC1_DELTA,
                HIST_FORMAT_GEOMETRIC_XOR):
        first, mult = struct.unpack_from("<dd", defn, 4)
        return GeometricScheme(first, mult, num,
                               code == HIST_FORMAT_GEOMETRIC1_DELTA)
    les = nibblepack.unpack_f64_xor(defn[4:], num)
    return CustomScheme(tuple(les.tolist()))


def encode_blob(values: np.ndarray,
                scheme: Optional[Scheme] = None,
                les: Optional[np.ndarray] = None) -> bytes:
    """One histogram sample -> BinaryHistogram wire bytes.

    Integral cumulative counts take the NibblePack-delta-long formats;
    non-integral values fall back to the XOR-double formats (the
    reference's HistFormat_*_XOR pair)."""
    values = np.asarray(values, np.float64)
    if scheme is None:
        scheme = detect_scheme(les)
    geometric = isinstance(scheme, GeometricScheme)
    integral = bool(np.isfinite(values).all()
                    and (values == np.rint(values)).all()
                    and (np.abs(values) < 2 ** 62).all())
    if integral:
        # zigzag'd bucket-axis deltas: non-negative for cumulative-le rows
        # (the reference packs unsigned deltas there), and still correct
        # for section-delta blobs whose bucket deltas may dip negative
        longs = np.rint(values).astype(np.int64)
        payload = nibblepack.pack_i64(np.diff(longs, prepend=0))
        if geometric:
            code = (HIST_FORMAT_GEOMETRIC1_DELTA if scheme.minus_one
                    else HIST_FORMAT_GEOMETRIC_DELTA)
        else:
            code = HIST_FORMAT_CUSTOM_DELTA
    else:
        payload = nibblepack.pack_f64_xor(values)
        if geometric and not scheme.minus_one:
            code = HIST_FORMAT_GEOMETRIC_XOR
        else:
            # no geometric_1 XOR format exists (matching the reference's
            # code table) — widen a minus_one scheme to explicit les so
            # the bucket bounds survive the round trip
            if geometric:
                scheme = CustomScheme(tuple(scheme.les().tolist()))
                geometric = False
            code = HIST_FORMAT_CUSTOM_XOR
    defn = scheme.serialize()
    body = struct.pack("<BH", code, len(defn)) + defn + payload
    if len(body) > 0xFFFF:
        raise ValueError(f"histogram blob too large: {len(body)} bytes")
    return struct.pack("<H", len(body)) + body


def decode_blob(data: bytes, offset: int = 0
                ) -> Tuple[np.ndarray, Scheme, int]:
    """-> (values f64 [B], scheme, bytes consumed incl. length prefix)."""
    total, = struct.unpack_from("<H", data, offset)
    code, def_len = struct.unpack_from("<BH", data, offset + 2)
    if code == HIST_FORMAT_NULL:
        return np.zeros(0), CustomScheme(()), total + 2
    defn = data[offset + 5:offset + 5 + def_len]
    scheme = _parse_scheme(code, defn)
    payload = data[offset + 5 + def_len:offset + 2 + total]
    B = scheme.num_buckets
    if code in (HIST_FORMAT_GEOMETRIC_XOR, HIST_FORMAT_CUSTOM_XOR):
        values = nibblepack.unpack_f64_xor(payload, B)
    else:
        values = np.cumsum(
            nibblepack.unpack_i64(payload, B)).astype(np.float64)
    return values, scheme, total + 2


def encode_blob_column(mat: np.ndarray, les: np.ndarray) -> bytes:
    """[n, B] histogram samples -> concatenated BinaryHistogram blobs
    (the RecordContainer hist-column wire form)."""
    scheme = detect_scheme(les)
    return b"".join(encode_blob(row, scheme=scheme) for row in
                    np.asarray(mat, np.float64))


def decode_blob_column(data: bytes, n: int
                       ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Concatenated blobs -> ([n, B] f64 matrix, les array)."""
    rows: List[np.ndarray] = []
    scheme: Optional[Scheme] = None
    off = 0
    for _ in range(n):
        values, s, used = decode_blob(data, off)
        off += used
        rows.append(values)
        scheme = scheme or s
    if not rows:
        return np.zeros((0, 0)), None
    B = max(len(r) for r in rows)
    mat = np.zeros((n, B))
    for i, r in enumerate(rows):
        mat[i, :len(r)] = r
    return mat, (scheme.les() if scheme is not None else None)


# ------------------------------------------------- section-based storage

_SECT_HEADER = struct.Struct("<HH")     # (num entries, section byte length)


class AppendableSectHistVector:
    """Appendable histogram column storing NibblePacked blobs in sections
    (ref: HistogramVector.scala:427 AppendableSectDeltaHistVector).

    Section layout: [u16 num_entries, u16 section_bytes, abs blob,
    delta blob, delta blob, ...].  The first histogram of a section is
    absolute; later ones are stored as (hist - section_start) — random
    access inside a section is two unpacks, and a counter DROP (any
    bucket lower than the section start) closes the section and starts a
    new one, exactly the reference's drop-triggered section roll."""

    def __init__(self, les: np.ndarray, section_limit: int = 16):
        self.scheme = detect_scheme(np.asarray(les, np.float64))
        self.section_limit = section_limit
        self._sections: List[bytearray] = []
        self._counts: List[int] = []
        self._section_start: Optional[np.ndarray] = None
        self.num_histograms = 0

    def append(self, values: np.ndarray) -> None:
        values = np.asarray(values, np.float64)
        # roll on: first append, entry cap, counter drop, or the u16
        # section length would overflow (wide custom-bucket XOR blobs)
        start_new = (not self._sections
                     or self._counts[-1] >= self.section_limit
                     or (self._section_start is not None
                         and (values < self._section_start).any())
                     or len(self._sections[-1]) > 0xC000)
        if start_new:
            blob = encode_blob(values, scheme=self.scheme)
            sect = bytearray(_SECT_HEADER.pack(1, len(blob)))
            sect += blob
            self._sections.append(sect)
            self._counts.append(1)
            self._section_start = values
        else:
            delta = values - self._section_start
            blob = encode_blob(delta, scheme=self.scheme)
            sect = self._sections[-1]
            if len(sect) + len(blob) - _SECT_HEADER.size > 0xFFFF:
                # blob would overflow the u16 section length: roll instead
                abs_blob = encode_blob(values, scheme=self.scheme)
                sect = bytearray(_SECT_HEADER.pack(1, len(abs_blob)))
                sect += abs_blob
                self._sections.append(sect)
                self._counts.append(1)
                self._section_start = values
                self.num_histograms += 1
                return
            sect += blob
            self._counts[-1] += 1
            n, _ = _SECT_HEADER.unpack_from(sect, 0)
            _SECT_HEADER.pack_into(sect, 0, n + 1,
                                   len(sect) - _SECT_HEADER.size)
        self.num_histograms += 1

    def to_bytes(self) -> bytes:
        head = struct.pack("<IH", self.num_histograms, len(self._sections))
        return head + b"".join(bytes(s) for s in self._sections)

    @property
    def num_bytes(self) -> int:
        return len(self.to_bytes())

    @staticmethod
    def decode(data: bytes) -> np.ndarray:
        """-> [n, B] absolute cumulative-count matrix."""
        n, num_sections = struct.unpack_from("<IH", data, 0)
        off = struct.calcsize("<IH")
        rows: List[np.ndarray] = []
        for _ in range(num_sections):
            entries, sect_bytes = _SECT_HEADER.unpack_from(data, off)
            off += _SECT_HEADER.size
            end = off + sect_bytes
            start: Optional[np.ndarray] = None
            for i in range(entries):
                values, _, used = decode_blob(data, off)
                off += used
                if i == 0:
                    start = values
                    rows.append(values)
                else:
                    rows.append(start + values)
            off = end
        if not rows:
            return np.zeros((0, 0))
        return np.stack(rows)
