"""Columnar chunk format.

A chunk is one partition's worth of samples between flush boundaries, encoded
per column (ref: core/.../store/ChunkSetInfo.scala:60-70 for the metadata
fields; memory/.../format/BinaryVector.scala for the per-column vector model).

TPU-native departure from the reference: chunks are *wire/storage* artifacts
only.  The query-hot working set is kept decoded as dense [series, time]
arrays (see core/blockstore.py) because TPUs want dense vectorized math, not
branchy bit-unpacking (SURVEY.md section 7 step 1).  Encoding therefore
optimizes for storage/replay, not random access.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, Optional

import numpy as np

from filodb_tpu.memory import nibblepack
from filodb_tpu.memory.histogram import HistogramBuckets, encode_hist_matrix, decode_hist_matrix


@dataclasses.dataclass(frozen=True)
class ChunkSetInfo:
    """Chunk metadata (ref: store/ChunkSetInfo.scala:60-70: id = timeuuid-like,
    ingestionTime, numRows, startTime, endTime)."""
    chunk_id: int
    ingestion_time_ms: int
    num_rows: int
    start_time_ms: int
    end_time_ms: int


@dataclasses.dataclass
class ColumnChunk:
    """One encoded column of a chunk."""
    kind: str        # 'ts-dd' | 'f64-xor' | 'f64-i64dd' | 'i64-dd' | 'hist-2d'
    payload: bytes
    base: int = 0             # ts-dd/i64-dd: line base
    slope: int = 0            # ts-dd/i64-dd: line slope
    num_buckets: int = 0      # hist-2d

    @property
    def nbytes(self) -> int:
        return len(self.payload)


@dataclasses.dataclass
class ChunkSet:
    info: ChunkSetInfo
    columns: Dict[str, ColumnChunk]
    bucket_scheme: Optional[HistogramBuckets] = None

    @property
    def nbytes(self) -> int:
        return sum(c.nbytes for c in self.columns.values())


def encode_ts_column(ts: np.ndarray) -> ColumnChunk:
    base, slope, payload = nibblepack.pack_timestamps(ts)
    return ColumnChunk("ts-dd", payload, base=base, slope=slope)


def encode_double_column(vals: np.ndarray) -> ColumnChunk:
    """Doubles: delta-delta-as-long when all values are integral (the
    DeltaDeltaVector trick, ref: memory/.../format/vectors/DoubleVector.scala
    delta-delta-as-long 'when integral' — real counters are integers and
    pack to ~1-2 B/sample), XOR-mantissa packing otherwise."""
    v = np.asarray(vals, dtype=np.float64)
    if (len(v) and np.isfinite(v).all() and (v == np.floor(v)).all()
            and (np.abs(v) < 2.0**53).all()):
        base, slope, deltas = nibblepack.delta_delta_encode(
            v.astype(np.int64))
        return ColumnChunk("f64-i64dd", nibblepack.pack_i64(deltas),
                           base=base, slope=slope)
    return ColumnChunk("f64-xor", nibblepack.pack_f64_xor(v))


def encode_long_column(vals: np.ndarray) -> ColumnChunk:
    base, slope, deltas = nibblepack.delta_delta_encode(vals)
    return ColumnChunk("i64-dd", nibblepack.pack_i64(deltas), base=base, slope=slope)


def encode_hist_column(mat: np.ndarray) -> ColumnChunk:
    return ColumnChunk("hist-2d", encode_hist_matrix(mat), num_buckets=mat.shape[1])


def decode_column(col: ColumnChunk, num_rows: int) -> np.ndarray:
    if col.kind == "ts-dd":
        return nibblepack.unpack_timestamps(col.base, col.slope, col.payload, num_rows)
    if col.kind == "f64-xor":
        return nibblepack.unpack_f64_xor(col.payload, num_rows)
    if col.kind == "f64-i64dd":
        return nibblepack.delta_delta_decode(
            col.base, col.slope,
            nibblepack.unpack_i64(col.payload, num_rows)).astype(np.float64)
    if col.kind == "i64-dd":
        return nibblepack.delta_delta_decode(
            col.base, col.slope, nibblepack.unpack_i64(col.payload, num_rows))
    if col.kind == "hist-2d":
        return decode_hist_matrix(col.payload, num_rows, col.num_buckets)
    raise ValueError(f"unknown column chunk kind {col.kind!r}")


# itertools.count.__next__ is atomic under the GIL — flush encoding runs on
# a thread pool, and a `x[0] += 1` load/add/store would race there
_next_chunk_id = itertools.count(1)


def make_chunk_id() -> int:
    """Monotonic chunk id (the reference uses timeuuid ordering,
    ref ChunkSetInfo 'id=timeuuid'); monotonicity is what recovery relies on."""
    return next(_next_chunk_id)


def encode_chunkset(ts: np.ndarray,
                    columns: Dict[str, np.ndarray],
                    col_types: Dict[str, str],
                    ingestion_time_ms: int,
                    bucket_scheme: Optional[HistogramBuckets] = None) -> ChunkSet:
    """Encode one sealed chunk.  `columns` excludes the timestamp column;
    `col_types` maps column name -> 'double' | 'long' | 'hist'."""
    ts = np.asarray(ts, dtype=np.int64)
    n = len(ts)
    info = ChunkSetInfo(make_chunk_id(), ingestion_time_ms, n,
                        int(ts[0]) if n else 0, int(ts[-1]) if n else 0)
    encoded: Dict[str, ColumnChunk] = {"timestamp": encode_ts_column(ts)}
    for name, vals in columns.items():
        t = col_types[name]
        if t == "double":
            encoded[name] = encode_double_column(vals)
        elif t == "long":
            encoded[name] = encode_long_column(vals)
        elif t == "hist":
            encoded[name] = encode_hist_column(vals)
        else:
            raise ValueError(f"unsupported column type {t!r}")
    return ChunkSet(info, encoded, bucket_scheme)


def decode_chunkset(cs: ChunkSet) -> Dict[str, np.ndarray]:
    return {name: decode_column(col, cs.info.num_rows)
            for name, col in cs.columns.items()}
