"""Bit-packed integer vectors with automatic width selection.

The reference stores int columns (and the inner storage of delta-delta
vectors) bit-packed at the narrowest width that fits: 2/4/8/16/32-bit
unsigned widths plus a const vector when every value is identical, and
masked variants carrying a validity bitmap
(ref: memory/.../format/vectors/IntBinaryVector.scala:15,357-433 —
OffheapUnsignedIntVector{2,4,8,16}, const vector, masked variants).

TPU-native departure: these are *storage/wire* codecs, not random-access
readers.  Decode is one vectorized numpy pass into a dense array (the
working set the device consumes is always dense — SURVEY.md section 7
step 1); there is no per-element accessor object.  A signed `base` offset
is subtracted before packing so narrow widths apply to any contiguous
value range, not just ones near zero.

Layout (little-endian):
    u8  kind      0=const, 1=packed
    u8  bits      width in bits (const: 0)
    i64 base      value offset
    -- kind=const: nothing else (value == base)
    -- kind=packed: ceil(n*bits/8) bytes of packed codes, LSB-first
Masked variant prepends a validity bitmap of ceil(n/8) bytes.
"""
from __future__ import annotations

import struct
from typing import Optional, Tuple

import numpy as np

_WIDTHS = (2, 4, 8, 16, 32, 64)
_HDR = struct.Struct("<BBq")


def _select_width(span: int) -> int:
    for b in _WIDTHS:
        if b == 64 or span < (1 << b):
            return b
    return 64


def _pack_bits(codes: np.ndarray, bits: int) -> bytes:
    """Pack unsigned codes (< 2**bits) at `bits` per value, LSB-first."""
    n = len(codes)
    if bits in (8, 16, 32, 64):
        return codes.astype(f"<u{bits // 8}").tobytes()
    # sub-byte widths: expand to a bit matrix, then np.packbits
    per_byte = 8 // bits
    padded = np.zeros((n + per_byte - 1) // per_byte * per_byte,
                      dtype=np.uint8)
    padded[:n] = codes.astype(np.uint8)
    out = np.zeros(len(padded) // per_byte, dtype=np.uint8)
    for k in range(per_byte):
        out |= padded[k::per_byte] << (k * bits)
    return out.tobytes()


def _unpack_bits(data: bytes, n: int, bits: int) -> np.ndarray:
    if bits in (8, 16, 32, 64):
        return np.frombuffer(data, dtype=f"<u{bits // 8}",
                             count=n).astype(np.uint64)
    per_byte = 8 // bits
    raw = np.frombuffer(data, dtype=np.uint8)
    mask = (1 << bits) - 1
    cols = [((raw >> (k * bits)) & mask) for k in range(per_byte)]
    codes = np.stack(cols, axis=1).reshape(-1)[:n]
    return codes.astype(np.uint64)


def pack_ints(values: np.ndarray) -> bytes:
    """Encode an int64 array at the narrowest width that fits its range."""
    v = np.asarray(values, dtype=np.int64)
    if len(v) == 0:
        return _HDR.pack(0, 0, 0)
    base = int(v.min())
    span = int(v.max()) - base
    if span == 0:
        return _HDR.pack(0, 0, base)
    bits = _select_width(span)
    codes = (v - base).astype(np.uint64)
    return _HDR.pack(1, bits, base) + _pack_bits(codes, bits)


def unpack_ints(data: bytes, n: int) -> np.ndarray:
    kind, bits, base = _HDR.unpack_from(data)
    if kind == 0:
        return np.full(n, base, dtype=np.int64)
    codes = _unpack_bits(data[_HDR.size:], n, bits)
    return (codes.astype(np.int64) + base)


def packed_width_bits(data: bytes) -> int:
    """Effective bits/value of an encoded vector (0 for const)."""
    _, bits, _ = _HDR.unpack_from(data)
    return bits


def pack_ints_masked(values: np.ndarray,
                     valid: Optional[np.ndarray] = None) -> bytes:
    """Masked variant: NaN-able int column as (validity bitmap, packed
    present values at positions where valid) — ref IntBinaryVector.scala
    masked variants.  `values` at invalid positions are ignored."""
    v = np.asarray(values, dtype=np.int64)
    if valid is None:
        valid = np.ones(len(v), dtype=bool)
    valid = np.asarray(valid, dtype=bool)
    bitmap = np.packbits(valid, bitorder="little").tobytes()
    body = pack_ints(v[valid])
    return struct.pack("<I", len(bitmap)) + bitmap + body


def unpack_ints_masked(data: bytes, n: int) -> Tuple[np.ndarray, np.ndarray]:
    """-> (values int64 [n] with 0 at invalid positions, valid bool [n])."""
    (blen,) = struct.unpack_from("<I", data)
    bitmap = np.frombuffer(data, dtype=np.uint8, count=blen, offset=4)
    valid = np.unpackbits(bitmap, count=n, bitorder="little").astype(bool)
    present = unpack_ints(data[4 + blen:], int(valid.sum()))
    out = np.zeros(n, dtype=np.int64)
    out[valid] = present
    return out, valid
