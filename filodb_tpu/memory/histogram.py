"""First-class histogram support: bucket schemes and bucket-matrix encoding.

Mirrors the reference's histogram model (ref:
memory/src/main/scala/filodb.memory/format/vectors/Histogram.scala:17,
HistogramBuckets.scala area `HistogramBuckets:340`): buckets are CUMULATIVE
counts with `le` (less-than-or-equal) upper bounds, last bucket is +Inf —
the Prometheus scheme.  Instead of the reference's per-sample BinaryHistogram
blobs, the TPU-native layout is a dense bucket matrix [time, buckets] per
series, which maps directly onto vectorized histogram_quantile kernels.
"""
from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import numpy as np

from filodb_tpu.memory import nibblepack


@dataclasses.dataclass(frozen=True)
class HistogramBuckets:
    """A bucket scheme: the array of `le` upper bounds (ascending, last may be
    +Inf).  ref: memory/.../vectors/HistogramBuckets geometric & custom forms."""
    les: Tuple[float, ...]

    @property
    def num_buckets(self) -> int:
        return len(self.les)

    def as_array(self) -> np.ndarray:
        return np.asarray(self.les, dtype=np.float64)

    @staticmethod
    def geometric(first: float, multiplier: float, num: int,
                  inf_bucket: bool = True) -> "HistogramBuckets":
        """ref: HistogramBuckets geometric scheme — le[i] = first * multiplier^i."""
        les = [first * (multiplier ** i) for i in range(num - (1 if inf_bucket else 0))]
        if inf_bucket:
            les.append(float("inf"))
        return HistogramBuckets(tuple(les))

    @staticmethod
    def custom(les: Sequence[float]) -> "HistogramBuckets":
        return HistogramBuckets(tuple(float(x) for x in les))


# The reference's canonical test scheme: 8 geometric buckets starting at 2, x2.
def default_buckets(num: int = 8) -> HistogramBuckets:
    return HistogramBuckets.geometric(2.0, 2.0, num, inf_bucket=False)


def union_les(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Union of two bucket schemes: sorted unique le boundaries.  The widened
    scheme every source can be mapped onto (ref: HistogramBuckets.scala:340
    scheme-change handling — queries spanning a scheme change evaluate on a
    common scheme instead of failing)."""
    return np.union1d(np.asarray(a, np.float64), np.asarray(b, np.float64))


def rebucket(mat: np.ndarray, src_les: np.ndarray,
             dst_les: np.ndarray) -> np.ndarray:
    """Map cumulative bucket counts [..., B_src] onto dst_les [..., B_dst].

    Buckets are cumulative (CDF samples at le boundaries), so the value at a
    destination boundary is the source CDF at the smallest source le >= that
    boundary — exact where boundaries coincide, and the tightest monotone
    upper bound at boundaries the source scheme never measured.  A dst le
    above every source le takes the topmost bucket (the +Inf total)."""
    src = np.asarray(src_les, np.float64)
    dst = np.asarray(dst_les, np.float64)
    idx = np.searchsorted(src, dst, side="left")
    idx = np.minimum(idx, len(src) - 1)
    return np.asarray(mat)[..., idx]


def encode_hist_matrix(mat: np.ndarray) -> bytes:
    """Encode a [time, buckets] cumulative-count matrix.

    2D-delta: each row is delta'd against the previous row (time-delta), and
    within a row buckets are delta'd against the previous bucket (the
    section-delta idea of ref AppendableSectDeltaHistVector:427) — increasing
    cumulative buckets make both deltas small and NibblePack-friendly.
    """
    m = np.asarray(mat, dtype=np.int64)
    if m.ndim != 2:
        raise ValueError("hist matrix must be [time, buckets]")
    bucket_delta = np.diff(m, axis=1, prepend=0)       # within-row
    time_delta = np.diff(bucket_delta, axis=0, prepend=0)  # across rows
    return nibblepack.pack_i64(time_delta.ravel())


def decode_hist_matrix(data: bytes, num_rows: int, num_buckets: int) -> np.ndarray:
    flat = nibblepack.unpack_i64(data, num_rows * num_buckets)
    time_delta = flat.reshape(num_rows, num_buckets)
    bucket_delta = np.cumsum(time_delta, axis=0)
    return np.cumsum(bucket_delta, axis=1)
