"""Compressed resident chunk tier — sealed history in host RAM.

The reference keeps its entire in-memory working set delta-delta/NibblePack
encoded off-heap (~1.5M series/GB, ref: doc/ingestion.md:110,
memory/.../format/vectors/DeltaDeltaVector.scala:28) and pages chunks into
query memory on demand.  The TPU rebuild inverts the layout — the query-hot
tier is DENSE [series, time] arrays because that is what the chip wants —
but raw f64 for all history caps cardinality ~10-50x below the reference.

This module is the middle tier that restores the footprint: sealed chunks
(the same encoded ChunkSets written to the ColumnStore at flush) stay
resident in RAM under a byte budget, so the dense tier can be truncated to
the active tail and re-paged from RAM at memory-bandwidth cost instead of
disk cost.  Over-budget chunks are dropped oldest-first — they are already
persisted, so this is a clean cache eviction (the BlockManager time-ordered
reclaim analogue, ref: memory/.../BlockManager.scala:16).
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

from filodb_tpu.memory.chunks import ChunkSet
from filodb_tpu.utils.metrics import registry as metrics_registry


class ResidentChunkCache:
    """Per-shard cache of sealed, encoded chunks keyed by partition id.

    Insertion order is flush order, which is time order per partition —
    eviction walks the global insertion queue (oldest flush first), the
    same reclaim ordering the reference's BlockManager guarantees.
    """

    # Per-chunk accounting overhead beyond the encoded payload: the ChunkSet
    # object, its info record, per-column bytes objects, and list/queue
    # slots.  Without this, many tiny chunks (frequent flushes) cost far
    # more RSS than bytes_used claims and the budget never triggers —
    # observed as unbounded growth in the ingestion soak.
    CHUNK_OVERHEAD = 1024

    def __init__(self, budget_bytes: int = 256 << 20,
                 dataset: str = "", shard: int = -1,
                 persistent: bool = True):
        """persistent=False (in-memory-only deployments, NullColumnStore):
        this cache IS the system of record for sealed history, so budget
        eviction would destroy data — it is disabled and growth is surfaced
        via the resident_cache_bytes gauge instead."""
        self.budget_bytes = budget_bytes
        self.persistent = persistent
        self.bytes_used = 0
        self.chunks_evicted = 0
        self._by_part: Dict[int, List[ChunkSet]] = {}
        self._queue: deque = deque()          # (part_id, chunk_id, nbytes)
        self._labels = dict(dataset=dataset, shard=str(shard))

    # ------------------------------------------------------------------ write

    def add(self, part_id: int, cs: ChunkSet) -> None:
        nb = cs.nbytes + self.CHUNK_OVERHEAD
        self._by_part.setdefault(part_id, []).append(cs)
        self._queue.append((part_id, cs.info.chunk_id, nb))
        self.bytes_used += nb
        self._enforce_budget()
        metrics_registry.gauge("resident_cache_bytes",
                               **self._labels).update(self.bytes_used)

    def _enforce_budget(self) -> None:
        if not self.persistent:
            return      # sole copy of sealed history — never drop it
        while self.bytes_used > self.budget_bytes and self._queue:
            part_id, chunk_id, nb = self._queue.popleft()
            lst = self._by_part.get(part_id)
            if lst is None:
                continue
            for i, cs in enumerate(lst):
                if cs.info.chunk_id == chunk_id:
                    del lst[i]
                    self.bytes_used -= nb
                    self.chunks_evicted += 1
                    metrics_registry.counter(
                        "resident_chunks_evicted",
                        **self._labels).increment()
                    break
            if not lst:
                self._by_part.pop(part_id, None)

    def drop_part(self, part_id: int) -> None:
        """Partition evicted from the shard entirely: forget its chunks
        (queue entries lazily skip missing chunks)."""
        lst = self._by_part.pop(part_id, None)
        if lst:
            self.bytes_used -= sum(cs.nbytes + self.CHUNK_OVERHEAD
                                   for cs in lst)

    # ------------------------------------------------------------------- read

    def read(self, part_id: int, start_time_ms: int,
             end_time_ms: int) -> List[ChunkSet]:
        """Chunks overlapping [start, end], time-ascending."""
        out = [cs for cs in self._by_part.get(part_id, ())
               if cs.info.end_time_ms >= start_time_ms
               and cs.info.start_time_ms <= end_time_ms]
        out.sort(key=lambda c: c.info.start_time_ms)
        return out

    def coverage_floor(self, part_id: int) -> Optional[int]:
        """Earliest start_time resident for the partition, or None."""
        lst = self._by_part.get(part_id)
        if not lst:
            return None
        return min(cs.info.start_time_ms for cs in lst)

    @property
    def num_chunks(self) -> int:
        return sum(len(v) for v in self._by_part.values())
