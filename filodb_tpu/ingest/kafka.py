"""Kafka ingestion transport.

ref: kafka/.../KafkaIngestionStream.scala:17-57 — one shard maps to exactly
one Kafka partition of the ingestion topic; messages are RecordContainer
bytes (here: RecordBatch.to_bytes frames); offsets are Kafka offsets, which
plug straight into the group-watermark checkpoint protocol.

The kafka-python client is an optional dependency: `KafkaIngestionStream`
imports it lazily and raises a clear error when absent.  `consumer_factory`
is injectable, so tests (and brokers-in-tests) run against a fake consumer
— the same seam the reference's TestConsumer/SourceSinkSuite uses.
"""
from __future__ import annotations

from typing import Callable, Iterator, Optional, Tuple

from filodb_tpu.core.records import RecordBatch
from filodb_tpu.core.schemas import Schemas, DEFAULT_SCHEMAS
from filodb_tpu.ingest.stream import IngestionStream, register_stream_factory


class KafkaIngestionStream(IngestionStream):
    """One stream = one (topic, partition) = one shard
    (ref: KafkaIngestionStream.scala:17: `shard == Kafka partition`)."""

    def __init__(self, topic: str, shard: int,
                 bootstrap_servers: str = "localhost:9092",
                 schemas: Schemas = DEFAULT_SCHEMAS,
                 consumer_factory: Optional[Callable] = None,
                 poll_timeout_ms: int = 1000):
        self.topic = topic
        self.shard = shard
        self.bootstrap_servers = bootstrap_servers
        self.schemas = schemas
        self.poll_timeout_ms = poll_timeout_ms
        self._consumer_factory = consumer_factory
        self._consumer = None

    def _make_consumer(self, from_offset: int):
        if self._consumer_factory is not None:
            return self._consumer_factory(self.topic, self.shard, from_offset)
        try:
            from kafka import KafkaConsumer, TopicPartition  # type: ignore
        except ImportError:
            # no kafka-python: speak the Kafka binary protocol directly
            # (ingest/kafka_wire.py — Fetch v4 / ListOffsets v1 against
            # any >= 0.11 broker; exercised by the env-gated IT in
            # tests/test_kafka_wire_it.py)
            from filodb_tpu.ingest.kafka_wire import WireConsumer
            consumer = WireConsumer(self.bootstrap_servers, self.topic,
                                    self.shard)
            if from_offset >= 0:
                consumer.seek(None, from_offset + 1)
            else:
                consumer.seek_to_beginning()
            return consumer
        consumer = KafkaConsumer(
            bootstrap_servers=self.bootstrap_servers,
            enable_auto_commit=False,   # offsets commit via flush watermarks
            value_deserializer=None)
        tp = TopicPartition(self.topic, self.shard)
        consumer.assign([tp])
        if from_offset >= 0:
            consumer.seek(tp, from_offset + 1)
        else:
            consumer.seek_to_beginning(tp)
        return consumer

    def batches(self, from_offset: int = -1
                ) -> Iterator[Tuple[RecordBatch, int]]:
        self._consumer = self._make_consumer(from_offset)
        for msg in self._consumer:
            if msg.offset <= from_offset:
                continue            # fakes may not support seeking
            batch = RecordBatch.from_bytes(msg.value, self.schemas)
            yield batch, msg.offset

    def teardown(self) -> None:
        if self._consumer is not None:
            close = getattr(self._consumer, "close", None)
            if close:
                close()
            self._consumer = None


register_stream_factory("kafka", KafkaIngestionStream)
