"""Durable local append-log broker with Kafka partition/offset semantics.

The reference decouples gateway from DB nodes through Kafka: the gateway
publishes per-shard RecordContainer frames, nodes consume their partition
and checkpoint offsets (ref: gateway/.../KafkaContainerSink.scala:24-69,
kafka/.../KafkaIngestionStream.scala:17-57).  This module is the
local-disk analogue of that broker for single-machine and test
deployments — the same philosophy as persist/localstore.py standing in
for Cassandra (SURVEY §7.7): real durability and replay semantics, no
external service.  One file per (topic, partition); a message is a
4-byte big-endian length + payload; the offset is the message index.

Works ACROSS OS processes: the gateway process appends, node processes
tail.  kafka-python deployments use ingest/kafka.py against a real
broker instead — both sides share the IngestionStream contract.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Iterator, List, Optional

from filodb_tpu.core.schemas import Schemas, DEFAULT_SCHEMAS
from filodb_tpu.ingest.stream import register_stream_factory


class FileBackedBroker:
    """Append-log-per-partition broker with Kafka offset semantics."""

    def __init__(self, root: str, fsync: bool = False):
        self.root = str(root)
        self.fsync = fsync
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        # (topic, partition) -> message count, maintained by THIS process's
        # produces (other processes' appends are re-counted lazily)
        self._count_cache: dict = {}

    def _path(self, topic: str, partition: int) -> str:
        return os.path.join(self.root, f"{topic}-{partition}.log")

    def produce(self, topic: str, partition: int, value: bytes) -> int:
        """Append one message; returns its assigned offset.  Atomic w.r.t.
        other producers in THIS process via the lock; cross-process
        single-writer per partition is the deployment contract (exactly
        Kafka's per-partition ordering model).  The per-partition count is
        cached after one initial header-only scan, so appends are O(1)."""
        with self._lock:
            key = (topic, partition)
            offset = self._count_cache.get(key)
            if offset is None:
                offset = self._scan_count(topic, partition)
            with open(self._path(topic, partition), "ab") as f:
                f.write(len(value).to_bytes(4, "big") + value)
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
            self._count_cache[key] = offset + 1
            return offset

    def _scan_count(self, topic: str, partition: int) -> int:
        """Message count via a header-only scan: read each 4-byte length,
        seek over the body — O(messages) tiny reads, O(1) memory."""
        path = self._path(topic, partition)
        if not os.path.exists(path):
            return 0
        n = 0
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            pos = 0
            while True:
                hdr = f.read(4)
                if len(hdr) < 4:
                    return n
                body_len = int.from_bytes(hdr, "big")
                pos += 4 + body_len
                if pos > size:
                    return n            # torn tail write
                f.seek(body_len, 1)
                n += 1

    def end_offset(self, topic: str, partition: int) -> int:
        return self._scan_count(topic, partition)

    def read_all(self, topic: str, partition: int) -> List[bytes]:
        path = self._path(topic, partition)
        out: List[bytes] = []
        if not os.path.exists(path):
            return out
        with open(path, "rb") as f:
            while True:
                hdr = f.read(4)
                if len(hdr) < 4:
                    return out
                body = f.read(int.from_bytes(hdr, "big"))
                if len(body) < int.from_bytes(hdr, "big"):
                    return out          # torn tail write: ignore like Kafka
                out.append(body)

    class _Msg:
        __slots__ = ("offset", "value")

        def __init__(self, offset: int, value: bytes):
            self.offset, self.value = offset, value

    def consume(self, topic: str, partition: int, from_offset: int = -1,
                follow: bool = False, poll_interval_s: float = 0.05,
                stop: Optional[threading.Event] = None
                ) -> Iterator["FileBackedBroker._Msg"]:
        """Yield messages with offset > from_offset.  follow=True tails the
        log (the live-node mode); otherwise stops at the current end.
        Reads are sequential with a remembered byte position — a tailing
        poll costs one stat-sized read attempt, not a rescan of the log."""
        path = self._path(topic, partition)
        offset = -1
        pos = 0
        while True:
            progressed = False
            if os.path.exists(path):
                with open(path, "rb") as f:
                    f.seek(pos)
                    while True:
                        hdr = f.read(4)
                        if len(hdr) < 4:
                            break
                        n = int.from_bytes(hdr, "big")
                        body = f.read(n)
                        if len(body) < n:
                            break       # torn tail: retry after the writer
                        offset += 1
                        pos = f.tell()
                        progressed = True
                        if offset > from_offset:
                            yield FileBackedBroker._Msg(offset, body)
            if not follow or (stop is not None and stop.is_set()):
                if not progressed:
                    return
                continue                 # drain to a quiescent end first
            time.sleep(poll_interval_s)

    def consumer_factory(self, follow: bool = False,
                         stop: Optional[threading.Event] = None) -> Callable:
        """Factory with the KafkaIngestionStream consumer contract."""
        def factory(topic: str, partition: int, from_offset: int):
            return self.consume(topic, partition, from_offset,
                                follow=follow, stop=stop)
        return factory


def _make_filebroker_stream(topic: str, shard: int,
                            broker_dir: str = "",
                            schemas: Schemas = DEFAULT_SCHEMAS,
                            follow: bool = False, **kwargs):
    """`filebroker` IngestionStream factory: reuses KafkaIngestionStream's
    framing/offset logic against the local broker."""
    from filodb_tpu.ingest.kafka import KafkaIngestionStream
    broker = FileBackedBroker(broker_dir)
    return KafkaIngestionStream(
        topic, shard, schemas=schemas,
        consumer_factory=broker.consumer_factory(follow=follow))


register_stream_factory("filebroker", _make_filebroker_stream)
