"""Ingestion transports.  Importing the package registers every built-in
IngestionStream factory ('csv', 'memory', 'kafka', 'filebroker') so
config-driven `create_stream(name)` resolves without explicit imports."""
from filodb_tpu.ingest import stream as _stream          # noqa: F401 csv/memory
from filodb_tpu.ingest import kafka as _kafka            # noqa: F401 kafka
from filodb_tpu.ingest import filebroker as _filebroker  # noqa: F401 filebroker
