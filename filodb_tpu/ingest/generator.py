"""Deterministic synthetic time-series generators.

The moral port of the reference's TestTimeseriesProducer / MachineMetricsData
(ref: gateway/src/main/scala/filodb/timeseries/TestTimeseriesProducer.scala:188,
core/src/test/.../MachineMetricsData) — shared by unit tests, stress apps and
benchmarks so perf runs and correctness runs see identical data shapes.
Produces the Prom-schema series the jmh harnesses use: `heap_usage{...}` gauges,
request counters, and native-histogram series, tagged with _ws_/_ns_ shard keys.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.records import RecordBatch, RecordBatchBuilder
from filodb_tpu.core.schemas import GAUGE, PROM_COUNTER, PROM_HISTOGRAM


def gauge_part_keys(num_series: int, metric: str = "heap_usage",
                    ws: str = "demo", num_apps: int = 10) -> List[PartKey]:
    """Series identities like TestTimeseriesProducer: 10 apps x N instances,
    _ns_ = 'App-<n>'."""
    keys = []
    for i in range(num_series):
        keys.append(PartKey.make(metric, {
            "_ws_": ws,
            "_ns_": f"App-{i % num_apps}",
            "instance": f"Instance-{i}",
            "dc": f"DC{i % 2}",
        }))
    return keys


def gauge_batch(num_series: int, num_samples: int,
                start_ms: int = 1_600_000_000_000, step_ms: int = 10_000,
                metric: str = "heap_usage", seed: int = 42,
                num_apps: int = 10) -> RecordBatch:
    """Sinusoid-ish gauge data, columnar (one batch = all samples)."""
    rng = np.random.default_rng(seed)
    keys = gauge_part_keys(num_series, metric, num_apps=num_apps)
    n = num_series * num_samples
    part_idx = np.repeat(np.arange(num_series, dtype=np.int32), num_samples)
    ts = np.tile(start_ms + np.arange(num_samples, dtype=np.int64) * step_ms,
                 num_series)
    phase = rng.uniform(0, 2 * np.pi, size=num_series)
    t = np.tile(np.arange(num_samples), num_series)
    values = (100.0 + 50.0 * np.sin(t / 20.0 + np.repeat(phase, num_samples))
              + rng.normal(0, 2.0, size=n))
    return RecordBatch(GAUGE, keys, part_idx, ts, {"value": values})


def counter_batch(num_series: int, num_samples: int,
                  start_ms: int = 1_600_000_000_000, step_ms: int = 10_000,
                  metric: str = "request_total", seed: int = 7,
                  resets: bool = True, num_apps: int = 10) -> RecordBatch:
    """Monotonic counters with occasional resets (counter dips) so counter
    correction paths are exercised."""
    rng = np.random.default_rng(seed)
    keys = gauge_part_keys(num_series, metric, num_apps=num_apps)
    part_idx = np.repeat(np.arange(num_series, dtype=np.int32), num_samples)
    ts = np.tile(start_ms + np.arange(num_samples, dtype=np.int64) * step_ms,
                 num_series)
    incr = rng.exponential(10.0, size=(num_series, num_samples))
    vals = np.cumsum(incr, axis=1)
    if resets and num_samples > 10:
        # each series resets to ~0 at one random point
        reset_at = rng.integers(num_samples // 2, num_samples, size=num_series)
        for s in range(num_series):
            r = reset_at[s]
            vals[s, r:] = np.cumsum(incr[s, r:], axis=0)
    return RecordBatch(PROM_COUNTER, keys, part_idx, ts,
                       {"count": vals.ravel()})


def histogram_batch(num_series: int, num_samples: int, num_buckets: int = 8,
                    start_ms: int = 1_600_000_000_000, step_ms: int = 10_000,
                    metric: str = "http_latency", seed: int = 11) -> RecordBatch:
    """Native-histogram series: cumulative increasing bucket counts, plus
    sum/count columns (prom-histogram schema)."""
    rng = np.random.default_rng(seed)
    keys = gauge_part_keys(num_series, metric)
    part_idx = np.repeat(np.arange(num_series, dtype=np.int32), num_samples)
    ts = np.tile(start_ms + np.arange(num_samples, dtype=np.int64) * step_ms,
                 num_series)
    n = num_series * num_samples
    # per-step per-bucket increments, cumulative over time and buckets
    inc = rng.poisson(3.0, size=(num_series, num_samples, num_buckets))
    per_bucket_cum = np.cumsum(inc, axis=1)           # cumulative over time
    hist = np.cumsum(per_bucket_cum, axis=2)          # cumulative over buckets
    count = hist[:, :, -1].astype(np.float64)
    total_sum = count * rng.uniform(5.0, 15.0)
    les = [2.0 * (2.0 ** i) for i in range(num_buckets)]
    return RecordBatch(PROM_HISTOGRAM, keys, part_idx, ts,
                       {"sum": total_sum.ravel(), "count": count.ravel(),
                        "h": hist.reshape(n, num_buckets).astype(np.float64)},
                       bucket_les=np.asarray(les))


def region_gauge_batch(num_series: int, num_samples: int,
                       region: str = "east",
                       start_ms: int = 1_600_000_000_000,
                       step_ms: int = 10_000, metric: str = "fed_gauge",
                       seed: int = 0, num_apps: int = 3) -> RecordBatch:
    """Integer-valued gauges tagged with a `region` ownership label —
    the federation fixture's data shape (parallel/testcluster.py
    make_federated_pair).  Integer values make cross-cluster merges
    bit-comparable against a single-store ground truth: sum/count/avg
    over exact integers carry no float-ordering noise."""
    rng = np.random.default_rng(seed)
    keys = [PartKey.make(metric, {
        "_ws_": "demo",
        "_ns_": f"App-{i % num_apps}",
        "region": region,
        "instance": f"{region}-{i}",
    }) for i in range(num_series)]
    part_idx = np.repeat(np.arange(num_series, dtype=np.int32), num_samples)
    ts = np.tile(start_ms + np.arange(num_samples, dtype=np.int64) * step_ms,
                 num_series)
    values = rng.integers(1, 64,
                          size=num_series * num_samples).astype(np.float64)
    return RecordBatch(GAUGE, keys, part_idx, ts, {"value": values})


def batch_stream(batch: RecordBatch, samples_per_chunk: int,
                 base_offset: int = 0) -> Iterator[Tuple[RecordBatch, int]]:
    """Split a big columnar batch into a stream of (smaller batch, offset) —
    the Kafka-container stream shape used by recovery tests."""
    num_series = len(batch.part_keys)
    num_samples = batch.num_records // max(num_series, 1)
    mat_idx = np.arange(batch.num_records).reshape(num_series, num_samples)
    for c, lo in enumerate(range(0, num_samples, samples_per_chunk)):
        hi = min(lo + samples_per_chunk, num_samples)
        sel = mat_idx[:, lo:hi].ravel()
        yield RecordBatch(
            batch.schema, batch.part_keys, batch.part_idx[sel],
            batch.timestamps[sel],
            {k: v[sel] for k, v in batch.columns.items()},
            batch.bucket_les), base_offset + c
