"""Pluggable ingestion streams + the per-shard ingestion lifecycle.

Mirrors the reference's transport abstraction (ref:
coordinator/.../IngestionStream.scala:14-43 — `IngestionStream.get` yields
record containers with offsets; `IngestionStreamFactory.create(config, schemas,
shard, offset)` builds one per shard) and the IngestionActor state machine
(ref: coordinator/.../IngestionActor.scala:58,114,171,294 — resync →
recover index → replay from checkpoints with progress events → normal
streaming).  Kafka's role (1 shard = 1 partition of containers) is played by
any stream yielding (RecordBatch, offset) in offset order.
"""
from __future__ import annotations

import csv
import enum
import threading
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.records import RecordBatch, RecordBatchBuilder
from filodb_tpu.core.schemas import Schemas, DEFAULT_SCHEMAS
from filodb_tpu.core.shard import TimeSeriesShard
from filodb_tpu.parallel.shardmapper import ShardEvent


class IngestionStream:
    """A source of (RecordBatch, offset) in ascending-offset order
    (ref: IngestionStream.scala:14-25)."""

    def batches(self, from_offset: int = -1) -> Iterator[Tuple[RecordBatch, int]]:
        raise NotImplementedError

    def teardown(self) -> None:
        pass


class MemoryStream(IngestionStream):
    """In-memory stream for tests/benchmarks — the noOpSource analogue
    (ref: jmh/.../QueryInMemoryBenchmark.scala:87)."""

    def __init__(self, items: Iterable[Tuple[RecordBatch, int]]):
        self.items = list(items)

    def batches(self, from_offset: int = -1):
        for batch, off in self.items:
            if off > from_offset:
                yield batch, off


class CsvStream(IngestionStream):
    """CSV file source (ref: coordinator/.../sources/CsvStream.scala:124).

    Format: header row with `timestamp` (ms), `metric` (or `__name__`), the
    schema's data columns by name, and any other columns as tags.  Offsets are
    data-line numbers grouped by `batch_size` (the offset of a batch is its
    LAST line number), so rewinding to a checkpoint offset works exactly like
    the reference's line-number rewind.
    """

    def __init__(self, path: str, schema_name: str = "gauge",
                 schemas: Schemas = DEFAULT_SCHEMAS, batch_size: int = 100):
        self.path = path
        self.schemas = schemas
        self.schema = schemas[schema_name]
        self.batch_size = batch_size

    def batches(self, from_offset: int = -1):
        value_cols = [c.name for c in self.schema.data_columns
                      if c.col_type != "hist"]
        with open(self.path, newline="") as f:
            reader = csv.DictReader(f)
            builder = RecordBatchBuilder(self.schema)
            pending = 0
            lineno = -1
            for row in reader:
                lineno += 1
                if lineno <= from_offset:
                    continue
                metric = row.get("metric") or row.get("__name__") or ""
                tags = {k: v for k, v in row.items()
                        if k not in ("timestamp", "metric", "__name__",
                                     "tags", *value_cols)
                        and v}
                # packed tag column: `tags` holds `k=v` pairs split by ';';
                # a plain value stays a literal `tags` label
                # (the map-column form of the reference's CSV source)
                packed = row.get("tags")
                if packed:
                    if "=" in packed:
                        for kv in packed.split(";"):
                            k, _, v = kv.partition("=")
                            if k and v:
                                tags[k] = v
                    else:
                        tags["tags"] = packed
                values = {c: float(row[c]) for c in value_cols if c in row}
                builder.add(PartKey.make(metric, tags),
                            int(row["timestamp"]), **values)
                pending += 1
                if pending >= self.batch_size:
                    yield builder.build(), lineno
                    builder = RecordBatchBuilder(self.schema)
                    pending = 0
            if pending:
                yield builder.build(), lineno


# Factory registry (ref: IngestionStreamFactory resolved from config
# `sourcefactory` class name, coordinator/.../IngestionStream.scala:43)
_STREAM_FACTORIES: Dict[str, Callable[..., IngestionStream]] = {}


def register_stream_factory(name: str, factory: Callable[..., IngestionStream]) -> None:
    _STREAM_FACTORIES[name] = factory


def create_stream(name: str, **kwargs) -> IngestionStream:
    return _STREAM_FACTORIES[name](**kwargs)


register_stream_factory("csv", CsvStream)
register_stream_factory("memory", MemoryStream)


# --------------------------------------------------------------- lifecycle

class IngestionState(enum.Enum):
    """ref: IngestionActor lifecycle states / published ShardEvents."""
    NOT_STARTED = "NotStarted"
    RECOVERING = "Recovering"
    NORMAL = "Normal"
    STOPPED = "Stopped"
    ERROR = "Error"


class IngestionLifecycle:
    """Drives one shard through recovery then normal ingestion
    (ref: IngestionActor.startIngestion:171 → doRecovery:294 →
    normalIngestion:139).  Flush groups rotate every `flush_stride` batches so
    persistence overlaps ingestion (the flush-group pipelining strategy,
    ref: TimeSeriesShard.scala:230-241, doc/ingestion.md:114-129)."""

    def __init__(self, shard: TimeSeriesShard, stream: IngestionStream,
                 subscribers: Iterable[Callable[[ShardEvent], None]] = (),
                 flush_stride: int = 0):
        self.shard = shard
        self.stream = stream
        self.subscribers = list(subscribers)
        self.flush_stride = flush_stride
        self.state = IngestionState.NOT_STARTED
        self.recovery_progress = 0.0
        self._next_flush_group = 0
        self._batches_since_flush = 0
        self._stop = threading.Event()

    def _publish(self, event_type: str, **extra) -> None:
        ev = ShardEvent(event_type, self.shard.dataset, self.shard.shard_num,
                        "local")
        for sub in self.subscribers:
            sub(ev)

    def _maybe_flush(self) -> None:
        if not self.flush_stride:
            return
        self._batches_since_flush += 1
        if self._batches_since_flush >= self.flush_stride:
            self.shard.flush_group(self._next_flush_group)
            self._next_flush_group = (self._next_flush_group + 1) % self.shard._groups
            self._batches_since_flush = 0

    def start(self) -> int:
        """Run recovery + ingest the stream to exhaustion.  Returns samples
        ingested (recovery replays + normal).  Continuous sources should call
        this on a dedicated thread and use stop()."""
        try:
            self._publish("RecoveryInProgress")
            self.state = IngestionState.RECOVERING
            self.shard.recover_index()
            cps = self.shard.meta_store.read_checkpoints(
                self.shard.dataset, self.shard.shard_num)
            start_off = min(cps.values()) if cps else -1
            end_off = max(cps.values()) if cps else -1
            total = 0
            started = False
            for batch, off in self.stream.batches(from_offset=start_off):
                if self._stop.is_set():
                    break
                if off <= end_off:
                    total += self.shard.recover_stream([(batch, off)])
                    span = max(end_off - start_off, 1)
                    self.recovery_progress = min((off - start_off) / span, 1.0)
                    self._publish("RecoveryInProgress")
                else:
                    if not started:
                        self.recovery_progress = 1.0
                        self.state = IngestionState.NORMAL
                        self._publish("IngestionStarted")
                        started = True
                    total += self.shard.ingest(batch, off)
                    self._maybe_flush()
            if not started:
                self.state = IngestionState.NORMAL
                self._publish("IngestionStarted")
            if self._stop.is_set():
                self.state = IngestionState.STOPPED
                self._publish("IngestionStopped")
            return total
        except Exception:
            self.state = IngestionState.ERROR
            self._publish("IngestionError")
            raise

    def stop(self) -> None:
        self._stop.set()
        self.stream.teardown()
