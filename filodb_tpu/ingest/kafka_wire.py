"""Zero-dependency Kafka wire-protocol client (consumer + producer).

The image has no kafka-python, so `KafkaIngestionStream`'s real-consumer
branch could never execute (round-4 verdict weak #7).  Instead of a
library shim, this module speaks the actual Kafka binary protocol over a
TCP socket — the contract a real broker implements — so the branch runs
against ANY Kafka >= 0.11 broker, or against the protocol-faithful
in-process broker in `tests/kafka_broker.py` for the env-gated IT
(`FILODB_KAFKA_IT=1`).

Implemented surface (deliberately minimal, version-pinned):
  - ApiVersions v0 (handshake sanity),
  - ListOffsets v1 (seek to beginning / end),
  - Fetch v4 (record-batch magic v2: varint records, CRC32C verified),
  - Produce v3 (record-batch v2, CRC32C computed, acks=-1).

Framing per the Kafka protocol guide: every request is
`int32 size | int16 api_key | int16 api_version | int32 correlation_id |
nullable_string client_id | body`; every response is
`int32 size | int32 correlation_id | body`.

No compression, no transactions, no consumer groups — offsets are
committed through FiloDB's own group-watermark protocol (ref:
kafka/.../KafkaIngestionStream.scala:63 the reference likewise manages
offsets itself with enable.auto.commit=false).
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import List, Optional, Tuple

API_PRODUCE, API_FETCH, API_LIST_OFFSETS, API_VERSIONS = 0, 1, 2, 18

EARLIEST, LATEST = -2, -1


# ------------------------------------------------------------------ crc32c

def _crc32c_table():
    poly = 0x82F63B78
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC_TABLE = _crc32c_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC-32C (Castagnoli) — the checksum Kafka record batches carry."""
    crc = ~crc & 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return ~crc & 0xFFFFFFFF


# ------------------------------------------------------------- zigzag varint

def write_varint(n: int) -> bytes:
    z = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift, z = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        z |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (z >> 1) ^ -(z & 1), pos


# --------------------------------------------------- record batch v2 codec

def encode_record_batch(base_offset: int, records: List[bytes],
                        timestamp_ms: int = 0) -> bytes:
    """records: value bytes (null keys) -> one magic-v2 batch."""
    body = bytearray()
    body += struct.pack(">iqBi", 0, 0, 2, 0)   # placeholder: filled below
    # attributes(int16) lastOffsetDelta(int32) firstTs(int64) maxTs(int64)
    # producerId(int64) producerEpoch(int16) baseSequence(int32)
    after_crc = bytearray()
    after_crc += struct.pack(">hiqqqhi", 0, len(records) - 1,
                             timestamp_ms, timestamp_ms, -1, -1, -1)
    after_crc += struct.pack(">i", len(records))
    for i, value in enumerate(records):
        rec = bytearray()
        rec += b"\x00"                          # attributes
        rec += write_varint(0)                  # timestamp delta
        rec += write_varint(i)                  # offset delta
        rec += write_varint(-1)                 # key = null
        rec += write_varint(len(value))
        rec += value
        rec += write_varint(0)                  # no headers
        after_crc += write_varint(len(rec)) + rec
    crc = crc32c(bytes(after_crc))
    # batch: baseOffset(8) batchLength(4) partitionLeaderEpoch(4) magic(1)
    #        crc(4) | after_crc
    batch_len = 4 + 1 + 4 + len(after_crc)      # from partitionLeaderEpoch on
    return struct.pack(">qi", base_offset, batch_len) + \
        struct.pack(">iB", 0, 2) + struct.pack(">I", crc) + bytes(after_crc)


def decode_record_batches(buf: bytes) -> List[Tuple[int, bytes]]:
    """-> [(offset, value bytes)] across all complete batches in buf
    (a Fetch response may truncate the final batch — skipped)."""
    out: List[Tuple[int, bytes]] = []
    pos = 0
    while pos + 12 <= len(buf):
        base_offset, batch_len = struct.unpack_from(">qi", buf, pos)
        start = pos + 12
        if batch_len < 9 or start + batch_len > len(buf):
            break                                # partial trailing batch
        magic = buf[start + 4]
        if magic != 2:
            raise ValueError(f"unsupported record batch magic {magic}")
        crc_stored, = struct.unpack_from(">I", buf, start + 5)
        after = buf[start + 9:start + batch_len]
        if crc32c(after) != crc_stored:
            raise ValueError("record batch CRC32C mismatch")
        p = 0
        p += struct.calcsize(">hiqqqhi")
        nrecs, = struct.unpack_from(">i", after, p)
        p += 4
        for _ in range(nrecs):
            rec_len, p = read_varint(after, p)
            rec_end = p + rec_len
            q = p + 1                            # attributes
            _, q = read_varint(after, q)         # ts delta
            off_delta, q = read_varint(after, q)
            klen, q = read_varint(after, q)
            if klen >= 0:
                q += klen
            vlen, q = read_varint(after, q)
            value = after[q:q + vlen] if vlen >= 0 else b""
            out.append((base_offset + off_delta, bytes(value)))
            p = rec_end
        pos = start + batch_len
    return out


# ------------------------------------------------------------ wire client

def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


class KafkaWireClient:
    """Blocking single-connection client for one broker."""

    def __init__(self, host: str, port: int, client_id: str = "filodb-tpu",
                 timeout_s: float = 30.0):
        self.client_id = client_id
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._corr = 0
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _roundtrip(self, api_key: int, api_version: int,
                   body: bytes) -> bytes:
        with self._lock:
            self._corr += 1
            corr = self._corr
            header = struct.pack(">hhi", api_key, api_version, corr) + \
                _str(self.client_id)
            msg = struct.pack(">i", len(header) + len(body)) + header + body
            self._sock.sendall(msg)
            raw = self._recv_exact(4)
            size, = struct.unpack(">i", raw)
            payload = self._recv_exact(size)
        rcorr, = struct.unpack_from(">i", payload, 0)
        if rcorr != corr:
            raise ValueError(f"correlation id mismatch {rcorr} != {corr}")
        return payload[4:]

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            c = self._sock.recv(n)
            if not c:
                raise ConnectionError("broker closed connection")
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    # -- ApiVersions v0

    def api_versions(self) -> dict:
        resp = self._roundtrip(API_VERSIONS, 0, b"")
        err, n = struct.unpack_from(">hi", resp, 0)
        if err:
            raise ValueError(f"ApiVersions error {err}")
        out, pos = {}, 6
        for _ in range(n):
            k, lo, hi = struct.unpack_from(">hhh", resp, pos)
            pos += 6
            out[k] = (lo, hi)
        return out

    # -- ListOffsets v1 (one topic, one partition)

    def list_offset(self, topic: str, partition: int, when: int) -> int:
        """when: EARLIEST (-2) or LATEST (-1) -> the offset."""
        body = struct.pack(">i", -1)             # replica_id
        body += struct.pack(">i", 1) + _str(topic)
        body += struct.pack(">i", 1)
        body += struct.pack(">iq", partition, when)
        resp = self._roundtrip(API_LIST_OFFSETS, 1, body)
        ntop, = struct.unpack_from(">i", resp, 0)
        pos = 4
        tlen, = struct.unpack_from(">h", resp, pos)
        pos += 2 + tlen
        nparts, = struct.unpack_from(">i", resp, pos)
        pos += 4
        part, err, _ts, offset = struct.unpack_from(">ihqq", resp, pos)
        if err:
            raise ValueError(f"ListOffsets error {err} on {topic}/{part}")
        return offset

    # -- Fetch v4 (one topic, one partition)

    def fetch(self, topic: str, partition: int, offset: int,
              max_wait_ms: int = 500, max_bytes: int = 8 << 20
              ) -> List[Tuple[int, bytes]]:
        body = struct.pack(">iiii", -1, max_wait_ms, 1, max_bytes)
        body += b"\x00"                          # isolation_level = 0
        body += struct.pack(">i", 1) + _str(topic)
        body += struct.pack(">i", 1)
        body += struct.pack(">iqi", partition, offset, max_bytes)
        resp = self._roundtrip(API_FETCH, 4, body)
        pos = 4                                   # throttle_time_ms
        ntop, = struct.unpack_from(">i", resp, pos)
        pos += 4
        tlen, = struct.unpack_from(">h", resp, pos)
        pos += 2 + tlen
        nparts, = struct.unpack_from(">i", resp, pos)
        pos += 4
        part, err, _hw, _lso = struct.unpack_from(">ihqq", resp, pos)
        pos += struct.calcsize(">ihqq")
        if err:
            raise ValueError(f"Fetch error {err} on {topic}/{part}")
        naborted, = struct.unpack_from(">i", resp, pos)
        pos += 4 + max(naborted, 0) * 16
        rlen, = struct.unpack_from(">i", resp, pos)
        pos += 4
        records = resp[pos:pos + max(rlen, 0)]
        return [(o, v) for o, v in decode_record_batches(records)
                if o >= offset]

    # -- Produce v3 (one topic, one partition)

    def produce(self, topic: str, partition: int,
                values: List[bytes]) -> int:
        """-> base offset assigned by the broker."""
        batch = encode_record_batch(0, values)
        body = _str(None)                        # transactional_id
        body += struct.pack(">hi", -1, 30_000)   # acks=-1, timeout
        body += struct.pack(">i", 1) + _str(topic)
        body += struct.pack(">i", 1)
        body += struct.pack(">i", partition)
        body += struct.pack(">i", len(batch)) + batch
        resp = self._roundtrip(API_PRODUCE, 3, body)
        ntop, = struct.unpack_from(">i", resp, 0)
        pos = 4
        tlen, = struct.unpack_from(">h", resp, pos)
        pos += 2 + tlen
        nparts, = struct.unpack_from(">i", resp, pos)
        pos += 4
        part, err, base_offset = struct.unpack_from(">ihq", resp, pos)
        if err:
            raise ValueError(f"Produce error {err} on {topic}/{part}")
        return base_offset


class WireConsumer:
    """kafka-python-shaped minimal consumer over KafkaWireClient — the
    object KafkaIngestionStream's real branch returns when kafka-python
    is absent.  Iterating yields messages with .offset/.value, polling
    the broker; iteration ends when `stop()` is called (or idle_stop_s
    elapses with no new data, for bounded test runs)."""

    class _Msg:
        __slots__ = ("offset", "value")

        def __init__(self, offset: int, value: bytes):
            self.offset = offset
            self.value = value

    def __init__(self, bootstrap: str, topic: str, partition: int,
                 idle_stop_s: float = 0.0):
        host, _, port = bootstrap.partition(":")
        self.client = KafkaWireClient(host, int(port or 9092))
        self.topic = topic
        self.partition = partition
        self.position = 0
        self.idle_stop_s = idle_stop_s
        self._stopped = threading.Event()

    # seek API (subset kafka-python exposes)

    def seek(self, _tp, offset: int) -> None:
        self.position = offset

    def seek_to_beginning(self, _tp=None) -> None:
        self.position = self.client.list_offset(self.topic, self.partition,
                                                EARLIEST)

    def seek_to_end(self, _tp=None) -> None:
        self.position = self.client.list_offset(self.topic, self.partition,
                                                LATEST)

    def stop(self) -> None:
        self._stopped.set()

    def __iter__(self):
        import time
        idle_since = time.monotonic()
        while not self._stopped.is_set():
            msgs = self.client.fetch(self.topic, self.partition,
                                     self.position)
            if msgs:
                idle_since = time.monotonic()
                for off, val in msgs:
                    yield self._Msg(off, val)
                    self.position = off + 1
            elif self.idle_stop_s and \
                    time.monotonic() - idle_since > self.idle_stop_s:
                return

    def close(self) -> None:
        self._stopped.set()
        self.client.close()
