"""FiloServer — the standalone process entry point.

ref: standalone/.../FiloServer.scala:39-60 — boots the coordinator, memstore,
and HTTP server for a single node owning every shard of its datasets.  The
TPU-native standalone wires: memstore (+ optional local-disk persistence),
shard mapper, planner stack (shard-key regex fan-out over the single-cluster
planner, long-time-range split when downsampling is enabled), Influx gateway,
and the HTTP API.  Cluster mode adds the ShardManager/controller from
filodb_tpu.parallel (multi-node assignment) on top of the same pieces.
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Dict, List, Optional, Sequence

from filodb_tpu.config import (FilodbSettings, apply_jax_runtime,
                               parse_warmup_shapes,
                               settings as default_settings)
from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.store import (ColumnStore, InMemoryColumnStore,
                                   InMemoryMetaStore, MetaStore,
                                   NullColumnStore)
from filodb_tpu.gateway.router import GatewayPipeline
from filodb_tpu.http.routes import PromHttpApi
from filodb_tpu.http.server import FiloHttpServer
from filodb_tpu.parallel.shardmapper import (ShardEvent, ShardMapper,
                                             ShardStatus, SpreadProvider)
from filodb_tpu.query.engine import QueryEngine
from filodb_tpu.query.planner import SingleClusterPlanner
from filodb_tpu.query.planners import (ShardKeyRegexPlanner,
                                       default_shard_key_matcher)


@dataclasses.dataclass
class DatasetConfig:
    """Per-dataset ingestion config (ref: conf/timeseries-dev-source.conf —
    dataset, num-shards, sourcefactory, store block)."""
    name: str = "prometheus"
    num_shards: int = 4
    downsample_resolutions: Sequence[int] = ()


class IndexCompactionLoop:
    """Churn maintenance for the part-key index (doc/index.md runbook).

    Eviction flips an alive bit and leaves a tombstone — O(1), no posting
    rewrite on the ingest path.  This daemon sweeps every shard of every
    dataset each interval and runs PartKeyIndex.compact() once a shard's
    tombstone backlog crosses `index.compaction_tombstone_threshold`,
    pruning dead postings, empty value/label dict entries, and fully-dead
    leading containers so index memory stays flat under series churn.
    Registered as the `index_compaction` job (GET /admin/jobs)."""

    def __init__(self, memstore, datasets: Sequence[str], interval_s: float,
                 tombstone_threshold: int):
        from filodb_tpu.utils.jobs import jobs
        self.memstore = memstore
        self.datasets = list(datasets)
        self.interval_s = interval_s
        self.tombstone_threshold = tombstone_threshold
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.job = jobs.register("index_compaction", interval_s=interval_s)

    def start(self) -> "IndexCompactionLoop":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="filodb-index-compaction", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=30)
            self._thread = None

    def run_once(self) -> int:
        """One sweep over every shard; returns shards compacted."""
        compacted = 0
        for name in self.datasets:
            for sh in self.memstore.shards_for(name):
                if sh.compact_index(self.tombstone_threshold):
                    compacted += 1
        return compacted

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                with self.job.tick() as jt:
                    n = self.run_once()
                    if n == 0:
                        # below threshold everywhere: neutral tick, the
                        # backlog keeps accruing until worth a rewrite
                        jt.skip()
                    else:
                        self.job.set_progress(f"compacted {n} shard indexes")
            except Exception:  # noqa: BLE001 — recorded by tick(); the
                pass           # sweep must survive one bad shard


class FiloServer:

    def __init__(self, datasets: Optional[List[DatasetConfig]] = None,
                 column_store: Optional[ColumnStore] = None,
                 meta_store: Optional[MetaStore] = None,
                 config: Optional[FilodbSettings] = None,
                 http_host: str = "127.0.0.1", http_port: int = 0,
                 node_name: str = "local",
                 replication_peers: Optional[Dict[str, tuple]] = None):
        self.config = config or default_settings()
        # health model (utils/health.py): phase machinery + per-subsystem
        # verdicts, served at /healthz, /ready and /api/v1/status/health.
        # Created FIRST so every boot step below lands as a phase/journal
        # event — the flight recorder starts at "booting"
        from filodb_tpu.utils.events import journal
        from filodb_tpu.utils.health import BOOTING, HealthEvaluator
        self.health = HealthEvaluator(node_name=node_name, phase=BOOTING)
        journal.configure(
            max_entries=self.config.event_journal_max_entries,
            path=self.config.event_journal_path)
        journal.emit("server_boot", subsystem="server", node=node_name)
        # persistent XLA compile cache BEFORE any jit runs: a restarted
        # server must answer its first heavy query from cached programs
        # (round-5 verdict item 2; measured 43.6-73.4 s cold compiles)
        apply_jax_runtime(self.config)
        self.datasets = datasets or [DatasetConfig()]
        self.column_store = column_store or InMemoryColumnStore()
        self.meta_store = meta_store or InMemoryMetaStore()
        self.node_name = node_name
        self.memstore = TimeSeriesMemStore(
            column_store=self.column_store, meta_store=self.meta_store,
            config=self.config)
        self.mappers: Dict[str, ShardMapper] = {}
        self.spreads: Dict[str, SpreadProvider] = {}
        self.engines: Dict[str, QueryEngine] = {}
        self.gateways: Dict[str, GatewayPipeline] = {}
        self.ds_stores: Dict[str, object] = {}
        self.flush_schedulers: Dict[str, object] = {}
        self.index_compactor: Optional[IndexCompactionLoop] = None
        self.wals: Dict[str, object] = {}
        self._earliest_cache: Dict[str, tuple] = {}
        # historical tier: one cold DeviceMirror region (byte-budgeted LRU
        # of persisted-segment blocks) shared across datasets, plus a
        # per-dataset PersistedTier + compaction scheduler — wired only
        # when the column store is disk-backed (LocalDiskColumnStore)
        self.cold_cache = None
        self.persisted_tiers: Dict[str, object] = {}
        self.compaction_schedulers: Dict[str, object] = {}
        if self.config.store.segment_compaction_enabled and \
                hasattr(self.column_store, "iter_chunk_refs"):
            from filodb_tpu.core.devicecache import ColdSegmentCache
            self.cold_cache = ColdSegmentCache(
                self.config.store.device_mirror_cold_limit_bytes)
        # disaggregated cold tier (persist/objectstore.py): when a shared
        # object-store root is configured next to a disk-backed segment
        # tier, compaction uploads content-addressed segments there,
        # retention gates on upload acks, and boot restores the local
        # segment dir from the manifests (doc/operations.md disk-loss
        # runbook)
        self.object_store = None
        self.uploaders: Dict[str, object] = {}
        if self.config.objectstore.root and self.cold_cache is not None \
                and getattr(self.column_store, "root", None):
            from filodb_tpu.persist.objectstore import LocalObjectStore
            self.object_store = LocalObjectStore(
                self.config.objectstore.root)
        # observability singletons take their knobs from THIS server's
        # settings: the slow-query flight recorder (ring size, JSONL
        # sink) and the per-tenant usage window (utils/slowlog, usage)
        from filodb_tpu.utils.slowlog import ingestlog, slowlog
        from filodb_tpu.utils.usage import usage
        slowlog.configure(
            threshold_s=self.config.query.slow_query_threshold_s,
            max_entries=self.config.query.slowlog_max_entries,
            path=self.config.query.slowlog_path)
        usage.window_s = self.config.query.tenant_limit_window_s
        # multi-tenant QoS (query/qos.py): validate the share map at
        # boot — a typo'd share must fail the deploy loudly, not
        # silently schedule that tenant at the default — and journal the
        # effective config so "who had what share when" is answerable
        # from the flight recorder next to the overload events
        qc = self.config.query
        from filodb_tpu.config import ConfigError
        for ws, share in qc.tenant_shares.items():
            try:
                bad = not (float(share) > 0)
            except (TypeError, ValueError):
                bad = True
            if bad:
                raise ConfigError(
                    f"query.tenant_shares.{ws}: expected a positive "
                    f"number, got {share!r}")
        if qc.tenant_max_queue_depth < 0:
            raise ConfigError("query.tenant_max_queue_depth must be "
                              ">= 0 (0 = unbounded)")
        if qc.shuffle_shard_factor < 0:
            raise ConfigError("query.shuffle_shard_factor must be "
                              ">= 0 (0 = disabled)")
        journal.emit(
            "qos_config", subsystem="query",
            max_concurrent=qc.max_concurrent_queries,
            shares=",".join(f"{k}={float(v):g}" for k, v in
                            sorted(qc.tenant_shares.items())) or "equal",
            max_queue_depth=qc.tenant_max_queue_depth,
            shed_enabled=qc.shed_enabled,
            shuffle_shard_factor=qc.shuffle_shard_factor)
        # write-path observability (doc/observability.md): the ingest
        # flight recorder, the freshness SLO fold feeding the health
        # evaluator's `ingest` verdict, the exemplar toggle, and the
        # node name stamped on every span this process records
        from filodb_tpu.utils import metrics as _metrics
        from filodb_tpu.utils.freshness import freshness
        ingestlog.configure(
            threshold_s=self.config.ingest.slow_batch_threshold_s,
            max_entries=self.config.ingest.ingestlog_max_entries,
            path=self.config.ingest.ingestlog_path)
        freshness.configure(
            threshold_s=self.config.ingest.slow_batch_threshold_s,
            breach_count=self.config.ingest.freshness_breach_count,
            window_s=self.config.ingest.freshness_window_s)
        _metrics.set_exemplars_enabled(self.config.exemplars_enabled)
        # live query introspection (query/activequeries.py): wire the
        # registry's knobs, default the crash-durable active-query file
        # next to the WAL when one is configured, and journal whatever
        # the PREVIOUS process left running at crash time
        from filodb_tpu.query.activequeries import active_queries
        aq_path = self.config.query.active_query_log_path
        if not aq_path and self.config.wal.enabled and self.config.wal.dir:
            import os as _os
            aq_path = _os.path.join(self.config.wal.dir, "queries.active")
        active_queries.configure(
            enabled=self.config.query.active_queries_enabled,
            path=aq_path)
        n_crash = active_queries.replay_crash_log()
        if n_crash:
            journal.emit("query_crash_replay", subsystem="query",
                         queries_active_at_crash=n_crash)
        if node_name != "local" or not _metrics.NODE_NAME:
            # an explicitly-named server stamps its spans (the cross-
            # node trace evidence); default-named embedded servers only
            # fill an empty slot so they never clobber a real identity
            _metrics.NODE_NAME = node_name
        # Cross-cluster federation (filodb_tpu/federation; doc/
        # federation.md): the registry parses `federation.clusters` and
        # probes remote doors; the door is THIS cluster's dispatch
        # endpoint.  Both exist before the dataset loop so each
        # dataset's planner stack gains a FederationPlanner outermost
        # and registers its inner stack at the door.
        self.federation_registry = None
        self.federation_door = None
        fed = self.config.federation
        if fed.enabled:
            from filodb_tpu.federation import (FederationDoor,
                                               FederationRegistry)
            cluster = fed.cluster_name or node_name
            self.federation_registry = FederationRegistry(
                fed, local_name=cluster)
            self.federation_door = FederationDoor(
                cluster, host=fed.door_host, port=fed.door_port)
        for dc in self.datasets:
            self._setup_dataset(dc)
        if self.federation_door is not None:
            # bound in __init__ (not start()) so embedders that query
            # without start() — and the two-cluster test pair reading
            # back an ephemeral port — see a live door immediately
            self.federation_door.start()
            self.health.probes["federation"] = \
                self.federation_registry.health_probe
            journal.emit("federation_door_open", subsystem="federation",
                         cluster=self.federation_registry.local_name,
                         port=self.federation_door.port,
                         clusters=",".join(
                             sorted(self.federation_registry.clusters)))
        if self.uploaders:
            # the `persistence` health subsystem: upload backlog age +
            # breaker state per dataset, worst-wins into the verdict
            from filodb_tpu.persist.objectstore import persistence_probe
            self.health.probes["persistence"] = persistence_probe(
                self.uploaders,
                backlog_warn_s=self.config.objectstore.backlog_warn_s)
        first = self.datasets[0].name
        self.api = PromHttpApi(self.engines, gateways=self.gateways,
                               shard_mappers=self.mappers,
                               default_dataset=first,
                               batch_window_ms=self.config.query
                               .batch_window_ms,
                               config=self.config, health=self.health)
        if self.federation_registry is not None:
            self.api.federation = self.federation_registry
        self.http = FiloHttpServer(self.api, http_host, http_port)
        # Ruler — recording & alerting rules (filodb_tpu/rules): standing
        # queries evaluated through this server's QueryFrontend whose
        # outputs write back through the columnar ingest path of the
        # configured dataset's shards.  Built AFTER the API so the
        # frontends exist; evaluation loops start in start().
        self.ruler = None
        if self.config.rules.enabled:
            from filodb_tpu.rules import MemstoreSink, Ruler
            ds = self.config.rules.dataset or first
            if ds not in self.engines:
                from filodb_tpu.config import ConfigError
                raise ConfigError(
                    f"rules.dataset {ds!r} is not a served dataset "
                    f"(have: {sorted(self.engines)})")
            # reload() re-reads the conf file from disk when one backs
            # the process, so /admin/rules/reload picks up edits to the
            # inline rules.groups block too (not just rules.file)
            conf_path = os.environ.get("FILODB_TPU_CONFIG")
            config_source = None
            if conf_path:
                config_source = (lambda p=conf_path:
                                 FilodbSettings.load(p).rules)
            self.ruler = Ruler(
                self.api.frontends[ds],
                MemstoreSink(self.memstore, ds, self.mappers[ds],
                             self.spreads[ds]),
                config=self.config.rules,
                config_source=config_source)
            self.api.ruler = self.ruler
        # self-scrape meta-monitoring (utils/selfmon.py): built here so a
        # misconfigured dataset fails boot loudly; the loop starts in
        # start() next to the other background jobs
        self.selfmon = None
        if self.config.selfmon.enabled:
            from filodb_tpu.utils.selfmon import SelfScraper
            sm_ds = self.config.selfmon.dataset or first
            if sm_ds not in self.engines:
                from filodb_tpu.config import ConfigError
                raise ConfigError(
                    f"selfmon.dataset {sm_ds!r} is not a served dataset "
                    f"(have: {sorted(self.engines)})")
            self.selfmon = SelfScraper(
                self.memstore, sm_ds, self.mappers[sm_ds],
                self.spreads[sm_ds], node_name=self.node_name,
                interval_s=self.config.selfmon.interval_s)
        # Replication layer (filodb_tpu/replication; doc/replication.md):
        # this node's replication door accepts slab appends / WAL-
        # segment fetches / snapshot streams from peers; with a peer
        # address book, ingest fans out through a ReplicationManager and
        # live handoffs drive through a HandoffCoordinator (both
        # surfaced at /admin/shards).  Single-node deployments without
        # peers still get the door — a future replica catches up from it.
        self.replication_server = None
        self.replicators: Dict[str, object] = {}
        self.handoff_coordinators: Dict[str, object] = {}
        if self.config.replication.enabled:
            from filodb_tpu.replication import (HandoffCoordinator,
                                                ReplicaClient,
                                                ReplicationManager,
                                                ReplicationServer)
            self.replication_server = ReplicationServer(
                self.memstore, node=node_name, wals=self.wals)
            peers = dict(replication_peers or {})
            clients: Dict[str, ReplicaClient] = {}

            def client_for(node: str) -> ReplicaClient:
                cli = clients.get(node)
                if cli is None:
                    if node == node_name and node not in peers:
                        # a handoff OFF this node dials its own door
                        # (the from-node side of the stream)
                        host, port = self.replication_server.address
                    else:
                        host, port = peers[node]
                    clients[node] = cli = ReplicaClient(
                        host, port,
                        timeout_s=self.config.replication.append_timeout_s)
                return cli

            peer_names = sorted(n for n in peers if n != node_name)
            for dc in self.datasets:
                mapper = self.mappers[dc.name]
                if peers:
                    # the RF intent lands on the mapper only when peers
                    # exist to place replicas on — a single node running
                    # just the door must not pin the health verdict at
                    # degraded-underReplicated forever
                    mapper.replication_factor = \
                        self.config.replication.factor
                    # static placement: every shard's replica tail
                    # fills from the peer address book, rotated by
                    # shard so copies spread — without this the
                    # documented conf would build a fan-out manager
                    # whose owner lists never contain a replica (a
                    # silent no-op pinned at degraded).  ACTIVE: a
                    # configured peer door is the deployment's claim
                    # that the copy serves (the cluster path flips
                    # these from heartbeats instead).
                    for s in range(dc.num_shards):
                        for i in range(
                                self.config.replication.factor - 1):
                            if not peer_names:
                                break
                            peer = peer_names[(s + i) % len(peer_names)]
                            mapper.register_replica(
                                s, peer, status=ShardStatus.ACTIVE)
                    self.replicators[dc.name] = ReplicationManager(
                        dc.name, mapper, client_for,
                        config=self.config.replication,
                        local_node=node_name)
                    self.handoff_coordinators[dc.name] = \
                        HandoffCoordinator(
                            dc.name, mapper, client_for,
                            tombstone_grace_s=self.config.replication
                            .handoff_tombstone_grace_s,
                            health=self.health)
            self.api.replicators = self.replicators
            self.api.handoffs = self.handoff_coordinators
        # boot WAL replay: runs AFTER the API exists (the transport-
        # agnostic routes answer /healthz — and /ready with 503 — while
        # the log replays) and BEFORE start() declares the node serving;
        # by the time the constructor returns, replay is complete, so
        # embedders that query without start() see the recovered store
        self._replay_wals()
        from filodb_tpu.utils.health import BOOTED
        self.health.set_phase(BOOTED)

    def _replay_wals(self) -> None:
        from filodb_tpu.utils.health import REPLAYING_WAL
        if not self.wals or not self.config.wal.replay_on_start:
            return
        self.health.set_phase(REPLAYING_WAL)
        for dc in self.datasets:
            wal = self.wals.get(dc.name)
            if wal is None:
                continue
            restart_points = {
                s: self.meta_store.read_earliest_checkpoint(dc.name, s)
                for s in range(dc.num_shards)}
            stats = wal.replay(self.memstore, restart_points)
            self.health.note_wal(dc.name, enabled=True,
                                 replay_done=True, stats=stats)

    # ------------------------------------------------------------- wiring

    def _setup_dataset(self, dc: DatasetConfig) -> None:
        from filodb_tpu.core.ratelimit import CardinalityTracker, QuotaSource
        mapper = ShardMapper(dc.num_shards)
        spread = SpreadProvider(default_spread=self.config.spread_default)
        quota_source = QuotaSource(self.config.quota_default)
        shards = []
        for s in range(dc.num_shards):
            shard = self.memstore.setup(dc.name, s)
            # tracker attaches BEFORE index recovery so recovered series are
            # counted and quotas survive restarts by recount
            shard.cardinality_tracker = CardinalityTracker(
                shard_key_len=len(
                    self.memstore.schemas.part.options.shard_key_columns),
                quota_source=quota_source)
            shard.recover_index()
            shards.append(shard)
            mapper.update_from_event(
                ShardEvent("IngestionStarted", dc.name, s, self.node_name))
        raw_planner = SingleClusterPlanner(dc.name, mapper, spread)
        planner = raw_planner
        ds_planner = None
        if dc.downsample_resolutions:
            ds_planner = self._make_downsample(dc, mapper)
        persisted_planner = None
        tier = None
        if self.cold_cache is not None \
                and getattr(self.column_store, "root", None):
            tier = self._make_persisted_tier(dc, spread, mapper)
            from filodb_tpu.query.planners import PersistedClusterPlanner
            persisted_planner = PersistedClusterPlanner(
                dc.name, mapper, tier, spread_provider=spread)
        if ds_planner is not None or persisted_planner is not None:
            from filodb_tpu.query.planners import LongTimeRangePlanner
            earliest = self._earliest_raw_time
            planner = LongTimeRangePlanner(
                raw_planner, ds_planner,
                earliest_raw_time_fn=lambda: earliest(dc.name),
                latest_downsample_time_fn=lambda: 1 << 62,
                persisted_planner=persisted_planner,
                persisted_range_fn=(tier.range if tier is not None
                                    else None))

        def label_vals(col: str) -> List[str]:
            out = set()
            for sh in shards:
                for v in sh.index.label_values(col):
                    out.add(v[0] if isinstance(v, tuple) else v)
            return sorted(out)

        matcher = default_shard_key_matcher(
            label_vals, self.memstore.schemas.part.options.shard_key_columns)
        planner = ShardKeyRegexPlanner(planner, matcher)
        if self.federation_registry is not None:
            # federation sits OUTERMOST: local-only selectors fall
            # straight through to the stack above; the door serves THIS
            # cluster's share of remote coordinators' queries through
            # the same inner stack (never the federated wrapper — a
            # mutually-federated pair must not bounce subtrees)
            from filodb_tpu.federation import FederationPlanner
            inner = planner
            planner = FederationPlanner(
                inner, self.federation_registry, dataset=dc.name,
                config=self.config.federation)
            store_source = self._source()
            shards = self.memstore.shards_for(dc.name)
            self.federation_door.register(
                dc.name, inner, store_source,
                token_fn=lambda sh=shards: [
                    (s.keys_serial, s.keys_epoch, s.index.mutations,
                     s.append_horizon_ms()) for s in sh],
                default=(dc.name == self.datasets[0].name))
        self.mappers[dc.name] = mapper
        self.spreads[dc.name] = spread
        self.engines[dc.name] = QueryEngine(dc.name, self._source(), mapper,
                                            planner=planner,
                                            config=self.config)
        self.gateways[dc.name] = GatewayPipeline(self.memstore, dc.name,
                                                 mapper, spread,
                                                 config=self.config)
        if self.config.wal.enabled:
            # durability front: the remote_write door appends through
            # this manager and acks only after the group commit; boot
            # replays the log through the same columnar ingest path
            # BEFORE the HTTP server opens (filodb_tpu/wal).  The replay
            # itself runs from __init__ AFTER the API is built (see
            # _replay_wals) so /ready can answer 503 while it runs.
            from filodb_tpu.wal import WalManager
            wal = WalManager(self.config.wal.dir, dc.name,
                             config=self.config.wal)
            self.wals[dc.name] = wal
            self.gateways[dc.name].wal = wal
            self.health.note_wal(dc.name, enabled=True,
                                 replay_done=not
                                 self.config.wal.replay_on_start)

    def _make_downsample(self, dc: DatasetConfig, mapper: ShardMapper):
        from filodb_tpu.downsample import (DownsampleClusterPlanner,
                                           DownsampledTimeSeriesStore,
                                           ShardDownsampler)
        ds_store = DownsampledTimeSeriesStore(
            dc.name, column_store=self.column_store,
            meta_store=self.meta_store,
            resolutions=dc.downsample_resolutions, config=self.config)
        self.ds_stores[dc.name] = ds_store
        for s in range(dc.num_shards):
            ds_store.setup_shard(s)
            ds_store.refresh_index(s)
            dsr = ShardDownsampler(resolutions=dc.downsample_resolutions)
            raw_shard = self.memstore.get_shard(dc.name, s)
            raw_shard.shard_downsampler = dsr
        return DownsampleClusterPlanner(ds_store, mapper)

    def _make_persisted_tier(self, dc: DatasetConfig, spread, mapper=None):
        """Segment store + cold tier + compaction job for one dataset
        (historical tier, doc/operations.md compaction runbook).  With a
        shared object store configured, this also mounts the shard
        manifests (restoring missing segments first when
        objectstore.restore_on_boot) and hangs a SegmentUploader off the
        compaction scheduler — /ready answers 503 until the mount
        lands."""
        from filodb_tpu.persist.compactor import (CompactionScheduler,
                                                  SegmentCompactor)
        from filodb_tpu.persist.segments import PersistedTier, SegmentStore
        seg_store = SegmentStore(self.column_store.root)
        uploader = None
        if self.object_store is not None:
            from filodb_tpu.persist.objectstore import (
                ObjectStoreError, SegmentUploader, restore_from_objectstore)
            from filodb_tpu.utils.events import journal
            oc = self.config.objectstore
            self.health.note_manifest_mount(dc.name, False)
            uploader = SegmentUploader(
                self.object_store, seg_store, dc.name, dc.num_shards,
                node=self.node_name, mapper=mapper,
                retry_base_s=oc.retry_base_s, retry_max_s=oc.retry_max_s,
                max_attempts=oc.max_attempts)
            self.uploaders[dc.name] = uploader
            # durability ordering: every raw-chunk prune for this dataset
            # clamps through the upload-ack gate, whoever asks for it
            uploader.install_prune_guard(self.column_store)
            try:
                if oc.restore_on_boot:
                    restore_from_objectstore(
                        self.object_store, seg_store, dc.name,
                        dc.num_shards, retry_base_s=oc.retry_base_s,
                        retry_max_s=oc.retry_max_s,
                        max_attempts=oc.max_attempts, node=self.node_name)
                uploader.mount()
                self.health.note_manifest_mount(dc.name, True)
            except ObjectStoreError as e:
                # the mount stays pending, so /ready keeps answering 503
                # — a node that cannot see the shared tier must not serve
                journal.emit("objectstore_mount_failed",
                             subsystem="persistence", dataset=dc.name,
                             node=self.node_name, error=str(e)[:200])
        tier = PersistedTier(seg_store, dc.name, dc.num_shards,
                             self.cold_cache,
                             schemas=self.memstore.schemas)
        self.persisted_tiers[dc.name] = tier
        compactor = SegmentCompactor(
            self.column_store, seg_store, dc.name, dc.num_shards,
            window_ms=self.config.store.segment_window_ms,
            closed_lag_ms=self.config.store.segment_closed_lag_ms,
            schemas=self.memstore.schemas, tier=tier)
        self.compaction_schedulers[dc.name] = CompactionScheduler(
            compactor,
            interval_s=self.config.store.segment_compact_interval_ms
            / 1000.0,
            retain_raw_ms=self.config.store.segment_retain_raw_ms,
            uploader=uploader)
        return tier

    def _earliest_raw_time(self, dataset: str) -> int:
        """Raw retention floor: earliest live sample across shards, cached a
        few seconds — this sits on the planning hot path (a real deployment
        derives it from retention config)."""
        import time
        cached = self._earliest_cache.get(dataset)
        now = time.monotonic()
        if cached is not None and now - cached[1] < 10.0:
            return cached[0]
        out = []
        for sh in self.memstore.shards_for(dataset):
            for store in sh.stores.values():
                live = store.ts[:store.num_series]
                if live.size:
                    valid = live[live > 0]
                    if valid.size:
                        out.append(int(valid.min()))
        val = min(out) if out else 0
        self._earliest_cache[dataset] = (val, now)
        return val

    def _source(self):
        server = self

        class _Source:
            """Routes leaf dataset names to raw or downsample stores."""
            def get_shard(self, dataset: str, shard_num: int):
                if "::ds::" in dataset:
                    raw = dataset.split("::ds::")[0]
                    ds_store = server.ds_stores.get(raw)
                    return ds_store.get_shard(dataset, shard_num) \
                        if ds_store else None
                return server.memstore.get_shard(dataset, shard_num)

            def shards_for(self, dataset: str):
                # the query frontend's result cache derives its
                # invalidation token from these shards.  Downsample
                # datasets — and raw datasets the planner may ROUTE to a
                # downsample store — return [] so the cache bypasses
                # them: downsampled points land with timestamps behind
                # the raw append horizon, invisible to the raw token
                if "::ds::" in dataset or dataset in server.ds_stores:
                    return []
                return server.memstore.shards_for(dataset)
        return _Source()

    # ------------------------------------------------------------ lifecycle

    def start(self, background_flush: bool = True) -> None:
        try:
            # seed the device-telemetry ledger with every local chip so
            # /admin/devices lists the fleet before the first dispatch
            import jax

            from filodb_tpu.utils.devicetelem import telem
            telem.register_devices(jax.local_devices())
        except Exception:  # noqa: BLE001 — telemetry boot is advisory
            pass
        self.http.start()
        if self.replication_server is not None:
            self.replication_server.start()
        self.trace_exporter = None
        if self.config.trace_export_url:
            from filodb_tpu.utils.traceexport import TraceExporter
            self.trace_exporter = TraceExporter(
                self.config.trace_export_url).start()
        self.warmup_thread = None
        shapes = parse_warmup_shapes(self.config.warmup_shapes)
        if shapes:
            # compile the configured headline shapes off the boot path
            # (first boot pays real XLA compiles; restarts deserialize
            # from the persistent cache wired in __init__) so the first
            # dashboard query finds its program ready — the reference's
            # "query path is always ready" stance (ref: coordinator/../
            # QueryActor.scala:98-117)
            import threading

            def _warm():
                from filodb_tpu.ops import pallas_fused as pf
                from filodb_tpu.utils.metrics import registry
                for (s, t, w, g) in shapes:
                    try:
                        secs = pf.warmup_compile(s, t, w, g)
                        registry.gauge("warmup_compile_seconds") \
                            .update(secs)
                    except Exception:  # noqa: BLE001 — warmup is advisory
                        registry.counter("warmup_compile_errors").increment()

            self.warmup_thread = threading.Thread(
                target=_warm, name="filodb-warmup", daemon=True)
            self.warmup_thread.start()
        if background_flush:
            from filodb_tpu.core.flush import FlushScheduler
            for dc in self.datasets:
                sched = FlushScheduler(
                    self.memstore, dc.name,
                    interval_s=self.config.store.flush_interval_ms / 1000.0,
                    wal=self.wals.get(dc.name))
                self.flush_schedulers[dc.name] = sched.start()
        for sched in self.compaction_schedulers.values():
            sched.start()
        if self.config.index.compaction_interval_s > 0:
            self.index_compactor = IndexCompactionLoop(
                self.memstore, [dc.name for dc in self.datasets],
                interval_s=self.config.index.compaction_interval_s,
                tombstone_threshold=self.config.index
                .compaction_tombstone_threshold).start()
        if self.ruler is not None:
            self.ruler.start()
        if self.selfmon is not None:
            self.selfmon.start()
        if self.federation_registry is not None:
            self.federation_registry.start()
        # the readiness flip: phase -> serving lands in the event
        # journal, so "replayed, recovered, took traffic" is one
        # greppable sequence at /admin/events
        from filodb_tpu.utils.health import SERVING
        self.health.set_phase(SERVING)

    def shutdown(self) -> None:
        from filodb_tpu.utils.health import STOPPING
        self.health.set_phase(STOPPING)
        if self.federation_registry is not None:
            self.federation_registry.stop()
        if self.federation_door is not None:
            self.federation_door.stop()
        if self.selfmon is not None:
            self.selfmon.stop()
        if self.ruler is not None:
            self.ruler.stop()
        if self.index_compactor is not None:
            self.index_compactor.stop()
            self.index_compactor = None
        for sched in self.compaction_schedulers.values():
            sched.stop()
        self.compaction_schedulers.clear()
        for sched in self.flush_schedulers.values():
            sched.stop(final_flush=True)
        self.flush_schedulers.clear()
        if getattr(self, "trace_exporter", None) is not None:
            self.trace_exporter.stop()
            self.trace_exporter = None
        for repl in self.replicators.values():
            repl.stop()
        if self.replication_server is not None:
            self.replication_server.stop()
            self.replication_server = None
        self.http.stop()
        for wal in self.wals.values():
            wal.close()
        self.wals.clear()

    def flush_and_downsample(self, dataset: str) -> int:
        """Flush all shards, then feed accumulated downsample records into
        the downsample store (the streaming ShardDownsampler → downsample
        ingestion hop, ref: ShardDownsampler.scala publishToDownsampleDataset)."""
        n = 0
        ds_store = self.ds_stores.get(dataset)
        for sh in self.memstore.shards_for(dataset):
            sh.flush_all_groups()
            if ds_store is not None and sh.shard_downsampler is not None:
                n += ds_store.ingest_downsample_batches(
                    sh.shard_num, sh.shard_downsampler.result_batches())
        return n
