"""Batch/maintenance jobs (maps ref: spark-jobs/ — DownsamplerMain lives in
filodb_tpu.downsample.batch_job; this package holds the repair/migration
jobs: ChunkCopier, PartitionKeysCopier, CardinalityBuster)."""
from filodb_tpu.jobs.copier import ChunkCopier, PartitionKeysCopier
from filodb_tpu.jobs.buster import CardinalityBuster

__all__ = ["ChunkCopier", "PartitionKeysCopier", "CardinalityBuster"]
