"""Cross-cluster chunk / partition-key migration jobs.

ref: spark-jobs/.../ChunkCopier.scala (210) and PartitionKeysCopier.scala
(180) — Spark batch jobs that copy a time slice of chunks / partkey records
from one Cassandra cluster to another for repair or migration.  The
TPU-native jobs run the same copy against any two ColumnStore backends;
shards are an embarrassingly parallel loop for the driver to fan out.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from filodb_tpu.core.store import ColumnStore, PartKeyRecord


@dataclasses.dataclass
class CopyStats:
    parts_scanned: int = 0
    chunks_copied: int = 0
    bytes_copied: int = 0
    partkeys_copied: int = 0


class ChunkCopier:
    """Copy chunks whose time range intersects [start, end) from source to
    target (ref: ChunkCopier.scala run loop)."""

    def __init__(self, source: ColumnStore, target: ColumnStore,
                 dataset: str, target_dataset: Optional[str] = None):
        self.source = source
        self.target = target
        self.dataset = dataset
        self.target_dataset = target_dataset or dataset

    def run(self, shards: Sequence[int], start_ms: int,
            end_ms: int) -> CopyStats:
        stats = CopyStats()
        for shard in shards:
            for rec in self.source.read_part_keys(self.dataset, shard):
                if rec.start_time_ms >= end_ms or rec.end_time_ms < start_ms:
                    continue
                stats.parts_scanned += 1
                chunks = self.source.read_chunks(self.dataset, shard,
                                                 rec.part_key, start_ms,
                                                 end_ms - 1)
                if not chunks:
                    continue
                self.target.write_chunks(self.target_dataset, shard,
                                         rec.part_key, chunks,
                                         rec.schema_name)
                stats.chunks_copied += len(chunks)
                stats.bytes_copied += sum(c.nbytes for c in chunks)
        return stats


class PartitionKeysCopier:
    """Copy part-key liveness records in a time window
    (ref: PartitionKeysCopier.scala)."""

    def __init__(self, source: ColumnStore, target: ColumnStore,
                 dataset: str, target_dataset: Optional[str] = None):
        self.source = source
        self.target = target
        self.dataset = dataset
        self.target_dataset = target_dataset or dataset

    def run(self, shards: Sequence[int], start_ms: int,
            end_ms: int) -> CopyStats:
        stats = CopyStats()
        for shard in shards:
            batch = []
            for rec in self.source.read_part_keys(self.dataset, shard):
                if rec.start_time_ms >= end_ms or rec.end_time_ms < start_ms:
                    continue
                batch.append(PartKeyRecord(rec.part_key, rec.schema_name,
                                           rec.start_time_ms,
                                           rec.end_time_ms))
            if batch:
                self.target.write_part_keys(self.target_dataset, shard, batch)
                stats.partkeys_copied += len(batch)
        return stats
