"""CardinalityBuster — delete runaway-cardinality part keys.

ref: spark-jobs/.../CardinalityBusterMain.scala (104) + cardbuster/ (74):
when a misbehaving tenant explodes series counts, this job deletes the
matching part-key records (and optionally their chunks) from the store so
index bootstrap stops resurrecting them.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

from filodb_tpu.core.store import ColumnStore


@dataclasses.dataclass
class BustStats:
    parts_scanned: int = 0
    parts_deleted: int = 0


class CardinalityBuster:
    """Delete part keys whose labels match ALL of `match_labels`
    (ref: CardinalityBusterMain filter config: bust by _ws_/_ns_/metric)."""

    def __init__(self, store: ColumnStore, dataset: str):
        self.store = store
        self.dataset = dataset

    def run(self, shards: Sequence[int], match_labels: Dict[str, str],
            start_ms: int = 0, end_ms: int = 1 << 62) -> BustStats:
        stats = BustStats()
        delete = type(self.store).delete_part_keys
        if delete is ColumnStore.delete_part_keys:
            # fail before any shard is mutated, not mid-run on shard N
            raise NotImplementedError(
                f"{type(self.store).__name__} does not support part-key "
                f"deletion")
        delete = self.store.delete_part_keys
        for shard in shards:
            doomed = []
            for rec in self.store.read_part_keys(self.dataset, shard):
                stats.parts_scanned += 1
                if rec.start_time_ms >= end_ms or rec.end_time_ms < start_ms:
                    continue
                labels = {**rec.part_key.tags_dict,
                          "_metric_": rec.part_key.metric}
                if all(labels.get(k) == v for k, v in match_labels.items()):
                    doomed.append(rec.part_key)
            if doomed:
                delete(self.dataset, shard, doomed)
                stats.parts_deleted += len(doomed)
        return stats
