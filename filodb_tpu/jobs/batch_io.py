"""Batch import/export bridge — the Spark-connector analogue.

The reference's legacy `spark/` module exposed FiloDB datasets to Spark
DataFrames for bulk load and batch analytics (ref: spark/src/main/scala/
filodb.spark/ — DataFrame read/write against a dataset).  The TPU-native
equivalent trades DataFrames for columnar NPZ bundles (numpy's portable
container — loadable by pandas/arrow/jax in one call) plus CSV for
interchange:

- export_series: filtered raw series -> one NPZ (per-series ts/column
  arrays + label table + histogram bucket boundaries).
- import_series: NPZ bundle -> RecordBatches -> shard ingest (bulk load).
- export_csv: the same data as flat CSV (label columns + timestamp +
  value); histogram columns are skipped — use the NPZ bundle for those.

Round trips are lossless, including histogram bucket schemes.
"""
from __future__ import annotations

import csv
import json
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.core.index import ColumnFilter
from filodb_tpu.memory import utf8vec
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.records import RecordBatch
from filodb_tpu.core.schemas import Schemas, DEFAULT_SCHEMAS


def _iter_series(memstore, dataset: str, filters: Sequence[ColumnFilter],
                 start_ms: int, end_ms: int
                 ) -> Iterator[Tuple[Dict[str, str], str, np.ndarray,
                                     Dict[str, np.ndarray],
                                     Optional[np.ndarray]]]:
    """Yield (labels, schema_name, ts_kept, cols_kept, bucket_les) for every
    matching series across all shards — the one shared gather loop (index
    lookup, demand paging, seqlock snapshot, time-range trim) both
    exporters consume."""
    for shard in memstore.shards_for(dataset):
        lookup = shard.lookup_partitions(filters, start_ms, end_ms)
        for schema_name, pids in lookup.pids_by_schema.items():
            shard.ensure_paged_pids(schema_name, pids, start_ms, end_ms)
            store = shard.stores[schema_name]
            rows = shard.rows_for(pids)
            ts, cols, counts = shard.snapshot_read(
                store, lambda: store.gather_rows(rows))
            for i, pid in enumerate(pids.tolist()):
                n = int(counts[i])
                t = ts[i, :n]
                keep = (t >= start_ms) & (t <= end_ms)
                if not keep.any():
                    continue
                info = shard.partitions[pid]
                labels = {**info.part_key.tags_dict,
                          "_metric_": info.part_key.metric}
                kept = {c: (v[i, :n][keep] if v is not None else None)
                        for c, v in cols.items()}
                yield labels, schema_name, t[keep], kept, store.bucket_les


def export_series(memstore, dataset: str, filters: Sequence[ColumnFilter],
                  start_ms: int, end_ms: int, path: str) -> int:
    """Gather matching raw series across all shards into one NPZ bundle.
    Returns the number of series exported."""
    keys: List[Dict[str, str]] = []
    schema_names: List[str] = []
    arrays: Dict[str, np.ndarray] = {}
    for labels, schema_name, t, cols, les in _iter_series(
            memstore, dataset, filters, start_ms, end_ms):
        i = len(keys)
        keys.append(labels)
        schema_names.append(schema_name)
        arrays[f"ts_{i}"] = t
        for c, v in cols.items():
            if v is not None:
                arrays[f"col_{i}_{c}"] = v
        if les is not None:
            arrays[f"les_{i}"] = np.asarray(les, np.float64)
    # Label table is dict-encoded columnar (memory/utf8vec.py) — the
    # DictUTF8Vector analogue: low-cardinality label columns collapse to a
    # few bits/row instead of repeating strings per series.
    arrays["__labels_dict__"] = np.frombuffer(
        utf8vec.pack_label_table(keys), dtype=np.uint8)
    arrays["__schemas__"] = np.frombuffer(
        json.dumps(schema_names).encode("utf-8"), dtype=np.uint8)
    with open(path, "wb") as f:
        np.savez_compressed(f, **arrays)
    return len(keys)


def load_bundle(path: str):
    """(labels, schema_names, per-series {ts, cols, les}) from a bundle."""
    with np.load(path) as z:
        if "__labels_dict__" in z.files:
            labels = utf8vec.unpack_label_table(bytes(z["__labels_dict__"]))
        else:  # bundles written before dict encoding
            labels = json.loads(bytes(z["__labels__"]).decode("utf-8"))
        schemas = json.loads(bytes(z["__schemas__"]).decode("utf-8"))
        # one pass over the archive members (NOT per-series scans: bundles
        # can hold 100k+ series and the member list is large)
        ts_names: Dict[int, str] = {}
        les_names: Dict[int, str] = {}
        col_names: Dict[int, List[Tuple[str, str]]] = {}
        for name in z.files:
            if name.startswith("ts_"):
                ts_names[int(name[3:])] = name
            elif name.startswith("les_"):
                les_names[int(name[4:])] = name
            elif name.startswith("col_"):
                idx_s, col = name[4:].split("_", 1)
                col_names.setdefault(int(idx_s), []).append((col, name))
        series = []
        for i in range(len(labels)):
            series.append({
                "ts": z[ts_names[i]],
                "cols": {c: z[n] for c, n in col_names.get(i, [])},
                "les": z[les_names[i]] if i in les_names else None,
            })
    return labels, schemas, series


def import_series(memstore, dataset: str, path: str,
                  schemas: Schemas = DEFAULT_SCHEMAS,
                  offset: int = -1) -> int:
    """Bulk-load an NPZ bundle through the normal ingest path (gateway
    routing is the caller's job — this targets shard 0 memstores or
    single-shard bulk restores).  Returns samples ingested."""
    labels, schema_names, series = load_bundle(path)
    total = 0
    by_schema: Dict[str, List[int]] = {}
    for i, sname in enumerate(schema_names):
        by_schema.setdefault(sname, []).append(i)
    for sname, idxs in by_schema.items():
        schema = schemas[sname]
        part_keys = []
        part_idx = []
        ts_all = []
        col_all: Dict[str, List[np.ndarray]] = {}
        bucket_les = None
        for j, i in enumerate(idxs):
            lab = dict(labels[i])
            metric = lab.pop("_metric_", lab.pop("__name__", ""))
            part_keys.append(PartKey.make(metric, lab, schemas.part))
            n = len(series[i]["ts"])
            part_idx.append(np.full(n, j, dtype=np.int32))
            ts_all.append(series[i]["ts"])
            for c, v in series[i]["cols"].items():
                col_all.setdefault(c, []).append(v)
            if series[i]["les"] is not None:
                bucket_les = series[i]["les"]
        batch = RecordBatch(
            schema, part_keys,
            np.concatenate(part_idx),
            np.concatenate(ts_all).astype(np.int64),
            {c: np.concatenate(vs) for c, vs in col_all.items()},
            bucket_les=bucket_les)
        for shard in memstore.shards_for(dataset):
            total += shard.ingest(batch, offset=offset)
            break                      # single-shard bulk restore
    return total


def export_csv(memstore, dataset: str, filters: Sequence[ColumnFilter],
               start_ms: int, end_ms: int, path: str,
               value_column: Optional[str] = None) -> int:
    """Flat CSV: one row per sample, label columns + timestamp + value.
    Histogram columns are skipped (use the NPZ bundle for those)."""
    rows_written = 0
    label_names: List[str] = []
    samples = []
    schemas = memstore.schemas
    for labels, schema_name, t, cols, _les in _iter_series(
            memstore, dataset, filters, start_ms, end_ms):
        schema = schemas[schema_name]
        col = value_column or schema.value_column
        if schema.column(col).col_type == "hist":
            continue
        for k in labels:
            if k not in label_names:
                label_names.append(k)
        vals = cols[col]
        for tt, vv in zip(t, vals):
            samples.append((labels, int(tt), float(vv)))
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(label_names + ["timestamp", "value"])
        for lab, tt, vv in samples:
            w.writerow([lab.get(k, "") for k in label_names] + [tt, vv])
            rows_written += 1
    return rows_written
