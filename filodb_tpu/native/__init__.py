"""Optional C++ acceleration library loader.

Builds are produced by `make -C filodb_tpu/native` (see Makefile / filodb_native.cc).
When the shared object is absent, `lib` is None and pure-Python fallbacks are
used everywhere, so the framework never hard-depends on a compiled artifact.
"""
from __future__ import annotations

import ctypes
import os

lib = None

_SO = os.path.join(os.path.dirname(__file__), "libfilodb_native.so")


class _NativeLib:
    def __init__(self, cdll: ctypes.CDLL):
        self._c = cdll
        self._c.filodb_xxhash32.restype = ctypes.c_uint32
        self._c.filodb_xxhash32.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32]
        self._c.filodb_xxhash64.restype = ctypes.c_uint64
        self._c.filodb_xxhash64.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64]

    def xxhash32(self, data: bytes, seed: int = 0) -> int:
        return self._c.filodb_xxhash32(data, len(data), seed)

    def xxhash64(self, data: bytes, seed: int = 0) -> int:
        return self._c.filodb_xxhash64(data, len(data), seed)


if os.path.exists(_SO):  # pragma: no cover - depends on local build
    try:
        lib = _NativeLib(ctypes.CDLL(_SO))
    except OSError:
        lib = None
